# Developer entry points. The tier-1 test command of record lives in
# ROADMAP.md; these targets wrap the static-analysis layer
# (docs/static_analysis.md).

PYTHON ?= python
# Diff base for lint-fast: any git ref (branch, SHA, HEAD~1, ...).
SINCE ?= HEAD

.PHONY: lint lint-fast lint-rules serve chaos chaos-serve bench-spec bench-fused

# Speculative-decoding bench only (docs/performance.md "Speculative
# decoding"): the three-arm vanilla / n-gram / draft-model A/B at the
# 64-slot config. On CPU this smokes structure; the headline
# accepted-tokens/s ratios are judged on chip (BENCH_SECTIONS gates the
# other sections off, including the primary SFT probe).
bench-spec:
	BENCH_SECTIONS=gen_spec $(PYTHON) bench.py

# Fused sampling-epilogue bench only (docs/performance.md "Fused sampling
# epilogue"): materialized-logits vs streamed-head A/B at the 64-slot
# config. On CPU this smokes structure + the exactness probe; the
# headline tokens/s ratio is judged on chip.
bench-fused:
	BENCH_SECTIONS=gen_sample_fused $(PYTHON) bench.py

# Chaos soak, short seeded schedule (CI-sized): drive the 4-process
# elastic CPU fault world through one seeded kill/hang + the serving-side
# probe and assert the end-state invariants (docs/fault_tolerance.md
# "Elastic multihost"). The long soak is `pytest -m slow
# tests/test_elastic_multihost.py`.
chaos:
	$(PYTHON) -m tools.chaos --seed 1 --faults 1 --steps 8 --ckpt-every 3

# Serving-plane survivability soak (docs/serving.md "Survivability"):
# two tiny identical-weight gen servers behind the real gateway, driven
# through backend death mid-stream (token-exact resume), a pre-first-chunk
# wedge (hedge wins), a deadline storm (in-queue shed, full refund), and
# a brownout ladder walk — then asserts nothing leaked and arealint is
# still clean.
chaos-serve:
	$(PYTHON) -m tools.chaos --serve

# Local serving stack (docs/serving.md): one generation engine + gen
# server + the OpenAI-compatible gateway in a single process. Pass a
# checkpoint with ARGS="--model-path /path/to/hf_ckpt --port 8000";
# without one it serves a tiny random-weight model (smoke-test mode).
serve:
	$(PYTHON) -m areal_tpu.gateway $(ARGS)

# Full whole-program scan: areal_tpu/ tools/ tests/, project rules on
# (incl. the v4 resource-lifecycle typestate family), baseline applied.
# This is what tier-1's TestFullTreeGate enforces. `make lint-rules`
# lists the full catalog, lifecycle rules included — rule modules
# register themselves through tools/arealint/__init__.py.
lint:
	$(PYTHON) -m tools.arealint

# Pre-commit fast path (<2 s on a small diff): scan only the Python
# files touched vs $(SINCE), PLUS untracked files — `git diff` alone
# never lists a brand-new module, which is exactly where a fresh
# PartitionSpec typo would live. git runs OUT HERE — the linter itself
# is pure stdlib and reads the file list from stdin (--changed-only).
# Cross-module context degrades to the changed set by design: the scan
# is exactly a full scan restricted to those files (property pinned by
# tests/test_arealint_spmd.py).
# The ref is verified first: a typo'd $(SINCE) must fail loudly, not
# let the pipeline swallow git's error and report a false "clean".
lint-fast:
	@git rev-parse --verify --quiet "$(SINCE)^{commit}" >/dev/null || \
		{ echo "lint-fast: unknown ref '$(SINCE)'" >&2; exit 2; }
	{ git diff --name-only $(SINCE); \
	  git ls-files --others --exclude-standard; } | \
		$(PYTHON) -m tools.arealint --changed-only --since $(SINCE)

lint-rules:
	$(PYTHON) -m tools.arealint --list-rules
