"""≈ reference ``tests/data/test_sequence_gather_split.py``."""

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample


def make_sample(rng, n_items=6, with_data=True):
    seqlens = rng.integers(2, 17, size=n_items).tolist()
    total = sum(seqlens)
    data = {
        "packed_input_ids": rng.integers(0, 100, size=total).astype(np.int64),
        "rewards": rng.normal(size=n_items).astype(np.float32),
    }
    return SequenceSample.from_default(
        ids=[f"id{i}" for i in range(n_items)],
        seqlens=seqlens,
        data=data,
        metadata={"birth_time": [float(i) for i in range(n_items)]},
    )


def test_from_default_shapes(rng):
    s = make_sample(rng)
    assert s.bs == 6
    assert s.seqlens["rewards"] == [[1]] * 6
    assert s.total_len("rewards") == 6


def test_gather_split_roundtrip(rng):
    s = make_sample(rng)
    parts = s.split_with_lengths([2, 3, 1])
    assert [p.bs for p in parts] == [2, 3, 1]
    regathered = SequenceSample.gather(parts)
    assert regathered.ids == s.ids
    np.testing.assert_array_equal(
        regathered.data["packed_input_ids"], s.data["packed_input_ids"]
    )
    np.testing.assert_array_equal(regathered.data["rewards"], s.data["rewards"])
    assert regathered.metadata["birth_time"] == s.metadata["birth_time"]


def test_unpack(rng):
    s = make_sample(rng)
    items = s.unpack()
    assert len(items) == s.bs
    for i, it in enumerate(items):
        assert it.ids == [f"id{i}"]
        assert it.item_total_len("packed_input_ids", 0) == s.item_total_len(
            "packed_input_ids", i
        )


def test_balanced_split(rng):
    s = make_sample(rng, n_items=10)
    parts = s.split(3)
    totals = [p.total_len("packed_input_ids") for p in parts]
    assert sum(totals) == s.total_len("packed_input_ids")
    # Balanced: max part within 2x of ideal.
    assert max(totals) <= 2 * (sum(totals) // 3 + 16)


def test_micro_batch_token_budget(rng):
    s = make_sample(rng, n_items=10)
    mbs = s.split_into_micro_batches(MicroBatchSpec(n_mbs=1, max_tokens_per_mb=30))
    assert all(
        mb.total_len("packed_input_ids") <= 30 or mb.bs == 1 for mb in mbs
    )


def test_meta_and_update(rng):
    s = make_sample(rng)
    m = s.meta()
    assert m.data is None and m.ids == s.ids
    extra = SequenceSample(
        keys={"advantages"},
        ids=list(s.ids),
        seqlens={"advantages": s.seqlens["packed_input_ids"]},
        data={
            "advantages": np.zeros(
                s.total_len("packed_input_ids"), dtype=np.float32
            )
        },
    )
    s.update_(extra)
    assert "advantages" in s.keys
    sel = s.select(["advantages", "rewards"])
    assert sel.keys == {"advantages", "rewards"}


def test_remap(rng):
    s = make_sample(rng)
    s.remap_keys_({"packed_input_ids": "input_ids"})
    assert "input_ids" in s.keys and "packed_input_ids" not in s.keys


def test_json_roundtrip(rng):
    s = make_sample(rng)
    d = s.as_json_compatible()
    import json

    d = json.loads(json.dumps(d))  # force plain types
    s2 = SequenceSample.from_json_compatible(d)
    assert s2.ids == [str(i) for i in s.ids]
    np.testing.assert_array_equal(
        s2.data["packed_input_ids"], s.data["packed_input_ids"]
    )
    np.testing.assert_allclose(s2.data["rewards"], s.data["rewards"], rtol=1e-6)


def test_gather_mismatched_keys_raises(rng):
    s1 = make_sample(rng)
    s2 = s1.select(["rewards"])
    with pytest.raises(ValueError):
        SequenceSample.gather([s1, s2], keys=["packed_input_ids"])
