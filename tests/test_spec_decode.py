"""Speculative decoding: distribution preservation, engine parity, and
composition with the chunked interruptible engine's guarantees.

The load-bearing contracts (docs/performance.md "Speculative decoding"):
- greedy spec decode is TOKEN-IDENTICAL to vanilla decode (acceptance is
  ``draft == argmax`` and the residual is the argmax);
- sampled-mode acceptance is exactly distribution-preserving (chi-square
  on a toy vocab, for both one-hot and general-q proposals);
- spec chunks compose with pause/resume interruption, hot weight swap,
  chunk pipelining, and the bounded-compile discipline.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gen.drafter import NGramDrafter, TransformerDrafter
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.sampling import SamplingParams, spec_rejection_sample
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


def _engine(params, spec, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seqlen", 128)
    return GenerationEngine(CFG, params, spec_decode=spec, **kw)


def _prompts(rng, sizes=(5, 9, 3)):
    return [[int(x) for x in rng.integers(1, 128, size=n)] for n in sizes]


class TestGreedyParity:
    def test_greedy_spec_matches_vanilla(self, params, rng):
        """Greedy spec decode must be token-exact vs vanilla decode: same
        output ids, same finish reasons, same (warped-target) logprobs."""
        prompts = _prompts(rng)
        outs = []
        for spec in (False, True):
            eng = _engine(params, spec, max_slots=4, spec_k=3)
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({o.rid: o for o in eng.run_until_done(decode_steps=3)})
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason
            np.testing.assert_allclose(
                outs[0][rid].output_logprobs, outs[1][rid].output_logprobs,
                atol=1e-4,
            )

    def test_spec_stop_tokens_truncate_mid_draft(self, params, rng):
        """A stop token accepted INSIDE a draft chain must truncate the
        emission exactly where vanilla decode stops (stop included)."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids
        stop = ref[4]
        eng = _engine(params, True, spec_k=4, stop_token_ids=[stop])
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert outs[0].finish_reason == "stop"
        assert outs[0].output_ids == ref[:5]

    def test_spec_min_new_tokens_suppresses_stop(self, params, rng):
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=8, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids
        stop = ref[1]  # would stop at the 2nd token without suppression
        eng = _engine(params, True, spec_k=3, stop_token_ids=[stop])
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=8, min_new_tokens=4,
            greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        # the early stop is suppressed below min_new_tokens; generation
        # runs on until a later stop occurrence or the cap
        assert len(outs[0].output_ids) >= 4
        assert outs[0].output_ids[:4] == ref[:4]

    def test_verify_logits_match_sequential_decode(self, params, rng):
        """The multi-token verify forward must produce the same logits as
        running decode_step_paged sequentially (teacher-forced) — the
        numerical anchor under everything above."""
        eng = _engine(params, False, max_slots=2, page_size=8)
        prompt = [int(x) for x in rng.integers(1, 128, size=6)]
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=8, greedy=True,
        ))
        eng.step(decode_steps=2)   # some resident context
        state = eng.state
        table = jnp.asarray(eng._table_host)
        drafts = jnp.asarray(
            rng.integers(1, 128, size=(eng.B, 3)), jnp.int32
        )
        chunk = jnp.concatenate([state.last_tokens[:, None], drafts], axis=1)
        C = int(chunk.shape[1])
        n_new = jnp.where(state.active, C, 0).astype(jnp.int32)
        wmask = state.active[:, None] & jnp.ones((1, C), bool)
        v_logits, _ = tfm.verify_step_paged(
            params, CFG, state.cache, chunk, table, state.lens, n_new, wmask,
        )
        # sequential teacher-forced decode over the same tokens
        cache, lens = state.cache, state.lens
        for i in range(C):
            logits_i, cache, lens = tfm.decode_step_paged(
                params, CFG, cache, chunk[:, i], table, lens, state.active,
                use_pallas=False,
            )
            b = 0  # slot 0 is the active one
            np.testing.assert_allclose(
                np.asarray(v_logits)[b, i], np.asarray(logits_i)[b],
                atol=2e-4, rtol=2e-4,
            )


class TestDistributionPreservation:
    def _marginal(self, key, logits, draft, sp, n, q_logprobs=None):
        """Empirical distribution of the FIRST emitted token over n runs.

        With a general proposal, the theorem requires the draft be DRAWN
        from it — so each run samples its own draft from ``q_logprobs``;
        one-hot proposals keep the fixed draft (the delta's only sample).
        """
        def one(k):
            d = draft
            if q_logprobs is not None:
                kd, k = jax.random.split(k)
                d = jax.vmap(
                    lambda kk, ql: jax.random.categorical(kk, ql, axis=-1),
                    in_axes=(None, 1), out_axes=1,
                )(kd, q_logprobs).astype(jnp.int32)
            _, tokens, _, _ = spec_rejection_sample(
                k, logits, d, sp, warp=False, q_logprobs=q_logprobs
            )
            return tokens[0, 0]

        toks = jax.vmap(one)(jax.random.split(key, n))
        V = logits.shape[-1]
        return np.bincount(np.asarray(toks), minlength=V) / n

    @pytest.mark.parametrize("general_q", [False, True])
    def test_first_token_marginal_chi_square(self, general_q):
        """The first emitted token (accepted draft OR residual) must be
        distributed exactly as the target — for one-hot proposals and for
        a general proposal distribution the drafts are sampled from."""
        V, K = 16, 2
        rng = np.random.default_rng(0)
        logits = jnp.asarray(
            rng.normal(size=(1, K + 1, V)), jnp.float32
        )
        draft = jnp.asarray([[3, 7]], jnp.int32)
        sp = SamplingParams.filled(1)
        q_lp = None
        if general_q:
            q = rng.normal(size=(1, K, V)).astype(np.float32)
            q_lp = jnp.asarray(jax.nn.log_softmax(jnp.asarray(q), axis=-1))
        n = 20000
        emp = self._marginal(
            jax.random.key(1), logits, draft, sp, n, q_logprobs=q_lp
        )
        want = np.asarray(jax.nn.softmax(logits[0, 0]))
        chi2 = (n * (emp - want) ** 2 / np.maximum(want, 1e-9)).sum()
        # df = 15; p=0.001 critical value ~37.7 — generous margin
        assert chi2 < 45.0, (chi2, emp, want)

    def test_accepted_prefix_then_residual_layout(self):
        """accept_len semantics: positions < accept_len are draft tokens,
        position accept_len the residual; greedy accepts iff argmax."""
        V = 8
        logits = np.full((1, 3, V), -10.0, np.float32)
        logits[0, 0, 2] = 10.0   # argmax 2
        logits[0, 1, 5] = 10.0   # argmax 5
        logits[0, 2, 1] = 10.0   # bonus argmax 1
        sp = SamplingParams.filled(1, temperature=0.0)
        # full acceptance: drafts match argmax chain -> bonus emitted
        a, toks, _, _ = spec_rejection_sample(
            jax.random.key(0), jnp.asarray(logits),
            jnp.asarray([[2, 5]], jnp.int32), sp, warp=False,
        )
        assert int(a[0]) == 2
        assert toks[0, :3].tolist() == [2, 5, 1]
        # first draft wrong -> rejected immediately, residual = argmax
        a, toks, _, _ = spec_rejection_sample(
            jax.random.key(0), jnp.asarray(logits),
            jnp.asarray([[4, 5]], jnp.int32), sp, warp=False,
        )
        assert int(a[0]) == 0
        assert int(toks[0, 0]) == 2

    def test_sampled_spec_engine_runs_and_varies(self, params):
        """Stochastic spec decode through the full engine: reproducible
        per-seed, diverse across slots (the vanilla sampling contract)."""
        outs = {}
        for run in range(2):
            eng = _engine(params, True, max_slots=4, spec_k=3, seed=7)
            for i in range(4):
                eng.submit(GenRequest(
                    rid=f"s{i}", input_ids=[5, 6, 7], max_new_tokens=8,
                    temperature=1.0, top_p=0.95,
                ))
            outs[run] = {
                o.rid: o.output_ids
                for o in eng.run_until_done(decode_steps=2)
            }
        assert outs[0] == outs[1]                       # seeded: reproducible
        assert len(set(map(tuple, outs[0].values()))) > 1  # slots differ


class TestComposition:
    def test_pause_mid_spec_chunk_harvests_valid_partial(self, params, rng):
        """pause() mid-spec-generation yields an 'interrupted' partial that
        is a PREFIX of the uninterrupted greedy chain, and resubmission
        completes it exactly (the partial-rollout protocol)."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids

        eng = _engine(params, True, spec_k=3)
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.step(decode_steps=1)
        parts = eng.pause()
        assert len(parts) == 1 and parts[0].finish_reason == "interrupted"
        got = parts[0].output_ids
        assert 0 < len(got) < 12
        assert got == ref[: len(got)]
        eng.resume()
        eng.submit(GenRequest(
            rid="a2", input_ids=prompt + got,
            max_new_tokens=12 - len(got), greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert got + outs[0].output_ids == ref

    def test_update_params_between_spec_chunks_bumps_version(
        self, params, monkeypatch
    ):
        # through the literal env knob (AREAL_SPEC_DECODE=1), not the
        # ctor override — the path a deployed fleet takes
        monkeypatch.setenv("AREAL_SPEC_DECODE", "1")
        monkeypatch.setenv("AREAL_SPEC_K", "2")
        eng = _engine(params, None, max_slots=1)
        assert eng.spec is True and eng.spec_k == 2
        eng.submit(GenRequest(
            rid="a", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=2)
        assert outs[0].version == 0
        new_params = tfm.init_params(CFG, jax.random.key(9))
        eng.update_params(new_params, version=3)
        assert len(eng.prefix) == 0
        eng.submit(GenRequest(
            rid="b", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=2)
        assert outs[0].version == 3

    def test_spec_pipelined_matches_unpipelined(self, params, rng):
        prompts = _prompts(rng, sizes=(5, 9, 3, 7))
        outs = []
        for pipelined in (False, True):
            eng = _engine(
                params, True, max_slots=4, spec_k=3,
                pipeline_chunks=pipelined,
            )
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({
                o.rid: o for o in eng.run_until_done(decode_steps=2)
            })
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason

    def test_mixed_spec_vanilla_traffic_bounded_compiles(self, params, rng):
        """Flipping spec on/off between chunks (one engine, one state
        pytree) must not grow jit specializations past the warm set —
        the n_compiles discipline extended to mixed traffic."""
        eng = _engine(params, False, max_slots=4, max_seqlen=256,
                      page_size=16, spec_k=3)
        def burst(tag, plens):
            for i, plen in enumerate(plens):
                eng.submit(GenRequest(
                    rid=f"{tag}{i}",
                    input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                    max_new_tokens=6, greedy=True,
                ))
            eng.run_until_done(decode_steps=3)

        burst("v", [3, 9, 17, 33])       # warm vanilla
        eng.spec = True
        burst("s", [3, 9, 17, 33])       # warm spec
        eng.spec = False
        burst("v2", [5, 21])
        eng.spec = True
        warmed = eng.n_compiles()
        # fresh prompt lengths + more toggles: no new specializations
        eng.spec = False
        burst("v3", [11, 29, 60])
        eng.spec = True
        burst("s2", [7, 45, 80])
        assert eng.n_compiles() == warmed

    def test_tp2_spec_greedy_matches_single_device(self, params, rng):
        """Spec decode on a 2-way `model` mesh (sampling replicated after
        the logits all-gather) must match the unsharded engine token for
        token."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        prompts = _prompts(rng)
        eng1 = _engine(params, True, max_slots=4, spec_k=3)
        eng2 = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=128,
            spec_decode=True, spec_k=3, mesh=mesh,
        )
        for eng in (eng1, eng2):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=8, greedy=True,
                ))
        o1 = {o.rid: o for o in eng1.run_until_done(decode_steps=2)}
        o2 = {o.rid: o for o in eng2.run_until_done(decode_steps=2)}
        assert set(o1) == set(o2)
        for rid in o1:
            assert o1[rid].output_ids == o2[rid].output_ids, rid

    def test_spec_telemetry_counters(self, params, rng):
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_DRAFT_TOKENS)
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_ACCEPTED_TOKENS)
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_ACCEPT_LEN)
        eng = _engine(params, True, spec_k=3)
        # a repetitive prompt: the n-gram drafter should accept something
        prompt = [7, 8, 9] * 6
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.run_until_done(decode_steps=2)
        drafted = eng.stats["spec_draft_tokens"]
        accepted = eng.stats["spec_accepted_tokens"]
        assert drafted > 0
        assert 0 <= accepted <= drafted
        assert metrics_mod.counters.get(
            metrics_mod.GEN_SPEC_DRAFT_TOKENS
        ) == drafted
        h = metrics_mod.counters.histogram(metrics_mod.GEN_SPEC_ACCEPT_LEN)
        assert h is not None and h.count > 0


def test_nondeterministic_drafter_rejected_at_construction(params):
    """Sampled drafters must declare provides_q_logprobs (and route
    through the model-drafter interface): one without q would silently
    bias generation toward its proposals (the distribution-preservation
    guarantee) — it must fail loudly, while drafters that DO supply q
    (TransformerDrafter) construct fine."""
    from areal_tpu.gen.drafter import Drafter

    class SampledDrafter(Drafter):
        # plain subclass, not the frozen dataclass: its generated __init__
        # would pin the instance attribute back to the dataclass default
        deterministic = False

        def propose(self, ctx_tokens, lens, fallback, k):  # pragma: no cover
            raise AssertionError("never reached")

    with pytest.raises(NotImplementedError, match="q_logprobs"):
        GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64,
            spec_decode=True, drafter=SampledDrafter(),
        )

    # declaring q without the propose_model wiring is equally loud: the
    # engine would otherwise call propose() and its q would never reach
    # the rejection sampler
    class LyingDrafter(Drafter):
        deterministic = False
        provides_q_logprobs = True

        def propose(self, ctx_tokens, lens, fallback, k):  # pragma: no cover
            raise AssertionError("never reached")

    with pytest.raises(NotImplementedError, match="TransformerDrafter"):
        GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64,
            spec_decode=True, drafter=LyingDrafter(),
        )

    # the relaxed guard's positive side: a sampled drafter that supplies
    # q through the model interface constructs (and serves) fine
    eng = GenerationEngine(
        CFG, params, max_slots=2, max_seqlen=64, spec_decode=True,
        drafter=TransformerDrafter.shared_prefix(CFG, params, 1),
    )
    assert eng._draft is not None

    # vocab mismatch is a construction error, not a runtime surprise
    bad_cfg = dataclasses.replace(CFG, vocab_size=64, n_layers=1)
    with pytest.raises(ValueError, match="vocab"):
        GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, spec_decode=True,
            drafter=TransformerDrafter(
                bad_cfg, tfm.init_params(bad_cfg, jax.random.key(0))
            ),
        )


def test_env_draft_model_ignored_when_spec_disabled(params, monkeypatch):
    """A fleet-wide AREAL_SPEC_DRAFT_MODEL must not make a spec-disabled
    engine pay for a draft model (pool HBM + a per-vanilla-step
    maintenance sweep): the env-knob checkpoint is only resolved when
    spec decode is on, so construction with spec off never even touches
    the path (a bogus one proves it)."""
    from areal_tpu.base import constants

    monkeypatch.setenv(constants.SPEC_DRAFT_MODEL_ENV, "/nonexistent/draft")
    eng = GenerationEngine(
        CFG, params, max_slots=2, max_seqlen=64, spec_decode=False,
    )
    assert eng._draft is None
    assert isinstance(eng.drafter, NGramDrafter)
    assert eng.state.draft_cache is None
    assert eng.draft_kv_pool_bytes() == 0


def test_draft_dtype_coerced_into_drafter_cfg(params):
    """The engine coerces a draft checkpoint's dtype to the target's —
    and must write it back into the drafter, because propose_model runs
    the draft forward under the DRAFTER's cfg: leaving the checkpoint
    dtype there would compute spec-chunk proposals in one dtype while
    the vanilla chunk's maintenance step writes KV in another."""
    dcfg = dataclasses.replace(CFG, n_layers=1, dtype="bfloat16")
    drafter = TransformerDrafter(
        dcfg, tfm.init_params(dcfg, jax.random.key(7), dtype="bfloat16")
    )
    eng = GenerationEngine(
        CFG, params, max_slots=2, max_seqlen=64, spec_decode=True,
        drafter=drafter,
    )
    assert eng.draft_cfg.dtype == CFG.dtype == "float32"
    assert eng.drafter.cfg.dtype == "float32"
    leaf = jax.tree.leaves(eng.draft_params)[0]
    assert leaf.dtype == jnp.float32


class TestTransformerDrafter:
    """Draft-MODEL speculative decoding: a small transformer proposes K
    tokens autoregressively inside the jitted chunk, with its own paged
    KV pool riding the engine state in lockstep with the target's, and
    its proposal distribution feeding the general-q rejection sampler."""

    def _draft_engine(self, params, n_layers=1, drafter=None, **kw):
        drafter = drafter or TransformerDrafter.shared_prefix(
            CFG, params, n_layers
        )
        return _engine(params, True, drafter=drafter, **kw)

    def test_greedy_token_exact_vs_vanilla_any_draft(self, params, rng):
        """Greedy draft-model spec decode must be token-exact vs vanilla
        — even when the draft is an INDEPENDENT random-init model whose
        proposals are garbage (acceptance can only cost speed, never
        correctness), and with the q_accept_prob telemetry folding."""
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_Q_ACCEPT_PROB)
        prompts = _prompts(rng)
        dcfg = dataclasses.replace(CFG, n_layers=1)
        garbage = TransformerDrafter(
            dcfg, tfm.init_params(dcfg, jax.random.key(123))
        )
        runs = {}
        for name, eng in (
            ("vanilla", _engine(params, False, max_slots=4)),
            ("garbage", self._draft_engine(
                params, drafter=garbage, max_slots=4, spec_k=3)),
            ("prefix", self._draft_engine(params, max_slots=4, spec_k=3)),
        ):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            runs[name] = {
                o.rid: o for o in eng.run_until_done(decode_steps=3)
            }
        for name in ("garbage", "prefix"):
            assert set(runs["vanilla"]) == set(runs[name])
            for rid, ref in runs["vanilla"].items():
                got = runs[name][rid]
                assert ref.output_ids == got.output_ids, (name, rid)
                assert ref.finish_reason == got.finish_reason
                np.testing.assert_allclose(
                    ref.output_logprobs, got.output_logprobs, atol=1e-4
                )
        h = metrics_mod.counters.histogram(
            metrics_mod.GEN_SPEC_Q_ACCEPT_PROB
        )
        assert h is not None and h.count > 0

    def test_first_token_marginal_chi_square_engine_general_q(self):
        """The full engine path — draft model proposes sampled tokens
        from q, verify scores, general-q rejection accepts — must leave
        the FIRST emitted token distributed exactly as the target
        (chi-square on a 32-token vocab against the target's softmax)."""
        V32 = ModelConfig(
            n_layers=2, n_q_heads=2, n_kv_heads=2, head_dim=8,
            hidden_dim=16, intermediate_dim=32, vocab_size=32,
            dtype="float32",
        )
        tparams = tfm.init_params(V32, jax.random.key(3))
        dcfg = dataclasses.replace(V32, n_layers=1)
        drafter = TransformerDrafter(
            dcfg, tfm.init_params(dcfg, jax.random.key(77))
        )
        eng = GenerationEngine(
            V32, tparams, max_slots=16, max_seqlen=32, spec_decode=True,
            spec_k=2, drafter=drafter, enable_prefix_cache=False,
        )
        prompt = [3, 9, 4, 1]
        n = 2048
        counts = np.zeros(32)
        r = 0
        while int(counts.sum()) < n:
            for i in range(16):
                eng.submit(GenRequest(
                    rid=f"{r}_{i}", input_ids=prompt, max_new_tokens=1,
                    temperature=1.0,
                ))
            for o in eng.run_until_done(decode_steps=1):
                counts[o.output_ids[0]] += 1
            r += 1
        T = len(prompt)
        logits = tfm.forward_packed(
            tparams, V32, jnp.asarray(prompt, jnp.int32),
            jnp.ones((T,), jnp.int32), jnp.arange(T, dtype=jnp.int32),
            remat=False,
        )[-1]
        want = np.asarray(jax.nn.softmax(logits))
        total = counts.sum()
        emp = counts / total
        chi2 = (total * (emp - want) ** 2 / np.maximum(want, 1e-9)).sum()
        # df = 31; p=0.001 critical value ~61.1 — generous margin (the
        # run is seeded, so this is a one-time calibration, not a flake)
        assert chi2 < 75.0, (chi2, emp, want)

    def test_tp2_draft_greedy_matches_single_device(self, params, rng):
        """Draft-model spec decode on a 2-way `model` mesh (draft params
        + draft pool sharded through the same rules as the target) must
        match the unsharded engine token for token."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        prompts = _prompts(rng)
        eng1 = self._draft_engine(params, max_slots=4, spec_k=3)
        eng2 = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=128, spec_decode=True,
            spec_k=3, mesh=mesh,
            drafter=TransformerDrafter.shared_prefix(CFG, params, 1),
        )
        for eng in (eng1, eng2):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=8, greedy=True,
                ))
        o1 = {o.rid: o for o in eng1.run_until_done(decode_steps=2)}
        o2 = {o.rid: o for o in eng2.run_until_done(decode_steps=2)}
        assert set(o1) == set(o2)
        for rid in o1:
            assert o1[rid].output_ids == o2[rid].output_ids, rid

    def test_draft_page_lockstep_under_pause_resume(self, params, rng):
        """Draft pages are the TARGET's pages (one index, two pools), so
        pause must release everything back to the pool, the interrupted
        partial must be a valid greedy prefix, and the resubmission —
        re-prefilling BOTH pools — must complete the chain exactly."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids

        eng = self._draft_engine(
            params, spec_k=3, enable_prefix_cache=False,
        )
        free0 = eng.pool.n_free
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.step(decode_steps=1)
        assert eng.pool.n_free < free0          # pages held (both pools)
        parts = eng.pause()
        assert eng.pool.n_free == free0         # all released in lockstep
        got = parts[0].output_ids
        assert parts[0].finish_reason == "interrupted"
        assert 0 < len(got) < 12 and got == ref[: len(got)]
        eng.resume()
        eng.submit(GenRequest(
            rid="a2", input_ids=prompt + got,
            max_new_tokens=12 - len(got), greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert got + outs[0].output_ids == ref
        assert eng.draft_kv_pool_bytes() > 0

    def test_draft_weight_swap_version_bump(self, params):
        """update_draft_params bumps draft_version WITHOUT touching the
        policy version (spec decode is distribution-preserving, greedy
        outputs are unchanged); update_params(draft_params=...) swaps
        both under one lock and bumps both versions."""
        eng = self._draft_engine(params, max_slots=1, spec_k=2)
        eng.submit(GenRequest(
            rid="a", input_ids=[1, 2, 3], max_new_tokens=4, greedy=True,
        ))
        o0 = eng.run_until_done(decode_steps=2)[0]
        dcfg = dataclasses.replace(CFG, n_layers=1)
        new_draft = tfm.init_params(dcfg, jax.random.key(9))
        eng.update_draft_params(new_draft)
        assert eng.draft_version == 1 and eng.version == 0
        assert len(eng.prefix) == 0
        eng.submit(GenRequest(
            rid="b", input_ids=[1, 2, 3], max_new_tokens=4, greedy=True,
        ))
        o1 = eng.run_until_done(decode_steps=2)[0]
        assert o1.output_ids == o0.output_ids   # outputs untouched
        assert o1.version == 0
        # policy + draft ride-along: one pause window, both versions move
        eng.update_params(
            tfm.init_params(CFG, jax.random.key(11)), version=3,
            draft_params=new_draft,
        )
        assert eng.version == 3 and eng.draft_version == 2

    def test_mixed_vanilla_spec_traffic_bounded_compiles(self, params, rng):
        """Toggling spec on/off on a draft-model engine (the vanilla
        chunk maintains the draft pool with a headless draft step, so
        both chunk kinds share one state pytree) must not grow jit
        specializations past the warm set."""
        eng = self._draft_engine(
            params, max_slots=4, max_seqlen=256, page_size=16, spec_k=3,
        )
        eng.spec = False

        def burst(tag, plens):
            for i, plen in enumerate(plens):
                eng.submit(GenRequest(
                    rid=f"{tag}{i}",
                    input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                    max_new_tokens=6, greedy=True,
                ))
            eng.run_until_done(decode_steps=3)

        burst("v", [3, 9, 17, 33])
        eng.spec = True
        burst("s", [3, 9, 17, 33])
        eng.spec = False
        burst("v2", [5, 21])
        eng.spec = True
        warmed = eng.n_compiles()
        eng.spec = False
        burst("v3", [11, 29, 60])
        eng.spec = True
        burst("s2", [7, 45, 80])
        assert eng.n_compiles() == warmed


class TestChunkBoundarySync:
    """The dispatch-ahead flag fetch: the harvest-flag D2H copy starts at
    chunk dispatch and resolves one chunk later (pipelined mode), so
    steady-state decode issues ZERO blocking device_get calls at chunk
    boundaries — proven by trace (a counting device_get shim) plus the
    engine's own blocked-resolve counter, the same event-log proof style
    as the fwd_pipe overlap test."""

    def test_steady_state_zero_blocking_device_get(self, params, monkeypatch):
        eng = _engine(
            params, False, max_slots=2, max_seqlen=512,
            pipeline_chunks=True,
        )
        eng.submit(GenRequest(
            rid="a", input_ids=[1, 2, 3, 4, 5], max_new_tokens=400,
            greedy=True,
        ))
        eng.step(decode_steps=4)    # admit + first dispatch
        eng.step(decode_steps=4)    # warm both pipeline stages
        metrics_mod.counters.clear(metrics_mod.GEN_CHUNK_FLAG_FETCHES)
        metrics_mod.counters.clear(metrics_mod.GEN_CHUNK_FLAG_BLOCKED)
        calls = []
        orig = jax.device_get
        monkeypatch.setattr(
            jax, "device_get",
            lambda *a, **kw: (calls.append(a), orig(*a, **kw))[1],
        )
        n_chunks = 10
        for _ in range(n_chunks):
            eng.step(decode_steps=4)
            # harness pacing only: wait out the in-flight chunk so the
            # next resolve measures the protocol, not CPU scheduling
            jax.block_until_ready(eng.state.lens)
        assert calls == []          # the trace assertion: zero device_get
        assert metrics_mod.counters.get(
            metrics_mod.GEN_CHUNK_FLAG_FETCHES
        ) == n_chunks
        assert metrics_mod.counters.get(
            metrics_mod.GEN_CHUNK_FLAG_BLOCKED
        ) == 0
        # the engine still harvests correctly after the window
        monkeypatch.setattr(jax, "device_get", orig)
        outs = eng.run_until_done(decode_steps=64)
        assert outs and outs[0].finish_reason == "length"

    def test_spec_chunk_flags_prefetch_too(self, params, rng):
        """The same protocol covers spec chunks (their longer aux tuple
        rides the same dispatch-ahead copy)."""
        eng = _engine(
            params, True, max_slots=2, max_seqlen=512, spec_k=3,
            pipeline_chunks=True,
        )
        eng.submit(GenRequest(
            rid="a",
            input_ids=[int(x) for x in rng.integers(1, 128, 6)],
            max_new_tokens=200, greedy=True,
        ))
        eng.step(decode_steps=2)
        eng.step(decode_steps=2)
        metrics_mod.counters.clear(metrics_mod.GEN_CHUNK_FLAG_BLOCKED)
        for _ in range(5):
            eng.step(decode_steps=2)
            jax.block_until_ready(eng.state.lens)
        assert metrics_mod.counters.get(
            metrics_mod.GEN_CHUNK_FLAG_BLOCKED
        ) == 0
        assert eng.stats["spec_draft_tokens"] > 0


class TestNGramDrafter:
    def test_bigram_match_proposes_continuation(self):
        d = NGramDrafter()
        # context ... 1 2 3 4 1 2 -> bigram (1, 2) matched at 0 -> 3 4 ...
        ctx = jnp.asarray([[1, 2, 3, 4, 1, 2, 0, 0]], jnp.int32)
        lens = jnp.asarray([5], jnp.int32)   # ctx[5] = 2 is the last token
        out = d.propose(ctx, lens, jnp.asarray([99], jnp.int32), 3)
        assert out[0].tolist() == [3, 4, 1]

    def test_unigram_fallback_then_hint(self):
        d = NGramDrafter()
        # no bigram (5, 2) occurs earlier; unigram 2 at index 1 -> 3, 4...
        ctx = jnp.asarray([[1, 2, 3, 4, 5, 2, 0, 0]], jnp.int32)
        lens = jnp.asarray([5], jnp.int32)
        out = d.propose(ctx, lens, jnp.asarray([99], jnp.int32), 3)
        assert out[0].tolist() == [3, 4, 5]
        # nothing matches at all -> the greedy-from-last-logits hint
        ctx = jnp.asarray([[1, 2, 3, 4, 5, 6, 0, 0]], jnp.int32)
        out = d.propose(ctx, jnp.asarray([5], jnp.int32),
                        jnp.asarray([99], jnp.int32), 2)
        assert out[0].tolist() == [99, 99]

    def test_proposals_never_cross_valid_region(self):
        d = NGramDrafter()
        # the current pair sits at (3, 4); the only EARLIER bigram (1, 2)
        # is at (1, 2), so the continuation starts at index 3 and may read
        # up to index lens (the pending last token) — past that, proposals
        # fill with the hint, never with stale buffer garbage (the 7s)
        ctx = jnp.asarray([[0, 1, 2, 1, 2, 7, 7, 7]], jnp.int32)
        lens = jnp.asarray([4], jnp.int32)
        out = d.propose(ctx, lens, jnp.asarray([50], jnp.int32), 4)
        assert out[0].tolist() == [1, 2, 50, 50]


class TestServingSurface:
    async def test_spec_toggle_endpoint_and_metrics(self, params):
        """POST /spec_decode flips the engine between chunks; /metrics_json
        reports the spec config + realized accept rate."""
        from aiohttp.test_utils import TestClient, TestServer

        from areal_tpu.gen.server import GenerationHTTPServer

        eng = _engine(params, True, spec_k=2)
        srv = GenerationHTTPServer(eng, decode_steps=2)
        client = TestClient(TestServer(srv.app))
        await client.start_server()
        try:
            r = await client.post("/spec_decode", json={"enabled": False})
            d = await r.json()
            assert d["success"] and d["spec_decode"] is False
            assert d["spec_k"] == 2 and eng.spec is False
            r = await client.post("/spec_decode", json={"enabled": True})
            assert (await r.json())["spec_decode"] is True
            r = await client.post("/spec_decode", json={})
            assert r.status == 400
            r = await client.get("/metrics_json")
            m = await r.json()
            assert m["spec_decode"] is True and m["spec_k"] == 2
            assert "spec_accept_rate" in m
            assert "engine_spec_draft_tokens" in m
            # draft-model gauges (no draft configured on this engine)
            assert m["spec_draft_model"] is False
            assert m["draft_kv_pool_bytes"] == 0
            assert m["draft_version"] == 0
        finally:
            await client.close()


def _run_gen_spec_stanza():
    """Shared three-arm ``gen_spec`` run for the tier-1 smoke and the
    slow throughput-ordering pin: an 8-layer micro target (so the
    2-layer shared-prefix draft is meaningfully cheaper) at a shape
    whose slots stay live through every measured chunk."""
    import bench as bench_mod

    cfg8 = dataclasses.replace(
        CFG, n_layers=8, dtype="float32",
    )
    return bench_mod._bench_gen_spec(
        819e9, 197e12, cfg=cfg8, B=8, PLEN=128, D_STEPS=4, N_CHUNKS=3,
        motif_len=8,
    )


def test_bench_gen_spec_stanza_end_to_end():
    """The three-arm ``gen_spec`` bench (vanilla / n-gram / draft-model)
    runs end-to-end on the CPU harness and the DETERMINISTIC draft-arm
    acceptance bars hold: its accept rate beats the n-gram drafter's
    (including the chip-measured 0.29). Accept rates are seeded greedy
    token counts, so they are exact; the wall-clock throughput ORDERING
    (draft_vs_baseline > vs_baseline) is real but CI-load-sensitive, so
    tier-1 only floors it against pathology and the strict ordering is
    pinned by the slow variant below (run unmarked locally + on chip).
    Absolute ratios are judged on chip (HBM-roofline economics)."""
    out = _run_gen_spec_stanza()
    assert set(out) >= {
        "vanilla_tokens_per_s", "accepted_tokens_per_s", "accept_rate",
        "vs_baseline", "spec_k", "draft_tokens_per_s", "draft_accept_rate",
        "draft_vs_baseline", "draft_layers",
    }
    assert out["accepted_tokens_per_s"] > 0
    assert 0.0 < out["accept_rate"] <= 1.0
    assert out["vs_baseline"] > 0.8
    # the draft-model acceptance bar (ISSUE 14): beat the n-gram's
    # accept rate and its chip-measured 0.29 — deterministic, so strict
    assert out["draft_accept_rate"] > max(0.29, out["accept_rate"])
    # throughput sanity floor only (see docstring): CPU-timer noise on a
    # loaded CI box must not flake tier-1
    assert out["draft_vs_baseline"] > 0.75 * out["vs_baseline"]


@pytest.mark.slow
def test_bench_gen_spec_draft_beats_ngram_throughput():
    """The strict CPU-smoke speed ordering (ISSUE 14 acceptance): the
    draft arm's accepted-tokens/s vs_baseline beats the n-gram arm at
    the same settings. Wall-clock comparison — slow-marked so a loaded
    tier-1 CI box can't flake it; verified per-PR by the spec verify
    driver and on every local/chip bench run."""
    out = _run_gen_spec_stanza()
    assert out["draft_accept_rate"] > max(0.29, out["accept_rate"])
    assert out["draft_vs_baseline"] > out["vs_baseline"]


# --------------------------------------------------------------------- #
# Exhaustive spec-vs-vanilla parity sweep. Tier-1 keeps ONE representative
# configuration (matching the round-6 kernel-test policy); the rest run
# unmarked locally and on chip.
# --------------------------------------------------------------------- #

SWEEP = [
    pytest.param(1, False, 4),
    pytest.param(2, True, 3, marks=pytest.mark.slow),
    pytest.param(4, False, 1, marks=pytest.mark.slow),
    pytest.param(4, True, 6, marks=pytest.mark.slow),
    pytest.param(8, False, 2, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("spec_k,pipelined,decode_steps", SWEEP)
def test_spec_parity_sweep(params, rng, spec_k, pipelined, decode_steps):
    prompts = _prompts(rng, sizes=(4, 11, 6))
    vanilla = _engine(params, False, max_slots=4)
    spec = _engine(
        params, True, max_slots=4, spec_k=spec_k, pipeline_chunks=pipelined,
    )
    for eng in (vanilla, spec):
        for i, p in enumerate(prompts):
            eng.submit(GenRequest(
                rid=f"r{i}", input_ids=p, max_new_tokens=9, greedy=True,
            ))
    o1 = {o.rid: o for o in vanilla.run_until_done(decode_steps=4)}
    o2 = {o.rid: o for o in spec.run_until_done(decode_steps=decode_steps)}
    assert set(o1) == set(o2)
    for rid in o1:
        assert o1[rid].output_ids == o2[rid].output_ids, rid
