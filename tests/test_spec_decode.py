"""Speculative decoding: distribution preservation, engine parity, and
composition with the chunked interruptible engine's guarantees.

The load-bearing contracts (docs/performance.md "Speculative decoding"):
- greedy spec decode is TOKEN-IDENTICAL to vanilla decode (acceptance is
  ``draft == argmax`` and the residual is the argmax);
- sampled-mode acceptance is exactly distribution-preserving (chi-square
  on a toy vocab, for both one-hot and general-q proposals);
- spec chunks compose with pause/resume interruption, hot weight swap,
  chunk pipelining, and the bounded-compile discipline.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gen.drafter import NGramDrafter
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.sampling import SamplingParams, spec_rejection_sample
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


def _engine(params, spec, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seqlen", 128)
    return GenerationEngine(CFG, params, spec_decode=spec, **kw)


def _prompts(rng, sizes=(5, 9, 3)):
    return [[int(x) for x in rng.integers(1, 128, size=n)] for n in sizes]


class TestGreedyParity:
    def test_greedy_spec_matches_vanilla(self, params, rng):
        """Greedy spec decode must be token-exact vs vanilla decode: same
        output ids, same finish reasons, same (warped-target) logprobs."""
        prompts = _prompts(rng)
        outs = []
        for spec in (False, True):
            eng = _engine(params, spec, max_slots=4, spec_k=3)
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({o.rid: o for o in eng.run_until_done(decode_steps=3)})
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason
            np.testing.assert_allclose(
                outs[0][rid].output_logprobs, outs[1][rid].output_logprobs,
                atol=1e-4,
            )

    def test_spec_stop_tokens_truncate_mid_draft(self, params, rng):
        """A stop token accepted INSIDE a draft chain must truncate the
        emission exactly where vanilla decode stops (stop included)."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids
        stop = ref[4]
        eng = _engine(params, True, spec_k=4, stop_token_ids=[stop])
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert outs[0].finish_reason == "stop"
        assert outs[0].output_ids == ref[:5]

    def test_spec_min_new_tokens_suppresses_stop(self, params, rng):
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=8, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids
        stop = ref[1]  # would stop at the 2nd token without suppression
        eng = _engine(params, True, spec_k=3, stop_token_ids=[stop])
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=8, min_new_tokens=4,
            greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        # the early stop is suppressed below min_new_tokens; generation
        # runs on until a later stop occurrence or the cap
        assert len(outs[0].output_ids) >= 4
        assert outs[0].output_ids[:4] == ref[:4]

    def test_verify_logits_match_sequential_decode(self, params, rng):
        """The multi-token verify forward must produce the same logits as
        running decode_step_paged sequentially (teacher-forced) — the
        numerical anchor under everything above."""
        eng = _engine(params, False, max_slots=2, page_size=8)
        prompt = [int(x) for x in rng.integers(1, 128, size=6)]
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=8, greedy=True,
        ))
        eng.step(decode_steps=2)   # some resident context
        state = eng.state
        table = jnp.asarray(eng._table_host)
        drafts = jnp.asarray(
            rng.integers(1, 128, size=(eng.B, 3)), jnp.int32
        )
        chunk = jnp.concatenate([state.last_tokens[:, None], drafts], axis=1)
        C = int(chunk.shape[1])
        n_new = jnp.where(state.active, C, 0).astype(jnp.int32)
        wmask = state.active[:, None] & jnp.ones((1, C), bool)
        v_logits, _ = tfm.verify_step_paged(
            params, CFG, state.cache, chunk, table, state.lens, n_new, wmask,
        )
        # sequential teacher-forced decode over the same tokens
        cache, lens = state.cache, state.lens
        for i in range(C):
            logits_i, cache, lens = tfm.decode_step_paged(
                params, CFG, cache, chunk[:, i], table, lens, state.active,
                use_pallas=False,
            )
            b = 0  # slot 0 is the active one
            np.testing.assert_allclose(
                np.asarray(v_logits)[b, i], np.asarray(logits_i)[b],
                atol=2e-4, rtol=2e-4,
            )


class TestDistributionPreservation:
    def _marginal(self, key, logits, draft, sp, n, q_logprobs=None):
        """Empirical distribution of the FIRST emitted token over n runs.

        With a general proposal, the theorem requires the draft be DRAWN
        from it — so each run samples its own draft from ``q_logprobs``;
        one-hot proposals keep the fixed draft (the delta's only sample).
        """
        def one(k):
            d = draft
            if q_logprobs is not None:
                kd, k = jax.random.split(k)
                d = jax.vmap(
                    lambda kk, ql: jax.random.categorical(kk, ql, axis=-1),
                    in_axes=(None, 1), out_axes=1,
                )(kd, q_logprobs).astype(jnp.int32)
            _, tokens, _, _ = spec_rejection_sample(
                k, logits, d, sp, warp=False, q_logprobs=q_logprobs
            )
            return tokens[0, 0]

        toks = jax.vmap(one)(jax.random.split(key, n))
        V = logits.shape[-1]
        return np.bincount(np.asarray(toks), minlength=V) / n

    @pytest.mark.parametrize("general_q", [False, True])
    def test_first_token_marginal_chi_square(self, general_q):
        """The first emitted token (accepted draft OR residual) must be
        distributed exactly as the target — for one-hot proposals and for
        a general proposal distribution the drafts are sampled from."""
        V, K = 16, 2
        rng = np.random.default_rng(0)
        logits = jnp.asarray(
            rng.normal(size=(1, K + 1, V)), jnp.float32
        )
        draft = jnp.asarray([[3, 7]], jnp.int32)
        sp = SamplingParams.filled(1)
        q_lp = None
        if general_q:
            q = rng.normal(size=(1, K, V)).astype(np.float32)
            q_lp = jnp.asarray(jax.nn.log_softmax(jnp.asarray(q), axis=-1))
        n = 20000
        emp = self._marginal(
            jax.random.key(1), logits, draft, sp, n, q_logprobs=q_lp
        )
        want = np.asarray(jax.nn.softmax(logits[0, 0]))
        chi2 = (n * (emp - want) ** 2 / np.maximum(want, 1e-9)).sum()
        # df = 15; p=0.001 critical value ~37.7 — generous margin
        assert chi2 < 45.0, (chi2, emp, want)

    def test_accepted_prefix_then_residual_layout(self):
        """accept_len semantics: positions < accept_len are draft tokens,
        position accept_len the residual; greedy accepts iff argmax."""
        V = 8
        logits = np.full((1, 3, V), -10.0, np.float32)
        logits[0, 0, 2] = 10.0   # argmax 2
        logits[0, 1, 5] = 10.0   # argmax 5
        logits[0, 2, 1] = 10.0   # bonus argmax 1
        sp = SamplingParams.filled(1, temperature=0.0)
        # full acceptance: drafts match argmax chain -> bonus emitted
        a, toks, _, _ = spec_rejection_sample(
            jax.random.key(0), jnp.asarray(logits),
            jnp.asarray([[2, 5]], jnp.int32), sp, warp=False,
        )
        assert int(a[0]) == 2
        assert toks[0, :3].tolist() == [2, 5, 1]
        # first draft wrong -> rejected immediately, residual = argmax
        a, toks, _, _ = spec_rejection_sample(
            jax.random.key(0), jnp.asarray(logits),
            jnp.asarray([[4, 5]], jnp.int32), sp, warp=False,
        )
        assert int(a[0]) == 0
        assert int(toks[0, 0]) == 2

    def test_sampled_spec_engine_runs_and_varies(self, params):
        """Stochastic spec decode through the full engine: reproducible
        per-seed, diverse across slots (the vanilla sampling contract)."""
        outs = {}
        for run in range(2):
            eng = _engine(params, True, max_slots=4, spec_k=3, seed=7)
            for i in range(4):
                eng.submit(GenRequest(
                    rid=f"s{i}", input_ids=[5, 6, 7], max_new_tokens=8,
                    temperature=1.0, top_p=0.95,
                ))
            outs[run] = {
                o.rid: o.output_ids
                for o in eng.run_until_done(decode_steps=2)
            }
        assert outs[0] == outs[1]                       # seeded: reproducible
        assert len(set(map(tuple, outs[0].values()))) > 1  # slots differ


class TestComposition:
    def test_pause_mid_spec_chunk_harvests_valid_partial(self, params, rng):
        """pause() mid-spec-generation yields an 'interrupted' partial that
        is a PREFIX of the uninterrupted greedy chain, and resubmission
        completes it exactly (the partial-rollout protocol)."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, False)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids

        eng = _engine(params, True, spec_k=3)
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.step(decode_steps=1)
        parts = eng.pause()
        assert len(parts) == 1 and parts[0].finish_reason == "interrupted"
        got = parts[0].output_ids
        assert 0 < len(got) < 12
        assert got == ref[: len(got)]
        eng.resume()
        eng.submit(GenRequest(
            rid="a2", input_ids=prompt + got,
            max_new_tokens=12 - len(got), greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert got + outs[0].output_ids == ref

    def test_update_params_between_spec_chunks_bumps_version(
        self, params, monkeypatch
    ):
        # through the literal env knob (AREAL_SPEC_DECODE=1), not the
        # ctor override — the path a deployed fleet takes
        monkeypatch.setenv("AREAL_SPEC_DECODE", "1")
        monkeypatch.setenv("AREAL_SPEC_K", "2")
        eng = _engine(params, None, max_slots=1)
        assert eng.spec is True and eng.spec_k == 2
        eng.submit(GenRequest(
            rid="a", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=2)
        assert outs[0].version == 0
        new_params = tfm.init_params(CFG, jax.random.key(9))
        eng.update_params(new_params, version=3)
        assert len(eng.prefix) == 0
        eng.submit(GenRequest(
            rid="b", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=2)
        assert outs[0].version == 3

    def test_spec_pipelined_matches_unpipelined(self, params, rng):
        prompts = _prompts(rng, sizes=(5, 9, 3, 7))
        outs = []
        for pipelined in (False, True):
            eng = _engine(
                params, True, max_slots=4, spec_k=3,
                pipeline_chunks=pipelined,
            )
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({
                o.rid: o for o in eng.run_until_done(decode_steps=2)
            })
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason

    def test_mixed_spec_vanilla_traffic_bounded_compiles(self, params, rng):
        """Flipping spec on/off between chunks (one engine, one state
        pytree) must not grow jit specializations past the warm set —
        the n_compiles discipline extended to mixed traffic."""
        eng = _engine(params, False, max_slots=4, max_seqlen=256,
                      page_size=16, spec_k=3)
        def burst(tag, plens):
            for i, plen in enumerate(plens):
                eng.submit(GenRequest(
                    rid=f"{tag}{i}",
                    input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                    max_new_tokens=6, greedy=True,
                ))
            eng.run_until_done(decode_steps=3)

        burst("v", [3, 9, 17, 33])       # warm vanilla
        eng.spec = True
        burst("s", [3, 9, 17, 33])       # warm spec
        eng.spec = False
        burst("v2", [5, 21])
        eng.spec = True
        warmed = eng.n_compiles()
        # fresh prompt lengths + more toggles: no new specializations
        eng.spec = False
        burst("v3", [11, 29, 60])
        eng.spec = True
        burst("s2", [7, 45, 80])
        assert eng.n_compiles() == warmed

    def test_tp2_spec_greedy_matches_single_device(self, params, rng):
        """Spec decode on a 2-way `model` mesh (sampling replicated after
        the logits all-gather) must match the unsharded engine token for
        token."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        prompts = _prompts(rng)
        eng1 = _engine(params, True, max_slots=4, spec_k=3)
        eng2 = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=128,
            spec_decode=True, spec_k=3, mesh=mesh,
        )
        for eng in (eng1, eng2):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=8, greedy=True,
                ))
        o1 = {o.rid: o for o in eng1.run_until_done(decode_steps=2)}
        o2 = {o.rid: o for o in eng2.run_until_done(decode_steps=2)}
        assert set(o1) == set(o2)
        for rid in o1:
            assert o1[rid].output_ids == o2[rid].output_ids, rid

    def test_spec_telemetry_counters(self, params, rng):
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_DRAFT_TOKENS)
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_ACCEPTED_TOKENS)
        metrics_mod.counters.clear(metrics_mod.GEN_SPEC_ACCEPT_LEN)
        eng = _engine(params, True, spec_k=3)
        # a repetitive prompt: the n-gram drafter should accept something
        prompt = [7, 8, 9] * 6
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.run_until_done(decode_steps=2)
        drafted = eng.stats["spec_draft_tokens"]
        accepted = eng.stats["spec_accepted_tokens"]
        assert drafted > 0
        assert 0 <= accepted <= drafted
        assert metrics_mod.counters.get(
            metrics_mod.GEN_SPEC_DRAFT_TOKENS
        ) == drafted
        h = metrics_mod.counters.histogram(metrics_mod.GEN_SPEC_ACCEPT_LEN)
        assert h is not None and h.count > 0


def test_nondeterministic_drafter_rejected_at_construction(params):
    """The engine only wires one-hot drafters today: a sampled drafter
    without threaded q_logprobs would silently bias generation (the
    distribution-preservation guarantee) — it must fail loudly."""
    from areal_tpu.gen.drafter import Drafter

    class SampledDrafter(Drafter):
        # plain subclass, not the frozen dataclass: its generated __init__
        # would pin the instance attribute back to the dataclass default
        deterministic = False

        def propose(self, ctx_tokens, lens, fallback, k):  # pragma: no cover
            raise AssertionError("never reached")

    with pytest.raises(NotImplementedError, match="q_logprobs"):
        GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64,
            spec_decode=True, drafter=SampledDrafter(),
        )


class TestNGramDrafter:
    def test_bigram_match_proposes_continuation(self):
        d = NGramDrafter()
        # context ... 1 2 3 4 1 2 -> bigram (1, 2) matched at 0 -> 3 4 ...
        ctx = jnp.asarray([[1, 2, 3, 4, 1, 2, 0, 0]], jnp.int32)
        lens = jnp.asarray([5], jnp.int32)   # ctx[5] = 2 is the last token
        out = d.propose(ctx, lens, jnp.asarray([99], jnp.int32), 3)
        assert out[0].tolist() == [3, 4, 1]

    def test_unigram_fallback_then_hint(self):
        d = NGramDrafter()
        # no bigram (5, 2) occurs earlier; unigram 2 at index 1 -> 3, 4...
        ctx = jnp.asarray([[1, 2, 3, 4, 5, 2, 0, 0]], jnp.int32)
        lens = jnp.asarray([5], jnp.int32)
        out = d.propose(ctx, lens, jnp.asarray([99], jnp.int32), 3)
        assert out[0].tolist() == [3, 4, 5]
        # nothing matches at all -> the greedy-from-last-logits hint
        ctx = jnp.asarray([[1, 2, 3, 4, 5, 6, 0, 0]], jnp.int32)
        out = d.propose(ctx, jnp.asarray([5], jnp.int32),
                        jnp.asarray([99], jnp.int32), 2)
        assert out[0].tolist() == [99, 99]

    def test_proposals_never_cross_valid_region(self):
        d = NGramDrafter()
        # the current pair sits at (3, 4); the only EARLIER bigram (1, 2)
        # is at (1, 2), so the continuation starts at index 3 and may read
        # up to index lens (the pending last token) — past that, proposals
        # fill with the hint, never with stale buffer garbage (the 7s)
        ctx = jnp.asarray([[0, 1, 2, 1, 2, 7, 7, 7]], jnp.int32)
        lens = jnp.asarray([4], jnp.int32)
        out = d.propose(ctx, lens, jnp.asarray([50], jnp.int32), 4)
        assert out[0].tolist() == [1, 2, 50, 50]


class TestServingSurface:
    async def test_spec_toggle_endpoint_and_metrics(self, params):
        """POST /spec_decode flips the engine between chunks; /metrics_json
        reports the spec config + realized accept rate."""
        from aiohttp.test_utils import TestClient, TestServer

        from areal_tpu.gen.server import GenerationHTTPServer

        eng = _engine(params, True, spec_k=2)
        srv = GenerationHTTPServer(eng, decode_steps=2)
        client = TestClient(TestServer(srv.app))
        await client.start_server()
        try:
            r = await client.post("/spec_decode", json={"enabled": False})
            d = await r.json()
            assert d["success"] and d["spec_decode"] is False
            assert d["spec_k"] == 2 and eng.spec is False
            r = await client.post("/spec_decode", json={"enabled": True})
            assert (await r.json())["spec_decode"] is True
            r = await client.post("/spec_decode", json={})
            assert r.status == 400
            r = await client.get("/metrics_json")
            m = await r.json()
            assert m["spec_decode"] is True and m["spec_k"] == 2
            assert "spec_accept_rate" in m
            assert "engine_spec_draft_tokens" in m
        finally:
            await client.close()


@pytest.mark.slow
def test_bench_gen_spec_stanza_end_to_end():
    """The ``gen_spec`` bench A/B runs end-to-end on the CPU harness and
    reports accept rate + accepted-tokens/s. The headline ``vs_baseline >
    1.0`` acceptance bar is judged on chip (HBM-roofline economics); on
    CPU the ratio is dominated by per-step dispatch, so this only pins
    structure and a loose floor against regressions."""
    import bench as bench_mod

    out = bench_mod._bench_gen_spec(
        819e9, 197e12, cfg=CFG, B=8, PLEN=64, D_STEPS=8, N_CHUNKS=3,
        motif_len=8,
    )
    assert set(out) >= {
        "vanilla_tokens_per_s", "accepted_tokens_per_s", "accept_rate",
        "vs_baseline", "spec_k",
    }
    assert out["accepted_tokens_per_s"] > 0
    assert 0.0 < out["accept_rate"] <= 1.0
    assert out["vs_baseline"] > 0.8


# --------------------------------------------------------------------- #
# Exhaustive spec-vs-vanilla parity sweep. Tier-1 keeps ONE representative
# configuration (matching the round-6 kernel-test policy); the rest run
# unmarked locally and on chip.
# --------------------------------------------------------------------- #

SWEEP = [
    pytest.param(1, False, 4),
    pytest.param(2, True, 3, marks=pytest.mark.slow),
    pytest.param(4, False, 1, marks=pytest.mark.slow),
    pytest.param(4, True, 6, marks=pytest.mark.slow),
    pytest.param(8, False, 2, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("spec_k,pipelined,decode_steps", SWEEP)
def test_spec_parity_sweep(params, rng, spec_k, pipelined, decode_steps):
    prompts = _prompts(rng, sizes=(4, 11, 6))
    vanilla = _engine(params, False, max_slots=4)
    spec = _engine(
        params, True, max_slots=4, spec_k=spec_k, pipeline_chunks=pipelined,
    )
    for eng in (vanilla, spec):
        for i, p in enumerate(prompts):
            eng.submit(GenRequest(
                rid=f"r{i}", input_ids=p, max_new_tokens=9, greedy=True,
            ))
    o1 = {o.rid: o for o in vanilla.run_until_done(decode_steps=4)}
    o2 = {o.rid: o for o in spec.run_until_done(decode_steps=decode_steps)}
    assert set(o1) == set(o2)
    for rid in o1:
        assert o1[rid].output_ids == o2[rid].output_ids, rid
