"""Env-gated profiler tracing (≈ the reference's REAL_DUMP_TRACE gating)."""

import glob
import os

import jax.numpy as jnp

from areal_tpu.base import constants, tracing


def test_disabled_is_free(monkeypatch):
    monkeypatch.delenv(constants.TRACE_ENV, raising=False)
    assert not tracing.trace_enabled()
    with tracing.maybe_trace("noop"):
        pass
    with tracing.annotate("noop"):
        pass


def test_trace_dumps_profile(monkeypatch, tmp_path):
    monkeypatch.setenv(constants.TRACE_ENV, "1")
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    assert tracing.trace_enabled()
    assert tracing.trace_step() == 3
    monkeypatch.setenv("AREAL_TRACE_STEP", "7")
    assert tracing.trace_step() == 7
    with tracing.maybe_trace("unit"):
        with tracing.annotate("mfc:actor_train"):
            jnp.ones((8, 8)).sum().block_until_ready()
    dumped = glob.glob(str(tmp_path / "traces" / "unit" / "**" / "*"),
                       recursive=True)
    assert any(os.path.isfile(f) for f in dumped), dumped
