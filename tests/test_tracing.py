"""Tracing plane: env-gated profiler tracing (≈ the reference's
REAL_DUMP_TRACE gating) + the distributed span plane
(docs/observability.md "Distributed tracing") — trace identity,
wire-context propagation, exception-exit spans, the bounded completed-
span ring, and the fileroot flush that feeds tracejoin."""

import glob
import json
import os
import threading

import jax.numpy as jnp
import pytest

from areal_tpu.base import constants, tracing
from areal_tpu.base import metrics as metrics_mod


def test_disabled_is_free(monkeypatch):
    monkeypatch.delenv(constants.TRACE_ENV, raising=False)
    assert not tracing.trace_enabled()
    with tracing.maybe_trace("noop"):
        pass
    with tracing.annotate("noop"):
        pass


def test_trace_dumps_profile(monkeypatch, tmp_path):
    monkeypatch.setenv(constants.TRACE_ENV, "1")
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    assert tracing.trace_enabled()
    assert tracing.trace_step() == 3
    monkeypatch.setenv("AREAL_TRACE_STEP", "7")
    assert tracing.trace_step() == 7
    with tracing.maybe_trace("unit"):
        with tracing.annotate("mfc:actor_train"):
            jnp.ones((8, 8)).sum().block_until_ready()
    dumped = glob.glob(str(tmp_path / "traces" / "unit" / "**" / "*"),
                       recursive=True)
    assert any(os.path.isfile(f) for f in dumped), dumped


# --------------------------------------------------------------------- #
# Span plane: identity + wire context
# --------------------------------------------------------------------- #


@pytest.fixture(autouse=True)
def _clean_ring():
    tracing.drain()
    yield
    tracing.drain()


class TestTraceIdentity:
    def test_id_formats(self):
        tid, sid = tracing.new_trace_id(), tracing.new_span_id()
        assert len(tid) == 32 and int(tid, 16) >= 0
        assert len(sid) == 16 and int(sid, 16) >= 0

    def test_traceparent_roundtrip(self):
        with tracing.activate() as tid:
            tp = tracing.traceparent()
            assert tp == f"00-{tid}-{'0' * 16}-01"
            assert tracing.parse_traceparent(tp) == (tid, None)
            with tracing.span("t/x"):
                tid2, psid = tracing.parse_traceparent(tracing.traceparent())
                assert tid2 == tid and psid is not None
        assert tracing.traceparent() is None

    @pytest.mark.parametrize("bad", [
        None, 7, "", "nonsense", "00-zz-ff-01",
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",   # short trace id
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",   # short span id
        "00-" + "a" * 32 + "-" + "b" * 16,           # missing flags
    ])
    def test_parse_tolerates_malformed(self, bad):
        assert tracing.parse_traceparent(bad) is None

    def test_wire_context_carries_qid(self):
        assert tracing.wire_context() is None  # no active context
        with tracing.activate(qid="q7") as tid:
            w = tracing.wire_context()
            assert w["qid"] == "q7"
            assert tracing.parse_traceparent(w["traceparent"])[0] == tid
            assert tracing.current_qid() == "q7"
        assert tracing.current_qid() is None

    def test_activate_continues_wire_context(self):
        with tracing.activate(qid="q1") as tid:
            with tracing.span("t/client"):
                wire = tracing.wire_context()
        # "server side": same trace id, parent = the client span, qid rides
        with tracing.activate(wire) as tid2:
            assert tid2 == tid
            assert tracing.current_qid() == "q1"
            with tracing.span("t/server"):
                pass
        spans = {s["name"]: s for s in tracing.drain()}
        client, server = spans["t/client"], spans["t/server"]
        assert server["trace_id"] == client["trace_id"] == tid
        assert server["parent_id"] == client["span_id"]
        assert server["attrs"]["qid"] == "q1"

    def test_activate_degrades_to_fresh_root(self):
        with tracing.activate({"traceparent": "garbage"}) as tid:
            assert len(tid) == 32  # malformed wire → new trace, no crash


class TestSpanRecords:
    def test_span_nesting_and_attrs(self):
        with tracing.activate() as tid:
            with tracing.span("t/outer", rid="r1") as attrs:
                attrs["late"] = 5
                with tracing.span("t/inner"):
                    pass
        recs = {s["name"]: s for s in tracing.drain()}
        outer, inner = recs["t/outer"], recs["t/inner"]
        assert outer["trace_id"] == inner["trace_id"] == tid
        assert inner["parent_id"] == outer["span_id"]
        assert outer["attrs"] == {"rid": "r1", "late": 5}
        assert outer["dur_s"] >= 0 and not outer["error"]

    def test_exception_exit_recorded(self):
        """The satellite regression: a span whose body raises must land in
        the ring stamped error=True with the exception type — not vanish."""
        before = metrics_mod.counters.get(metrics_mod.TRACE_SPAN_ERRORS)
        with pytest.raises(ValueError):
            with tracing.span("t/boom", rid="r9"):
                raise ValueError("nope")
        (rec,) = [s for s in tracing.drain() if s["name"] == "t/boom"]
        assert rec["error"] is True and rec["exc"] == "ValueError"
        assert rec["attrs"]["rid"] == "r9"
        assert (
            metrics_mod.counters.get(metrics_mod.TRACE_SPAN_ERRORS)
            == before + 1
        )
        # the live registry must not leak the aborted span
        assert all(s["name"] != "t/boom" for s in tracing.live_spans())

    def test_span_counters_always_accumulate(self, monkeypatch):
        monkeypatch.setenv(constants.TRACE_SPANS_ENV, "0")
        before_s = metrics_mod.counters.get("t/off_s")
        before_n = metrics_mod.counters.get("t/off_n")
        with tracing.span("t/off"):
            pass
        assert metrics_mod.counters.get("t/off_s") >= before_s
        assert metrics_mod.counters.get("t/off_n") == before_n + 1
        assert tracing.drain() == []  # disabled: nothing recorded
        assert tracing.wire_context(qid="q") is None
        with tracing.activate() as tid:
            assert tid is None

    def test_ring_bounded_with_drop_counter(self, monkeypatch):
        monkeypatch.setenv(constants.TRACE_RING_ENV, "16")
        before = metrics_mod.counters.get(metrics_mod.TRACE_DROPPED)
        for i in range(40):
            with tracing.span("t/ring"):
                pass
        spans = tracing.drain()
        assert len(spans) == 16
        assert metrics_mod.counters.get(metrics_mod.TRACE_DROPPED) \
            == before + 24

    def test_recent_spans_survive_drain(self):
        with tracing.span("t/recent"):
            pass
        tracing.drain()
        assert any(
            s["name"] == "t/recent" for s in tracing.recent_spans(50)
        )


class TestFlush:
    def test_flush_appends_worker_stamped_jsonl(self, tmp_path):
        with tracing.span("t/flush", rid="r1"):
            pass
        n = tracing.flush("gw/0", root=str(tmp_path))
        assert n == 1
        assert tracing.flush("gw/0", root=str(tmp_path)) == 0  # drained
        path = tmp_path / "gw_0.jsonl"
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        assert recs[0]["worker"] == "gw/0"
        assert recs[0]["name"] == "t/flush"
        assert recs[0]["pid"] == os.getpid()
        # append, not truncate: a second flush adds lines
        with tracing.span("t/flush2"):
            pass
        tracing.flush("gw/0", root=str(tmp_path))
        assert len(path.read_text().splitlines()) == 2

    def test_span_flusher_gated_off_by_default(self, monkeypatch):
        monkeypatch.delenv(constants.TRACE_FLUSH_ENV, raising=False)
        assert tracing.SpanFlusher.maybe_start("w") is None

    def test_span_flusher_final_drain(self, tmp_path, monkeypatch):
        monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
        monkeypatch.setenv(constants.TRACE_FLUSH_ENV, "30")
        t = tracing.SpanFlusher.maybe_start("bg/1")
        assert isinstance(t, threading.Thread)
        with tracing.span("t/bg"):
            pass
        t.stop()  # final drain flushes without waiting out the interval
        path = tmp_path / "trace_spans" / "bg_1.jsonl"
        assert path.exists()
        assert any(
            json.loads(l)["name"] == "t/bg"
            for l in path.read_text().splitlines()
        )
