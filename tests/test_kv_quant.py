"""Int8-quantized paged KV pool (docs/performance.md "KV quantization").

The load-bearing contracts:
- the quantizing post-scan scatter roundtrips values within int8 precision
  and lands scales at the same flat rows as their pages;
- all three paged-attention entry points (decode / extend / verify) with
  an int8 pool + scales match the same attention over the explicitly
  dequantized pool — dequant is FUSED, never a materialized pool copy;
- the Pallas decode kernel's in-register dequant matches the XLA path;
- engine-level: greedy decode over an int8 pool is token-identical to the
  raw-dtype pool for (nearly) every sequence of the parity corpus, the
  verify path's logit error is bounded, prefix sharing reuses quantized
  pages AND their scales, TP serving and pause/resume compose, and int8
  mode buys itemsize-ratio x pages (2x under bf16 serving) at the same
  configured pool HBM.

Exhaustive dtype x path sweeps ride the ``slow`` marker (run unmarked
locally + compiled on chip); tier-1 keeps one representative per feature,
per the round-6 budget policy.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.ops import paged_attention as xla_paged
from areal_tpu.ops.pallas import compat

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


def _quantize_pool(pool_f: np.ndarray):
    """Reference quantization: symmetric per-(layer, page, K|V, head,
    token-slot) over head_dim — exactly what the scatter writes."""
    amax = np.abs(pool_f).max(axis=-1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(pool_f / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale


def _rand_pool(rng, L=3, P=20, Hkv=2, page=8, D=16):
    return rng.normal(size=(L, P, 2, Hkv, page, D)).astype(np.float32)


class TestQuantScatter:
    def test_scatter_roundtrip_and_scale_rows(self, rng):
        """The int8 scatter must write q = round(x/scale) pages AND their
        scales through the same flat rows; dequant recovers the inputs to
        int8 precision; invalid positions and other slots stay zero."""
        L, P, Hkv, page, D, B, M = 2, 6, 2, 8, 16, 3, 2
        cache = tfm.PagedKVCache.empty(
            dataclasses.replace(CFG, n_layers=L, n_kv_heads=Hkv, head_dim=D),
            P, page, kv_dtype="int8",
        )
        ks = rng.normal(size=(L, B, 1, Hkv, D)).astype(np.float32)
        vs = rng.normal(size=(L, B, 1, Hkv, D)).astype(np.float32)
        table = rng.permutation(P)[: B * M].reshape(B, M).astype(np.int32)
        positions = np.asarray([[0], [7], [9]], np.int32)
        valid = np.asarray([[True], [True], [False]])
        out = tfm._scatter_chunk_kv(
            cache, jnp.asarray(ks), jnp.asarray(vs), jnp.asarray(table),
            jnp.asarray(positions), jnp.asarray(valid),
        )
        pages = np.asarray(out.pages)
        scales = np.asarray(out.scales)
        for b in range(B):
            p_, o = table[b, positions[b, 0] // page], positions[b, 0] % page
            for l in range(L):
                for kv, src in ((0, ks), (1, vs)):
                    got = (
                        pages[l, p_, kv, :, o, :].astype(np.float32)
                        * scales[l, p_, kv, :, o, None]
                    )
                    if valid[b, 0]:
                        np.testing.assert_allclose(
                            got, src[l, b, 0], atol=2e-2, rtol=1.5 / 127,
                        )
                    else:
                        np.testing.assert_array_equal(got, 0.0)

    def test_unquantized_scatter_untouched(self, rng):
        """scales=None keeps the raw-dtype scatter byte-for-byte (pinned by
        test_paged_engine.test_pool_scatter_matches_reference; this guards
        the branch itself)."""
        cache = tfm.PagedKVCache.empty(CFG, 4, 8)
        assert cache.scales is None and not cache.quantized
        out = tfm._scatter_chunk_kv(
            cache,
            jnp.zeros((CFG.n_layers, 1, 1, CFG.n_kv_heads, CFG.head_dim)),
            jnp.zeros((CFG.n_layers, 1, 1, CFG.n_kv_heads, CFG.head_dim)),
            jnp.zeros((1, 2), jnp.int32), jnp.zeros((1, 1), jnp.int32),
            jnp.ones((1, 1), bool),
        )
        assert out.scales is None


def _attend_all_paths(pool, scales, q3, k3, v3, table, lens, n_new,
                      soft_cap=None, window=None):
    """(decode, extend, verify) outputs for one pool; q3/k3/v3 are the
    [B, C, H(kv), D] chunk operands, decode uses position 0."""
    kw = dict(soft_cap=soft_cap, sliding_window=window)
    dec = xla_paged.paged_decode_attention(
        q3[:, 0], k3[:, 0], v3[:, 0], pool, jnp.int32(1), table, lens,
        use_pallas=False, scales=scales, **kw,
    )
    ext = xla_paged.paged_extend_attention(
        q3, k3, v3, pool, jnp.int32(1), table, lens, n_new,
        scales=scales, **kw,
    )
    ver = xla_paged.paged_verify_attention(
        q3, k3, v3, pool, jnp.int32(1), table, lens, n_new,
        scales=scales, **kw,
    )
    return dec, ext, ver


class TestXLAPathParity:
    """Int8 pool + fused dequant == the same attention over an explicitly
    dequantized pool, for every entry point. Tier-1 runs the plain
    variant; the soft-cap/sliding-window sweep is ``slow``."""

    @pytest.mark.parametrize(
        "soft_cap,window",
        [(None, None),
         pytest.param(5.0, None, marks=pytest.mark.slow),
         pytest.param(None, 6, marks=pytest.mark.slow)],
    )
    def test_all_paths_match_dequantized_pool(self, rng, soft_cap, window):
        B, C, Hq, Hkv, D, page, M, P, L = 3, 3, 4, 2, 16, 8, 4, 20, 3
        pool_f = _rand_pool(rng, L, P, Hkv, page, D)
        pool_q, scale = _quantize_pool(pool_f)
        deq = pool_q.astype(np.float32) * scale[..., None]
        q3 = rng.normal(size=(B, C, Hq, D)).astype(np.float32)
        k3 = rng.normal(size=(B, C, Hkv, D)).astype(np.float32)
        v3 = rng.normal(size=(B, C, Hkv, D)).astype(np.float32)
        table = rng.permutation(P)[: B * M].reshape(B, M).astype(np.int32)
        lens = np.asarray([1, 17, 0], np.int32)
        n_new = np.asarray([C, C, 0], np.int32)
        got = _attend_all_paths(
            jnp.asarray(pool_q), jnp.asarray(scale), q3, k3, v3,
            table, lens, n_new, soft_cap, window,
        )
        want = _attend_all_paths(
            jnp.asarray(deq), None, q3, k3, v3, table, lens, n_new,
            soft_cap, window,
        )
        for name, g, w in zip(("decode", "extend", "verify"), got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=2e-5, err_msg=name
            )


@pytest.mark.skipif(
    not (compat.compiler_params_available()
         and compat.memory_space_available()),
    reason="installed jax lacks pltpu CompilerParams or MemorySpace",
)
class TestPallasInt8Decode:
    """The kernel's in-register dequant (int8 page DMA + scale-stripe DMA,
    scales folded into the score/probability dots) vs the XLA int8 path.
    Tier-1 keeps the multi-step (2, 2) pipeline grid; the full grid x
    mask-feature sweep is ``slow``."""

    @pytest.mark.parametrize(
        "kp_sb,soft_cap,window",
        [((2, 2), None, None),
         pytest.param((8, 8), None, None, marks=pytest.mark.slow),
         pytest.param((1, 2), None, None, marks=pytest.mark.slow),
         pytest.param((2, 2), 5.0, None, marks=pytest.mark.slow),
         pytest.param((2, 2), None, 6, marks=pytest.mark.slow)],
    )
    def test_parity_vs_xla_int8(self, rng, kp_sb, soft_cap, window):
        from areal_tpu.ops.pallas import paged_attention as pl_paged

        B, Hq, Hkv, D, page, M, P, L = 4, 4, 2, 16, 8, 4, 20, 3
        pool_f = _rand_pool(rng, L, P, Hkv, page, D)
        pool_q, scale = _quantize_pool(pool_f)
        q = rng.normal(size=(B, Hq, D)).astype(np.float32)
        k_self = rng.normal(size=(B, Hkv, D)).astype(np.float32)
        v_self = rng.normal(size=(B, Hkv, D)).astype(np.float32)
        table = rng.permutation(P)[: B * M].reshape(B, M).astype(np.int32)
        lens = np.asarray([1, 9, 32, 0], np.int32)
        got = pl_paged.decode(
            q, k_self, v_self, pool_q, jnp.int32(1), table, lens,
            soft_cap=soft_cap, sliding_window=window,
            pages_per_step=kp_sb[0], slots_per_step=kp_sb[1],
            scales=jnp.asarray(scale),
        )
        want = xla_paged.paged_decode_attention(
            q, k_self, v_self, pool_q, jnp.int32(1), table, lens,
            soft_cap=soft_cap, sliding_window=window, use_pallas=False,
            scales=jnp.asarray(scale),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def _run_greedy(params, prompts, max_new, kv_dtype, **kw):
    kw.setdefault("max_slots", max(4, len(prompts)))
    eng = GenerationEngine(
        CFG, params, max_seqlen=128, page_size=8, seed=0,
        kv_dtype=kv_dtype, **kw,
    )
    for i, p in enumerate(prompts):
        eng.submit(GenRequest(
            rid=f"r{i}", input_ids=p, max_new_tokens=max_new, greedy=True,
        ))
    return {o.rid: o for o in eng.run_until_done(decode_steps=4)}


class TestEngineParity:
    def test_greedy_corpus_token_match(self, params, rng):
        """CPU parity corpus: >= 95% of greedy sequences token-identical
        between raw and int8 pools (the acceptance bar); the engine serves
        both from ONE code path, only the pool dtype differs."""
        prompts = [
            [int(x) for x in rng.integers(1, 128, n)]
            for n in (3, 5, 7, 9, 11, 13, 17, 19, 21, 6, 10, 15)
        ]
        raw = _run_greedy(params, prompts, 12, None)
        q = _run_greedy(params, prompts, 12, "int8")
        assert set(raw) == set(q)
        same = sum(
            raw[r].output_ids == q[r].output_ids
            and raw[r].finish_reason == q[r].finish_reason
            for r in raw
        )
        assert same >= 0.95 * len(prompts), f"{same}/{len(prompts)} matched"

    def test_verify_path_logit_error_bounded(self, params, rng):
        """Per-position max-abs logit error of the verify forward over an
        int8 pool vs the raw pool, teacher-forced on the same tokens —
        the quantization-noise bound spec decode and PPO logprobs see."""
        prompt = [int(x) for x in rng.integers(1, 128, size=9)]
        engines = {}
        for kd in (None, "int8"):
            eng = GenerationEngine(
                CFG, params, max_slots=2, max_seqlen=64, page_size=8,
                seed=0, kv_dtype=kd,
            )
            eng.submit(GenRequest(
                rid="a", input_ids=prompt, max_new_tokens=8, greedy=True,
            ))
            eng.step(decode_steps=3)  # resident context incl. decoded KV
            engines[kd] = eng
        chunk = jnp.asarray(
            [[5, 9, 2, 14]] * 2, jnp.int32
        )
        logits = {}
        for kd, eng in engines.items():
            state = eng.state
            W = eng._table_width(int(np.asarray(state.lens).max()) + 8)
            lg, _ = tfm.verify_step_paged(
                eng.params, CFG, state.cache, chunk,
                jnp.asarray(eng._table_host[:, :W]), state.lens,
                jnp.where(state.active, 4, 0).astype(jnp.int32),
                state.active[:, None] & jnp.ones((2, 4), bool),
            )
            logits[kd] = np.asarray(lg)
        err = np.abs(logits["int8"] - logits[None]).max()
        assert err < 0.1, f"max verify logit delta {err}"

    def test_spec_decode_over_int8_pool(self, params, rng):
        """Spec decode composes: greedy spec over an int8 pool is
        token-identical to vanilla decode over the SAME int8 pool."""
        prompts = [[int(x) for x in rng.integers(1, 128, n)] for n in (5, 9)]
        outs = {}
        for spec in (False, True):
            outs[spec] = {
                r: o.output_ids
                for r, o in _run_greedy(
                    params, prompts, 10, "int8",
                    spec_decode=spec, spec_k=3,
                ).items()
            }
        assert outs[True] == outs[False]

    def test_tp2_int8_matches_single_device(self, params, rng):
        """Int8 pool + scales sharded over a 2-way ``model`` mesh (both on
        the kv-head axis) must reproduce the single-device outputs."""
        from jax.sharding import Mesh

        prompts = [[int(x) for x in rng.integers(1, 128, n)] for n in (5, 9)]
        ref = None
        for mesh in (None, Mesh(np.array(jax.devices()[:2]), ("model",))):
            outs = {
                r: o.output_ids
                for r, o in _run_greedy(
                    params, prompts, 8, "int8", max_slots=2, mesh=mesh,
                ).items()
            }
            if ref is None:
                ref = outs
            else:
                assert outs == ref

    def test_pause_resume_roundtrip(self, params, rng):
        """Interrupt mid-generation over an int8 pool: the partial is a
        valid prefix of the uninterrupted run, resumed work completes, and
        every page (and scale slot with it) is accounted for."""
        prompt = [int(x) for x in rng.integers(1, 128, size=7)]
        full = _run_greedy(params, [prompt], 12, "int8")["r0"].output_ids
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=8, seed=0,
            kv_dtype="int8",
        )
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.step(decode_steps=4)
        outs = eng.pause()
        assert outs[0].finish_reason == "interrupted"
        assert outs[0].output_ids == full[: len(outs[0].output_ids)]
        eng.resume()
        eng.submit(GenRequest(
            rid="b", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert outs[0].output_ids == full
        eng.prefix.clear()
        assert eng.pool.n_free == eng.n_pages


class TestPrefixSharingQuantized:
    def test_group_shares_quantized_pages_and_scales(self, params, rng):
        """A GRPO group over one prompt on an int8 engine: one prefill
        serves everyone (prefix_hits), and the borrowers' outputs equal
        the owner's AND a no-sharing cold engine's — the shared pages'
        SCALES travel with them (wrong scales would corrupt exactly the
        borrowers)."""
        prompt = [int(x) for x in rng.integers(1, 128, 21)]  # 2 full pages
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=64, page_size=8, seed=0,
            kv_dtype="int8",
        )
        for i in range(4):
            eng.submit(GenRequest(
                rid=f"g{i}", input_ids=prompt, max_new_tokens=6, greedy=True,
            ))
        outs = eng.run_until_done(decode_steps=3)
        assert eng.stats["prefix_hits"] == 3
        assert len({tuple(o.output_ids) for o in outs}) == 1
        cold = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=8, seed=0,
            kv_dtype="int8", enable_prefix_cache=False,
        )
        cold.submit(GenRequest(
            rid="c", input_ids=prompt, max_new_tokens=6, greedy=True,
        ))
        ref = cold.run_until_done(decode_steps=3)[0]
        assert outs[0].output_ids == ref.output_ids


class TestCapacity:
    def test_default_pool_scales_by_itemsize_ratio(self, params):
        """int8 mode resizes the DEFAULT pool to the serving-dtype HBM
        budget: itemsize-ratio x pages (2x under bf16 serving, 4x under
        this float32 test config) — same page-array bytes, more pages."""
        raw = GenerationEngine(CFG, params, max_slots=2, max_seqlen=64,
                               page_size=8)
        q = GenerationEngine(CFG, params, max_slots=2, max_seqlen=64,
                             page_size=8, kv_dtype="int8")
        ratio = jnp.dtype(CFG.dtype).itemsize
        assert q.n_pages == raw.n_pages * ratio
        raw_page_bytes = raw.n_pages * jnp.dtype(CFG.dtype).itemsize
        assert q.n_pages * 1 == raw_page_bytes  # page arrays: equal bytes
        # reported footprint includes the scales (4/head_dim overhead)
        assert q.kv_pool_bytes() > raw.kv_pool_bytes()

    def test_serves_ratio_x_slots_at_equal_pool_hbm(self, params, rng):
        """At the same configured page-array HBM, the int8 engine admits
        itemsize-ratio x the slot count concurrently (the acceptance bar:
        2x under bf16 serving)."""
        B = 2
        raw = GenerationEngine(CFG, params, max_slots=B, max_seqlen=64,
                               page_size=8)
        ratio = jnp.dtype(CFG.dtype).itemsize
        q = GenerationEngine(
            CFG, params, max_slots=B * ratio, max_seqlen=64, page_size=8,
            kv_dtype="int8", n_pages=raw.n_pages * ratio,
            enable_prefix_cache=False,
        )
        # page arrays occupy identical HBM
        assert q.n_pages * 1 == raw.n_pages * jnp.dtype(CFG.dtype).itemsize
        for i in range(B * ratio):
            q.submit(GenRequest(
                rid=f"r{i}",
                input_ids=[int(x) for x in rng.integers(1, 128, 9)],
                max_new_tokens=48, greedy=True,
            ))
        q.step(decode_steps=1)
        assert q.n_running() == B * ratio  # everyone resident at once
        outs = q.run_until_done(decode_steps=8)
        assert len(outs) == B * ratio

    def test_kvq_telemetry_counters(self, params, rng):
        """gen/kvq_pages_quantized counts int8 pages entering service and
        the occupancy histogram records per-chunk pool fractions."""
        before = metrics_mod.counters.get(metrics_mod.GEN_KVQ_PAGES_QUANTIZED)
        h0 = metrics_mod.counters.histogram(metrics_mod.GEN_KV_POOL_OCCUPANCY)
        n0 = h0.count if h0 else 0
        _run_greedy(
            params, [[int(x) for x in rng.integers(1, 128, 9)]], 6, "int8",
        )
        assert metrics_mod.counters.get(
            metrics_mod.GEN_KVQ_PAGES_QUANTIZED
        ) > before
        h1 = metrics_mod.counters.histogram(metrics_mod.GEN_KV_POOL_OCCUPANCY)
        assert h1 is not None and h1.count > n0


class TestKnobResolution:
    def test_env_knob_enables_int8(self, params, monkeypatch):
        from areal_tpu.base import constants

        monkeypatch.setenv(constants.KV_DTYPE_ENV, "int8")
        eng = GenerationEngine(CFG, params, max_slots=1, max_seqlen=32,
                               page_size=8)
        assert eng.kv_quantized and eng.kv_dtype == "int8"

    def test_explicit_arg_overrides_env(self, params, monkeypatch):
        from areal_tpu.base import constants

        monkeypatch.setenv(constants.KV_DTYPE_ENV, "int8")
        eng = GenerationEngine(CFG, params, max_slots=1, max_seqlen=32,
                               page_size=8, kv_dtype="bf16")
        assert not eng.kv_quantized

    def test_unknown_env_value_falls_back(self, params, monkeypatch):
        from areal_tpu.base import constants

        monkeypatch.setenv(constants.KV_DTYPE_ENV, "fp3")
        eng = GenerationEngine(CFG, params, max_slots=1, max_seqlen=32,
                               page_size=8)
        assert not eng.kv_quantized

    def test_unknown_engine_arg_raises(self, params):
        with pytest.raises(ValueError, match="kv_dtype"):
            GenerationEngine(CFG, params, max_slots=1, max_seqlen=32,
                             page_size=8, kv_dtype="fp8")

    def test_metrics_json_gauges(self, params):
        """The serving gauges the fleet watches: kv_dtype / kv_pool_bytes /
        n_pages_free / occupancy, straight off the engine."""
        from areal_tpu.gen.server import GenerationHTTPServer

        eng = GenerationEngine(CFG, params, max_slots=1, max_seqlen=32,
                               page_size=8, kv_dtype="int8")
        srv = GenerationHTTPServer(eng)
        m = srv._metrics_dict()
        assert m["kv_dtype"] == "int8"
        assert m["kv_pool_bytes"] == eng.kv_pool_bytes() > 0
        assert m["n_pages_free"] == eng.pool.n_free
        assert 0.0 <= m["kv_pool_occupancy"] <= 1.0


@pytest.mark.slow
class TestBenchStanza:
    def test_gen_kvq_smoke(self):
        """The ``gen_kvq`` bench stanza end-to-end on CPU at a tiny shape:
        all arms run and report tokens/s, vs_baseline, and a finite max
        logit delta (the acceptance bar for the CPU leg; chip numbers ride
        the ROADMAP item 3 capture)."""
        import bench

        out = bench._bench_gen_kvq(
            819e9, 197e12, cfg=CFG, B=2, PLEN=32, D_STEPS=4, N_CHUNKS=2,
        )
        assert out["bf16_tokens_per_s"] > 0
        assert out["int8_tokens_per_s"] > 0
        assert out["int8_2x_slots_tokens_per_s"] > 0
        assert out["vs_baseline"] > 0
        assert np.isfinite(out["max_logit_delta"])
        assert out["slots_2x"] == 2 * out["slots"]
