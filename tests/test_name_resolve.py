"""≈ reference ``tests/distributed/test_name_resolve.py``: parametrized over
backends."""

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.base.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    NameResolveConfig,
    make_repository,
)


@pytest.fixture(params=["memory", "file"])
def repo(request, tmp_path):
    cfg = NameResolveConfig(type=request.param, root=str(tmp_path / "nr"))
    r = make_repository(cfg)
    yield r
    r.reset()


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y", "c")
    assert repo.get_subtree("root/x") == ["a", "b"]
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    assert sorted(repo.get_subtree("root")) == ["a", "b", "c"]
    repo.clear_subtree("root/x")
    assert repo.get_subtree("root/x") == []
    assert repo.get("root/y") == "c"


def test_wait(repo):
    import threading, time

    def _adder():
        time.sleep(0.2)
        repo.add("late/key", "zzz")

    t = threading.Thread(target=_adder)
    t.start()
    assert repo.wait("late/key", timeout=5) == "zzz"
    t.join()
    with pytest.raises(TimeoutError):
        repo.wait("never/key", timeout=0.2)


def test_add_subentry(repo):
    k1 = repo.add_subentry("sub", "v1")
    k2 = repo.add_subentry("sub", "v2")
    assert k1 != k2
    assert sorted(repo.get_subtree("sub")) == ["v1", "v2"]


def test_reset(repo):
    repo.add("keep", "1", delete_on_exit=False)
    repo.add("drop", "2", delete_on_exit=True)
    repo.reset()
    assert repo.get("keep") == "1"
    with pytest.raises(NameEntryNotFoundError):
        repo.get("drop")


def test_module_level_default():
    name_resolve.reconfigure(NameResolveConfig(type="memory"))
    name_resolve.add("m/k", "v")
    assert name_resolve.get("m/k") == "v"
    name_resolve.reset()
