"""≈ reference ``tests/distributed/test_name_resolve.py``: parametrized over
backends."""

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.base.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    NameResolveConfig,
    make_repository,
)


@pytest.fixture(scope="module")
def rpc_server():
    from areal_tpu.base.name_resolve_server import NameResolveServer

    srv = NameResolveServer("127.0.0.1", 0)
    addr = srv.start()
    yield addr
    srv.stop()


@pytest.fixture(params=["memory", "file", "rpc"])
def repo(request, tmp_path):
    if request.param == "rpc":
        root = request.getfixturevalue("rpc_server")
    else:
        root = str(tmp_path / "nr")
    cfg = NameResolveConfig(type=request.param, root=root)
    r = make_repository(cfg)
    yield r
    r.reset()


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y", "c")
    assert repo.get_subtree("root/x") == ["a", "b"]
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    assert sorted(repo.get_subtree("root")) == ["a", "b", "c"]
    repo.clear_subtree("root/x")
    assert repo.get_subtree("root/x") == []
    assert repo.get("root/y") == "c"


def test_wait(repo):
    import threading, time

    def _adder():
        time.sleep(0.2)
        repo.add("late/key", "zzz")

    t = threading.Thread(target=_adder)
    t.start()
    assert repo.wait("late/key", timeout=5) == "zzz"
    t.join()
    with pytest.raises(TimeoutError):
        repo.wait("never/key", timeout=0.2)


def test_add_subentry(repo):
    k1 = repo.add_subentry("sub", "v1")
    k2 = repo.add_subentry("sub", "v2")
    assert k1 != k2
    assert sorted(repo.get_subtree("sub")) == ["v1", "v2"]


def test_reset(repo):
    repo.add("keep", "1", delete_on_exit=False)
    repo.add("drop", "2", delete_on_exit=True)
    repo.reset()
    assert repo.get("keep") == "1"
    with pytest.raises(NameEntryNotFoundError):
        repo.get("drop")


def test_module_level_default():
    name_resolve.reconfigure(NameResolveConfig(type="memory"))
    name_resolve.add("m/k", "v")
    assert name_resolve.get("m/k") == "v"
    name_resolve.reset()


def test_rpc_cross_client_visibility_and_reset(rpc_server):
    """Two clients (= two workers on different nodes) share the tree; one
    client's reset() removes only ITS delete_on_exit keys."""
    a = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    b = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    a.add("fleet/server/0", "http://h0:1", replace=True)
    b.add("fleet/server/1", "http://h1:1", replace=True)
    assert a.get_subtree("fleet/server") == ["http://h0:1", "http://h1:1"]
    assert b.find_subtree("fleet/server") == [
        "fleet/server/0", "fleet/server/1",
    ]
    a.reset()
    with pytest.raises(NameEntryNotFoundError):
        b.get("fleet/server/0")
    assert b.get("fleet/server/1") == "http://h1:1"
    b.reset()
    a.close(), b.close()


def test_rpc_lease_expires_without_keepalive(rpc_server):
    """A key with keepalive_ttl outlives its TTL only while its owner's
    keepalive thread runs — kill the owner (close) and the key expires
    (the death-watch mechanism for crashed workers)."""
    import time as _time

    owner = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    other = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    owner.add("hb/w0", "alive", keepalive_ttl=1.5)
    _time.sleep(2.5)          # > ttl: keepalive thread kept it alive
    assert other.get("hb/w0") == "alive"
    owner.close()             # owner dies; no more touches
    _time.sleep(2.5)
    with pytest.raises(NameEntryNotFoundError):
        other.get("hb/w0")
    other.close()


def test_store_touch_does_not_resurrect_expired_key():
    """A keepalive arriving AFTER the lease lapsed must not revive the key
    (ADVICE r3): expiry is final — a worker that stalled past its TTL stays
    dead and must re-add."""
    import time as _time

    from areal_tpu.base.name_resolve_server import _Store

    st = _Store()
    st.add("hb/w0", "alive", replace=True, ttl=0.05)
    _time.sleep(0.1)          # lease lapsed, not yet lazily expired
    # the lapsed name comes back as `missing` so the client can re-ADD
    assert st.touch(["hb/w0"], ttl=60.0) == {"ok": True, "missing": ["hb/w0"]}
    assert st.get("hb/w0") == {"ok": False, "error": "not_found"}


def test_rpc_keepalive_readds_after_stall(rpc_server):
    """A client that stalls past its TTL loses the lease (death-watchers see
    it gone) but its keepalive loop re-ADDs on the next tick — the worker
    re-registers instead of staying silently invisible forever."""
    import time as _time

    owner = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    other = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    owner.add("hb/stall", "alive", keepalive_ttl=1.5)
    # simulate a stall: silence the keepalive thread past the TTL by taking
    # its lease snapshot away, then restore it
    with owner._lock:
        saved = dict(owner._leases)
        owner._leases.clear()
    _time.sleep(2.5)
    with pytest.raises(NameEntryNotFoundError):
        other.get("hb/stall")          # lease lapsed while stalled
    with owner._lock:
        owner._leases.update(saved)    # stall ends; keepalive resumes
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        try:
            assert other.get("hb/stall") == "alive"
            break
        except NameEntryNotFoundError:
            _time.sleep(0.2)
    else:
        pytest.fail("keepalive did not re-add the lapsed key")
    owner.close(); other.close()


def test_rpc_add_distinguishes_exists_from_protocol_error(rpc_server):
    """Only an 'exists' server response maps to NameEntryExistsError; any
    other failure surfaces as RuntimeError with the server's message
    (ADVICE r3)."""
    repo = make_repository(NameResolveConfig(type="rpc", root=rpc_server))
    repo.add("err/x", "1", replace=True)
    with pytest.raises(NameEntryExistsError):
        repo.add("err/x", "2", replace=False)
    orig_call = repo._call
    repo._call = lambda msg: {"ok": False, "error": "bad_request"}
    with pytest.raises(RuntimeError, match="bad_request"):
        repo.add("err/y", "1")
    repo._call = orig_call
    repo.delete("err/x")  # don't leak into the module-scoped server
    repo.close()
