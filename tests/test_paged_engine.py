"""Paged-KV generation engine: prefix sharing, pool accounting, bounded
compiles, and thread-safety under pause/submit racing step.

Counterpart of the capacity behaviors the reference inherits from SGLang
(radix cache sharing one prefill across a GRPO group, paged KV memory,
``patch/sglang/v0.4.6.post4.patch``).
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.pages import OutOfPagesError, PagePool, PrefixRegistry
from areal_tpu.ops.pallas import compat
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


class TestPagePool:
    def test_alloc_release_refcount(self):
        pool = PagePool(4, page_size=8)
        a = pool.alloc(2)
        assert pool.n_free == 2
        pool.ref(a)                 # shared
        pool.release(a)             # one ref left
        assert pool.n_free == 2
        pool.release(a)
        assert pool.n_free == 4
        with pytest.raises(OutOfPagesError):
            pool.alloc(5)
        with pytest.raises(ValueError):
            pool.release(a)         # double free

    def test_prefix_registry_share_evict(self):
        pool = PagePool(8, page_size=4)
        reg = PrefixRegistry(pool)
        prompt = list(range(10))
        pages = pool.alloc(2)       # 2 full pages = first 8 tokens
        reg.insert(prompt, pages)
        assert pool.n_free == 6
        got = reg.lookup(prompt, 2)
        assert got == pages
        # radix: a shorter request hits the chain's prefix...
        one = reg.lookup(prompt, 1)
        assert one == pages[:1]
        pool.release(one)
        # ...and a prompt diverging in page 2 shares page 1 only
        sib = prompt[:4] + [99] * 6
        part = reg.lookup(sib, 2)
        assert part == pages[:1]
        pool.release(part)
        # a prompt diverging in page 1: cold miss
        assert reg.lookup([9] + prompt[1:], 2) is None
        pool.release(got)           # borrower done
        pool.release(pages)         # original owner done; registry ref remains
        assert pool.n_free == 6
        reg.evict_lru(8)            # need pages -> registry lets go
        assert pool.n_free == 8

    def test_prefix_registry_radix_extends_chains(self):
        """Sibling prompts extend the tree past the shared preamble, and LRU
        eviction drops leaves before their parents."""
        pool = PagePool(8, page_size=4)
        reg = PrefixRegistry(pool)
        pre = [1, 2, 3, 4]
        a = pre + [5, 6, 7, 8]
        b = pre + [9, 10, 11, 12]
        pa = pool.alloc(2)
        reg.insert(a, pa)                 # chain: pre -> a-tail
        shared = reg.lookup(b, 2)         # sibling: preamble page only
        assert shared == pa[:1]
        pb_tail = pool.alloc(1)
        reg.insert(b, shared + pb_tail)   # extend: pre -> b-tail
        assert len(reg) == 3
        full_b = reg.lookup(b, 2)
        assert full_b == [pa[0], pb_tail[0]]
        pool.release(full_b)
        pool.release(shared)
        pool.release(pa)
        pool.release(pb_tail)
        # all 3 pages held only by the tree (pool.n_free == 5 of 8). Demand
        # 7 free: the tree must give up 2 pages — the two LEAF tails — and
        # keep the shared preamble (their parent) resident.
        assert pool.n_free == 5
        evicted = reg.evict_lru(7)
        assert evicted == 2 and pool.n_free == 7 and len(reg) == 1
        got = reg.lookup(a, 1)
        assert got == pa[:1]       # the preamble page survived
        pool.release(got)
        # demand everything: the remaining parent goes too
        assert reg.evict_lru(8) == 1 and pool.n_free == 8 and len(reg) == 0

    def test_evict_skips_pages_borrowed_by_running_slots(self):
        """Evicting a page a resident slot still borrows frees nothing —
        the tree must keep it (hot prefixes survive transient pressure)
        instead of draining itself for zero freed pages."""
        pool = PagePool(4, page_size=4)
        reg = PrefixRegistry(pool)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        pages = pool.alloc(2)
        reg.insert(prompt, pages)
        borrowed = reg.lookup(prompt, 2)   # a running slot holds both pages
        assert borrowed == pages
        pool.release(pages)                # prefill owner done
        assert pool.n_free == 2
        # pressure: nothing evictable actually frees -> tree stays intact
        assert reg.evict_lru(4) == 0
        assert len(reg) == 2
        pool.release(borrowed)             # slot finishes
        assert reg.evict_lru(4) == 2 and pool.n_free == 4 and len(reg) == 0


class TestPrefixSharing:
    def test_one_prefill_serves_group_of_8(self, params):
        """8 identical prompts (a GRPO group): the prompt's full pages are
        computed ONCE; members 2-8 extend only the sub-page tail."""
        page = 8
        prompt = [int(x) for x in np.random.default_rng(0).integers(1, 128, 21)]
        # plen_eff = 20 = 2 full pages (16 tokens) + tail 4
        eng = GenerationEngine(
            CFG, params, max_slots=8, max_seqlen=64, page_size=page, seed=0,
        )
        for i in range(8):
            eng.submit(GenRequest(
                rid=f"g{i}", input_ids=prompt, max_new_tokens=4, greedy=True,
            ))
        outs = eng.run_until_done(decode_steps=4)
        assert len(outs) == 8
        # all members produced identical greedy outputs from the shared KV
        assert len({tuple(o.output_ids) for o in outs}) == 1
        # one slot computed the full 20; seven extended only the 4-token tail
        assert eng.stats["prefix_hits"] == 7
        assert eng.stats["prefix_hit_tokens"] == 7 * 16
        assert eng.stats["prefill_tokens"] == 20 + 7 * 4
        # registry entry survives for the NEXT group on the same prompt
        eng.submit(GenRequest(rid="late", input_ids=prompt, max_new_tokens=4,
                              greedy=True))
        late = eng.run_until_done(decode_steps=4)
        assert eng.stats["prefix_hits"] == 8
        assert late[0].output_ids == outs[0].output_ids

    def test_shared_pages_memory_accounting(self, params):
        """Group members don't pay for the shared prompt pages."""
        page = 8
        prompt = list(range(1, 18))   # plen_eff 16 = 2 full pages, no tail
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=64, page_size=page,
        )
        for i in range(4):
            eng.submit(GenRequest(
                rid=f"g{i}", input_ids=prompt, max_new_tokens=8, greedy=True,
            ))
        eng.step(decode_steps=1)
        # per slot: ceil((16+8)/8)=3 pages total; the 2 prompt pages are
        # shared, so members own only 1 — pool usage = 3 + 3*1 = 6 pages
        used = eng.n_pages - eng.pool.n_free
        assert used == 6
        eng.run_until_done(decode_steps=4)
        # slots released; only the registry's hold on the 2 prompt pages stays
        assert eng.n_pages - eng.pool.n_free == 2

    def test_weight_update_invalidates_prefix(self, params):
        prompt = list(range(1, 18))
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=8,
        )
        eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=2,
                              greedy=True))
        eng.run_until_done(decode_steps=2)
        assert len(eng.prefix) == 2   # 2 full prompt pages resident
        eng.update_params(params, version=1)
        assert len(eng.prefix) == 0   # old-weight KV never seeds new rollouts
        eng.submit(GenRequest(rid="b", input_ids=prompt, max_new_tokens=2,
                              greedy=True))
        eng.run_until_done(decode_steps=2)
        assert eng.stats["prefix_hits"] == 0


class TestCapacity:
    def test_small_pool_defers_admission(self, params):
        """A pool smaller than slots x capacity admits what fits and keeps
        the rest pending instead of crashing — HBM is bounded by n_pages."""
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=64, page_size=8,
            n_pages=6, enable_prefix_cache=False,
        )
        # each request needs ceil((7+16)/8) = 3 pages -> only 2 fit
        for i in range(4):
            eng.submit(GenRequest(
                rid=f"r{i}", input_ids=list(range(1, 9)), max_new_tokens=16,
                greedy=True,
            ))
        eng.step(decode_steps=1)
        assert eng.n_running() == 2 and len(eng._pending) == 2
        outs = eng.run_until_done(decode_steps=8)   # turnover drains the rest
        assert len(outs) == 4
        assert eng.pool.n_free == 6

    def test_compile_count_stable_across_mixed_workload(self, params, rng):
        """Compile count is bounded by admit-row buckets + decode chunk —
        NOT by prompt lengths (chunked prefill kills the length dimension)."""
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=256, page_size=16,
        )
        for i, plen in enumerate([3, 9, 17, 33, 65, 100, 130, 7, 55, 23]):
            eng.submit(GenRequest(
                rid=f"m{i}",
                input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                max_new_tokens=4, greedy=True,
            ))
        eng.run_until_done(decode_steps=4)
        # warm every admit-row bucket with varying arrival counts
        for n_batch in (1, 2, 3, 4):
            for i in range(n_batch):
                eng.submit(GenRequest(
                    rid=f"w{n_batch}-{i}",
                    input_ids=[int(x) for x in rng.integers(1, 128, 40)],
                    max_new_tokens=4, greedy=True,
                ))
            eng.run_until_done(decode_steps=4)
        warmed = eng.n_compiles()
        # hard bound: up to two extends per bucket (cold-prompt skip-pool
        # variant + pool variant) + one commit per bucket + one decode chunk
        assert warmed <= 3 * len(eng.admit_buckets) + 1
        # fresh prompt lengths never trigger new specializations
        for i, plen in enumerate([11, 29, 77, 128, 201]):
            eng.submit(GenRequest(
                rid=f"n{i}",
                input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                max_new_tokens=4, greedy=True,
            ))
        eng.run_until_done(decode_steps=4)
        assert eng.n_compiles() == warmed


class TestThreadSafety:
    @pytest.mark.slow
    def test_pause_and_submit_racing_step(self, params, rng):
        """A server thread pausing/submitting while the step thread runs:
        no slot leaks, no double frees, every request resolves exactly once."""
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=64, page_size=8, seed=0,
        )
        results = {}
        errors = []
        stop = threading.Event()

        def stepper():
            try:
                while not stop.is_set():
                    for o in eng.step(decode_steps=2):
                        results[o.rid] = results.get(o.rid, 0) + 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def chaos():
            try:
                for i in range(30):
                    eng.submit(GenRequest(
                        rid=f"c{i}",
                        input_ids=[int(x) for x in rng.integers(1, 128, 5)],
                        max_new_tokens=6, greedy=True,
                    ))
                    if i % 5 == 4:
                        for o in eng.pause():
                            results[o.rid] = results.get(o.rid, 0) + 1
                        eng.resume()
                    time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        t1 = threading.Thread(target=stepper)
        t2 = threading.Thread(target=chaos)
        t1.start(); t2.start()
        t2.join(timeout=120)
        # drain the rest
        deadline = time.time() + 120
        while (eng._pending or eng.n_running()) and time.time() < deadline:
            time.sleep(0.05)
        stop.set()
        t1.join(timeout=30)
        assert not errors, errors
        assert sum(results.values()) == 30           # each exactly once
        assert all(v == 1 for v in results.values())
        assert eng.n_running() == 0
        # every page accounted for (registry may hold prompt pages)
        eng.prefix.clear()
        assert eng.pool.n_free == eng.n_pages


@pytest.mark.skipif(
    not (compat.compiler_params_available()
         and compat.memory_space_available()),
    reason="installed jax lacks pltpu CompilerParams or MemorySpace "
    "under either spelling",
)
class TestPallasPagedDecode:
    """Pallas paged-decode kernel parity vs the XLA gather path (interpret
    mode on CPU; the same kernel runs compiled on TPU). Both paths take the
    current token's K/V as SEPARATE operands (the pool is read-only during
    the layer scan) and fold its self-attention into the online softmax."""

    # (pages_per_step, slots_per_step): the default derives sb=4/kp=4 at
    # this shape -> a (1, 1) grid that never runs the double-buffer
    # prefetch pipeline; the (2, 2) and (1, 2) cases force multi-step
    # linearized grids (buffer-parity alternation, next-step zero guard,
    # cross-bb prefetch) — ADVICE r4: the pipeline must not be dead in CI.
    # interpret mode is slow on CPU: tier-1 keeps the (1,1)-grid default
    # and the (2,2) multi-step pipeline; the (1,2) cross-bb prefetch case
    # rides the slow sweep (runs unmarked + compiled on chip)
    @pytest.mark.parametrize(
        "kp_sb",
        [(8, 8), (2, 2), pytest.param((1, 2), marks=pytest.mark.slow)],
    )
    @pytest.mark.parametrize(
        "soft_cap,window", [(None, None), (5.0, None), (None, 6)]
    )
    def test_parity_vs_xla_and_dense(self, soft_cap, window, kp_sb):
        from areal_tpu.ops import paged_attention as xla_paged
        from areal_tpu.ops.pallas import paged_attention as pl_paged

        rng = np.random.default_rng(0)
        B, Hq, Hkv, D, page, M, P, L = 4, 4, 2, 16, 8, 4, 20, 3
        layer = 1
        q = rng.normal(size=(B, Hq, D)).astype(np.float32)
        k_self = rng.normal(size=(B, Hkv, D)).astype(np.float32)
        v_self = rng.normal(size=(B, Hkv, D)).astype(np.float32)
        pool = rng.normal(size=(L, P, 2, Hkv, page, D)).astype(np.float32)
        # dense views in [P, page, Hkv, D] order for the numpy reference
        k_pages = np.swapaxes(pool[:, :, 0], 2, 3)
        v_pages = np.swapaxes(pool[:, :, 1], 2, 3)
        table = rng.permutation(P)[: B * M].reshape(B, M).astype(np.int32)
        lens = np.asarray([1, 9, 32, 0], np.int32)  # partial/full/empty pool

        got = pl_paged.decode(
            q, k_self, v_self, pool, jnp.int32(layer), table,
            lens, soft_cap=soft_cap, sliding_window=window,
            pages_per_step=kp_sb[0], slots_per_step=kp_sb[1],
        )
        want = xla_paged.paged_decode_attention(
            q, k_self, v_self, pool, jnp.int32(layer), table,
            lens, soft_cap=soft_cap, sliding_window=window, use_pallas=False,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )

        # dense reference: gather pool positions [0, len) + self at the end
        scale = D ** -0.5
        n_rep = Hq // Hkv
        for b in range(B):
            flat_k = np.concatenate(
                [k_pages[layer, table[b]].reshape(-1, Hkv, D)[: lens[b]],
                 k_self[b][None]]
            )
            flat_v = np.concatenate(
                [v_pages[layer, table[b]].reshape(-1, Hkv, D)[: lens[b]],
                 v_self[b][None]]
            )
            S = flat_k.shape[0]
            for h in range(Hq):
                g = h // n_rep
                s = flat_k[:, g] @ q[b, h] * scale
                if soft_cap is not None:
                    s = soft_cap * np.tanh(s / soft_cap)
                if window is not None:
                    pos = np.arange(S)
                    s = np.where(pos > lens[b] - window, s, -1e30)
                p = np.exp(s - s.max())
                p /= p.sum()
                ref = p @ flat_v[:, g]
                np.testing.assert_allclose(
                    np.asarray(got)[b, h], ref, atol=2e-5, err_msg=f"b{b}h{h}"
                )


class TestRadixPartialPrefix:
    def test_sibling_prompts_share_preamble_pages(self, params):
        """Two prompts with a common 2-page system preamble but different
        questions: the second admission borrows the preamble pages (partial
        radix hit) and still produces exactly the generations a cold engine
        would — the KV served from shared pages is the same."""
        page = 8
        rng = np.random.default_rng(3)
        pre = [int(x) for x in rng.integers(1, 128, 16)]   # 2 full pages
        qa = pre + [int(x) for x in rng.integers(1, 128, 5)]
        qb = pre + [int(x) for x in rng.integers(1, 128, 5)]

        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=page, seed=0,
        )
        eng.submit(GenRequest(rid="a", input_ids=qa, max_new_tokens=4, greedy=True))
        out_a = eng.run_until_done(decode_steps=4)
        eng.submit(GenRequest(rid="b", input_ids=qb, max_new_tokens=4, greedy=True))
        out_b = eng.run_until_done(decode_steps=4)
        # b's admission partially hit a's preamble (2 pages = 16 tokens)
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["prefix_hit_tokens"] == 16
        # prefilled tokens: a's 20 (plen_eff) + b's 4 uncovered
        assert eng.stats["prefill_tokens"] == 20 + 4

        cold = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=page, seed=0,
        )
        cold.submit(GenRequest(rid="b2", input_ids=qb, max_new_tokens=4, greedy=True))
        ref_b = cold.run_until_done(decode_steps=4)
        assert out_b[0].output_ids == ref_b[0].output_ids
        assert out_a[0].output_ids != out_b[0].output_ids or qa == qb

    def test_partial_hit_registers_divergent_tail(self, params):
        """After a partial hit, the divergent tail joins the radix tree so a
        THIRD prompt identical to the second fully hits."""
        page = 8
        rng = np.random.default_rng(4)
        pre = [int(x) for x in rng.integers(1, 128, 16)]
        qb = pre + [int(x) for x in rng.integers(1, 128, 9)]  # 3 full pages

        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, page_size=page, seed=0,
        )
        eng.submit(GenRequest(rid="a", input_ids=pre + [1, 2], max_new_tokens=2, greedy=True))
        eng.run_until_done(decode_steps=2)
        eng.submit(GenRequest(rid="b", input_ids=qb, max_new_tokens=2, greedy=True))
        eng.run_until_done(decode_steps=2)
        hits_before = eng.stats["prefix_hit_tokens"]
        eng.submit(GenRequest(rid="b-twin", input_ids=qb, max_new_tokens=2, greedy=True))
        outs = eng.run_until_done(decode_steps=2)
        # the twin borrows ALL 3 full pages (16 preamble + 8 tail)
        assert eng.stats["prefix_hit_tokens"] - hits_before == 24
        assert outs[0].finish_reason in ("stop", "length")


class TestProtocolLengthGeneration:
    """The published benchmark protocol is 32k context with ~31k generated
    tokens (reference benchmark/verl_v0_3_0_post1_76084d3/README.md:39-41).
    These tests run the paged engine at that table geometry on CPU: a
    ~31.5k-token prompt chunk-prefills through the pool and decode crosses
    page boundaries near the 32k edge."""

    def test_32k_table_deep_prompt_decode(self, params):
        S = 32768
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=S, max_new_tokens_cap=31744,
            page_size=128, n_pages=2 * (S // 128),
        )
        assert eng.M == S // 128  # 256-wide page table
        rng = np.random.default_rng(0)
        plen = 31500
        prompt = [int(x) for x in rng.integers(1, 128, size=plen)]
        eng.submit(GenRequest(
            rid="deep", input_ids=prompt, max_new_tokens=1200, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=64, timeout=1200.0)
        assert len(outs) == 1
        o = outs[0]
        # capacity: plen-1 prefilled + 1200 generated > 32640 = capped by
        # the slot budget? no: 31499 + 1200 = 32699 <= 32768 fits
        assert len(o.output_ids) == 1200
        assert o.finish_reason == "length"
        # slot released; only the radix registry's hold on the prompt's
        # full pages remains (246 pages for a 31499-token prefix)
        assert eng.n_pages - eng.pool.n_free == (plen - 1) // 128
        # prefill streamed the whole prompt through page-size chunks
        assert eng.stats["prefill_tokens"] == plen - 1

    def test_32k_geometry_matches_small_engine(self, params):
        """Table width must not change results: the same short request
        through a 256-wide-table engine and a 1-page-per-slot-ish engine."""
        big = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=32768, page_size=128,
            n_pages=512,
        )
        small = GenerationEngine(CFG, params, max_slots=2, max_seqlen=256)
        prompt = [5, 9, 2, 14, 3, 8, 1]
        for eng in (big, small):
            eng.submit(GenRequest(
                rid="x", input_ids=prompt, max_new_tokens=12, greedy=True
            ))
        ob = big.run_until_done(decode_steps=4)[0]
        os_ = small.run_until_done(decode_steps=4)[0]
        assert ob.output_ids == os_.output_ids

    def test_pool_pressure_at_long_context(self, params):
        """Two long requests against a pool that only fits ~1.2 of them:
        admission must defer (not corrupt) and both finish eventually."""
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=8192, page_size=128,
            n_pages=80,  # 80*128 = 10240 tokens: < 2 full slots
        )
        rng = np.random.default_rng(1)
        for i in range(2):
            prompt = [int(x) for x in rng.integers(1, 128, size=6000)]
            eng.submit(GenRequest(
                rid=f"r{i}", input_ids=prompt, max_new_tokens=64, greedy=True
            ))
        outs = eng.run_until_done(decode_steps=32, timeout=600.0)
        assert sorted(o.rid for o in outs) == ["r0", "r1"]
        assert all(len(o.output_ids) == 64 for o in outs)
        # every held page is accounted for by the radix registry (no slot
        # leaks); draining the registry returns the pool to full
        assert eng.n_pages - eng.pool.n_free == len(eng.prefix)
        eng.prefix.clear()
        assert eng.pool.n_free == eng.n_pages


def test_pool_scatter_matches_reference():
    """The flat-row pool scatter (layout-neutral form: a permuted-layout
    multi-dim scatter forced two full-pool relayout copies per decode
    step) must write active slots at (table[lens//page], lens%page) and
    leave inactive slots untouched."""
    from areal_tpu.models.transformer import PagedKVCache, _scatter_chunk_kv

    rng = np.random.default_rng(0)
    L, P, Hkv, page, D, B, M = 3, 10, 2, 8, 16, 4, 2
    pages = rng.normal(size=(L, P, 2, Hkv, page, D)).astype(np.float32)
    ks = rng.normal(size=(L, B, Hkv, D)).astype(np.float32)
    vs = rng.normal(size=(L, B, Hkv, D)).astype(np.float32)
    table = rng.permutation(P)[: B * M].reshape(B, M).astype(np.int32)
    lens = np.asarray([0, 7, 8, 15], np.int32)     # page starts/ends
    active = np.asarray([True, True, False, True])

    got = np.asarray(_scatter_chunk_kv(
        PagedKVCache(pages=jnp.asarray(pages)),
        jnp.asarray(ks[:, :, None]), jnp.asarray(vs[:, :, None]),
        jnp.asarray(table), jnp.asarray(lens[:, None]),
        jnp.asarray(active[:, None]),
    ).pages)
    want = pages.copy()
    for b in range(B):
        if not active[b]:
            continue
        p_, o = table[b, lens[b] // page], lens[b] % page
        for l in range(L):
            want[l, p_, 0, :, o, :] = ks[l, b]
            want[l, p_, 1, :, o, :] = vs[l, b]
    np.testing.assert_array_equal(got, want)
