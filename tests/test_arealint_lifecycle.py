"""Fixture tests for the arealint v4 lifecycle rule family
(``tools/arealint/rules_lifecycle.py`` + the resource catalog in
``tools/arealint/resources.py``).

Every rule gets positive + negative + suppression fixtures (the
acceptance contract from docs/static_analysis.md), plus
ownership-transfer-through-callgraph cases, cancellation-shape fixtures
(await between acquire and release), and the catalog-drift test pinning
the parsed resource pairs against the runtime modules (same loud-drift
contract as the mesh model).
"""

import importlib
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import (  # noqa: E402
    Config,
    DEFAULT_RESOURCE_DEFS,
    ResourceCatalog,
    ResourceSpec,
    parse_resources,
    scan_sources,
)
from tools.arealint.resources import spec_pairs  # noqa: E402

pytestmark = pytest.mark.arealint


def dedent(s):
    return textwrap.dedent(s).lstrip()


CAT = ResourceCatalog([
    ResourceSpec(
        name="test.pages", kind="handle",
        acquires=(("Pool", "alloc"), ("Pool", "ref")),
        releases=(("Pool", "release"),),
        handle_from_arg=("ref",),
    ),
    ResourceSpec(
        name="test.bucket", kind="charge",
        acquires=(("Bucket", "try_acquire"),),
        releases=(("Bucket", "refund"),),
    ),
    ResourceSpec(
        name="test.slot", kind="charge",
        acquires=(("Mgr", "allocate"),),
        releases=(("Mgr", "finish"),),
    ),
    ResourceSpec(
        name="test.span", kind="context",
        func_acquires=("pkg.tracing.span",),
    ),
    ResourceSpec(
        name="test.lease", kind="handle",
        acquires=(("Lease", "start"),),
        release_on_handle=("stop",),
        handle_is_receiver=("start",),
    ),
    ResourceSpec(
        name="test.session", kind="handle", external=True,
        func_acquires=("aiohttp.ClientSession",),
        release_on_handle=("close",),
    ),
])
CFG = Config(resources=CAT)

POOL = dedent(
    """
    class Pool:
        def alloc(self, n): ...
        def ref(self, pages): ...
        def release(self, pages): ...
    """
)
BUCKET = dedent(
    """
    class Bucket:
        def try_acquire(self, cost): ...
        def refund(self, amount): ...
    """
)


def rules_of(sources, config=CFG):
    return [f.rule for f in scan_sources(sources, config=config)]


def findings(sources, rule, config=CFG):
    return [f for f in scan_sources(sources, config=config) if f.rule == rule]


def one(sources, rule, config=CFG):
    found = findings(sources, rule, config=config)
    assert len(found) == 1, (rule, [str(f) for f in scan_sources(
        sources, config=config
    )])
    return found[0]


# ------------------------------------------------------------------ #
# leak-on-cancellation: the PR-10 orphaned-slot shape
# ------------------------------------------------------------------ #


class TestLeakOnCancellation:
    def test_fires_on_await_between_acquire_and_release(self):
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)
                await chunk()
                pool.release(pages)
            """
        )
        f = one({"m.py": src}, "leak-on-cancellation")
        assert f.line == 7  # the await, not the acquire
        assert "test.pages" in f.message
        assert "CancelledError" in f.message

    def test_quiet_with_try_finally(self):
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)
                try:
                    await chunk()
                finally:
                    pool.release(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_except_exception_does_not_protect_await(self):
        # CancelledError is a BaseException: an `except Exception`
        # cleanup arm never runs on cancellation
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)
                try:
                    await chunk()
                except Exception:
                    pool.release(pages)
                    raise
                pool.release(pages)
            """
        )
        assert "leak-on-cancellation" in rules_of({"m.py": src})

    def test_except_base_exception_protects_await(self):
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)
                try:
                    await chunk()
                except BaseException:
                    pool.release(pages)
                    raise
                pool.release(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_handle_from_arg_ref_shape(self):
        src = POOL + dedent(
            """
            async def borrow(pool: Pool, pages):
                pool.ref(pages)
                await chunk()
                pool.release(pages)
            """
        )
        assert "leak-on-cancellation" in rules_of({"m.py": src})

    def test_suppression_on_acquire_line(self):
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)  # arealint: ok(fixture reason)
                await chunk()
                pool.release(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_suppression_on_await_line(self):
        src = POOL + dedent(
            """
            async def work(pool: Pool):
                pages = pool.alloc(2)
                await chunk()  # arealint: ok(pause point is lock-free)
                pool.release(pages)
            """
        )
        assert rules_of({"m.py": src}) == []


# ------------------------------------------------------------------ #
# leak-on-exception-path
# ------------------------------------------------------------------ #


class TestLeakOnExceptionPath:
    def test_fires_on_unprotected_call_between(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                compute(1)
                pool.release(pages)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert f.line == 6  # the acquire
        assert "finally" in f.message

    def test_fires_when_never_released(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                return None
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "not released on every path" in f.message

    def test_fires_on_discarded_result(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pool.alloc(2)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "discarded" in f.message

    def test_quiet_with_context_manager_acquire(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                with pool.alloc(2) as pages:
                    compute(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_quiet_when_release_in_except_handler_covers_risk(self):
        # the _admit_pending shape: risky alloc inside try, handler
        # releases the earlier acquire
        src = POOL + dedent(
            """
            def admit(pool: Pool):
                shared = pool.ref
                pages = pool.alloc(1)
                try:
                    more = pool.alloc(4)
                except RuntimeError:
                    pool.release(pages)
                    raise
                pool.release(pages)
                return more
            """
        )
        assert findings({"m.py": src}, "leak-on-exception-path") == []

    def test_owns_annotation_discharges(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)  # arealint: owns(test.pages, slot table owns them until harvest)
                compute(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_owns_wrong_resource_name_does_not_discharge(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)  # arealint: owns(test.other, reason)
                compute(1)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "malformed" in f.message

    def test_owns_without_reason_does_not_discharge(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)  # arealint: owns(test.pages)
                compute(1)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "malformed" in f.message

    def test_released_only_on_some_paths(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, keep):
                pages = pool.alloc(2)
                if keep:
                    pool.release(pages)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "some paths" in f.message


# ------------------------------------------------------------------ #
# ownership transfer through the call graph
# ------------------------------------------------------------------ #


class TestOwnershipTransfer:
    def test_resolved_releasing_callee_discharges(self):
        src = POOL + dedent(
            """
            def cleanup(pool: Pool, pages):
                pool.release(pages)

            def work(pool: Pool):
                pages = pool.alloc(2)
                compute(1)
                cleanup(pool, pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_cross_module_transfer_discharges(self):
        helper = POOL + dedent(
            """
            def cleanup(pool: Pool, pages):
                pool.release(pages)
            """
        )
        main = dedent(
            """
            from pkg.helper import cleanup
            from pkg.helper import Pool

            def work(pool: Pool):
                pages = pool.alloc(2)
                compute(1)
                cleanup(pool, pages)
            """
        )
        assert rules_of(
            {"pkg/__init__.py": "", "pkg/helper.py": helper,
             "pkg/main.py": main}
        ) == []

    def test_transitive_transfer_discharges(self):
        src = POOL + dedent(
            """
            def inner(pool: Pool, pages):
                pool.release(pages)

            def outer(pool: Pool, pages):
                inner(pool, pages)

            def work(pool: Pool):
                pages = pool.alloc(2)
                outer(pool, pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_unresolvable_callee_degrades(self):
        src = POOL + dedent(
            """
            import external

            def work(pool: Pool):
                pages = pool.alloc(2)
                external.take(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_storing_callee_degrades(self):
        src = POOL + dedent(
            """
            class Table:
                def keep(self, pages):
                    self.rows = pages

            def work(pool: Pool, table: Table):
                pages = pool.alloc(2)
                table.keep(pages)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_resolved_non_releasing_callee_is_plain_use(self):
        src = POOL + dedent(
            """
            def log_pages(pages):
                print(pages)

            def work(pool: Pool):
                pages = pool.alloc(2)
                log_pages(pages)
            """
        )
        f = one({"m.py": src}, "leak-on-exception-path")
        assert "not released on every path" in f.message

    def test_store_and_return_degrade(self):
        src = POOL + dedent(
            """
            class Slots:
                def __init__(self, pool: Pool):
                    self.held = None
                    self.pool = pool

                def admit(self):
                    pages = self.pool.alloc(2)
                    self.held = pages

                def lookup(self):
                    pages = self.pool.alloc(2)
                    return pages
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_charge_transfer_via_spawned_task(self):
        # run_async's shape: create_task(self._task()) where the task
        # body settles the charge — the UNRESOLVED spawn wrapper doesn't
        # matter, the inner resolved call does
        src = dedent(
            """
            class Mgr:
                async def allocate(self): ...
                async def finish(self): ...

            class W:
                def __init__(self, mgr: Mgr):
                    self.mgr = mgr

                async def _task(self):
                    await self.mgr.finish()

                async def run(self, spawn):
                    if await self.mgr.allocate():
                        spawn(self._task())
            """
        )
        assert rules_of({"m.py": src}) == []


# ------------------------------------------------------------------ #
# charge-refund-asymmetry
# ------------------------------------------------------------------ #


class TestChargeRefundAsymmetry:
    def test_fires_on_charge_without_refund_path(self):
        src = BUCKET + dedent(
            """
            def admit(bucket: Bucket, cost):
                if not bucket.try_acquire(cost):
                    raise RuntimeError("limited")
                enqueue(cost)
            """
        )
        f = one({"m.py": src}, "charge-refund-asymmetry")
        assert "test.bucket" in f.message

    def test_fires_on_risky_call_before_refund(self):
        src = BUCKET + dedent(
            """
            def settle(bucket: Bucket, cost):
                if not bucket.try_acquire(cost):
                    return False
                run(cost)
                bucket.refund(cost)
                return True
            """
        )
        f = one({"m.py": src}, "charge-refund-asymmetry")
        assert "finally" in f.message

    def test_quiet_with_refund_in_finally(self):
        src = BUCKET + dedent(
            """
            def settle(bucket: Bucket, cost):
                if not bucket.try_acquire(cost):
                    return False
                try:
                    run(cost)
                finally:
                    bucket.refund(cost)
                return True
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_quiet_when_receiver_escapes(self):
        src = BUCKET + dedent(
            """
            import external

            def admit(bucket: Bucket, cost):
                if not bucket.try_acquire(cost):
                    return
                external.settle_later(bucket, cost)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_owns_annotation_discharges(self):
        src = BUCKET + dedent(
            """
            def admit(bucket: Bucket, cost):
                # arealint: owns(test.bucket, settled by the completion path)
                if not bucket.try_acquire(cost):
                    raise RuntimeError("limited")
                enqueue(cost)
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_return_annotation_types_the_receiver(self):
        # scheduler.submit's shape: the bucket comes from a helper with
        # a return annotation, not a ctor assignment
        src = BUCKET + dedent(
            """
            class Sched:
                def _bucket(self, tenant) -> Bucket:
                    return make()

                def submit(self, tenant, cost):
                    bucket = self._bucket(tenant)
                    if not bucket.try_acquire(cost):
                        raise RuntimeError("limited")
                    enqueue(cost)
            """
        )
        assert "charge-refund-asymmetry" in rules_of({"m.py": src})


# ------------------------------------------------------------------ #
# double-release
# ------------------------------------------------------------------ #


class TestDoubleRelease:
    def test_fires_on_straight_line_double_free(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                pool.release(pages)
                pool.release(pages)
            """
        )
        f = one({"m.py": src}, "double-release")
        assert f.line == 8
        assert "double free" in f.message

    def test_fires_on_release_in_loop(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, rounds):
                pages = pool.alloc(2)
                for _ in rounds:
                    pool.release(pages)
            """
        )
        f = one({"m.py": src}, "double-release")
        assert "loop" in f.message

    def test_quiet_on_exclusive_branches(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, cond):
                pages = pool.alloc(2)
                if cond:
                    pool.release(pages)
                else:
                    pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "double-release") == []

    def test_quiet_on_try_except_arms(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                try:
                    commit(pages)
                    pool.release(pages)
                except RuntimeError:
                    pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "double-release") == []

    def test_quiet_on_reacquire_between(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                pool.release(pages)
                pages = pool.alloc(2)
                pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "double-release") == []

    def test_quiet_without_in_function_acquire(self):
        # settle-elsewhere pattern (engine._harvest): releases of a
        # handle this function never acquired are out of scope
        src = POOL + dedent(
            """
            def harvest(pool: Pool, info):
                pool.release(info)
                pool.release(info)
            """
        )
        assert findings({"m.py": src}, "double-release") == []

    def test_suppression(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                pool.release(pages)
                pool.release(pages)  # arealint: ok(fixture double free)
            """
        )
        assert findings({"m.py": src}, "double-release") == []


# ------------------------------------------------------------------ #
# release-without-acquire
# ------------------------------------------------------------------ #


class TestReleaseWithoutAcquire:
    def test_fires_on_conditional_acquire_unconditional_release(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, cond):
                if cond:
                    pages = pool.alloc(2)
                finishup()
                pool.release(pages)
            """
        )
        f = one({"m.py": src}, "release-without-acquire")
        assert "only on some" in f.message

    def test_quiet_with_truthiness_guard(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, cond):
                pages = []
                if cond:
                    pages = pool.alloc(2)
                try:
                    finishup()
                finally:
                    if pages:
                        pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "release-without-acquire") == []

    def test_quiet_with_prior_binding(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, cond):
                pages = []
                if cond:
                    pages = pool.alloc(2)
                pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "release-without-acquire") == []

    def test_quiet_without_in_function_acquire(self):
        src = POOL + dedent(
            """
            def refund_path(pool: Pool, pages):
                pool.release(pages)
            """
        )
        assert findings({"m.py": src}, "release-without-acquire") == []

    def test_charge_kind_variant(self):
        src = BUCKET + dedent(
            """
            def settle(bucket: Bucket, fast, cost):
                if fast:
                    ok = bucket.try_acquire(cost)
                run(cost)
                bucket.refund(cost)
            """
        )
        assert "release-without-acquire" in rules_of({"m.py": src})

    def test_suppression(self):
        src = POOL + dedent(
            """
            def work(pool: Pool, cond):
                if cond:
                    pages = pool.alloc(2)
                finishup()
                pool.release(pages)  # arealint: ok(cond is invariant here)
            """
        )
        assert findings({"m.py": src}, "release-without-acquire") == []


# ------------------------------------------------------------------ #
# context kind (tracing.span) + handle-is-receiver + sessions
# ------------------------------------------------------------------ #


class TestContextAndSpecialShapes:
    TRACING = "def span(name): ...\n"

    def test_bare_span_call_fires(self):
        main = dedent(
            """
            from pkg import tracing

            def work():
                tracing.span("step")
            """
        )
        f = one(
            {"pkg/__init__.py": "", "pkg/tracing.py": self.TRACING,
             "pkg/main.py": main},
            "leak-on-exception-path",
        )
        assert "with" in f.message

    def test_span_in_with_is_quiet(self):
        main = dedent(
            """
            from pkg import tracing

            def work():
                with tracing.span("step"):
                    compute()
            """
        )
        assert rules_of(
            {"pkg/__init__.py": "", "pkg/tracing.py": self.TRACING,
             "pkg/main.py": main}
        ) == []

    def test_span_bound_then_with_is_quiet(self):
        main = dedent(
            """
            from pkg import tracing

            def work():
                cm = tracing.span("step")
                with cm:
                    compute()
            """
        )
        assert rules_of(
            {"pkg/__init__.py": "", "pkg/tracing.py": self.TRACING,
             "pkg/main.py": main}
        ) == []

    def test_lease_receiver_handle(self):
        src = dedent(
            """
            class Lease:
                def start(self): ...
                def stop(self): ...

            async def run():
                lease = Lease()
                lease.start()
                await step()
                lease.stop()
            """
        )
        assert "leak-on-cancellation" in rules_of({"m.py": src})

    def test_lease_attribute_receiver_degrades(self):
        # cross-method protocols (self.lease started in join, stopped in
        # stop) hand ownership to the object: out of scope by contract
        src = dedent(
            """
            class Lease:
                def start(self): ...
                def stop(self): ...

            class Mgr:
                def __init__(self):
                    self.lease = Lease()

                async def join(self):
                    self.lease.start()
                    await step()
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_session_non_cm_without_close_fires(self):
        src = dedent(
            """
            import aiohttp

            async def fetch():
                s = aiohttp.ClientSession()
                await s.get("http://x")
                await s.close()
            """
        )
        assert "leak-on-cancellation" in rules_of({"m.py": src})

    def test_session_async_with_is_quiet(self):
        src = dedent(
            """
            import aiohttp

            async def fetch():
                async with aiohttp.ClientSession() as s:
                    await s.get("http://x")
            """
        )
        assert rules_of({"m.py": src}) == []

    def test_session_close_in_finally_is_quiet(self):
        src = dedent(
            """
            import aiohttp

            async def fetch():
                s = aiohttp.ClientSession()
                try:
                    await s.get("http://x")
                finally:
                    await s.close()
            """
        )
        assert rules_of({"m.py": src}) == []


# ------------------------------------------------------------------ #
# typing conservatism: no resolution -> no obligation
# ------------------------------------------------------------------ #


class TestTypingDegradation:
    def test_untyped_receiver_creates_no_obligation(self):
        src = dedent(
            """
            def work(pool):
                pages = pool.alloc(2)
                compute(pages)
            """
        )
        assert rules_of({"m.py": src}, config=CFG) == []

    def test_name_collision_with_other_class_is_quiet(self):
        src = dedent(
            """
            class Arena:
                def alloc(self, n): ...

            def work(arena: Arena):
                block = arena.alloc(2)
                compute(block)
            """
        )
        assert rules_of({"m.py": src}, config=CFG) == []

    def test_no_catalog_disables_family(self):
        src = POOL + dedent(
            """
            def work(pool: Pool):
                pages = pool.alloc(2)
                compute(pages)
            """
        )
        assert rules_of({"m.py": src}, config=Config(resources=None)) == []


# ------------------------------------------------------------------ #
# catalog provenance + drift (the loud-drift contract)
# ------------------------------------------------------------------ #


class TestCatalogDrift:
    def test_every_declared_spec_verifies_against_the_tree(self):
        catalog, dropped = parse_resources(REPO)
        assert dropped == [], (
            f"resource specs dropped at provenance: {dropped} — the "
            "declared (class, method) pairs no longer exist; update "
            "tools/arealint/resources.py"
        )
        assert sorted(s.name for s in catalog) == sorted(
            s.name for s in DEFAULT_RESOURCE_DEFS
        )

    def test_parsed_pairs_match_runtime_modules(self):
        """Import each catalog module and check every declared operation
        exists at runtime — a rename in the runtime module must fail HERE,
        not silently disable the rule family."""
        catalog, _ = parse_resources(REPO)
        for spec in catalog:
            if spec.external:
                continue
            mod_name = spec.module[:-3].replace("/", ".")
            mod = importlib.import_module(mod_name)
            for cls, method in spec_pairs(spec):
                owner = getattr(mod, cls) if cls else mod
                assert callable(getattr(owner, method, None)), (
                    f"{spec.name}: {spec.module} has no "
                    f"{cls + '.' if cls else ''}{method}"
                )
            for m in spec.release_on_handle:
                # release-on-handle ops live on the ACQUIRING class(es)
                for cls in spec.acquire_classes():
                    assert callable(getattr(getattr(mod, cls), m, None)), (
                        f"{spec.name}: {cls} has no {m}()"
                    )

    def test_expected_resources_present(self):
        catalog, _ = parse_resources(REPO)
        names = {s.name for s in catalog}
        assert {
            "gen.kv-pages", "gen.engine-slot", "gateway.token-bucket",
            "gateway.wfq", "gateway.request", "rollout.manager-slot",
            "elastic.rank-lease", "tracing.span", "aiohttp.client-session",
        } <= names

    def test_missing_module_drops_spec(self, tmp_path):
        cat, dropped = parse_resources(tmp_path)
        assert "gen.kv-pages" in dropped
        # external specs survive (declaration-only)
        assert "aiohttp.client-session" in {s.name for s in cat}


# ------------------------------------------------------------------ #
# CLI integration: explicit-path scans (the --changed-only file set)
# cover the lifecycle family
# ------------------------------------------------------------------ #


class TestCliScoping:
    def test_explicit_path_scan_fires_lifecycle_rules(self, tmp_path):
        bad = tmp_path / "leaky.py"
        bad.write_text(dedent(
            """
            class PagePool:
                def alloc(self, n): ...
                def release(self, pages): ...

            async def work(pool: PagePool):
                pages = pool.alloc(2)
                await chunk()
                pool.release(pages)
            """
        ))
        r = subprocess.run(
            [sys.executable, "-m", "tools.arealint", str(bad),
             "--no-baseline"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 1
        assert "leak-on-cancellation" in r.stdout

    def test_changed_only_stdin_covers_lifecycle(self, tmp_path):
        bad = tmp_path / "leaky.py"
        bad.write_text(dedent(
            """
            class PagePool:
                def alloc(self, n): ...
                def release(self, pages): ...

            def work(pool: PagePool):
                pages = pool.alloc(2)
                compute(1)
                pool.release(pages)
            """
        ))
        r = subprocess.run(
            [sys.executable, "-m", "tools.arealint", str(tmp_path),
             "--no-baseline", "--changed-only"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            input=f"{bad}\n",
        )
        assert r.returncode == 1
        assert "leak-on-exception-path" in r.stdout

    def test_rules_listed(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.arealint", "--list-rules"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0
        for rid in (
            "leak-on-exception-path", "leak-on-cancellation",
            "double-release", "release-without-acquire",
            "charge-refund-asymmetry",
        ):
            assert rid in r.stdout
