"""Pipelined host↔device data plane (round 6).

Proves the three pipeline invariants on the virtual CPU mesh:
1. dispatch-ahead ``forward()`` returns results EXACTLY equal to the serial
   path while genuinely keeping ≥2 micro-batches in flight (dispatch/fetch
   event order + counters, not wall-time inference), on a single device AND
   a 2×2 data×fsdp mesh;
2. the prefetched minibatch train loop is numerically identical to the
   serial loop (same jitted program, same dispatch order);
3. the trainer worker's stats fetch is deferred to the logging interval —
   zero blocking per-step ``device_get`` calls, one batched flush.
"""

import json
import os

import numpy as np
import pytest

import jax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.base import constants
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.train import batching
from areal_tpu.train.engine import (
    OptimizerConfig,
    TrainEngine,
    fwd_pipeline_depth,
    train_prefetch_enabled,
    vmapped_forward,
)

TINY = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


def _make_sample(rng, n_items=12, low=6, high=14):
    seqlens = [int(n) for n in rng.integers(low, high, size=n_items)]
    return SequenceSample.from_default(
        ids=list(range(n_items)),
        seqlens=seqlens,
        data={
            "packed_input_ids": np.concatenate(
                [rng.integers(0, 128, size=n).astype(np.int64) for n in seqlens]
            ),
            "prompt_mask": np.concatenate(
                [np.r_[np.ones(2, np.bool_), np.zeros(n - 2, np.bool_)]
                 for n in seqlens]
            ),
        },
    )


def _logprob_fn(params, cfg, arrays):
    from areal_tpu.ops import ppo as ppo_ops

    logits = vmapped_forward(params, cfg, arrays)
    return jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
        logits, arrays["input_ids"], arrays["segment_ids"]
    )


def _sft_loss(params, cfg, arrays):
    import jax.numpy as jnp

    from areal_tpu.ops import ppo as ppo_ops

    lp = _logprob_fn(params, cfg, arrays)
    seg = arrays["segment_ids"]
    has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
    mask = has_next & ~arrays["prompt_mask"]
    n = jnp.maximum(mask.sum(), 1)
    loss = -jnp.sum(jnp.where(mask, lp, 0.0)) / n
    return loss, {"n_tokens": n.astype(jnp.float32)}


def test_env_knob_parsing(monkeypatch):
    monkeypatch.delenv(constants.FWD_PIPELINE_ENV, raising=False)
    monkeypatch.delenv(constants.TRAIN_PREFETCH_ENV, raising=False)
    assert fwd_pipeline_depth() == 2            # default ON
    assert train_prefetch_enabled()
    monkeypatch.setenv(constants.FWD_PIPELINE_ENV, "0")
    monkeypatch.setenv(constants.TRAIN_PREFETCH_ENV, "false")
    assert fwd_pipeline_depth() == 0
    assert not train_prefetch_enabled()
    monkeypatch.setenv(constants.FWD_PIPELINE_ENV, "4")
    assert fwd_pipeline_depth() == 4


def test_prefetcher_order_and_errors():
    out = list(batching.Prefetcher(range(7), lambda x: x * x))
    assert out == [i * i for i in range(7)]

    def boom(x):
        if x == 2:
            raise RuntimeError("packer failed")
        return x

    it = iter(batching.Prefetcher(range(5), boom))
    assert next(it) == 0 and next(it) == 1
    with pytest.raises(RuntimeError, match="packer failed"):
        for _ in it:
            pass

    # a consumer that abandons iteration must be able to release the
    # producer (otherwise the thread blocks on the full queue forever,
    # pinning whatever it prepared)
    p = batching.Prefetcher(range(100), lambda x: x)
    assert next(iter(p)) == 0
    p.close()
    p._thread.join(2.0)
    assert not p._thread.is_alive()


@pytest.mark.parametrize(
    "par", [ParallelConfig(), ParallelConfig(data=2, fsdp=2)],
    ids=["single", "d2f2"],
)
def test_forward_pipeline_identical_and_overlapped(rng, par, monkeypatch):
    """The acceptance bar: byte-identical outputs AND counter-proven overlap
    (≥2 micro-batches in flight; mb 1 dispatched before mb 0 is fetched)."""
    eng = TrainEngine(TINY, parallel=par)
    eng.init_random(0)
    sample = _make_sample(rng, n_items=12)
    spec = MicroBatchSpec(n_mbs=4)

    monkeypatch.setenv(constants.FWD_PIPELINE_ENV, "0")
    serial = eng.forward(sample, spec, _logprob_fn)
    serial_events = eng._last_forward_events
    # serial discipline: every fetch directly follows its own dispatch
    assert serial_events == [
        (kind, i) for i in range(len(serial_events) // 2)
        for kind in ("dispatch", "fetch")
    ]

    monkeypatch.setenv(constants.FWD_PIPELINE_ENV, "2")
    metrics_mod.counters.reset()
    piped = eng.forward(sample, spec, _logprob_fn)
    events = eng._last_forward_events

    assert len(piped) == len(serial)
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # ≥2 in flight, proven by event order: mb 1's dispatch precedes mb 0's
    # fetch, and the realized depth counter saw 2
    assert events.index(("dispatch", 1)) < events.index(("fetch", 0))
    assert metrics_mod.counters.get("fwd_pipe/max_in_flight") >= 2
    n_mbs = len(events) // 2
    assert n_mbs >= 2  # the split really produced multiple micro-batches
    assert metrics_mod.counters.get("fwd_pipe/dispatched") == n_mbs
    # every micro-batch was fetched exactly once
    assert sorted(i for k, i in events if k == "fetch") == list(range(n_mbs))


def test_forward_explicit_depth_overrides_env(rng, monkeypatch):
    eng = TrainEngine(TINY)
    eng.init_random(0)
    sample = _make_sample(rng, n_items=8)
    monkeypatch.setenv(constants.FWD_PIPELINE_ENV, "2")
    eng.forward(sample, MicroBatchSpec(n_mbs=3), _logprob_fn, pipeline_depth=1)
    events = eng._last_forward_events
    assert events.index(("fetch", 0)) < events.index(("dispatch", 1))


def test_train_batches_pipelined_matches_serial(rng, monkeypatch):
    """The prefetched minibatch loop runs the SAME jitted steps in the same
    order as the serial loop — final params and per-step losses agree.
    (Mesh-independence of the pipeline is covered by the forward test; one
    device keeps this at a single train-step compile.)"""

    def run(knob):
        monkeypatch.setenv(constants.TRAIN_PREFETCH_ENV, knob)
        eng = TrainEngine(
            TINY, parallel=ParallelConfig(), optimizer=OptimizerConfig(lr=1e-3)
        )
        eng.init_random(0)
        eng.setup_optimizer(total_train_steps=50)
        mbs = [_make_sample(np.random.default_rng(s), n_items=4)
               for s in range(3)]
        stats = eng.train_batches_pipelined(
            mbs, MicroBatchSpec(n_mbs=1), _sft_loss, fetch_stats=False
        )
        losses = [float(np.asarray(jax.device_get(s["loss"]))) for s in stats]
        return losses, jax.device_get(eng.params)

    losses_serial, params_serial = run("0")
    losses_piped, params_piped = run("1")
    assert losses_serial == losses_piped
    for a, b in zip(jax.tree.leaves(params_serial), jax.tree.leaves(params_piped)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class _Stream:
    def __init__(self, items):
        self.items = list(items)

    def get_batch(self, n, timeout=None):
        out, self.items = self.items[:n], self.items[n:]
        return out


def _traj(qid, n=2, ln=8):
    lens = [ln] * n
    data = {
        "packed_input_ids": np.arange(n * ln, dtype=np.int64) % 64,
        "prompt_mask": np.concatenate(
            [np.r_[np.ones(3, bool), np.zeros(ln - 3, bool)] for _ in range(n)]
        ),
        "packed_logprobs": np.zeros(n * ln, np.float32),
        "rewards": np.ones(n, np.float32),
        "seq_no_eos_mask": np.zeros(n, bool),
    }
    seqlens = {
        "packed_input_ids": [lens],
        "prompt_mask": [lens],
        "packed_logprobs": [lens],
        "rewards": [[1] * n],
        "seq_no_eos_mask": [[1] * n],
    }
    return SequenceSample(
        keys=set(seqlens), ids=[qid], seqlens=seqlens, data=data
    )


def _make_worker(eng, stream, tmp_path, name, n_steps, flush_every):
    from areal_tpu.api.model import PPOHyperparameters
    from areal_tpu.base.metrics import MetricLogger
    from areal_tpu.system.trainer_worker import (
        AsyncPPOTrainerWorker,
        TrainerControl,
    )

    return AsyncPPOTrainerWorker(
        name, "t0",
        actor_engine=eng,
        stream=stream,
        hp=PPOHyperparameters(
            disable_value=True, use_decoupled_loss=False,
            recompute_logprob=False, ppo_n_minibatches=2,
        ),
        control=TrainerControl(
            total_train_steps=n_steps,
            weight_sync_freq_steps=10**9,   # no HF export in a unit test
            ckpt_freq_steps=None, ckpt_freq_secs=None,
            stats_log_freq_steps=flush_every,
        ),
        train_batch_size=4,
        mb_spec=MicroBatchSpec(),
        metric_logger=MetricLogger(str(tmp_path), backends=("jsonl",)),
    )


def test_trainer_worker_defers_stats_fetch(tmp_path, monkeypatch):
    """Acceptance bar: the train loop performs ZERO blocking per-step
    ``device_get`` of stats; device scalars flush ONCE per logging interval
    (and land in the jsonl with their per-step timestamps). Also covers the
    exit path: trailing steps that never hit the interval boundary still
    land in the jsonl when ``run()`` exits."""
    monkeypatch.setenv(constants.TRAIN_PREFETCH_ENV, "1")
    eng = TrainEngine(
        ModelConfig(
            n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8, hidden_dim=16,
            intermediate_dim=32, vocab_size=64, dtype="float32",
        ),
        ParallelConfig(),
        OptimizerConfig(lr=1e-3),
    )
    eng.init_random(0)
    eng.setup_optimizer(100)
    n_steps = 4
    stream = _Stream([_traj(f"q{i}") for i in range(4 * n_steps)])
    worker = _make_worker(
        eng, stream, tmp_path / "a", "pipe-defer", n_steps,
        flush_every=n_steps,
    )

    for step in range(n_steps - 1):
        blocking_before = metrics_mod.counters.get("stats_fetch/blocking")
        assert worker.run_step() is not None
        # no per-step blocking stats pull, no flush yet
        assert metrics_mod.counters.get("stats_fetch/blocking") == blocking_before
        assert len(worker._pending_stats) == step + 1
    flushes_before = metrics_mod.counters.get("train_pipe/stats_flushes")
    assert worker.run_step() is not None           # interval boundary
    assert metrics_mod.counters.get("train_pipe/stats_flushes") == flushes_before + 1
    assert worker._pending_stats == []

    with open(os.path.join(str(tmp_path / "a"), "metrics.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert [l["step"] for l in lines] == list(range(1, n_steps + 1))
    # per-step wall clocks survive the deferred flush (monotone, distinct
    # from flush time) and device scalars arrived as plain floats
    assert all(lines[i]["time"] <= lines[i + 1]["time"] for i in range(len(lines) - 1))
    assert all(isinstance(l["ppo/actor_loss"], float) for l in lines)
    assert all(np.isfinite(l["ppo/actor_loss"]) for l in lines)
    # the pipeline counters rode along into the jsonl
    assert any(k.startswith("ppo/pipe/") for k in lines[0])

    # exit-path flush: a fresh worker on the SAME engine (jit cache stays
    # warm), interval larger than the run — run() must flush on the way out
    stream2 = _Stream([_traj(f"r{i}") for i in range(8)])
    worker2 = _make_worker(
        eng, stream2, tmp_path / "b", "pipe-exit", 2, flush_every=100,
    )
    worker2.step = 0
    assert worker2.run() == 2
    with open(os.path.join(str(tmp_path / "b"), "metrics.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert [l["step"] for l in lines] == [1, 2]
