"""Whole-experiment integration tests through the launcher.

Counterpart of the reference's ``tests/experiments/`` (``run_test_exp``):
real multiprocess worlds — SFT in-process, async PPO with spawned gen
server / manager / rollout / trainer processes rendezvousing over the
file-backed name_resolve — tiny models, CPU devices.
"""

import json
import os

import numpy as np
import pytest

TINY_ARCH = dict(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, use_attention_bias=True,
    dtype="float32",
)


def _write_prompt_dataset(path, n=8, plen=6):
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "query_id": f"q{i}",
                "prompt_ids": [int(x) for x in rng.integers(1, 128, plen)],
                "task": "math",
                "solutions": ["\\boxed{7}"],
            }) + "\n")


def _write_sft_dataset(path, n=16):
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "qid": f"s{i}",
                "prompt_ids": [int(x) for x in rng.integers(1, 128, 4)],
                "answer_ids": [int(x) for x in rng.integers(1, 128, 6)],
            }) + "\n")


def test_sft_experiment(tmp_path):
    from areal_tpu.apps import launcher
    from areal_tpu.experiments import SFTExperiment, load_config

    data = str(tmp_path / "sft.jsonl")
    _write_sft_dataset(data)
    cfg = load_config(SFTExperiment, None, [
        "experiment_name=sft-test",
        "trial_name=t0",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "dataset.name=prompt_answer",
        "batch_size=4",
        "max_tokens_per_mb=256",
        "control.total_train_steps=3",
        "control.save_freq_steps=3",
        "model.parallel=d2m1",
        f"model.arch={json.dumps(TINY_ARCH)}",
        "model.optimizer.lr=0.001",
    ])
    assert cfg.model.arch["hidden_dim"] == 32
    rc = launcher.run_sft(cfg)
    assert rc == 0
    # saved an HF export at step 3
    save_dir = os.path.join(f"{tmp_path}/root", "checkpoints", "sft-test", "t0",
                            "step3")
    assert os.path.exists(os.path.join(save_dir, "model.safetensors"))
    # metrics logged
    log_root = os.path.join(f"{tmp_path}/root", "logs", "sft-test", "t0")
    metrics = os.path.join(log_root, "metrics.jsonl")
    assert os.path.exists(metrics)
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 3 and "sft/loss" in lines[0]
    # the worker folds HBM gauges into per-step stats; on CPU (no
    # memory_stats) that's the live-array fallback gauge
    assert "sft/hbm_live_array_bytes" in lines[0]


def test_sync_ppo_experiment(tmp_path):
    """In-process sync-PPO (generate-on-trainer) for 2 steps with a save."""
    from areal_tpu.apps import launcher
    from areal_tpu.experiments import SyncPPOExperiment, load_config

    data = str(tmp_path / "math.jsonl")
    _write_prompt_dataset(data)
    cfg = load_config(SyncPPOExperiment, None, [
        "experiment_name=sppo-test",
        "trial_name=t0",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "batch_size=2",
        "max_tokens_per_mb=512",
        "control.total_train_steps=2",
        "control.save_freq_steps=2",
        f"actor.arch={json.dumps(TINY_ARCH)}",
        "actor.parallel=d2m1",
        "actor.optimizer.lr=0.0001",
        "use_ref_model=true",
        "trainer_device=cpu",
        'gconfig={"n": 2, "max_new_tokens": 12}',
        'ppo={"ppo_n_minibatches": 1, "disable_value": true,'
        ' "use_decoupled_loss": false, "recompute_logprob": false}',
    ])
    rc = launcher.run_sync_ppo(cfg)
    assert rc == 0
    metrics = os.path.join(
        f"{tmp_path}/root", "logs", "sppo-test", "t0", "metrics.jsonl"
    )
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 2
    assert np.isfinite(lines[-1]["sync_ppo/actor_loss"])
    assert "sync_ppo/reward_mean" in lines[-1]
    save_dir = os.path.join(
        f"{tmp_path}/root", "checkpoints", "sppo-test", "t0", "step2"
    )
    assert os.path.exists(os.path.join(save_dir, "model.safetensors"))


@pytest.mark.slow
def test_async_ppo_experiment(tmp_path):
    """Full multiprocess async-PPO world for 2 training steps."""
    from areal_tpu.apps import launcher
    from areal_tpu.experiments import AsyncPPOExperiment, load_config

    data = str(tmp_path / "math.jsonl")
    _write_prompt_dataset(data)
    cfg = load_config(AsyncPPOExperiment, None, [
        "experiment_name=appo-test",
        "trial_name=t0",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "train_batch_size=2",
        "max_tokens_per_mb=512",
        "control.total_train_steps=2",
        "control.ckpt_freq_steps=null",
        "control.ckpt_freq_secs=null",
        f"actor.arch={json.dumps(TINY_ARCH)}",
        "actor.parallel=d1m1",
        "actor.optimizer.lr=0.0001",
        "use_ref_model=true",
        "gen.n_servers=1",
        "gen.max_slots=4",
        "gen.max_seqlen=256",
        "gen.device=cpu",
        "trainer_device=cpu",
        "rollout.n_workers=1",
        "rollout.max_concurrent_tasks=4",
        "rollout.new_tokens_per_chunk=8",
        "manager.max_head_offpolicyness=100",
        'gconfig={"n": 2, "max_new_tokens": 12}',
        'ppo={"ppo_n_minibatches": 1, "disable_value": true, "use_decoupled_loss": true}',
    ])
    assert cfg.gconfig.n == 2
    assert cfg.ppo.disable_value is True
    rc = launcher.run_async_ppo(cfg)
    assert rc == 0
    # trainer logged 2 PPO steps with finite losses
    metrics = os.path.join(
        f"{tmp_path}/root", "logs", "appo-test", "t0", "metrics.jsonl"
    )
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 2
    assert np.isfinite(lines[-1]["ppo/actor_loss"])
    # weight snapshots were published for the fleet (v0 + per-step)
    sync_root = os.path.join(
        f"{tmp_path}/root", "checkpoints", "appo-test", "t0", "weight_sync"
    )
    versions = sorted(os.listdir(sync_root))
    # v0 was published then pruned by the manager's keep-2 policy; the two
    # per-step snapshots remain
    assert versions == ["v1", "v2"]


def test_model_spec_overrides():
    from areal_tpu.experiments.config import ModelSpec

    spec = ModelSpec(
        arch=dict(
            n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8, hidden_dim=16,
            intermediate_dim=32, vocab_size=64,
        ),
        overrides=dict(attn_max_seqlen=256, remat_policy="dots_attn"),
    )
    cfg = spec.model_config()
    assert cfg.attn_max_seqlen == 256
    assert cfg.remat_policy == "dots_attn"


@pytest.mark.slow
def test_qwen7b_yaml_executes_scaled_down(tmp_path):
    """VERDICT r4 weak #7: the 7B serving config was 'paper math' — parse
    the REAL examples/qwen2_5_7b_async_v5e.yaml and RUN its assembled world
    with only size knobs overridden (tiny arch, 1 TP-2 server, short
    generations): every structural knob in the file (fleet layout, paging,
    chunking, GRPO group, decoupled loss, manager gate) flows end-to-end."""
    from areal_tpu.apps import launcher
    from areal_tpu.experiments import AsyncPPOExperiment, load_config

    yaml_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "qwen2_5_7b_async_v5e.yaml",
    )
    data = str(tmp_path / "math.jsonl")
    _write_prompt_dataset(data)
    cfg = load_config(AsyncPPOExperiment, yaml_path, [
        # size/scale overrides ONLY — structure comes from the file
        "trial_name=t7b",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "actor.path=null",
        f"actor.arch={json.dumps(TINY_ARCH)}",
        "actor.parallel=d1m1",
        "use_ref_model=false",
        "train_batch_size=8",
        "max_tokens_per_mb=512",
        "control.total_train_steps=1",
        "control.ckpt_freq_steps=null",
        "control.ckpt_freq_secs=null",
        "gen.n_servers=1",
        "gen.tp_size=2",
        "gen.max_slots=4",
        "gen.max_seqlen=256",
        "gen.max_new_tokens_cap=64",
        "gen.n_pages=64",
        "gen.device=cpu",
        "trainer_device=cpu",
        "rollout.n_workers=1",
        "rollout.max_concurrent_tasks=4",
        "rollout.new_tokens_per_chunk=8",
        'gconfig={"n": 2, "max_new_tokens": 12}',
        "manager.max_head_offpolicyness=100",
    ])
    # structural knobs straight from the yaml file
    assert cfg.gen.page_size == 128
    assert cfg.gen.decode_steps_per_chunk == 64
    assert cfg.rollout.agent == "math-single-step"
    assert cfg.ppo.use_decoupled_loss is True
    assert cfg.ppo.ppo_n_minibatches == 4
    assert cfg.ppo.disable_value is True
    assert cfg.control.weight_sync_freq_steps == 1
    # and the world actually runs: TP-2 gen server + manager + rollout +
    # trainer as processes
    rc = launcher.run_async_ppo(cfg)
    assert rc == 0
    metrics = os.path.join(
        f"{tmp_path}/root", "logs", "qwen2_5-7b-async", "t7b",
        "metrics.jsonl",
    )
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 1 and np.isfinite(lines[-1]["ppo/actor_loss"])


@pytest.mark.slow
def test_async_ppo_telemetry(tmp_path, monkeypatch):
    """ISSUE 5 acceptance: the same multiprocess async-PPO world with the
    telemetry exporter ENABLED produces a merged ``fleet/`` record in
    metrics.jsonl — fleet-total ``ft/`` counters from >= 2 distinct worker
    processes, a staleness histogram with observations and sane
    percentiles — and the ops CLI renders the published snapshots."""
    import subprocess
    import sys

    from areal_tpu.apps import launcher
    from areal_tpu.base import metrics as metrics_mod
    from areal_tpu.experiments import AsyncPPOExperiment, load_config

    # fast export period so every worker publishes several snapshots
    # within the ~1-minute run (spawned workers inherit the env)
    monkeypatch.setenv("AREAL_TELEMETRY_EXPORT", "0.5")
    data = str(tmp_path / "math.jsonl")
    _write_prompt_dataset(data)
    cfg = load_config(AsyncPPOExperiment, None, [
        "experiment_name=appo-tele",
        "trial_name=t0",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "train_batch_size=2",
        "max_tokens_per_mb=512",
        "control.total_train_steps=2",
        "control.ckpt_freq_steps=null",
        "control.ckpt_freq_secs=null",
        f"actor.arch={json.dumps(TINY_ARCH)}",
        "actor.parallel=d1m1",
        "actor.optimizer.lr=0.0001",
        "use_ref_model=false",
        "gen.n_servers=1",
        "gen.max_slots=4",
        "gen.max_seqlen=256",
        "gen.device=cpu",
        "trainer_device=cpu",
        "rollout.n_workers=1",
        "rollout.max_concurrent_tasks=4",
        "rollout.new_tokens_per_chunk=8",
        "manager.max_head_offpolicyness=100",
        'gconfig={"n": 2, "max_new_tokens": 12}',
        'ppo={"ppo_n_minibatches": 1, "disable_value": true, "use_decoupled_loss": true}',
    ])
    rc = launcher.run_async_ppo(cfg)
    assert rc == 0

    metrics = os.path.join(
        f"{tmp_path}/root", "logs", "appo-tele", "t0", "metrics.jsonl"
    )
    lines = [json.loads(l) for l in open(metrics)]
    step_lines = [l for l in lines if "ppo/actor_loss" in l]
    fleet_lines = [
        l for l in lines if any(k.startswith("fleet/") for k in l)
    ]
    assert len(step_lines) == 2
    assert fleet_lines, "trainer never folded a fleet/ record"
    rec = fleet_lines[-1]

    # every role published: trainer + manager + gen server + rollout
    # worker, each a distinct OS process
    assert rec["fleet/workers"] >= 3.0
    assert rec["fleet/worker_pids"] >= 2.0
    # fleet-total activity counters prove cross-process merge (the gen
    # server / rollout / manager counters only exist in THEIR processes)
    assert rec[f"fleet/{metrics_mod.TRAIN_STEPS}"] >= 1.0
    assert rec[f"fleet/{metrics_mod.ROLLOUT_PUSHED}"] > 0.0
    assert rec[f"fleet/{metrics_mod.GEN_SERVED}"] > 0.0
    assert rec[f"fleet/{metrics_mod.MANAGER_SCHEDULED}"] > 0.0
    # the full ft/ catalog is zero-filled; a healthy run reports zeros
    assert rec[f"fleet/{metrics_mod.FT_EVICTIONS}"] == 0.0
    assert rec[f"fleet/{metrics_mod.FT_ROLLOUT_DROPPED}"] == 0.0
    # breaker tallies from the manager's fleet view
    assert rec["fleet/servers_total"] == 1.0
    assert rec["fleet/servers_closed"] == 1.0

    # the paper's staleness story as a measured distribution: recorded at
    # the trainer's batch-commit point, merged through its live-registry
    # snapshot
    sv = f"fleet/{metrics_mod.STALENESS_VERSIONS}"
    assert rec[f"{sv}/count"] > 0
    p50, p95, p99 = rec[f"{sv}/p50"], rec[f"{sv}/p95"], rec[f"{sv}/p99"]
    assert 0.0 <= p50 <= p95 <= p99 <= rec[f"{sv}/max"]
    assert rec[f"{sv}/max"] <= cfg.manager.max_head_offpolicyness
    qw = f"fleet/{metrics_mod.QUEUE_WAIT_S}"
    assert rec[f"{qw}/count"] > 0
    assert rec[f"{qw}/p50"] >= 0.0

    # ops CLI renders the (persisted) snapshots post-mortem without error
    out = subprocess.run(
        [sys.executable, "-m", "areal_tpu.apps.obs",
         f"{tmp_path}/root", "--once"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "trainer" in out.stdout
    assert "rollout_worker/0" in out.stdout
    assert "gen_server/0" in out.stdout
    assert metrics_mod.STALENESS_VERSIONS in out.stdout
    # and the --json frame is the same flat scalar dict shape
    out = subprocess.run(
        [sys.executable, "-m", "areal_tpu.apps.obs",
         f"{tmp_path}/root", "--once", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    frame = json.loads(out.stdout)
    assert frame["workers"] >= 3.0
