"""End-to-end async rollout smoke test.

The whole generation-side architecture in one process (counterpart of the
reference's ``tests/experiments/test_math_ppo.py`` decoupled mode): a real
tiny-model generation HTTP server, the gserver manager (routing + staleness +
weight updates), a rollout worker driving the math agent through the chunked
generation client, ZMQ push → PullerStreamDataset, and finally a PPO train
step on the collected trajectories.
"""

import asyncio
import json
import os

import numpy as np
import pytest

import jax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    GenerationHyperparameters,
    PPOHyperparameters,
    make_interface,
)
from areal_tpu.base import name_resolve, names
from areal_tpu.agents.math_single_step import MathSingleStepAgent
from areal_tpu.envs.math_code_single_step import MathCodeSingleStepEnv
from areal_tpu.api.dataset import DatasetUtility
from areal_tpu.datasets.prompt import MathCodePromptDataset
from areal_tpu.gen.engine import GenerationEngine
from areal_tpu.gen.server import serve
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    serve_manager,
)
from areal_tpu.system.push_pull_stream import ZMQJsonPuller, ZMQJsonPusher
from areal_tpu.system.rollout_worker import RolloutWorker
from areal_tpu.system.stream_dataset import PullerStreamDataset
from areal_tpu.base import network

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)

EXP, TRIAL = "e2e", "t0"


def _write_dataset(path, rng, n=6, plen=8):
    with open(path, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "query_id": f"q{i}",
                        "prompt_ids": [int(x) for x in rng.integers(1, 128, plen)],
                        "task": "math",
                        "solutions": ["\\boxed{7}"],
                    }
                )
                + "\n"
            )


async def test_async_rollout_end_to_end(tmp_path, rng):
    name_resolve.reset()

    # --- generation server (tiny model) --------------------------------
    params = tfm.init_params(CFG, jax.random.key(0))
    eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=256, seed=0)
    gen_port = network.find_free_port()
    gen_runner = await serve(eng, "127.0.0.1", gen_port, decode_steps=4)
    gen_url = f"http://127.0.0.1:{gen_port}"
    name_resolve.add(names.gen_server(EXP, TRIAL, 0), gen_url, replace=True)

    # --- gserver manager ------------------------------------------------
    mcfg = GserverManagerConfig(
        experiment_name=EXP, trial_name=TRIAL, train_batch_size=4,
        max_head_offpolicyness=100, max_concurrent_rollouts=8,
    )
    manager = GserverManager(mcfg)
    manager.discover_servers()
    assert manager.server_urls == [gen_url]
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    # --- dataset / env / agent -----------------------------------------
    data_path = str(tmp_path / "math.jsonl")
    _write_dataset(data_path, rng)
    util = DatasetUtility(seed=1, dp_rank=0, world_size=1)
    dataset = MathCodePromptDataset(util=util, path=data_path)
    env = MathCodeSingleStepEnv(dataset.load_metadata())
    agent = MathSingleStepAgent(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=16),
        answer_save_path=str(tmp_path / "answers"),
    )

    # --- ZMQ plumbing (explicit, single process) ------------------------
    pull_port = network.find_free_port()
    puller = ZMQJsonPuller("*", pull_port, default_timeout_ms=200)
    pusher = ZMQJsonPusher("127.0.0.1", pull_port)
    stream = PullerStreamDataset(
        EXP, TRIAL, 0, offline_dataset_size=len(dataset), puller=puller
    )

    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=agent, env=env, dataset=dataset,
        new_tokens_per_chunk=8,  # forces chunked re-scheduling
        max_concurrent_tasks=4, pusher=pusher,
        manager_url=f"http://127.0.0.1:{mgr_port}",
    )

    run_task = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        samples = []
        for _ in range(600):  # up to ~60s
            await asyncio.sleep(0.1)
            samples.extend(stream.get_batch(8, timeout=0.01))
            if len(samples) >= 4:
                break
        assert len(samples) >= 4, (
            f"only {len(samples)} trajectories arrived; "
            f"pushed={worker.push_cnt}"
        )
    finally:
        run_task.cancel()

    # --- trajectory structure -------------------------------------------
    s = samples[0]
    assert s.keys >= {
        "packed_input_ids", "prompt_mask", "packed_logprobs", "rewards",
        "seq_no_eos_mask", "version_start", "version_end",
    }
    group = len(s.seqlens["packed_input_ids"][0])
    assert group == 2  # gconfig.n
    total = sum(s.seqlens["packed_input_ids"][0])
    assert s.data["packed_input_ids"].shape[0] == total
    assert s.data["packed_logprobs"].shape[0] == total
    # chunked generation really happened across >1 chunk per sequence
    assert manager.rollout_stat.accepted >= 2

    # --- weight update path ---------------------------------------------
    from areal_tpu.models import hf as hf_conv

    ckpt = str(tmp_path / "v1")
    import dataclasses as dc

    cfg32 = dc.replace(CFG, use_attention_bias=True)
    params2 = tfm.init_params(cfg32, jax.random.key(1))
    hf_conv.save_hf_checkpoint(params2, cfg32, "qwen2", ckpt)
    name_resolve.add(
        names.model_version(EXP, TRIAL, "actor"), f"1:{ckpt}", replace=True
    )
    path = await manager.check_new_params()
    assert path == ckpt and manager.version == 1 and eng.version == 1

    # --- PPO training consumes the stream batch -------------------------
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    batch = SequenceSample.gather(
        samples[:4],
        keys={"packed_input_ids", "prompt_mask", "packed_logprobs",
              "rewards", "seq_no_eos_mask"},
    )
    teng = TrainEngine(
        CFG, ParallelConfig(data=2, fsdp=1, model=1), OptimizerConfig(lr=1e-4)
    )
    teng.init_random(0)
    teng.setup_optimizer(10)
    actor = make_interface(
        "ppo_actor",
        hp=PPOHyperparameters(
            ppo_n_minibatches=1, disable_value=True, adv_norm=True,
            use_decoupled_loss=False, recompute_logprob=False,
        ),
    )
    stats = actor.train_step(teng, batch, MicroBatchSpec(max_tokens_per_mb=256))
    assert np.isfinite(stats["actor_loss"])

    stream.close()
    await gen_runner.cleanup()
    await mgr_runner.cleanup()
