"""End-to-end async rollout smoke test.

The whole generation-side architecture in one process (counterpart of the
reference's ``tests/experiments/test_math_ppo.py`` decoupled mode): a real
tiny-model generation HTTP server, the gserver manager (routing + staleness +
weight updates), a rollout worker driving the math agent through the chunked
generation client, ZMQ push → PullerStreamDataset, and finally a PPO train
step on the collected trajectories.
"""

import asyncio
import json
import os

import numpy as np
import pytest

import jax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    GenerationHyperparameters,
    PPOHyperparameters,
    make_interface,
)
from areal_tpu.base import name_resolve, names
from areal_tpu.agents.math_single_step import MathSingleStepAgent
from areal_tpu.envs.math_code_single_step import MathCodeSingleStepEnv
from areal_tpu.api.dataset import DatasetUtility
from areal_tpu.datasets.prompt import MathCodePromptDataset
from areal_tpu.gen.engine import GenerationEngine
from areal_tpu.gen.server import serve
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    serve_manager,
)
from areal_tpu.system.push_pull_stream import ZMQJsonPuller, ZMQJsonPusher
from areal_tpu.system.rollout_worker import RolloutWorker
from areal_tpu.system.stream_dataset import PullerStreamDataset
from areal_tpu.base import network

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)

EXP, TRIAL = "e2e", "t0"


def _write_dataset(path, rng, n=6, plen=8):
    with open(path, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "query_id": f"q{i}",
                        "prompt_ids": [int(x) for x in rng.integers(1, 128, plen)],
                        "task": "math",
                        "solutions": ["\\boxed{7}"],
                    }
                )
                + "\n"
            )


@pytest.mark.parametrize("pipelined", [False, True],
                         ids=["plain", "pipelined"])
async def test_async_rollout_end_to_end(tmp_path, rng, pipelined):
    """Full async rollout loop; parametrized over the chunk-pipelined
    decode mode (r5) so the deferred-harvest engine is exercised through
    the REAL server + manager + partial-rollout world, not just unit
    tests."""
    name_resolve.reset()

    # --- generation server (tiny model) --------------------------------
    params = tfm.init_params(CFG, jax.random.key(0))
    eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=256, seed=0,
                           pipeline_chunks=pipelined)
    gen_port = network.find_free_port()
    gen_runner = await serve(eng, "127.0.0.1", gen_port, decode_steps=4)
    gen_url = f"http://127.0.0.1:{gen_port}"
    name_resolve.add(names.gen_server(EXP, TRIAL, 0), gen_url, replace=True)

    # --- gserver manager ------------------------------------------------
    mcfg = GserverManagerConfig(
        experiment_name=EXP, trial_name=TRIAL, train_batch_size=4,
        max_head_offpolicyness=100, max_concurrent_rollouts=8,
    )
    manager = GserverManager(mcfg)
    manager.discover_servers()
    assert manager.server_urls == [gen_url]
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    # --- dataset / env / agent -----------------------------------------
    data_path = str(tmp_path / "math.jsonl")
    _write_dataset(data_path, rng)
    util = DatasetUtility(seed=1, dp_rank=0, world_size=1)
    dataset = MathCodePromptDataset(util=util, path=data_path)
    env = MathCodeSingleStepEnv(dataset.load_metadata())
    agent = MathSingleStepAgent(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=16),
        answer_save_path=str(tmp_path / "answers"),
    )

    # --- ZMQ plumbing (explicit, single process) ------------------------
    pull_port = network.find_free_port()
    puller = ZMQJsonPuller("*", pull_port, default_timeout_ms=200)
    pusher = ZMQJsonPusher("127.0.0.1", pull_port)
    stream = PullerStreamDataset(
        EXP, TRIAL, 0, offline_dataset_size=len(dataset), puller=puller
    )

    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=agent, env=env, dataset=dataset,
        new_tokens_per_chunk=8,  # forces chunked re-scheduling
        max_concurrent_tasks=4, pusher=pusher,
        manager_url=f"http://127.0.0.1:{mgr_port}",
    )

    run_task = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        samples = []
        for _ in range(600):  # up to ~60s
            await asyncio.sleep(0.1)
            samples.extend(stream.get_batch(8, timeout=0.01))
            if len(samples) >= 4:
                break
        assert len(samples) >= 4, (
            f"only {len(samples)} trajectories arrived; "
            f"pushed={worker.push_cnt}"
        )
    finally:
        run_task.cancel()

    # --- trajectory structure -------------------------------------------
    s = samples[0]
    assert s.keys >= {
        "packed_input_ids", "prompt_mask", "packed_logprobs", "rewards",
        "seq_no_eos_mask", "version_start", "version_end",
    }
    group = len(s.seqlens["packed_input_ids"][0])
    assert group == 2  # gconfig.n
    total = sum(s.seqlens["packed_input_ids"][0])
    assert s.data["packed_input_ids"].shape[0] == total
    assert s.data["packed_logprobs"].shape[0] == total
    # chunked generation really happened across >1 chunk per sequence
    assert manager.rollout_stat.accepted >= 2

    # --- weight update path ---------------------------------------------
    from areal_tpu.models import hf as hf_conv

    ckpt = str(tmp_path / "v1")
    import dataclasses as dc

    cfg32 = dc.replace(CFG, use_attention_bias=True)
    params2 = tfm.init_params(cfg32, jax.random.key(1))
    hf_conv.save_hf_checkpoint(params2, cfg32, "qwen2", ckpt)
    name_resolve.add(
        names.model_version(EXP, TRIAL, "actor"), f"1:{ckpt}", replace=True
    )
    path = await manager.check_new_params()
    assert path == ckpt and manager.version == 1 and eng.version == 1

    # --- PPO training consumes the stream batch -------------------------
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    batch = SequenceSample.gather(
        samples[:4],
        keys={"packed_input_ids", "prompt_mask", "packed_logprobs",
              "rewards", "seq_no_eos_mask"},
    )
    teng = TrainEngine(
        CFG, ParallelConfig(data=2, fsdp=1, model=1), OptimizerConfig(lr=1e-4)
    )
    teng.init_random(0)
    teng.setup_optimizer(10)
    actor = make_interface(
        "ppo_actor",
        hp=PPOHyperparameters(
            ppo_n_minibatches=1, disable_value=True, adv_norm=True,
            use_decoupled_loss=False, recompute_logprob=False,
        ),
    )
    stats = actor.train_step(teng, batch, MicroBatchSpec(max_tokens_per_mb=256))
    assert np.isfinite(stats["actor_loss"])

    stream.close()
    await gen_runner.cleanup()
    await mgr_runner.cleanup()


async def test_weight_sync_sharded_trainer_to_tp_gen_server(tmp_path, rng):
    """VERDICT r2 #6: the full weight-sync channel across HETEROGENEOUS
    placements — trainer params sharded over a 4-device dp x tp mesh,
    generation served TP-sharded on a DIFFERENT 2-device block — driven
    through TWO complete round trips:
    train_step -> save_hf (gathers shards) -> name_resolve version bump ->
    manager fan-out (HTTP update_weights_from_disk) -> TP engine re-shard.
    After each swap the engine's greedy outputs must match the trainer's
    current policy, and version tags must propagate to outputs."""
    import dataclasses as dc

    from jax.sharding import Mesh
    from areal_tpu.models import hf as hf_conv
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    name_resolve.reset()
    exp, trial = "e2e-sync", "t0"
    cfg = dc.replace(CFG, use_attention_bias=True)  # qwen2-exportable

    # trainer: d2 x m2 over devices [0:4]
    teng = TrainEngine(
        cfg, ParallelConfig(data=2, model=2), OptimizerConfig(lr=5e-2)
    )
    teng.init_random(0)
    teng.setup_optimizer(10)

    # generation server: TP over devices [4:6]
    gmesh = Mesh(np.array(jax.devices()[4:6]), ("model",))
    ckpt0 = str(tmp_path / "v0")
    teng.save_hf(ckpt0, "qwen2")
    _, host0 = hf_conv.load_hf_checkpoint(ckpt0)
    geng = GenerationEngine(
        cfg, host0, max_slots=2, max_seqlen=128, seed=0, mesh=gmesh
    )
    gen_port = network.find_free_port()
    gen_runner = await serve(geng, "127.0.0.1", gen_port, decode_steps=4)
    name_resolve.add(
        names.gen_server(exp, trial, 0),
        f"http://127.0.0.1:{gen_port}", replace=True,
    )

    mcfg = GserverManagerConfig(
        experiment_name=exp, trial_name=trial, train_batch_size=4,
        max_head_offpolicyness=1, max_concurrent_rollouts=8,
    )
    manager = GserverManager(mcfg)
    manager.discover_servers()
    mgr_runner = await serve_manager(manager, "127.0.0.1", network.find_free_port())

    import aiohttp

    async def greedy_via_server(n=6):
        """Probe through the HTTP endpoint — the engine is owned by the
        server's background loop; direct step() calls would race it."""
        async with aiohttp.ClientSession() as sess:
            async with sess.post(
                f"http://127.0.0.1:{gen_port}/generate",
                json={
                    "rid": f"probe{np.random.randint(1 << 30)}",
                    "input_ids": [3, 14, 15, 9, 2],
                    "sampling_params": {"max_new_tokens": n, "greedy": True},
                },
            ) as r:
                d = await r.json()
        import types

        return types.SimpleNamespace(
            output_ids=d["output_ids"], version=d["version"]
        )

    def trainer_greedy(n=6):
        """Teacher-forced argmax chain on the trainer's CURRENT params."""
        host = jax.tree.map(np.asarray, multihost_gather(teng))
        ids = [3, 14, 15, 9, 2]
        for _ in range(n):
            T = len(ids)
            pad = ((T + 127) // 128) * 128
            logits = tfm.forward_packed(
                jax.tree.map(jnp_asarray, host), cfg,
                _arr(np.r_[ids, np.zeros(pad - T)], np.int32),
                _arr(np.r_[np.ones(T), np.zeros(pad - T)], np.int32),
                _arr(np.r_[np.arange(T), np.zeros(pad - T)], np.int32),
                remat=False,
            )
            ids.append(int(np.argmax(np.asarray(logits)[T - 1])))
        return ids[5:]

    import jax.numpy as _jnp

    def multihost_gather(eng):
        from areal_tpu.parallel import multihost
        return multihost.gather_params_to_host(eng.params)

    def jnp_asarray(x):
        return _jnp.asarray(x)

    def _arr(x, dt):
        return _jnp.asarray(np.asarray(x, dt))

    def train_one_step():
        n, t = 4, 24
        sample = SequenceSample.from_default(
            ids=list(range(n)), seqlens=[t] * n,
            data={
                "packed_input_ids": np.random.default_rng(1).integers(
                    5, 120, size=n * t
                ).astype(np.int64),
                "prompt_mask": np.tile(
                    np.r_[np.ones(4, np.bool_), np.zeros(t - 4, np.bool_)], n
                ),
            },
        )
        from areal_tpu.interfaces.sft import sft_loss_fn
        teng.train_batch(sample, MicroBatchSpec(max_tokens_per_mb=128),
                         sft_loss_fn)

    try:
        # round trip 1
        train_one_step()
        ckpt1 = str(tmp_path / "v1")
        teng.save_hf(ckpt1, "qwen2")
        name_resolve.add(
            names.model_version(exp, trial, "actor"), f"1:{ckpt1}",
            replace=True,
        )
        path = await manager.check_new_params()
        assert path == ckpt1 and manager.version == 1 and geng.version == 1
        # the TP engine now serves the trainer's post-step policy, sharded
        assert geng.params["layers"]["attn"]["wq"].sharding.spec[-1] == "model"
        out1 = await greedy_via_server()
        assert out1.version == 1
        assert out1.output_ids == trainer_greedy()

        # round trip 2 (lr is large so params demonstrably moved)
        train_one_step()
        ckpt2 = str(tmp_path / "v2")
        teng.save_hf(ckpt2, "qwen2")
        name_resolve.add(
            names.model_version(exp, trial, "actor"), f"2:{ckpt2}",
            replace=True,
        )
        path = await manager.check_new_params()
        assert path == ckpt2 and manager.version == 2 and geng.version == 2
        out2 = await greedy_via_server()
        assert out2.version == 2
        assert out2.output_ids == trainer_greedy()

        # staleness gate reflects the synced version: with version=2 and
        # max_head_offpolicyness=1, intake stays open until training_samples
        # implies a version > 3
        name_resolve.add(
            names.training_samples(exp, trial), "12", replace=True
        )
        assert not manager.is_staled()   # 12 // 4 = 3 <= 2 + 1
        name_resolve.add(
            names.training_samples(exp, trial), "16", replace=True
        )
        assert manager.is_staled()       # 16 // 4 = 4 > 3
    finally:
        await gen_runner.cleanup()
        await mgr_runner.cleanup()
