"""Gserver manager tests with stub generation servers.

Counterpart of ``tests/system/test_gserver_manager.py``: scheduling policies,
sticky qid routing, staleness gating, weight-update fan-out.
"""

import asyncio
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from areal_tpu.base import name_resolve, names
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
)

class StubGenServer:
    """Mock generation server recording update_weights calls.
    ``fail_updates=True`` makes it report update failure (success=False)."""

    def __init__(self, fail_updates: bool = False):
        self.update_calls = []
        self.fail_updates = fail_updates
        self.app = web.Application()
        self.app.router.add_post(
            "/update_weights_from_disk", self._update
        )
        self.app.router.add_get("/health", lambda r: web.json_response({}))

    async def _update(self, request):
        d = await request.json()
        self.update_calls.append(d)
        if self.fail_updates:
            return web.json_response(
                {"success": False, "message": "disk error"}
            )
        return web.json_response(
            {"success": True, "message": "ok", "num_paused_requests": 2}
        )


@pytest.fixture
def cfg():
    name_resolve.reset()
    return GserverManagerConfig(
        experiment_name="t", trial_name="t", train_batch_size=4,
        max_head_offpolicyness=1, max_concurrent_rollouts=3,
    )


async def _client(manager):
    server = TestServer(manager.app)
    client = TestClient(server)
    await client.start_server()
    return client


async def test_round_robin_and_sticky(cfg):
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    c = await _client(m)
    urls = []
    for i in range(4):
        r = await c.post(
            "/schedule_request",
            json={"qid": f"q{i}", "prompt_len": 10, "group_size": 2,
                  "new_token_budget": 100},
        )
        urls.append((await r.json())["url"])
    assert urls == ["http://a", "http://b", "http://a", "http://b"]
    # same qid → same server (sticky)
    r = await c.post("/schedule_request", json={"qid": "q0", "prompt_len": 1,
                                                "group_size": 1, "new_token_budget": 1})
    assert (await r.json())["url"] == "http://a"
    await c.close()


async def test_least_requests_policy(cfg):
    import dataclasses

    cfg = dataclasses.replace(cfg, schedule_policy="least_requests")
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    m._request_counts["http://a"] = 5
    c = await _client(m)
    r = await c.post("/schedule_request", json={"qid": "x", "prompt_len": 1,
                                                "group_size": 1, "new_token_budget": 1})
    assert (await r.json())["url"] == "http://b"
    await c.close()


async def test_staleness_gate(cfg):
    m = GserverManager(cfg, server_urls=["http://a"])
    c = await _client(m)
    # version 0, batch 4, offpolicyness 1 => allow until
    # (trained + running) // 4 > 1, i.e. 8 running
    oks = []
    for i in range(10):
        r = await c.post("/allocate_rollout", json={"qid": f"q{i}"})
        oks.append((await r.json())["success"])
    # capacity cap (3) kicks in first here
    assert oks[:3] == [True] * 3 and not any(oks[3:])
    # free capacity: finish two; staleness then still allows more
    for i in range(2):
        await c.post("/finish_rollout", json={"qid": f"q{i}", "accepted": True})
    r = await c.post("/allocate_rollout", json={"qid": "q10"})
    assert (await r.json())["success"]

    # trainer reports many consumed samples without version bump -> staled
    name_resolve.add(
        names.training_samples("t", "t"), "64", replace=True
    )
    r = await c.post("/allocate_rollout", json={"qid": "q11"})
    d = await r.json()
    assert not d["success"] and "staled" in d["reason"]

    # version bump unblocks
    m.version = 100
    r = await c.post("/allocate_rollout", json={"qid": "q12"})
    assert (await r.json())["success"]
    await c.close()


async def test_weight_update_fanout(cfg, tmp_path):
    stubs = [StubGenServer(), StubGenServer()]
    servers = []
    urls = []
    for s in stubs:
        ts = TestServer(s.app)
        await ts.start_server()
        servers.append(ts)
        urls.append(str(ts.make_url("")).rstrip("/"))
    m = GserverManager(cfg, server_urls=urls)

    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version("t", "t", "actor"), f"1:{ckpt}", replace=True
    )
    path = await m.check_new_params()
    assert path == str(ckpt)
    assert m.version == 1
    for s in stubs:
        assert len(s.update_calls) == 1
        assert s.update_calls[0]["model_path"] == str(ckpt)
        assert s.update_calls[0]["allow_interrupt"] is True
    # no re-update on same version
    assert await m.check_new_params() is None
    for ts in servers:
        await ts.close()


async def _start_stubs(stubs):
    servers, urls = [], []
    for s in stubs:
        ts = TestServer(s.app)
        await ts.start_server()
        servers.append(ts)
        urls.append(str(ts.make_url("")).rstrip("/"))
    return servers, urls


async def test_weight_update_partial_failure_proceeds_on_survivors(
    cfg, tmp_path
):
    """One server reporting failure must not block the fleet: survivors get
    the new version, the failure is evicted, and the version advances."""
    stubs = [StubGenServer(), StubGenServer(fail_updates=True), StubGenServer()]
    servers, urls = await _start_stubs(stubs)
    m = GserverManager(cfg, server_urls=urls)
    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version("t", "t", "actor"), f"1:{ckpt}", replace=True
    )
    assert await m.check_new_params() == str(ckpt)
    assert m.version == 1
    for i in (0, 2):
        assert len(stubs[i].update_calls) == 1
        assert m.fleet.get(urls[i]).acked_version == 1
    assert m.fleet.get(urls[1]).state == "open"
    assert set(m.fleet.healthy_urls()) == {urls[0], urls[2]}
    for ts in servers:
        await ts.close()


async def test_poll_loop_does_not_hot_loop_after_partial_failure(
    cfg, tmp_path
):
    """The version bumps despite a failed server, so subsequent poll ticks
    are no-ops — the old behavior re-flushed the whole fleet every 0.5s
    forever (and never advanced the version)."""
    stubs = [StubGenServer(), StubGenServer(fail_updates=True)]
    servers, urls = await _start_stubs(stubs)
    m = GserverManager(cfg, server_urls=urls)
    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version("t", "t", "actor"), f"1:{ckpt}", replace=True
    )
    assert await m.check_new_params() == str(ckpt)
    assert m.version == 1
    # several poll ticks: nothing re-flushes, neither survivor nor corpse
    for _ in range(5):
        assert await m.check_new_params() is None
    assert len(stubs[0].update_calls) == 1
    assert len(stubs[1].update_calls) == 1
    # the evicted server is also out of the next version's fan-out
    ckpt2 = tmp_path / "v2"
    ckpt2.mkdir()
    name_resolve.add(
        names.model_version("t", "t", "actor"), f"2:{ckpt2}", replace=True
    )
    assert await m.check_new_params() == str(ckpt2)
    assert len(stubs[0].update_calls) == 2
    assert len(stubs[1].update_calls) == 1
    for ts in servers:
        await ts.close()


async def test_prune_respects_unacked_servers(cfg, tmp_path):
    """A checkpoint dir is only deleted once every *healthy* server has
    acked a version >= the dir's (a slow loader may still be reading it)."""
    import dataclasses

    cfg = dataclasses.replace(cfg, n_checkpoints_to_keep=1)
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    dirs = []
    for v in (1, 2, 3):
        d = tmp_path / f"v{v}"
        d.mkdir()
        dirs.append(str(d))
        m._ckpt_dirs.append(str(d))
        m._ckpt_versions[str(d)] = v
    # a acked v3, b lags at v1 → v1's dir may go (min_acked 1 >= 1), but
    # v2's dir must survive (b may still be loading it)
    m.fleet.ack_version("http://a", 3)
    m.fleet.ack_version("http://b", 1)
    m._prune_checkpoints()
    assert m._ckpt_dirs == dirs[1:]
    assert not (tmp_path / "v1").exists()
    assert (tmp_path / "v2").exists()
    # b catches up → v2's dir becomes prunable
    m.fleet.ack_version("http://b", 3)
    m._prune_checkpoints()
    assert m._ckpt_dirs == dirs[2:]
    assert not (tmp_path / "v2").exists()
    assert (tmp_path / "v3").exists()
    # an EVICTED laggard does not block pruning (it catches up from the
    # newest dir on re-admission)
    m._ckpt_dirs.insert(0, str(tmp_path / "v2b"))
    (tmp_path / "v2b").mkdir()
    m._ckpt_versions[str(tmp_path / "v2b")] = 2
    m.fleet.ack_version("http://a", 2)  # no-op (already 3)
    m.fleet.get("http://b").acked_version = 1
    m.fleet.evict("http://b", "test")
    m._prune_checkpoints()
    assert not (tmp_path / "v2b").exists()


async def test_all_breakers_open_answers_503_with_retry_after(cfg):
    """Every backend evicted/breaker-open: /schedule_request must answer
    503 with an honest Retry-After (the probe cooldown) instead of
    routing into a known-dead fleet."""
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    for u in ["http://a", "http://b"]:
        m.fleet.evict(u, "test: breaker open")
    c = await _client(m)
    r = await c.post(
        "/schedule_request",
        json={"qid": "q-dead", "prompt_len": 1, "group_size": 1,
              "new_token_budget": 1},
    )
    assert r.status == 503
    assert int(r.headers["Retry-After"]) >= 1
    await c.close()


async def test_report_failure_attributes_qid_in_breaker_reason(cfg):
    """Every rollout worker sends the failing rollout's qid with
    /report_failure; the manager must keep it in the breaker's
    last_failure_reason so evictions in fleet state dumps are
    attributable to a specific rollout (regression: the handler used to
    drop the field on the floor)."""
    m = GserverManager(cfg, server_urls=["http://a"])
    c = await _client(m)
    r = await c.post(
        "/report_failure",
        json={"url": "http://a", "reason": "connect timeout",
              "qid": "q-42"},
    )
    assert r.status == 200
    s = m.fleet.get("http://a")
    assert "connect timeout" in s.last_failure_reason
    assert "qid=q-42" in s.last_failure_reason
    # reporters that predate the qid field still work
    r = await c.post(
        "/report_failure", json={"url": "http://a", "reason": "refused"}
    )
    assert r.status == 200
    assert "qid=" not in m.fleet.get("http://a").last_failure_reason
    await c.close()
