"""Gserver manager tests with stub generation servers.

Counterpart of ``tests/system/test_gserver_manager.py``: scheduling policies,
sticky qid routing, staleness gating, weight-update fan-out.
"""

import asyncio
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from areal_tpu.base import name_resolve, names
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
)

class StubGenServer:
    """Mock generation server recording update_weights calls."""

    def __init__(self):
        self.update_calls = []
        self.app = web.Application()
        self.app.router.add_post(
            "/update_weights_from_disk", self._update
        )
        self.app.router.add_get("/health", lambda r: web.json_response({}))

    async def _update(self, request):
        d = await request.json()
        self.update_calls.append(d)
        return web.json_response(
            {"success": True, "message": "ok", "num_paused_requests": 2}
        )


@pytest.fixture
def cfg():
    name_resolve.reset()
    return GserverManagerConfig(
        experiment_name="t", trial_name="t", train_batch_size=4,
        max_head_offpolicyness=1, max_concurrent_rollouts=3,
    )


async def _client(manager):
    server = TestServer(manager.app)
    client = TestClient(server)
    await client.start_server()
    return client


async def test_round_robin_and_sticky(cfg):
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    c = await _client(m)
    urls = []
    for i in range(4):
        r = await c.post(
            "/schedule_request",
            json={"qid": f"q{i}", "prompt_len": 10, "group_size": 2,
                  "new_token_budget": 100},
        )
        urls.append((await r.json())["url"])
    assert urls == ["http://a", "http://b", "http://a", "http://b"]
    # same qid → same server (sticky)
    r = await c.post("/schedule_request", json={"qid": "q0", "prompt_len": 1,
                                                "group_size": 1, "new_token_budget": 1})
    assert (await r.json())["url"] == "http://a"
    await c.close()


async def test_least_requests_policy(cfg):
    import dataclasses

    cfg = dataclasses.replace(cfg, schedule_policy="least_requests")
    m = GserverManager(cfg, server_urls=["http://a", "http://b"])
    m._request_counts["http://a"] = 5
    c = await _client(m)
    r = await c.post("/schedule_request", json={"qid": "x", "prompt_len": 1,
                                                "group_size": 1, "new_token_budget": 1})
    assert (await r.json())["url"] == "http://b"
    await c.close()


async def test_staleness_gate(cfg):
    m = GserverManager(cfg, server_urls=["http://a"])
    c = await _client(m)
    # version 0, batch 4, offpolicyness 1 => allow until
    # (trained + running) // 4 > 1, i.e. 8 running
    oks = []
    for i in range(10):
        r = await c.post("/allocate_rollout", json={"qid": f"q{i}"})
        oks.append((await r.json())["success"])
    # capacity cap (3) kicks in first here
    assert oks[:3] == [True] * 3 and not any(oks[3:])
    # free capacity: finish two; staleness then still allows more
    for i in range(2):
        await c.post("/finish_rollout", json={"qid": f"q{i}", "accepted": True})
    r = await c.post("/allocate_rollout", json={"qid": "q10"})
    assert (await r.json())["success"]

    # trainer reports many consumed samples without version bump -> staled
    name_resolve.add(
        names.training_samples("t", "t"), "64", replace=True
    )
    r = await c.post("/allocate_rollout", json={"qid": "q11"})
    d = await r.json()
    assert not d["success"] and "staled" in d["reason"]

    # version bump unblocks
    m.version = 100
    r = await c.post("/allocate_rollout", json={"qid": "q12"})
    assert (await r.json())["success"]
    await c.close()


async def test_weight_update_fanout(cfg, tmp_path):
    stubs = [StubGenServer(), StubGenServer()]
    servers = []
    urls = []
    for s in stubs:
        ts = TestServer(s.app)
        await ts.start_server()
        servers.append(ts)
        urls.append(str(ts.make_url("")).rstrip("/"))
    m = GserverManager(cfg, server_urls=urls)

    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version("t", "t", "actor"), f"1:{ckpt}", replace=True
    )
    path = await m.check_new_params()
    assert path == str(ckpt)
    assert m.version == 1
    for s in stubs:
        assert len(s.update_calls) == 1
        assert s.update_calls[0]["model_path"] == str(ckpt)
        assert s.update_calls[0]["allow_interrupt"] is True
    # no re-update on same version
    assert await m.check_new_params() is None
    for ts in servers:
        await ts.close()
