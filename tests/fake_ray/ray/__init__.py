"""Minimal in-process stand-in for the ``ray`` package (test asset).

Implements exactly the surface ``RaySchedulerClient`` consumes —
``init``/``is_initialized``, the ``@ray.remote`` decorator with
``.options(...).remote(...)``, ``wait``/``get``/``cancel``, and
``exceptions.TaskCancelledError`` — executing each remote task in a
forked daemon process. ``cancel`` delivers SIGINT so the task's
``finally`` block runs (the client relies on it to SIGTERM the worker's
process group), like Ray's non-force cancel raising inside the task.
"""

import multiprocessing as mp
import os
import signal
import time

_CTX = mp.get_context("fork")  # remote fns are closures: not picklable
_inited = False


class TaskCancelledError(Exception):
    pass


class exceptions:  # noqa: N801 - mirrors ray.exceptions
    TaskCancelledError = TaskCancelledError


def init(address=None, runtime_env=None, ignore_reinit_error=True, **kw):
    global _inited
    _inited = True


def is_initialized():
    return _inited


class _Ref:
    def __init__(self, fn, args, name):
        self.name = name
        self._q = _CTX.Queue()
        self._result = None     # ("ok", rc) | ("err", msg) | ("cancelled",)
        self._proc = _CTX.Process(
            target=self._entry, args=(fn, args), daemon=True
        )
        self._proc.start()

    def _entry(self, fn, args):
        try:
            rc = fn(*args)
            self._q.put(("ok", rc))
        except KeyboardInterrupt:
            self._q.put(("cancelled", None))
        except BaseException as e:  # noqa: BLE001
            self._q.put(("err", repr(e)))
        finally:
            # flush the queue's feeder thread BEFORE the hard exit (which
            # skips the parent's jax-laden atexit machinery)
            self._q.close()
            self._q.join_thread()
            os._exit(0)

    def _poll(self):
        if self._result is None:
            try:
                self._result = self._q.get_nowait()
            except Exception:
                if not self._proc.is_alive():
                    # died without reporting (SIGKILL): a moment for a
                    # late queue flush, then record the crash
                    time.sleep(0.05)
                    try:
                        self._result = self._q.get_nowait()
                    except Exception:
                        self._result = ("err", "task process died")
        return self._result is not None


class _RemoteFunction:
    def __init__(self, fn, opts=None):
        self._fn = fn
        self._opts = opts or {}

    def options(self, **kw):
        return _RemoteFunction(self._fn, {**self._opts, **kw})

    def remote(self, *args):
        return _Ref(self._fn, args, self._opts.get("name", "task"))


def remote(fn):
    return _RemoteFunction(fn)


def wait(refs, timeout=None):
    t0 = time.monotonic()
    while True:
        ready = [r for r in refs if r._poll()]
        if ready or timeout is not None and time.monotonic() - t0 >= timeout:
            return ready, [r for r in refs if r not in ready]
        time.sleep(0.02)


def get(ref):
    while not ref._poll():
        time.sleep(0.02)
    kind, val = ref._result
    if kind == "ok":
        return val
    if kind == "cancelled":
        raise TaskCancelledError(ref.name)
    raise RuntimeError(val)


def cancel(ref, force=False):
    if ref._proc.is_alive():
        # non-force: SIGINT -> KeyboardInterrupt inside the task, its
        # finally runs (the scheduler client kills the worker's pgroup)
        try:
            os.kill(ref._proc.pid, signal.SIGKILL if force else signal.SIGINT)
        except ProcessLookupError:
            pass  # exited between is_alive() and the kill
