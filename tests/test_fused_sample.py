"""Fused LM-head + sampling epilogue: exactness, distribution, and engine
composition (docs/performance.md "Fused sampling epilogue").

The load-bearing contracts:
- greedy slots are TOKEN-exact and logprob-exact (up to float
  associativity) vs the materialize-then-sample reference, at the op level
  across block sizes and through the full engine;
- temperature / top-k / exclusion sampling is distribution-exact
  (chi-square on a toy vocab) — same marginal, different RNG stream;
- the fused spec acceptance (``fused_spec_rejection``) preserves the
  reference rejection-sampling semantics: greedy spec-over-fused equals
  vanilla decode token for token;
- composition: warp-bucket fallback rows (top-p), pause/resume, tp2
  serving, bounded compiles, adaptive spec-K, telemetry counters;
- the ``sample_tokens(warp=False)`` gather-then-normalize logprob fast
  path equals the full ``log_softmax`` formulation exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.sampling import (
    SamplingParams,
    _plain_temperature,
    sample_tokens,
    spec_rejection_sample,
)
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.ops import fused_sample as fs

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)

# chi-square threshold: df = 15 (16-token toy vocab), p ~ 1e-4
CHI2_CRIT = 45.0
N_DRAWS = 20000


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


def _engine(params, spec=False, fused=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seqlen", 128)
    return GenerationEngine(
        CFG, params, spec_decode=spec, fused_sample=fused, **kw
    )


def _prompts(rng, sizes=(5, 9, 3)):
    return [[int(x) for x in rng.integers(1, 128, size=n)] for n in sizes]


def _head_problem(R=6, E=32, V=500, seed=0):
    kx, kw = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(kx, (R, E), jnp.float32)
    w = jax.random.normal(kw, (E, V), jnp.float32) * 0.3
    return x, w, (x @ w).astype(jnp.float32)


class TestOpParity:
    """fused_sample vs sample_tokens over materialized logits."""

    @pytest.mark.parametrize(
        "block", [100, pytest.param(64, marks=pytest.mark.slow),
                  pytest.param(500, marks=pytest.mark.slow),
                  pytest.param(512, marks=pytest.mark.slow),
                  pytest.param(7, marks=pytest.mark.slow)],
    )
    def test_greedy_exact_and_lp_formula(self, block):
        x, w, logits = _head_problem()
        temp = jnp.array([0.0, 1.0, 0.7, 0.0, 1.3, 1.0], jnp.float32)
        greedy = temp <= 0.0
        R = x.shape[0]
        sp = SamplingParams(
            temperature=temp, top_p=jnp.ones((R,), jnp.float32),
            top_k=jnp.full((R,), 1 << 30, jnp.int32),
        )
        key = jax.random.key(3)
        ref_tok, ref_lp = sample_tokens(key, logits, sp, warp=False)
        out = fs.fused_sample(
            key, x, w, temp, greedy, block_size=block, use_pallas=False
        )
        g = np.asarray(greedy)
        # greedy rows: token- and logprob-exact
        assert np.array_equal(np.asarray(out["tokens"])[g],
                              np.asarray(ref_tok)[g])
        np.testing.assert_allclose(
            np.asarray(out["logprobs"])[g], np.asarray(ref_lp)[g], atol=1e-4
        )
        # every row: raw argmax exact; returned lp == log_softmax at the
        # sampled token w.r.t. the warped distribution
        assert np.array_equal(
            np.asarray(out["argmax"]), np.asarray(jnp.argmax(logits, -1))
        )
        warped = np.asarray(logits) / np.maximum(
            np.asarray(temp)[:, None], 1e-6
        )
        lse = np.asarray(
            jax.scipy.special.logsumexp(jnp.asarray(warped), axis=-1)
        )
        tok = np.asarray(out["tokens"])
        np.testing.assert_allclose(
            np.asarray(out["logprobs"]),
            warped[np.arange(R), tok] - lse, atol=1e-4,
        )

    def test_topk_sample_stays_in_topk_set(self):
        x, w, logits = _head_problem()
        R = x.shape[0]
        temp = jnp.ones((R,), jnp.float32)
        topk = jnp.array([1 << 30, 5, 1 << 30, 1 << 30, 3, 1 << 30],
                         jnp.int32)
        for seed in range(8):
            out = fs.fused_sample(
                jax.random.key(seed), x, w, temp,
                jnp.zeros((R,), bool), topk=topk, block_size=64,
                use_pallas=False,
            )
            tok = np.asarray(out["tokens"])
            for r in (1, 4):
                k = int(topk[r])
                top_ids = np.argsort(-np.asarray(logits)[r])[:k]
                assert tok[r] in top_ids

    def test_gathered_lp_scores_requested_token(self):
        x, w, logits = _head_problem()
        R = x.shape[0]
        temp = jnp.full((R,), 0.9, jnp.float32)
        gids = jnp.arange(R, dtype=jnp.int32) * 3
        out = fs.fused_sample(
            jax.random.key(1), x, w, temp, jnp.zeros((R,), bool),
            gather_ids=gids, block_size=33, use_pallas=False,
        )
        warped = np.asarray(logits) / 0.9
        lse = np.asarray(
            jax.scipy.special.logsumexp(jnp.asarray(warped), axis=-1)
        )
        np.testing.assert_allclose(
            np.asarray(out["gathered_lp"]),
            warped[np.arange(R), np.arange(R) * 3] - lse, atol=1e-4,
        )

    def test_pallas_interpret_matches_xla(self):
        """The kernel (CPU interpret mode) agrees with the streamed XLA
        path on everything deterministic: greedy tokens, argmax, and the
        logprob formula for whatever token its own stream sampled."""
        x, w, logits = _head_problem()
        temp = jnp.array([0.0, 1.0, 0.7, 0.0, 1.3, 1.0], jnp.float32)
        greedy = temp <= 0.0
        R = x.shape[0]
        out = fs.fused_sample(
            jax.random.key(3), x, w, temp, greedy, block_size=128,
            use_pallas=True,
        )
        g = np.asarray(greedy)
        ref = np.asarray(jnp.argmax(logits, -1))
        assert np.array_equal(np.asarray(out["tokens"])[g], ref[g])
        assert np.array_equal(np.asarray(out["argmax"]), ref)
        warped = np.asarray(logits) / np.maximum(
            np.asarray(temp)[:, None], 1e-6
        )
        lse = np.asarray(
            jax.scipy.special.logsumexp(jnp.asarray(warped), axis=-1)
        )
        tok = np.asarray(out["tokens"])
        np.testing.assert_allclose(
            np.asarray(out["logprobs"]),
            warped[np.arange(R), tok] - lse, atol=1e-3,
        )

    def test_explicit_pallas_with_topk_or_mesh_raises(self):
        x, w, _ = _head_problem()
        R = x.shape[0]
        temp = jnp.ones((R,), jnp.float32)
        with pytest.raises(ValueError, match="top-k"):
            fs.fused_sample(
                jax.random.key(0), x, w, temp, jnp.zeros((R,), bool),
                topk=jnp.full((R,), 4, jnp.int32), use_pallas=True,
            )
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        with pytest.raises(ValueError, match="mesh"):
            fs.fused_sample(
                jax.random.key(0), x, w, temp, jnp.zeros((R,), bool),
                use_pallas=True, mesh=mesh,
            )


class TestDistribution:
    """Chi-square: the fused sampler's first-token marginal equals the
    softmax of the (warped / restricted) head output."""

    def _marginal(self, sample_fn, n=N_DRAWS, vocab=16):
        keys = jax.random.split(jax.random.key(7), n)
        toks = np.asarray(jax.vmap(sample_fn)(keys))
        return np.bincount(toks, minlength=vocab)

    def _chi2(self, counts, p):
        n = counts.sum()
        mask = p > 0
        return float(
            (((counts[mask] - n * p[mask]) ** 2) / (n * p[mask])).sum()
        )

    def test_temperature_marginal(self):
        x, w, logits = _head_problem(R=1, E=8, V=16, seed=1)
        p = np.asarray(jax.nn.softmax(logits[0]))
        f = jax.jit(lambda k: fs.fused_sample(
            k, x, w, jnp.ones((1,)), jnp.zeros((1,), bool),
            block_size=7, use_pallas=False,
        )["tokens"][0])
        assert self._chi2(self._marginal(f), p) < CHI2_CRIT

    def test_topk_marginal(self):
        x, w, logits = _head_problem(R=1, E=8, V=16, seed=1)
        k = 5
        lg = np.asarray(logits[0])
        keep = np.argsort(-lg)[:k]
        p = np.zeros_like(lg)
        p[keep] = np.exp(lg[keep] - lg[keep].max())
        p /= p.sum()
        f = jax.jit(lambda key: fs.fused_sample(
            key, x, w, jnp.ones((1,)), jnp.zeros((1,), bool),
            topk=jnp.full((1,), k, jnp.int32), block_size=7,
            use_pallas=False,
        )["tokens"][0])
        counts = self._marginal(f)
        assert counts[np.setdiff1d(np.arange(16), keep)].sum() == 0
        assert self._chi2(counts, p) < CHI2_CRIT

    @pytest.mark.slow
    def test_excluded_token_marginal(self):
        """The spec-residual distribution: p with one token removed,
        renormalized — the excluded token must never appear."""
        x, w, logits = _head_problem(R=1, E=8, V=16, seed=1)
        p = np.asarray(jax.nn.softmax(logits[0]))
        ex = int(np.argmax(p))
        f = jax.jit(lambda k: fs.fused_sample(
            k, x, w, jnp.ones((1,)), jnp.zeros((1,), bool),
            exclude=jnp.array([ex]), block_size=16, use_pallas=False,
        )["tokens"][0])
        counts = self._marginal(f)
        assert counts[ex] == 0
        p2 = p.copy()
        p2[ex] = 0.0
        p2 /= p2.sum()
        assert self._chi2(counts.astype(float), p2) < CHI2_CRIT

    @pytest.mark.slow
    def test_pallas_temperature_marginal(self):
        x, w, logits = _head_problem(R=1, E=8, V=16, seed=1)
        p = np.asarray(jax.nn.softmax(logits[0]))
        f = jax.jit(lambda k: fs.fused_sample(
            k, x, w, jnp.ones((1,)), jnp.zeros((1,), bool),
            block_size=128, use_pallas=True,
        )["tokens"][0])
        assert self._chi2(self._marginal(f), p) < CHI2_CRIT


class TestLogprobFastPath:
    def test_warp_false_lp_equals_log_softmax(self):
        """The gather-then-normalize fast path in sample_tokens(warp=False)
        is EXACT vs the full log_softmax formulation (same reduction, so
        bitwise-comparable at f32 tolerance ~0)."""
        _, _, logits = _head_problem()
        R = logits.shape[0]
        temp = jnp.array([0.0, 1.0, 0.7, 0.0, 1.3, 1.0], jnp.float32)
        sp = SamplingParams(
            temperature=temp, top_p=jnp.ones((R,), jnp.float32),
            top_k=jnp.full((R,), 1 << 30, jnp.int32),
        )
        tok, lp = sample_tokens(jax.random.key(11), logits, sp, warp=False)
        warped = _plain_temperature(logits, sp)
        full = jnp.take_along_axis(
            jax.nn.log_softmax(warped, axis=-1), tok[:, None], axis=-1
        )[:, 0]
        np.testing.assert_allclose(
            np.asarray(lp), np.asarray(full), atol=1e-5
        )


class TestEngineFused:
    def test_greedy_fused_matches_reference(self, params, rng):
        """The tentpole contract: AREAL_FUSED_SAMPLE greedy decode is
        token- and logprob-exact vs the materialized reference through
        the full engine."""
        prompts = _prompts(rng)
        outs = []
        for fused in (False, True):
            eng = _engine(params, fused=fused, max_slots=4)
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({
                o.rid: o for o in eng.run_until_done(decode_steps=3)
            })
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason
            np.testing.assert_allclose(
                outs[0][rid].output_logprobs, outs[1][rid].output_logprobs,
                atol=1e-4,
            )

    def test_env_knob_enables_fused(self, params, monkeypatch):
        monkeypatch.setenv("AREAL_FUSED_SAMPLE", "1")
        eng = _engine(params, max_slots=1)
        assert eng.fused is True
        eng.submit(GenRequest(
            rid="a", input_ids=[1, 2, 3], max_new_tokens=4, greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=2)
        assert len(outs[0].output_ids) == 4

    def test_mixed_batch_fallback_rows_reproducible(self, params):
        """A top-p slot routes through the sorted fallback while greedy /
        top-k / plain-temperature slots stay fused — seeded runs are
        reproducible and the greedy slot stays exact."""

        def run(fused):
            eng = _engine(params, fused=fused, max_slots=4, seed=3)
            eng.submit(GenRequest(
                rid="g", input_ids=[5, 6, 7], max_new_tokens=8,
                greedy=True,
            ))
            eng.submit(GenRequest(
                rid="p", input_ids=[5, 6, 7], max_new_tokens=8,
                temperature=1.0, top_p=0.9,
            ))
            eng.submit(GenRequest(
                rid="k", input_ids=[5, 6, 7], max_new_tokens=8,
                temperature=1.0, top_k=8,
            ))
            eng.submit(GenRequest(
                rid="t", input_ids=[5, 6, 7], max_new_tokens=8,
                temperature=0.8,
            ))
            return {o.rid: o for o in eng.run_until_done(decode_steps=2)}

        m1, m2 = run(True), run(True)
        assert {r: o.output_ids for r, o in m1.items()} == \
               {r: o.output_ids for r, o in m2.items()}
        ref = run(False)
        assert m1["g"].output_ids == ref["g"].output_ids
        for o in m1.values():
            assert len(o.output_ids) == 8
            assert all(np.isfinite(o.output_logprobs))

    def test_spec_over_fused_greedy_matches_vanilla(self, params, rng):
        """Fused spec acceptance (streamed verify head) == vanilla greedy
        decode == reference spec decode, token for token."""
        prompts = _prompts(rng)

        def run(spec, fused):
            eng = _engine(params, spec=spec, fused=fused, max_slots=4,
                          spec_k=3)
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            return {o.rid: o for o in eng.run_until_done(decode_steps=3)}

        ref = run(False, False)
        sf = run(True, True)
        assert set(ref) == set(sf)
        for rid in ref:
            assert ref[rid].output_ids == sf[rid].output_ids, rid
            np.testing.assert_allclose(
                ref[rid].output_logprobs, sf[rid].output_logprobs,
                atol=1e-4,
            )

    def test_pause_resume_prefix_parity_fused(self, params, rng):
        """Interruption composes: a fused engine paused mid-generation
        yields a prefix of the uninterrupted chain and resubmission
        completes it exactly (the partial-rollout protocol)."""
        prompt = [int(x) for x in rng.integers(1, 128, size=5)]
        ref_eng = _engine(params, fused=True)
        ref_eng.submit(GenRequest(
            rid="ref", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        ref = ref_eng.run_until_done(decode_steps=4)[0].output_ids
        eng = _engine(params, fused=True)
        eng.submit(GenRequest(
            rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        ))
        eng.step(decode_steps=1)
        parts = eng.pause()
        assert len(parts) == 1 and parts[0].finish_reason == "interrupted"
        got = parts[0].output_ids
        assert 0 < len(got) < 12
        assert got == ref[: len(got)]
        eng.resume()
        eng.submit(GenRequest(
            rid="a2", input_ids=prompt + got,
            max_new_tokens=12 - len(got), greedy=True,
        ))
        outs = eng.run_until_done(decode_steps=4)
        assert got + outs[0].output_ids == ref

    def test_tp2_fused_greedy_matches_single_device(self, params, rng):
        """Fused sampling on a 2-way `model` mesh (streamed XLA epilogue
        under GSPMD, hidden states replicated before sampling) matches
        the unsharded fused engine token for token."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        prompts = _prompts(rng)
        eng1 = _engine(params, fused=True, max_slots=4)
        eng2 = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=128,
            fused_sample=True, mesh=mesh,
        )
        for eng in (eng1, eng2):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=8,
                    greedy=True,
                ))
        o1 = {o.rid: o for o in eng1.run_until_done(decode_steps=2)}
        o2 = {o.rid: o for o in eng2.run_until_done(decode_steps=2)}
        assert set(o1) == set(o2)
        for rid in o1:
            assert o1[rid].output_ids == o2[rid].output_ids, rid

    def test_fused_bounded_compiles_and_counters(self, params, rng):
        """Fused traffic obeys the n_compiles discipline (mixed fused
        chunks + fallback buckets add a bounded set of programs, never
        per-prompt) and ticks the fused/fallback counters."""
        metrics_mod.counters.clear(metrics_mod.GEN_FUSED_SAMPLE_STEPS)
        metrics_mod.counters.clear(metrics_mod.GEN_SAMPLER_FALLBACK_ROWS)
        eng = _engine(params, fused=True, max_slots=4, max_seqlen=256,
                      page_size=16)

        def burst(tag, plens, **req_kw):
            for i, plen in enumerate(plens):
                eng.submit(GenRequest(
                    rid=f"{tag}{i}",
                    input_ids=[int(x) for x in rng.integers(1, 128, plen)],
                    max_new_tokens=6, **req_kw,
                ))
            eng.run_until_done(decode_steps=3)

        burst("g", [3, 9, 17, 33], greedy=True)         # warm greedy
        burst("p", [3, 9], temperature=1.0, top_p=0.9)  # warm fallback
        burst("k", [5, 21], temperature=1.0, top_k=8)   # warm online top-k
        warmed = eng.n_compiles()
        burst("g2", [11, 29, 60], greedy=True)
        burst("p2", [7, 45], temperature=1.0, top_p=0.9)
        burst("k2", [13, 80], temperature=1.0, top_k=8)
        assert eng.n_compiles() == warmed
        assert metrics_mod.counters.get(
            metrics_mod.GEN_FUSED_SAMPLE_STEPS
        ) > 0
        assert metrics_mod.counters.get(
            metrics_mod.GEN_SAMPLER_FALLBACK_ROWS
        ) > 0


class TestAdaptiveSpecK:
    def test_retunes_up_under_predictable_traffic(self, params):
        """A repetitive prompt (n-gram drafter accepts ~everything) must
        drive K up through the choice set, update the gauge, and keep
        compiles bounded by the visited-K set."""
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=512, spec_decode=True,
            spec_k=1, spec_k_adapt=True,
        )
        assert eng.spec_k_adapt is True
        pat = [7, 8, 9, 10] * 10
        for rid in ("a", "b"):
            eng.submit(GenRequest(
                rid=rid, input_ids=pat, max_new_tokens=250, greedy=True,
            ))
        eng.run_until_done(decode_steps=4)
        assert eng.spec_k > 1
        assert eng.spec_k in eng._spec_k_choices
        snap = metrics_mod.counters.snapshot()
        assert snap.get(metrics_mod.GEN_SPEC_K_CURRENT) == float(eng.spec_k)
        # one spec-chunk program per (chunk key, visited K): bounded
        assert len(eng._jit_spec) <= 4 * len(eng._spec_k_choices)

    def test_static_without_knob(self, params):
        eng = _engine(params, spec=True, spec_k=3)
        assert eng.spec_k_adapt is False
        eng.submit(GenRequest(
            rid="a", input_ids=[7, 8, 9] * 6, max_new_tokens=30,
            greedy=True,
        ))
        eng.run_until_done(decode_steps=3)
        assert eng.spec_k == 3

    def test_env_knob_and_gauge_init(self, params, monkeypatch):
        monkeypatch.setenv("AREAL_SPEC_K_ADAPT", "1")
        eng = _engine(params, spec=True, spec_k=2)
        assert eng.spec_k_adapt is True
        snap = metrics_mod.counters.snapshot()
        assert snap.get(metrics_mod.GEN_SPEC_K_CURRENT) == 2.0


def _run_fused_stanza(B=8):
    """Shared ``gen_sample_fused`` bench run for the tier-1 smoke and the
    slow throughput-ordering pin: a tiny 2-layer model with a LARGE-ish
    vocab (8192) so the ``[B, V]`` materialization the fused path removes
    is actually visible on the CPU harness."""
    import dataclasses

    import bench as bench_mod

    cfg = dataclasses.replace(CFG, vocab_size=8192)
    return bench_mod._bench_gen_sample_fused(
        819e9, 197e12, cfg=cfg, B=B, PLEN=64, D_STEPS=8, N_CHUNKS=3,
    )


def test_bench_gen_sample_fused_stanza_end_to_end():
    """The ``gen_sample_fused`` A/B bench runs end-to-end on the CPU
    harness: both arms decode, the sampled-logprob probe is exact (greedy
    fused logprobs are token-exact, so the delta is float-associativity
    noise), and throughput is floored against pathology only — the strict
    >= 1.0 ordering is pinned by the slow variant below (CPU wall clock
    on a loaded CI box must not flake tier-1); absolute ratios are judged
    on chip (HBM-roofline economics)."""
    out = _run_fused_stanza()
    assert set(out) >= {
        "tokens_per_s", "baseline_tokens_per_s", "vs_baseline",
        "max_logprob_delta",
    }
    assert out["tokens_per_s"] > 0
    assert out["baseline_tokens_per_s"] > 0
    # pathology floor only: the 8-slot timed window is a few hundred ms,
    # so scheduler noise on a busy CI box swings the ratio well below the
    # real ~1.25x (measured cold); the ordering bar lives in the slow pin
    assert out["vs_baseline"] > 0.5
    assert out["max_logprob_delta"] < 1e-4


@pytest.mark.slow
def test_bench_gen_sample_fused_beats_baseline():
    """The strict CPU-smoke speed ordering (the ISSUE 16 acceptance bar):
    at the 64-slot smoke shape the fused epilogue beats the materialized
    baseline (measured 1.59x on the CPU harness). Wall-clock comparison —
    slow-marked so a loaded tier-1 CI box can't flake it."""
    out = _run_fused_stanza(B=64)
    assert out["vs_baseline"] >= 1.0
    assert out["max_logprob_delta"] < 1e-4


class TestGaugeKind:
    def test_gauge_last_value_wins_and_delta_reports_as_is(self):
        name = "test/fused_gauge"
        metrics_mod.counters.clear(name)
        base = metrics_mod.counters.snapshot()
        metrics_mod.counters.gauge(name, 4.0)
        metrics_mod.counters.gauge(name, 2.0)
        assert metrics_mod.counters.get(name) == 2.0
        assert metrics_mod.counters.kind(name) == metrics_mod.KIND_GAUGE
        d = metrics_mod.counters.delta(base)
        assert d[name] == 2.0
        metrics_mod.counters.clear(name)

    def test_telemetry_merges_gauges_with_max(self):
        from areal_tpu.system.telemetry import FleetAggregate

        agg = FleetAggregate()
        for i, v in enumerate((2.0, 4.0, 1.0)):
            agg.merge_snapshot({
                "worker": f"w{i}",
                "counters": {metrics_mod.GEN_SPEC_K_CURRENT: v},
            })
        # gauges merge via fleet max (the conservative view when workers
        # retune at different times), not via sum
        assert agg.counters[metrics_mod.GEN_SPEC_K_CURRENT] == 4.0
        assert agg.kinds[metrics_mod.GEN_SPEC_K_CURRENT] == \
            metrics_mod.KIND_GAUGE
