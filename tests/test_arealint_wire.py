"""Fixture tests for the arealint v5 wire-contract rule family
(``tools/arealint/rules_wire.py`` + the endpoint/call model in
``tools/arealint/wiremodel.py``).

Every rule gets positive + negative + suppression fixtures on a
synthetic client/server package pair (the acceptance contract from
docs/static_analysis.md), plus the degrade cases (dynamic path,
computed field name, ``**kwargs`` payload), partial-scan gating, the
catalog-drift contract test pinning the statically parsed route table
against the routes the real aiohttp apps register at runtime, and the
``--changed-only`` parity property for the wire family.
"""

import ast
import json
import pathlib
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import (  # noqa: E402
    Config,
    DEFAULT_WIRE_DEFS,
    WireSpec,
    build_model,
    parse_server_module,
    scan_sources,
    verify_defs,
)
from tools.arealint.core import PROJECT_RULES  # noqa: E402
from tools.arealint.wiremodel import find_routes  # noqa: E402

pytestmark = pytest.mark.arealint


def dedent(s):
    return textwrap.dedent(s).lstrip()


# ------------------------------------------------------------------ #
# synthetic package pair
# ------------------------------------------------------------------ #

SPEC = WireSpec(
    servers=("pkg/server.py",),
    clients=("pkg/client.py",),
    non_idempotent=frozenset({"/submit", "/stream"}),
)
CFG = Config(wire=SPEC)

SERVER = dedent(
    """
    import json

    from aiohttp import web


    class Server:
        def __init__(self):
            self.app = web.Application()
            self.app.router.add_post("/submit", self._submit)
            self.app.router.add_post("/stream", self._stream)
            self.app.router.add_get("/stats", self._stats)

        async def _submit(self, request):
            d = await request.json()
            rid = d["rid"]
            prio = d.get("prio", 0)
            if not rid:
                return web.json_response({"error": "empty rid"}, status=400)
            if self.busy:
                raise web.HTTPConflict()
            return web.json_response(
                {"rid": rid, "tokens": [1, 2], "version": 3}
            )

        async def _stream(self, request):
            d = await request.json()
            rid = d["rid"]
            resp = web.StreamResponse()
            await resp.prepare(request)
            frame = {"tok": 1, "fin": False}
            await resp.write(b"data: " + json.dumps(frame).encode() + b"\\n\\n")
            return resp

        async def _stats(self, request):
            return web.json_response({"load": 0.5, "slots": 4})
    """
)


def wire_scan(client_src, rule, server_src=SERVER, config=CFG):
    sources = {"pkg/client.py": dedent(client_src)}
    if server_src is not None:
        sources["pkg/server.py"] = server_src
    return [
        f for f in scan_sources(sources, rules=[rule], config=config)
        if f.rule == rule
    ]


CLIENT_HEADER = """
    import aiohttp


    class Client:
        def __init__(self):
            self._session = aiohttp.ClientSession()
"""


# ------------------------------------------------------------------ #
# unknown-endpoint
# ------------------------------------------------------------------ #


class TestUnknownEndpoint:
    def test_unregistered_path_fires(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/nope", json=None) as resp:
                return resp.status
        """
        (f,) = wire_scan(src, "unknown-endpoint")
        assert f.severity == "error"
        assert "/nope" in f.message and "404" in f.message
        assert f.path == "pkg/client.py"

    def test_method_drift_names_registered_methods(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.get(f"{base}/submit") as resp:
                return resp.status
        """
        (f,) = wire_scan(src, "unknown-endpoint")
        assert "method drift" in f.message
        assert "POST" in f.message

    def test_registered_pair_is_clean(self):
        src = CLIENT_HEADER + """
        async def poke(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                return resp.status
        """
        assert wire_scan(src, "unknown-endpoint") == []

    def test_wire_annotation_suppresses(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/nope", json=None) as resp:  # arealint: wire(/nope, lands in the next server rev)
                return resp.status
        """
        assert wire_scan(src, "unknown-endpoint") == []

    def test_wrong_endpoint_annotation_fires_with_note(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/nope", json=None) as resp:  # arealint: wire(/other, wrong endpoint)
                return resp.status
        """
        (f,) = wire_scan(src, "unknown-endpoint")
        assert "malformed" in f.message


# ------------------------------------------------------------------ #
# request-field-drift
# ------------------------------------------------------------------ #


class TestRequestFieldDrift:
    def test_missing_required_field_is_error(self):
        src = CLIENT_HEADER + """
        async def submit(self, base):
            async with self._session.post(f"{base}/submit", json={"prio": 1}) as resp:
                return resp.status
        """
        findings = wire_scan(src, "request-field-drift")
        errs = [f for f in findings if f.severity == "error"]
        (f,) = errs
        assert "'rid'" in f.message and "KeyError" in f.message

    def test_unread_sent_field_is_warn(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            body = {
                "rid": rid,
                "color": 1,
            }
            async with self._session.post(f"{base}/submit", json=body) as resp:
                return resp.status
        """
        findings = wire_scan(src, "request-field-drift")
        assert [f.severity for f in findings] == ["warn"]
        assert "'color'" in findings[0].message
        # reported at the key's own line inside the dict literal
        lines = dedent(src).splitlines()
        assert '"color"' in lines[findings[0].line - 1]

    def test_matching_fields_are_clean(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid, "prio": 2}) as resp:
                return resp.status
        """
        assert wire_scan(src, "request-field-drift") == []

    def test_wire_annotation_on_key_line_suppresses_warn(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            body = {
                "rid": rid,
                "color": 1,  # arealint: wire(/submit, fwd-compat key for v2 dashboards)
            }
            async with self._session.post(f"{base}/submit", json=body) as resp:
                return resp.status
        """
        assert wire_scan(src, "request-field-drift") == []

    def test_kwargs_splat_payload_degrades(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, kw):
            async with self._session.post(f"{base}/submit", **kw) as resp:
                return resp.status
        """
        assert wire_scan(src, "request-field-drift") == []

    def test_computed_field_name_degrades(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, key):
            async with self._session.post(f"{base}/submit", json={key: 1}) as resp:
                return resp.status
        """
        assert wire_scan(src, "request-field-drift") == []

    def test_open_handler_fields_skip_the_warn(self):
        server = dedent(
            """
            from aiohttp import web


            class Server:
                def __init__(self):
                    self.app = web.Application()
                    self.app.router.add_post("/submit", self._submit)

                async def _submit(self, request):
                    d = await request.json()
                    self.sink.consume(d)
                    return web.json_response({"ok": True})
            """
        )
        src = CLIENT_HEADER + """
        async def submit(self, base):
            async with self._session.post(f"{base}/submit", json={"anything": 1}) as resp:
                return resp.status
        """
        assert wire_scan(src, "request-field-drift", server_src=server) == []


# ------------------------------------------------------------------ #
# response-field-drift
# ------------------------------------------------------------------ #


class TestResponseFieldDrift:
    def test_unemitted_body_key_fires(self):
        src = CLIENT_HEADER + """
        async def stats(self, base):
            async with self._session.get(f"{base}/stats") as resp:
                d = await resp.json()
            return d["throughput"]
        """
        (f,) = wire_scan(src, "response-field-drift")
        assert "'throughput'" in f.message and "/stats" in f.message

    def test_emitted_body_key_is_clean(self):
        src = CLIENT_HEADER + """
        async def stats(self, base):
            async with self._session.get(f"{base}/stats") as resp:
                d = await resp.json()
            return d["load"], d.get("slots")
        """
        assert wire_scan(src, "response-field-drift") == []

    def test_unwritten_sse_frame_key_fires(self):
        src = CLIENT_HEADER + """
        async def stream(self, base, rid):
            async with self._session.post(f"{base}/stream", json={"rid": rid}) as resp:
                async for raw in resp.content:
                    yield raw


    async def consume(client: Client, base):
        async for ev in client.stream(base, "r1"):
            if ev["fin"]:
                break
            print(ev["nope"])
        """
        (f,) = wire_scan(src, "response-field-drift")
        assert "SSE frame key 'nope'" in f.message

    def test_written_sse_frame_keys_are_clean(self):
        src = CLIENT_HEADER + """
        async def stream(self, base, rid):
            async with self._session.post(f"{base}/stream", json={"rid": rid}) as resp:
                async for raw in resp.content:
                    yield raw


    async def consume(client: Client, base):
        async for ev in client.stream(base, "r1"):
            if ev["fin"]:
                break
            print(ev["tok"])
        """
        assert wire_scan(src, "response-field-drift") == []

    def test_wire_annotation_suppresses_sse_read(self):
        src = CLIENT_HEADER + """
        async def stream(self, base, rid):
            async with self._session.post(f"{base}/stream", json={"rid": rid}) as resp:
                async for raw in resp.content:
                    yield raw


    async def consume(client: Client, base):
        async for ev in client.stream(base, "r1"):
            print(ev["nope"])  # arealint: wire(/stream, frame key lands with the next server rev)
        """
        assert wire_scan(src, "response-field-drift") == []

    def test_open_producer_degrades(self):
        server = dedent(
            """
            from aiohttp import web


            class Server:
                def __init__(self):
                    self.app = web.Application()
                    self.app.router.add_get("/stats", self._stats)

                async def _stats(self, request):
                    return web.json_response({**self.gauges()})
            """
        )
        src = CLIENT_HEADER + """
        async def stats(self, base):
            async with self._session.get(f"{base}/stats") as resp:
                d = await resp.json()
            return d["anything"]
        """
        assert wire_scan(src, "response-field-drift", server_src=server) == []


# ------------------------------------------------------------------ #
# status-code-drift
# ------------------------------------------------------------------ #


class TestStatusCodeDrift:
    def test_branch_on_impossible_status_is_error(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                if resp.status == 418:
                    return None
                return resp.status
        """
        findings = wire_scan(src, "status-code-drift")
        errs = [f for f in findings if f.severity == "error"]
        (f,) = errs
        assert "418" in f.message and "dead error handling" in f.message
        assert f.path == "pkg/client.py"

    def test_branch_on_emitted_status_is_clean(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                if resp.status == 409:
                    return None
                if resp.status == 400:
                    return None
                return resp.status
        """
        findings = wire_scan(src, "status-code-drift")
        assert [f for f in findings if f.severity == "error"] == []

    def test_unhandled_emitted_status_warns_at_the_handler(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                d = await resp.json()
            return d["rid"]
        """
        findings = wire_scan(src, "status-code-drift")
        warns = [f for f in findings if f.severity == "warn"]
        assert warns, findings
        assert all(f.path == "pkg/server.py" for f in warns)
        assert any("HTTP 409" in f.message for f in warns)

    def test_generic_guard_covers_every_status(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                resp.raise_for_status()
                d = await resp.json()
            return d["rid"]
        """
        assert wire_scan(src, "status-code-drift") == []

    def test_except_status_branch_counts_as_handled(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            try:
                async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                    d = await resp.json()
                return d["rid"]
            except aiohttp.ClientResponseError as e:
                if e.status == 409:
                    return None
                raise
        """
        assert wire_scan(src, "status-code-drift") == []

    def test_wire_annotation_suppresses_the_dead_branch(self):
        src = CLIENT_HEADER + """
        async def submit(self, base, rid):
            async with self._session.post(f"{base}/submit", json={"rid": rid}) as resp:
                if resp.status == 418:  # arealint: wire(/submit, probing a teapot-capable fork)
                    return None
                resp.raise_for_status()
                return resp.status
        """
        assert wire_scan(src, "status-code-drift") == []


# ------------------------------------------------------------------ #
# retry-unbounded-status
# ------------------------------------------------------------------ #

RETRY_CLIENT_HEADER = """
    import aiohttp


    class Client:
        def __init__(self):
            self._session = aiohttp.ClientSession()

        async def _req(self, method, base, ep, json_body=None,
                       retry_connection_only=False):
            for _attempt in range(3):
                async with self._session.request(
                    method, f"{base}{ep}", json=json_body
                ) as resp:
                    resp.raise_for_status()
                    return await resp.json()
"""


class TestRetryUnboundedStatus:
    def test_status_retry_on_non_idempotent_endpoint_fires(self):
        src = RETRY_CLIENT_HEADER + """
        async def submit(self, base, rid):
            return await self._req("POST", base, "/submit", json_body={"rid": rid})
        """
        (f,) = wire_scan(src, "retry-unbounded-status")
        assert f.severity == "error"
        assert "/submit" in f.message
        assert "retry_connection_only=True" in f.message

    def test_connection_only_retry_is_clean(self):
        src = RETRY_CLIENT_HEADER + """
        async def submit(self, base, rid):
            return await self._req(
                "POST", base, "/submit", json_body={"rid": rid},
                retry_connection_only=True,
            )
        """
        assert wire_scan(src, "retry-unbounded-status") == []

    def test_idempotent_endpoint_is_clean(self):
        src = RETRY_CLIENT_HEADER + """
        async def stats(self, base):
            return await self._req("GET", base, "/stats")
        """
        assert wire_scan(src, "retry-unbounded-status") == []

    def test_wire_annotation_suppresses(self):
        src = RETRY_CLIENT_HEADER + """
        async def submit(self, base, rid):
            return await self._req("POST", base, "/submit", json_body={"rid": rid})  # arealint: wire(/submit, server dedupes by rid)
        """
        assert wire_scan(src, "retry-unbounded-status") == []

    def test_fires_without_server_modules_in_scan(self):
        # the retry rule needs only the verified spec, so it stays live
        # under --changed-only even when no server module was scanned
        src = RETRY_CLIENT_HEADER + """
        async def submit(self, base, rid):
            return await self._req("POST", base, "/submit", json_body={"rid": rid})
        """
        (f,) = wire_scan(src, "retry-unbounded-status", server_src=None)
        assert "/submit" in f.message


# ------------------------------------------------------------------ #
# degrade + gating
# ------------------------------------------------------------------ #

WIRE_RULES = (
    "unknown-endpoint",
    "request-field-drift",
    "response-field-drift",
    "status-code-drift",
    "retry-unbounded-status",
)


class TestDegrade:
    def test_dynamic_path_degrades_everywhere(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/{self.ep}", json={"x": 1}) as resp:
                return resp.status
        """
        for rule in ("unknown-endpoint", "request-field-drift"):
            assert wire_scan(src, rule) == []

    def test_server_absent_degrades_catalog_rules(self):
        # /nope would be unknown-endpoint, but without every declared
        # server module in the scan the catalog is partial: no finding
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/nope", json=None) as resp:
                return resp.status
        """
        for rule in ("unknown-endpoint", "request-field-drift",
                     "response-field-drift", "status-code-drift"):
            assert wire_scan(src, rule, server_src=None) == []

    def test_no_wire_spec_disables_the_family(self):
        src = CLIENT_HEADER + """
        async def poke(self, base):
            async with self._session.post(f"{base}/nope", json=None) as resp:
                return resp.status
        """
        for rule in WIRE_RULES:
            assert wire_scan(src, rule, config=Config()) == []


class TestRegistry:
    def test_wire_family_registered(self):
        assert set(WIRE_RULES) <= set(PROJECT_RULES)


# ------------------------------------------------------------------ #
# catalog-drift contract: parsed table vs runtime route registration
# ------------------------------------------------------------------ #

SERVER_CLASSES = {
    "areal_tpu/gateway/api.py": ("areal_tpu.gateway.api", "GatewayServer"),
    "areal_tpu/gen/server.py": ("areal_tpu.gen.server", "GenerationHTTPServer"),
    "areal_tpu/system/gserver_manager.py": (
        "areal_tpu.system.gserver_manager", "GserverManager",
    ),
}


class TestCatalogDrift:
    def test_default_defs_survive_verification(self):
        spec, dropped = verify_defs(pathlib.Path(REPO))
        assert spec is not None, dropped
        assert dropped == []
        assert set(spec.servers) == set(DEFAULT_WIRE_DEFS.server_modules)

    def test_real_catalog_has_the_load_bearing_endpoints(self):
        spec, _ = verify_defs(pathlib.Path(REPO))
        modules = {}
        for rel in spec.servers:
            src = open(os.path.join(REPO, rel), encoding="utf-8").read()
            modules[rel] = (ast.parse(src), src)
        model = build_model(spec, modules)
        assert ("POST", "/generate") in model.endpoints
        assert ("POST", "/generate_stream") in model.endpoints
        # /health and /metrics_json are registered by all three planes
        assert len(model.endpoints[("GET", "/health")]) == 3
        assert len(model.endpoints[("GET", "/metrics_json")]) == 3
        gen = next(
            ep for ep in model.endpoints[("POST", "/generate")]
            if ep.module.endswith("gen/server.py")
        )
        assert "input_ids" in gen.required or "input_ids" in gen.optional

    @pytest.mark.parametrize("rel", sorted(SERVER_CLASSES))
    def test_parsed_routes_match_runtime_registration(self, rel):
        """The statically parsed route table must equal the (method,
        path) pairs the real server's ``_bind_routes`` registers on a
        bare aiohttp Application — loud drift, no silent skew."""
        web = pytest.importorskip("aiohttp.web")
        import importlib

        modname, clsname = SERVER_CLASSES[rel]
        mod = importlib.import_module(modname)
        cls = getattr(mod, clsname)
        srv = object.__new__(cls)  # routes must not need a live engine
        app = web.Application()
        srv._bind_routes(app)
        runtime = {
            (r.method, r.resource.canonical)
            for r in app.router.routes()
            if r.method != "HEAD"  # aiohttp auto-adds HEAD for GET
        }
        src = open(os.path.join(REPO, rel), encoding="utf-8").read()
        parsed = {
            (method, path)
            for method, path, _handler, _ln in find_routes(ast.parse(src))
        }
        assert parsed == runtime, (
            f"{rel}: static wire catalog drifted from runtime routes\n"
            f"  parsed-only:  {sorted(parsed - runtime)}\n"
            f"  runtime-only: {sorted(runtime - parsed)}"
        )

    @pytest.mark.parametrize("rel", sorted(SERVER_CLASSES))
    def test_every_runtime_route_has_a_parsed_handler(self, rel):
        """find_routes degrades (drops the route) when the handler is
        not a literal attribute in the module — the contract test above
        would then pass vacuously. Pin that every route parses."""
        src = open(os.path.join(REPO, rel), encoding="utf-8").read()
        routes = find_routes(ast.parse(src))
        eps = parse_server_module(rel, ast.parse(src), src)
        assert len(eps) == len(routes)


# ------------------------------------------------------------------ #
# --changed-only parity for the wire family
# ------------------------------------------------------------------ #


class TestChangedOnlyWire:
    def _run(self, *args, stdin=None):
        return subprocess.run(
            [sys.executable, "-m", "tools.arealint", *args],
            cwd=REPO, capture_output=True, text=True, timeout=180,
            input=stdin,
        )

    def test_partial_wire_surface_stays_clean(self):
        # a diff touching one client module: catalog rules degrade
        # instead of false-positiving against a partial server table
        r = self._run(
            "areal_tpu", "--changed-only", "--no-baseline",
            "--format", "json",
            stdin="areal_tpu/gen/client.py\n",
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.loads(r.stdout)["findings"] == []

    def test_full_wire_surface_matches_explicit_paths(self):
        spec, _ = verify_defs(pathlib.Path(REPO))
        rels = sorted(set(spec.servers) | set(spec.clients))
        r_changed = self._run(
            "areal_tpu", "--changed-only", "--no-baseline",
            "--format", "json",
            stdin="".join(rel + "\n" for rel in rels),
        )
        r_explicit = self._run(
            *rels, "--no-baseline", "--format", "json",
        )
        assert r_changed.returncode == r_explicit.returncode, (
            r_changed.stdout + r_changed.stderr
        )
        assert json.loads(r_changed.stdout) == json.loads(r_explicit.stdout)
