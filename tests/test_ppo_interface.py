"""PPO actor/critic interface smoke + semantics tests on the CPU mesh.

Counterpart of the reference's ``tests/interfaces`` PPO tests: run the full
inference → prepare (GAE) → minibatched train_step path on tiny models.
"""

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import PPOHyperparameters, make_interface
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.train.engine import OptimizerConfig, TrainEngine

ACTOR_CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)
CRITIC_CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32", is_critic=True,
)


def _rollout_sample(rng, n_items=4, group=1):
    """Fake rollout output: grouped sequences with prompt masks, behavior
    logprobs (token-aligned), scalar rewards per sequence."""
    ids = list(range(n_items))
    seqlens, data_ids, pmask, lps, rewards, noeos = [], [], [], [], [], []
    for _ in range(n_items):
        inner = []
        for _ in range(group):
            plen = int(rng.integers(2, 4))
            glen = int(rng.integers(3, 8))
            n = plen + glen
            inner.append(n)
            data_ids.append(rng.integers(0, 128, size=n).astype(np.int64))
            pmask.append(np.r_[np.ones(plen, bool), np.zeros(glen, bool)])
            lp = np.zeros(n, np.float32)
            lp[plen - 1 : n - 1] = rng.normal(size=glen) * 0.1 - 1.0
            lps.append(lp)
            rewards.append(float(rng.normal()))
            noeos.append(False)
        seqlens.append(inner)
    return SequenceSample(
        keys={"packed_input_ids", "prompt_mask", "packed_logprobs",
              "packed_ref_logprobs", "rewards", "seq_no_eos_mask"},
        ids=ids,
        seqlens={
            "packed_input_ids": seqlens,
            "prompt_mask": seqlens,
            "packed_logprobs": seqlens,
            "packed_ref_logprobs": seqlens,
            "rewards": [[1] * group for _ in range(n_items)],
            "seq_no_eos_mask": [[1] * group for _ in range(n_items)],
        },
        data={
            "packed_input_ids": np.concatenate(data_ids),
            "prompt_mask": np.concatenate(pmask),
            "packed_logprobs": np.concatenate(lps),
            "packed_ref_logprobs": np.concatenate(lps) * 0.9,
            "rewards": np.array(rewards, np.float32),
            "seq_no_eos_mask": np.array(noeos),
        },
    )


@pytest.fixture(scope="module")
def engines():
    par = ParallelConfig(data=2, fsdp=1, model=2)
    actor = TrainEngine(ACTOR_CFG, par, OptimizerConfig(lr=1e-4))
    actor.init_random(0).setup_optimizer(100)
    critic = TrainEngine(CRITIC_CFG, par, OptimizerConfig(lr=1e-4))
    critic.init_random(1).setup_optimizer(100)
    return actor, critic


def test_full_ppo_round(engines, rng):
    actor_eng, critic_eng = engines
    hp = PPOHyperparameters(ppo_n_minibatches=2, use_decoupled_loss=True)
    actor = make_interface("ppo_actor", hp=hp)
    critic = make_interface("ppo_critic", hp=hp)
    sample = _rollout_sample(rng, n_items=4)
    spec = MicroBatchSpec(max_tokens_per_mb=128)

    # critic_inf -> values; actor_inf -> prox_logp (like the MFC graph)
    values = critic.inference(critic_eng, sample, spec)
    sample.update_(values)
    prox = actor.inference(actor_eng, sample, spec)
    sample.update_(prox)
    assert sample.data["values"].shape == sample.data["packed_input_ids"].shape
    assert sample.data["prox_logp"].shape == sample.data["packed_input_ids"].shape

    v0 = actor_eng.version
    stats = actor.train_step(actor_eng, sample, spec)
    assert actor_eng.version == v0 + 1
    for k in ("actor_loss", "importance_weight", "actor_clip_ratio", "approx_kl"):
        assert np.isfinite(stats[k]), (k, stats)
    # advantages were attached by _prepare and are finite
    assert np.isfinite(sample.data["advantages"]).all()
    assert sample.data["advantages"].shape == sample.data["packed_input_ids"].shape

    cstats = critic.train_step(critic_eng, sample, spec)
    assert np.isfinite(cstats["critic_loss"])


def test_grpo_critic_free(engines, rng):
    actor_eng, _ = engines
    hp = PPOHyperparameters(
        ppo_n_minibatches=1, disable_value=True, group_adv_norm=True,
        adv_norm=False, group_size=2, use_decoupled_loss=False,
        recompute_logprob=False,
    )
    actor = make_interface("ppo_actor", hp=hp)
    sample = _rollout_sample(rng, n_items=3, group=2)
    stats = actor.train_step(actor_eng, sample, MicroBatchSpec(max_tokens_per_mb=128))
    assert np.isfinite(stats["actor_loss"])
    # group normalization: per-item advantage mean ~ 0 over action tokens
    adv = sample.data["advantages"]
    pm = sample.data["prompt_mask"]
    offsets = np.cumsum(
        [0] + [sum(l) for l in sample.seqlens["packed_input_ids"]]
    )
    for i in range(sample.bs):
        seg = slice(offsets[i], offsets[i + 1])
        sel = adv[seg][~pm[seg]]
        # last token of each sequence has no action; approximate check
        assert abs(sel[np.nonzero(sel)].mean()) < 0.7


def test_advantages_match_manual_gae(engines, rng):
    """Critic-free, no normalization: advantages should equal the discounted
    reward-to-go of the KL-shaped rewards (values = 0)."""
    actor_eng, _ = engines
    hp = PPOHyperparameters(
        ppo_n_minibatches=1, disable_value=True, adv_norm=False,
        use_decoupled_loss=False, recompute_logprob=False,
        kl_ctl=0.0, discount=0.9, gae_lambda=0.8,
    )
    actor = make_interface("ppo_actor", hp=hp)
    sample = _rollout_sample(rng, n_items=2)
    actor.train_step(actor_eng, sample, MicroBatchSpec(max_tokens_per_mb=128))
    adv = sample.data["advantages"]
    pm = sample.data["prompt_mask"]
    rew = sample.data["rewards"]
    offsets = np.cumsum([0] + [sum(l) for l in sample.seqlens["packed_input_ids"]])
    for i in range(sample.bs):
        seg = slice(offsets[i], offsets[i + 1])
        a = adv[seg]
        mask = ~pm[seg]
        # action positions: prompt_len-1 .. n-2
        plen = int(pm[seg].sum())
        n = offsets[i + 1] - offsets[i]
        acts = np.arange(plen - 1, n - 1)
        # reward only at last action; values zero -> A_t = (g*l)^(k) * r
        r = np.clip(rew[i], -hp.max_reward_clip, hp.max_reward_clip)
        gl = hp.discount * hp.gae_lambda
        expected = r * gl ** (acts[-1] - acts)
        np.testing.assert_allclose(a[acts], expected, rtol=1e-4, atol=1e-5)
