"""Multi-host trainer: 2-process × 4-device CPU world vs single-process d8.

The pjit analogue of the reference's multi-process NCCL test world
(``tests/comm/test_param_realloc.py:550-552``): spawn real OS processes, each
with its own 4-device virtual CPU backend, connect them with
``jax.distributed`` (Gloo CPU collectives), and check the distributed run
computes the SAME training trajectory as a single process over all 8 devices
— per-host batch feeding, global loss weighting, and cross-host stats
reduction all in the loop.
"""

import json
import os
import re
import socket
import subprocess
import sys

import pytest

SCRIPT = os.path.join(os.path.dirname(__file__), "multihost_train_script.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


# A failed coordinator bind (another suite's world grabbed the port
# between _free_port() and jax.distributed's grpc server start) is
# retryable with a fresh port — anything else is a real failure.
_BIND_FAILURE = re.compile(
    r"address already in use|failed to (bind|start server)|"
    r"could not bind", re.IGNORECASE,
)


def _launch_world(num_processes, local_devices, outs, n_mbs, timeout, extra):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the parent pytest process pins JAX_PLATFORMS/XLA_FLAGS for its own
    # in-process backend; children configure their own
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = []
    for pid in range(num_processes):
        cmd = [
            sys.executable, SCRIPT,
            "--num-processes", str(num_processes),
            "--process-id", str(pid),
            "--local-devices", str(local_devices),
            "--n-mbs", str(n_mbs),
            "--out", outs[pid],
        ]
        cmd += list(extra)
        if num_processes > 1:
            cmd += ["--coordinator", f"localhost:{port}"]
        procs.append(
            subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT
            )
        )
    try:
        logs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        # a hung world (collective straddle) must not leak live ranks into
        # the rest of the session
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, logs


def _run_world(num_processes, local_devices, outs, n_mbs=1, timeout=240,
               extra=(), attempts=3):
    """Launch an N-process training world; returns parsed rank-0 output.

    Worlds are serialized across suites via the conftest file lock, and a
    coordinator-bind race retries with a fresh port (bounded attempts) —
    the two deflakes for the standalone failures in the PR-8 log."""
    from tests.conftest import multihost_world_lock

    with multihost_world_lock():
        for attempt in range(attempts):
            procs, logs = _launch_world(
                num_processes, local_devices, outs, n_mbs, timeout, extra
            )
            failed = [i for i, p in enumerate(procs) if p.returncode != 0]
            if not failed:
                break
            if attempt + 1 < attempts and any(
                _BIND_FAILURE.search(logs[i]) for i in failed
            ):
                continue  # lost the port race: relaunch on a fresh one
            for i in failed:
                assert procs[i].returncode == 0, (
                    f"rank {i} failed:\n{logs[i][-3000:]}"
                )
    with open(outs[0]) as f:
        return json.load(f)


@pytest.mark.slow
def test_two_process_world_matches_single_process(tmp_path):
    single = _run_world(
        1, 8, [str(tmp_path / "single.json")]
    )
    dist = _run_world(
        2, 4, [str(tmp_path / f"r{i}.json") for i in range(2)]
    )
    assert dist["process_count"] == 2
    assert dist["device_count"] == 8
    # same global batch, same model, same optimizer -> same trajectory
    # (tolerance = float32 cross-process reduction-order noise)
    for a, b in zip(single["losses"], dist["losses"]):
        assert a == pytest.approx(b, rel=2e-4)
    assert single["losses"][-1] < single["losses"][0]
    # cross-host scalar reduction: mean of per-rank values (0+1)/2
    assert dist["rank_sum"] == pytest.approx(0.5)
    assert single["rank_sum"] == pytest.approx(0.0)


@pytest.mark.slow
def test_two_process_grad_accumulation(tmp_path):
    dist = _run_world(
        2, 4, [str(tmp_path / f"r{i}.json") for i in range(2)], n_mbs=2
    )
    single = _run_world(
        1, 8, [str(tmp_path / "single.json")], n_mbs=2
    )
    for a, b in zip(single["losses"], dist["losses"]):
        assert a == pytest.approx(b, rel=2e-4)


@pytest.mark.slow
def test_four_process_uneven_hosts_with_straggler(tmp_path):
    """VERDICT r4 weak #6: N>2 world with UNEVEN per-host batches (10 items
    over 4 hosts -> 3/3/2/2), an injected straggler rank, and per-host
    control-state divergence — the trajectory must match the single-process
    baseline and every rank must take process 0's control branch."""
    outs = [str(tmp_path / f"r{i}.json") for i in range(4)]
    single = _run_world(
        1, 8, [str(tmp_path / "single.json")],
        extra=["--n-items", "10"],
    )
    dist = _run_world(
        4, 2, outs, timeout=420,
        extra=["--n-items", "10", "--slow-rank", "2", "--slow-secs", "0.3",
               "--out-all-ranks"],
    )
    assert dist["process_count"] == 4 and dist["device_count"] == 8
    ranks = [json.load(open(o)) for o in outs]
    # uneven feeding: strided split of 10 items over 4 hosts
    assert [r["n_local_items"] for r in ranks] == [3, 3, 2, 2]
    # same global batch => same trajectory as the single-process world,
    # straggler or not (collectives synchronize; only wall time differs)
    for a, b in zip(single["losses"], dist["losses"]):
        assert a == pytest.approx(b, rel=2e-4)
    # every rank observed the SAME decision sequence — process 0's local
    # flags — even though local flags diverged across ranks every step
    decided = [[d for _, d in r["decisions"]] for r in ranks]
    assert all(seq == decided[0] for seq in decided[1:])
    local0 = [l for l, _ in ranks[0]["decisions"]]
    assert decided[0] == local0
    diverged = any(
        l != local0[i]
        for r in ranks[1:]
        for i, (l, _) in enumerate(r["decisions"])
    )
    assert diverged  # the predicate really did differ across ranks
    # cross-host stats reduction over 4 ranks: mean(0,1,2,3)
    assert dist["rank_sum"] == pytest.approx(1.5)
