"""Subprocess body for tests/test_multihost.py.

Trains the tiny model for N steps on deterministic synthetic data over a
(possibly multi-process) virtual CPU mesh and dumps per-step losses + reduced
stats as JSON — the pjit analogue of the reference's multi-process NCCL tests
(``tests/comm/test_param_realloc.py``'s 8-process world).

Run single-process (baseline) or as one rank of a multi-process world:
    python multihost_train_script.py --num-processes 2 --process-id 0 \
        --coordinator localhost:12345 --local-devices 4 --out r0.json
"""

import argparse
import json
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--local-devices", type=int, default=8)
    ap.add_argument("--parallel", default="d2f2m2")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--n-mbs", type=int, default=1)
    ap.add_argument("--n-items", type=int, default=12)
    # fault injection (VERDICT r4 weak #6): a rank that runs slow — per-host
    # clocks skew, collective-safe control decisions must still agree
    ap.add_argument("--slow-rank", type=int, default=-1)
    ap.add_argument("--slow-secs", type=float, default=0.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--out-all-ranks", action="store_true")
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.local_devices}"
    )

    import jax

    # the axon sitecustomize force-registers the TPU plugin and overrides
    # JAX_PLATFORMS; the config update wins over both (as in tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    from areal_tpu.parallel import multihost

    if args.num_processes > 1:
        # cross-process CPU collectives need gloo (the jaxlib default of
        # "none" fails every collective with "Multiprocess computations
        # aren't implemented on the CPU backend") ...
        multihost.enable_cpu_collectives()
        # ... and serialized device dispatch: async-dispatched
        # computations run their gloo collectives concurrently, and
        # rank-dependent execution order can wedge the transport with
        # mismatched-preamble aborts — the standalone flakes the PR-8 log
        # attributed to "CPU contention"
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        multihost.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    expected = args.local_devices * args.num_processes
    assert jax.device_count() == expected, (
        f"device_count={jax.device_count()} expected={expected} "
        f"platform={jax.default_backend()}"
    )

    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base import stats_tracker
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.ops import ppo as ppo_ops
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine, vmapped_forward

    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, dtype="float32",
    )
    eng = TrainEngine(
        cfg,
        parallel=ParallelConfig.from_str(args.parallel),
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=100)

    def sft_loss(params, mcfg, arrays):
        logits = vmapped_forward(params, mcfg, arrays)
        lp = jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
            logits, arrays["input_ids"], arrays["segment_ids"]
        )
        seg = arrays["segment_ids"]
        has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
        mask = has_next & ~arrays["prompt_mask"]
        n = jnp.maximum(mask.sum(), 1)
        return -jnp.sum(jnp.where(mask, lp, 0.0)) / n, {}

    # The GLOBAL batch is identical in every configuration; each process
    # takes a strided slice of the items (per-host data feeding).
    rng = np.random.default_rng(0)
    n_items = args.n_items
    seqlens = [int(n) for n in rng.integers(6, 14, size=n_items)]
    ids_all = rng.integers(0, 128, size=sum(seqlens)).astype(np.int64)
    pmask = np.concatenate(
        [np.r_[np.ones(2, np.bool_), np.zeros(n - 2, np.bool_)] for n in seqlens]
    )
    offs = np.cumsum([0] + seqlens)
    mine = list(range(args.process_id, n_items, args.num_processes))
    sample = SequenceSample.from_default(
        ids=mine,
        seqlens=[seqlens[i] for i in mine],
        data={
            "packed_input_ids": np.concatenate(
                [ids_all[offs[i] : offs[i + 1]] for i in mine]
            ),
            "prompt_mask": np.concatenate(
                [pmask[offs[i] : offs[i + 1]] for i in mine]
            ),
        },
    )

    import time as _time

    losses = []
    rounds_per_step = []
    decisions = []          # (local_flag, decided) per step
    for step in range(args.steps):
        if args.process_id == args.slow_rank and args.slow_secs > 0:
            _time.sleep(args.slow_secs)   # injected straggler
        r0 = multihost.collective_rounds()
        stats = eng.train_batch(sample, MicroBatchSpec(n_mbs=args.n_mbs), sft_loss)
        losses.append(stats["loss"])
        rounds_per_step.append(multihost.collective_rounds() - r0)
        # a per-host control predicate that DIVERGES across ranks (clock
        # skew being the usual real-world cause — the straggler sleep above
        # skews real clocks, but collectives re-synchronize step timing, so
        # the divergence here is made deterministic): main_decides must
        # hand every rank process 0's branch
        local_flag = (step + args.process_id) % 2 == 0
        decided = multihost.main_decides(local_flag)
        decisions.append((bool(local_flag), bool(decided)))
    # consolidated agreement: [longest, count] + [capacity, weights] = 2
    # host-collective rounds per train_batch (VERDICT r2 weak #7)
    if args.num_processes > 1:
        assert max(rounds_per_step) <= 2, rounds_per_step

    # host-local stats -> cross-host reduction (each host records its rank)
    stats_tracker.DEFAULT.scalar(rank_sum=float(args.process_id))
    reduced = stats_tracker.DEFAULT.export(cross_host=args.num_processes > 1)

    if args.out and (multihost.is_main() or args.out_all_ranks):
        with open(args.out, "w") as f:
            json.dump(
                {
                    "losses": losses,
                    "rank_sum": reduced["rank_sum"],
                    "process_count": jax.process_count(),
                    "device_count": jax.device_count(),
                    "n_local_items": len(mine),
                    "decisions": decisions,
                },
                f,
            )
    multihost.barrier("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
