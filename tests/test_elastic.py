"""Elastic-multihost unit tests (docs/fault_tolerance.md "Elastic
multihost") — the fast, in-process side: world-epoch records, liveness
leases + key hygiene, bounded-timeout collectives, supervisor culprit
decisions (driven end-to-end with jax-free stub ranks), seeded chaos
schedules, the fault-point/doc catalog sync, and the trainer's surgical
recovery. The real N-process jax worlds live in
tests/test_elastic_multihost.py (slow)."""

import json
import os
import re
import sys
import textwrap
import time

import pytest

from areal_tpu.apps.launcher import WorldSupervisor, WorldSupervisorConfig
from areal_tpu.base import faults, name_resolve, names
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.parallel import elastic
from tools import chaos

EXP, TRIAL = "elastic_test", "t0"


@pytest.fixture(autouse=True)
def _memory_name_resolve():
    prev = name_resolve.default_repository()
    name_resolve.set_repository(name_resolve.MemoryNameRecordRepository())
    yield
    name_resolve.set_repository(prev)


@pytest.fixture(autouse=True)
def _faults_reset():
    yield
    faults.reset()


# --------------------------------------------------------------------- #
# world-epoch record
# --------------------------------------------------------------------- #


def test_world_record_roundtrip():
    ws = elastic.WorldState(epoch=3, coordinator="127.0.0.1:1234",
                            num_processes=4)
    elastic.write_world(EXP, TRIAL, ws)
    got = elastic.read_world(EXP, TRIAL)
    assert got == ws
    # replace semantics: the supervisor bumps in place
    elastic.write_world(EXP, TRIAL, elastic.WorldState(4, "127.0.0.1:9", 4))
    assert elastic.read_world(EXP, TRIAL).epoch == 4


def test_read_world_tolerates_absent_and_malformed():
    assert elastic.read_world(EXP, TRIAL) is None
    name_resolve.add(names.elastic_world(EXP, TRIAL), "{not json",
                     replace=True)
    assert elastic.read_world(EXP, TRIAL) is None


def test_wait_for_world_min_epoch_and_timeout():
    elastic.write_world(EXP, TRIAL, elastic.WorldState(1, "c:1", 2))
    assert elastic.wait_for_world(EXP, TRIAL, min_epoch=1, timeout=1).epoch == 1
    with pytest.raises(TimeoutError):
        elastic.wait_for_world(EXP, TRIAL, min_epoch=2, timeout=0.3,
                               poll_s=0.05)


# --------------------------------------------------------------------- #
# leases + key hygiene (the dead-rank sweep satellite)
# --------------------------------------------------------------------- #


def test_lease_publish_and_read():
    lease = elastic.RankLease(EXP, TRIAL, 2, interval_s=30.0)
    lease.start()
    lease.set_epoch(5)
    try:
        got = elastic.read_leases(EXP, TRIAL)
        assert got[2]["epoch"] == 5
        assert got[2]["pid"] == os.getpid()
    finally:
        lease.stop()


def test_sweep_rank_keys_removes_all_residue():
    """Dead-rank keys (lease, heartbeat, telemetry snapshot) must be swept
    on the world-epoch bump instead of accumulating across reformations."""
    worker = elastic.rank_worker_name(1)
    name_resolve.add(names.elastic_lease(EXP, TRIAL, 1), "{}", replace=True)
    name_resolve.add(names.worker_status(EXP, TRIAL, worker), "123",
                     replace=True)
    name_resolve.add(names.telemetry(EXP, TRIAL, worker), "{}", replace=True)
    # an unrelated rank's keys must survive the sweep
    name_resolve.add(names.elastic_lease(EXP, TRIAL, 0), "{}", replace=True)
    assert elastic.sweep_rank_keys(EXP, TRIAL, 1) == 3
    assert elastic.read_leases(EXP, TRIAL) == {0: {}}
    with pytest.raises(name_resolve.NameEntryNotFoundError):
        name_resolve.get(names.worker_status(EXP, TRIAL, worker))
    with pytest.raises(name_resolve.NameEntryNotFoundError):
        name_resolve.get(names.telemetry(EXP, TRIAL, worker))
    # idempotent: a second sweep finds nothing
    assert elastic.sweep_rank_keys(EXP, TRIAL, 1) == 0


def test_timeout_reports_roundtrip_and_sweep():
    elastic.report_timeout(EXP, TRIAL, 0, 1, "barrier timed out")
    elastic.report_timeout(EXP, TRIAL, 0, 3, "allgather timed out")
    elastic.report_timeout(EXP, TRIAL, 1, 2, "next epoch")
    assert sorted(elastic.read_timeout_reports(EXP, TRIAL, 0)) == [1, 3]
    elastic.sweep_timeout_reports(EXP, TRIAL, upto_epoch=0)
    assert elastic.read_timeout_reports(EXP, TRIAL, 0) == {}
    assert sorted(elastic.read_timeout_reports(EXP, TRIAL, 1)) == [2]


# --------------------------------------------------------------------- #
# bounded-timeout collectives
# --------------------------------------------------------------------- #


def test_guard_runs_and_returns():
    g = elastic.CollectiveGuard(timeout_s=5.0)
    assert g.run(lambda: 42, "test") == 42


def test_guard_timeout_within_deadline():
    g = elastic.CollectiveGuard(timeout_s=0.3)
    before = metrics_mod.counters.get(metrics_mod.FT_COLLECTIVE_TIMEOUTS)
    t0 = time.monotonic()
    with pytest.raises(elastic.CollectiveTimeoutError):
        g.run(lambda: time.sleep(10), "wedged")
    assert time.monotonic() - t0 < 3.0  # raised near the deadline, no hang
    assert (
        metrics_mod.counters.get(metrics_mod.FT_COLLECTIVE_TIMEOUTS)
        == before + 1
    )
    # the worker thread is wedged; reset installs a fresh one
    g.reset()
    assert g.run(lambda: "fresh", "after-reset") == "fresh"


def test_guard_abort_condemns_epoch():
    g = elastic.CollectiveGuard(timeout_s=5.0)
    g.abort()
    with pytest.raises(elastic.CollectiveTimeoutError):
        g.run(lambda: 1, "condemned")
    g.reset()
    assert g.run(lambda: 1, "recovered") == 1


def test_guard_classifies_transport_errors():
    g = elastic.CollectiveGuard(timeout_s=5.0)

    def boom():
        raise ConnectionResetError("peer died")

    with pytest.raises(elastic.CollectiveFailedError):
        g.run(boom, "transport")

    def bug():
        raise ValueError("a real bug")

    with pytest.raises(ValueError):  # program bugs propagate unchanged
        g.run(bug, "bug")


def test_guard_fault_point_injects_timeout():
    """The collective.timeout fault point deterministically scripts a
    timeout without real wedging (used by the chaos harness)."""
    g = elastic.CollectiveGuard(timeout_s=30.0)
    ran = []
    faults.inject("collective.timeout", action="trip", times=1,
                  label="barrier:x")
    with pytest.raises(elastic.CollectiveTimeoutError):
        g.run(lambda: ran.append(1), "barrier:x")
    assert not ran  # the collective body never executed
    assert g.run(lambda: "ok", "barrier:x") == "ok"  # rule exhausted


def test_as_world_failure_classification():
    assert elastic.as_world_failure(ValueError("x")) is None
    wf = elastic.as_world_failure(ConnectionError("reset"))
    assert isinstance(wf, elastic.CollectiveFailedError)
    original = elastic.CollectiveTimeoutError("t")
    assert elastic.as_world_failure(original) is original

    class XlaRuntimeError(RuntimeError):  # matched by name, not import
        pass

    assert isinstance(
        elastic.as_world_failure(XlaRuntimeError("gloo died")),
        elastic.CollectiveFailedError,
    )
    # deterministic rank-local XLA errors must NOT trigger reforms — an
    # OOM or shape bug reproduces identically after every rebuild
    assert elastic.as_world_failure(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory on device")
    ) is None
    assert elastic.as_world_failure(
        XlaRuntimeError("INVALID_ARGUMENT: shapes do not match")
    ) is None


# --------------------------------------------------------------------- #
# supervisor culprit decisions
# --------------------------------------------------------------------- #


def test_decide_culprits_exited_only():
    assert WorldSupervisor.decide_culprits(
        {2: -9}, {0: {}, 1: {}}, alive=[0, 1, 3]
    ) == [2]
    # clean exits are never culprits
    assert WorldSupervisor.decide_culprits({3: 0}, {}, alive=[0, 1, 2]) == []


def test_decide_culprits_wedged_only_after_deadline():
    reports = {0: {}, 1: {}}
    alive = [0, 1, 2]
    assert WorldSupervisor.decide_culprits(
        {}, reports, alive, wedge_deadline_passed=False
    ) == []
    assert WorldSupervisor.decide_culprits(
        {}, reports, alive, wedge_deadline_passed=True
    ) == [2]


def test_decide_culprits_mixed_counts_once():
    # a rank that exited AND reported (died while reforming) counts once
    assert WorldSupervisor.decide_culprits(
        {1: -6, 2: 1}, {1: {}, 0: {}}, alive=[0, 3],
        wedge_deadline_passed=True,
    ) == [1, 2, 3]


# --------------------------------------------------------------------- #
# supervisor end-to-end with jax-free stub ranks
# --------------------------------------------------------------------- #

_STUB = textwrap.dedent(
    """
    import json, os, sys, time
    rank = int(sys.argv[1]); root = sys.argv[2]; mode = sys.argv[3]
    sys.path.insert(0, sys.argv[4])
    from areal_tpu.base import name_resolve, names
    from areal_tpu.parallel import elastic
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(type="file", root=root))
    EXP, TRIAL = "elastic_test", "t0"
    lease = elastic.RankLease(EXP, TRIAL, rank, interval_s=0.1).start()
    while True:
        ws = elastic.read_world(EXP, TRIAL)
        if ws is None:
            time.sleep(0.05); continue
        lease.set_epoch(ws.epoch)
        if ws.epoch == 0:
            if mode == "die":
                os._exit(3)
            if mode == "worldfail":
                os._exit(77)   # EXIT_WORLD_FAILED: explicit escalation
            if mode == "preempted":
                os._exit(75)   # EXIT_PREEMPTED: slice reclaimed
            if mode == "hang":
                time.sleep(600)
            if mode == "survivor":
                # a survivor's bounded collective "timed out": report and
                # wait for the next epoch, like WorldEpochManager.reform
                elastic.report_timeout(EXP, TRIAL, 0, rank, "stub timeout")
                ws = elastic.wait_for_world(EXP, TRIAL, min_epoch=1,
                                            timeout=30)
                lease.set_epoch(ws.epoch)
        # any rank at epoch >= 1 (or a plain rank at epoch 0) finishes
        if ws.epoch >= 1 or mode == "normal":
            time.sleep(0.3)   # outlive one supervisor poll
            os._exit(0)
        time.sleep(0.05)
    """
)


def _stub_world(tmp_path, modes, **cfg_kw):
    """A WorldSupervisor over jax-free stub ranks; returns (rc, sup)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nr_root = str(tmp_path / "nr")
    stub = tmp_path / "stub.py"
    stub.write_text(_STUB)
    # the supervisor process reads/writes the same file-backed repo
    name_resolve.set_repository(
        name_resolve.make_repository(
            name_resolve.NameResolveConfig(type="file", root=nr_root)
        )
    )
    sup = WorldSupervisor(
        WorldSupervisorConfig(
            experiment_name=EXP,
            trial_name=TRIAL,
            num_processes=len(modes),
            rank_cmd=lambda r: [
                sys.executable, str(stub), str(r), nr_root, modes[r], repo
            ],
            poll_s=0.05,
            exit_grace_s=0.1,
            collective_timeout_s=cfg_kw.pop("collective_timeout_s", 0.5),
            report_grace_s=cfg_kw.pop("report_grace_s", 0.5),
            reform_timeout_s=20.0,
            **cfg_kw,
        )
    )
    rc = sup.start().run(timeout=60.0)
    return rc, sup


def test_supervisor_recovers_dead_rank(tmp_path):
    before = metrics_mod.counters.get(metrics_mod.FT_RANK_RESTARTS)
    rc, sup = _stub_world(tmp_path, {0: "survivor", 1: "die", 2: "survivor"})
    assert rc == 0
    assert sup.rank_restarts == 1 and sup.epoch == 1
    assert len(sup.recovery_times) == 1
    assert (
        metrics_mod.counters.get(metrics_mod.FT_RANK_RESTARTS) == before + 1
    )
    # hygiene: the relaunched rank's lease exists at the final epoch only
    leases = elastic.read_leases(EXP, TRIAL)
    assert sorted(leases) == [0, 1, 2]
    assert all(d["epoch"] == 1 for d in leases.values())
    # consumed timeout reports were swept on the bump
    assert elastic.read_timeout_reports(EXP, TRIAL, 0) == {}


def test_supervisor_kills_wedged_rank_after_deadline(tmp_path):
    rc, sup = _stub_world(
        tmp_path, {0: "survivor", 1: "hang", 2: "survivor"}
    )
    assert rc == 0
    assert sup.rank_restarts == 1 and sup.epoch == 1


def test_supervisor_clean_world_no_reform(tmp_path):
    rc, sup = _stub_world(tmp_path, {0: "normal", 1: "normal"})
    assert rc == 0
    assert sup.rank_restarts == 0 and sup.epoch == 0


def test_supervisor_escalates_on_exit_world_failed(tmp_path):
    """EXIT_WORLD_FAILED (77) is a rank explicitly giving up on surgical
    recovery — the supervisor must escalate to restart-the-world, not
    hand the rank a fresh reform budget."""
    rc, sup = _stub_world(tmp_path, {0: "survivor", 1: "worldfail"})
    assert rc == 1
    assert sup.rank_restarts == 0 and sup.epoch == 0


def test_supervisor_stops_on_preemption(tmp_path):
    """EXIT_PREEMPTED means the slice is being reclaimed: the rank's
    state is its committed checkpoint — relaunching would burn the
    preemption grace window on churn."""
    from areal_tpu.system import worker_base

    rc, sup = _stub_world(tmp_path, {0: "survivor", 1: "preempted"})
    assert rc == worker_base.EXIT_PREEMPTED
    assert sup.rank_restarts == 0 and sup.epoch == 0


def test_supervisor_budget_exhaustion(tmp_path):
    # every relaunch dies again at epoch... the stub dies only at epoch 0;
    # use a mode map where rank 1 dies at every epoch via max_rank_restarts=0
    rc, sup = _stub_world(
        tmp_path, {0: "survivor", 1: "die"}, max_rank_restarts=0
    )
    assert rc == 1
    assert sup.rank_restarts == 0


# --------------------------------------------------------------------- #
# seeded chaos schedules
# --------------------------------------------------------------------- #


def test_schedule_deterministic_and_bounded():
    a = chaos.make_schedule(7, 4, 4, 20, 5)
    b = chaos.make_schedule(7, 4, 4, 20, 5)
    assert a == b and len(a) == 4
    for i, ev in enumerate(a):
        assert ev["kind"] in ("kill", "hang")
        assert 0 <= ev["rank"] < 4
        assert ev["epoch"] == i
        assert 1 <= ev["step"] < 20


def test_schedule_events_guaranteed_to_fire():
    """Each epoch's fault step must be reachable from the previous
    fault's committed-checkpoint resume point."""
    for seed in range(20):
        sched = chaos.make_schedule(seed, 5, 4, 24, 4)
        resume = 0
        for ev in sched:
            assert ev["step"] >= resume, (seed, sched)
            resume = (ev["step"] // 4) * 4


# --------------------------------------------------------------------- #
# catalog sync: FAULT_POINTS vs docs/fault_tolerance.md
# --------------------------------------------------------------------- #


def test_fault_point_catalog_matches_docs_table():
    """The injection-point table in docs/fault_tolerance.md and the
    FAULT_POINTS registry must name exactly the same points — the same
    loud-drift contract as the arealint mesh catalog."""
    doc = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "fault_tolerance.md",
    )
    with open(doc) as f:
        text = f.read()
    # rows look like: | `gen.http`          | where ... | kwargs |
    documented = set(
        re.findall(r"^\|\s*`([a-z_.]+)`\s*\|", text, flags=re.MULTILINE)
    )
    assert documented == set(faults.FAULT_POINTS), (
        "docs/fault_tolerance.md injection-point table drifted from "
        f"base/faults.py FAULT_POINTS: doc-only={documented - set(faults.FAULT_POINTS)}, "
        f"registry-only={set(faults.FAULT_POINTS) - documented}"
    )


# --------------------------------------------------------------------- #
# metrics + obs surfacing
# --------------------------------------------------------------------- #


def test_elastic_counters_registered():
    from areal_tpu.system.telemetry import _ft_catalog

    cat = _ft_catalog()
    for key in (
        metrics_mod.FT_RANK_RESTARTS,
        metrics_mod.FT_WORLD_EPOCHS,
        metrics_mod.FT_COLLECTIVE_TIMEOUTS,
    ):
        assert key in cat  # zero-filled into every fleet/ record
    assert (
        metrics_mod.METRIC_KINDS[metrics_mod.RECOVERY_TIME_S]
        == metrics_mod.KIND_HISTOGRAM
    )
    reg = metrics_mod.CounterRegistry()
    reg.observe(metrics_mod.RECOVERY_TIME_S, 12.5)
    assert reg.histogram_summaries()[metrics_mod.RECOVERY_TIME_S]["count"] == 1


def test_obs_has_supervisor_headline_row():
    from areal_tpu.apps.obs import _ROLE_HEADLINE

    label, key = _ROLE_HEADLINE["supervisor"]
    assert key == metrics_mod.FT_RANK_RESTARTS


def test_exit_world_failed_code_distinct():
    from areal_tpu.system import worker_base

    assert worker_base.EXIT_WORLD_FAILED == 77
    assert len({
        worker_base.EXIT_PREEMPTED,
        worker_base.EXIT_WATCHDOG,
        worker_base.EXIT_WORLD_FAILED,
    }) == 3


# --------------------------------------------------------------------- #
# trainer surgical recovery (fake world manager, real engines)
# --------------------------------------------------------------------- #


def test_trainer_elastic_recover_rolls_back_and_republishes(
    tmp_path, monkeypatch
):
    """_elastic_recover must: reform, swap in factory-built engines,
    restore the committed recover checkpoint (identical step), and
    republish under a NEW monotonic version so the manager cannot drop
    the announce."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    from areal_tpu.base import constants
    from tests import test_fault_tolerance as ft
    from tests.test_fault_tolerance import _tiny_trainer

    constants.set_experiment_trial_names(ft.EXP, ft.TRIAL)
    worker, eng, stream = _tiny_trainer()
    worker.step = 4
    worker.samples_consumed = 8
    worker.save_recover_checkpoint()
    ckpt_step = worker.step
    # the run moved on past the checkpoint before the world failed
    worker.step = 6
    eng.version = 9

    class _FakeWorld:
        epoch = 2

    class _FakeMgr:
        world = _FakeWorld()

        def __init__(self):
            self.reform_reasons = []

        def reform(self, reason):
            self.reform_reasons.append(reason)
            return self.world

    mgr = _FakeMgr()
    _, fresh_eng, _ = _tiny_trainer()

    def factory():
        return fresh_eng, None, None, None

    worker._elastic_recover(
        mgr, factory, elastic.CollectiveFailedError("peer died")
    )
    assert mgr.reform_reasons  # the world actually reformed
    assert worker.actor_engine is fresh_eng  # engines rebuilt
    assert worker.step == ckpt_step  # identical resume step
    # republished under a NEW version the fleet cannot drop
    assert worker.actor_engine.version > 9
    v = name_resolve.get(names.model_version(ft.EXP, ft.TRIAL, "actor"))
    assert int(v.split(":")[0]) == worker.actor_engine.version

    # and WITHOUT a committed checkpoint, survivors reset to the fresh
    # start the relaunched rank will take — keeping the pre-failure step
    # would desynchronize every step-keyed collective branch
    worker.step = 6
    worker.samples_consumed = 12
    import unittest.mock as mock

    with mock.patch.object(
        type(worker), "load_recover_checkpoint", return_value=False
    ):
        worker._elastic_recover(
            mgr, factory, elastic.CollectiveFailedError("peer died again")
        )
    assert worker.step == 0 and worker.samples_consumed == 0
