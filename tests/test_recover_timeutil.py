from areal_tpu.base import recover
from areal_tpu.base.recover import RecoverInfo, StepInfo
from areal_tpu.base.timeutil import EpochStepTimeFreqCtl


def test_freq_ctl_step():
    ctl = EpochStepTimeFreqCtl(freq_step=3)
    assert [ctl.check() for _ in range(7)] == [
        False, False, True, False, False, True, False,
    ]


def test_freq_ctl_epoch():
    ctl = EpochStepTimeFreqCtl(freq_epoch=2)
    assert not ctl.check(epochs=1)
    assert ctl.check(epochs=1)


def test_freq_ctl_state_roundtrip():
    ctl = EpochStepTimeFreqCtl(freq_step=5)
    ctl.check()
    ctl.check()
    st = ctl.state_dict()
    ctl2 = EpochStepTimeFreqCtl(freq_step=5)
    ctl2.load_state_dict(st)
    assert not ctl2.check()
    assert not ctl2.check()
    assert ctl2.check()


def test_step_info_next():
    s = StepInfo(0, 4, 9)
    s2 = s.next(steps_per_epoch=5)
    assert (s2.epoch, s2.epoch_step, s2.global_step) == (1, 0, 10)


def test_recover_info_roundtrip(tmp_path):
    info = RecoverInfo(
        recover_start=StepInfo(1, 2, 3),
        last_step_info=StepInfo(1, 1, 2),
        save_ctl_states={"actor": {"epoch_count": 0, "step_count": 1}},
        hash_vals_to_ignore=[123, 456],
    )
    recover.dump(info, root=str(tmp_path))
    loaded = recover.load(root=str(tmp_path))
    assert loaded.recover_start == StepInfo(1, 2, 3)
    assert loaded.hash_vals_to_ignore == [123, 456]
    assert recover.load(root=str(tmp_path / "nope")) is None
