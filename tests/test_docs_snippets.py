"""Docs snippets are executable (VERDICT r4 #10 'Done' criterion): every
fenced ```python block in docs/ runs top-to-bottom in one namespace per
document — a guide whose code drifts from the API fails CI, the way the
reference treats extensibility docs as part of the product
(``/root/reference/docs/customization/``)."""

import glob
import os
import re

import pytest

DOCS = os.path.join(os.path.dirname(__file__), os.pardir, "docs")
_FENCE = re.compile(r"```python\n(.*?)```", re.DOTALL)


def _doc_files():
    return sorted(
        glob.glob(os.path.join(DOCS, "**", "*.md"), recursive=True)
    )


def test_docs_exist():
    names = {os.path.relpath(p, DOCS) for p in _doc_files()}
    for required in (
        "architecture.md",
        "multihost.md",
        os.path.join("customization", "agent.md"),
        os.path.join("customization", "dataset.md"),
        os.path.join("customization", "reward.md"),
        os.path.join("customization", "model_family.md"),
    ):
        assert required in names, required


@pytest.mark.parametrize(
    "path", _doc_files(), ids=lambda p: os.path.relpath(p, DOCS)
)
def test_doc_snippets_run(path):
    blocks = _FENCE.findall(open(path).read())
    if not blocks:
        pytest.skip("no python blocks")
    ns: dict = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"{path}#block{i}", "exec"), ns)
        except Exception as e:
            raise AssertionError(
                f"snippet {i} in {os.path.relpath(path, DOCS)} failed: "
                f"{e!r}\n---\n{block}"
            ) from e
