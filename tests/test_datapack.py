import numpy as np
import pytest

from areal_tpu.base import datapack


@pytest.mark.parametrize("k", [1, 2, 4, 7])
def test_partition_balanced_valid(rng, k):
    nums = rng.integers(1, 1000, size=37).tolist()
    bounds = datapack.partition_balanced(nums, k)
    assert bounds[0] == 0 and bounds[-1] == len(nums)
    assert all(bounds[i] < bounds[i + 1] for i in range(k))


def test_partition_balanced_optimal_small():
    # Brute-force check optimality on small inputs.
    import itertools

    rng = np.random.default_rng(0)
    for _ in range(20):
        nums = rng.integers(1, 50, size=8).tolist()
        k = 3
        bounds = datapack.partition_balanced(nums, k)
        got = max(
            sum(nums[bounds[i]: bounds[i + 1]]) for i in range(k)
        )
        best = min(
            max(sum(nums[a:b]), sum(nums[b:c]), sum(nums[c:]))
            for a, b, c in [(0, b, c) for b in range(1, 7) for c in range(b + 1, 8)]
        )
        assert got == best


def test_partition_min_size():
    nums = [100, 1, 1, 1]
    bounds = datapack.partition_balanced(nums, 2, min_size=2)
    assert bounds == [0, 2, 4]


def test_ffd_allocate():
    sizes = [5, 9, 3, 7, 2, 6]
    bins = datapack.ffd_allocate(sizes, capacity=10)
    seen = sorted(i for b in bins for i in b)
    assert seen == list(range(6))
    for b in bins:
        assert sum(sizes[i] for i in b) <= 10


def test_ffd_min_groups():
    bins = datapack.ffd_allocate([1, 1], capacity=100, min_groups=2)
    assert len(bins) >= 2


def test_ffd_oversize_item():
    bins = datapack.ffd_allocate([50, 5], capacity=10)
    assert [50] in [[sum([50, 5][i] for i in b)] for b in bins] or any(
        b == [0] for b in bins
    )
