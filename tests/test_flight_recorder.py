"""Crash flight recorder (docs/observability.md "Crash flight
recorder"): dump payload schema + atomic file naming, every wired
trigger — unhandled-crash excepthook, SIGTERM/preemption request,
watchdog trip — and the module-level ``flight_dump`` no-plumbing hook."""

import json
import logging
import sys
import time

import pytest

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.base import tracing
from areal_tpu.system import worker_base


@pytest.fixture
def recorder(tmp_path):
    """An installed recorder with a private counter registry, uninstalled
    (and module state restored) on teardown."""
    reg = metrics_mod.CounterRegistry()
    rec = worker_base.FlightRecorder(
        "trainer/0", root=str(tmp_path), span_tail=32, log_tail=50,
        registry=reg,
    )
    rec.install()
    yield rec
    rec.uninstall()


def _dumps(tmp_path):
    return sorted(tmp_path.glob("*.json"))


class TestFlightRecorder:
    def test_dump_payload_and_naming(self, tmp_path, recorder):
        recorder._registry.add("train/steps", 7)
        logging.getLogger("areal_tpu.fr_test").warning("last words")
        with tracing.span("t/fr_done"):
            pass
        with tracing.span("t/fr_open", rid="r1"):
            path = recorder.dump("watchdog", extra={"stalled_s": 12.5})
        assert path is not None
        files = _dumps(tmp_path)
        assert [f.name for f in files] == [
            f"trainer_0-{recorder._payload('x', None)['pid']}-001-"
            "watchdog.json"
        ]
        assert not list(tmp_path.glob("*.tmp"))  # atomic: no tmp left
        d = json.loads(files[0].read_text())
        assert d["schema"] == 1
        assert d["worker"] == "trainer/0"
        assert d["reason"] == "watchdog"
        assert d["extra"] == {"stalled_s": 12.5}
        assert d["time"] <= time.time()
        # counter DELTA since install, from the recorder's own registry
        assert d["counters"] == {"train/steps": 7.0}
        assert any(s["name"] == "t/fr_done" for s in d["spans"])
        assert any(s["name"] == "t/fr_open" for s in d["open_spans"])
        assert any("last words" in l for l in d["log_tail"])

    def test_dump_sequence_numbers(self, tmp_path, recorder):
        recorder.dump("preempt")
        recorder.dump("crash")
        names = [f.name for f in _dumps(tmp_path)]
        assert names[0].endswith("-001-preempt.json")
        assert names[1].endswith("-002-crash.json")
        assert recorder.dumps == 2

    def test_excepthook_dumps_then_chains(self, tmp_path, monkeypatch):
        seen = []
        monkeypatch.setattr(
            sys, "excepthook", lambda *a: seen.append(a[0])
        )
        rec = worker_base.FlightRecorder("gw/1", root=str(tmp_path))
        rec.install()
        try:
            assert sys.excepthook == rec._excepthook
            sys.excepthook(ValueError, ValueError("boom"), None)
        finally:
            rec.uninstall()
        assert seen == [ValueError]  # prior hook still ran
        (f,) = _dumps(tmp_path)
        assert f.name.endswith("-crash.json")
        d = json.loads(f.read_text())
        assert d["extra"]["exc"] == "ValueError"
        assert any("boom" in l for l in d["extra"]["traceback"])
        # uninstall restored the monkeypatched hook
        assert sys.excepthook is not rec._excepthook

    def test_flight_dump_noop_without_recorder(self):
        assert worker_base.flight_recorder() is None
        assert worker_base.flight_dump("crash") is None

    def test_install_registers_module_recorder(self, recorder, tmp_path):
        assert worker_base.flight_recorder() is recorder
        assert worker_base.flight_dump("train_guard_rollback",
                                       {"live_version": 3}) is not None
        d = json.loads(_dumps(tmp_path)[0].read_text())
        assert d["reason"] == "train_guard_rollback"
        assert d["extra"] == {"live_version": 3}


class TestFlightTriggers:
    def test_preempt_request_dumps_once(self, tmp_path, recorder):
        gs = worker_base.GracefulShutdown(deadline_s=30.0, install=False)
        assert not gs.should_stop()
        assert not _dumps(tmp_path)
        gs.request()
        gs.request()  # idempotent: evidence from the FIRST request only
        assert gs.should_stop()
        files = _dumps(tmp_path)
        assert len(files) == 1
        d = json.loads(files[0].read_text())
        assert d["reason"] == "preempt"
        assert d["extra"] == {"deadline_s": 30.0}

    def test_watchdog_trip_dumps(self, tmp_path, recorder, monkeypatch):
        monkeypatch.delenv("AREAL_WATCHDOG_ABORT", raising=False)
        tripped = []
        wd = worker_base.HangWatchdog(
            "unit", timeout_s=0.05, poll_interval=0.02,
            on_dump=tripped.append,
        )
        wd.start()
        try:
            deadline = time.monotonic() + 5.0
            while not tripped and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            wd.stop()
        assert tripped and wd.dumps >= 1
        d = json.loads(_dumps(tmp_path)[0].read_text())
        assert d["reason"] == "watchdog"
        assert d["extra"]["timeout_s"] == 0.05
        assert d["extra"]["stalled_s"] >= 0.05

    def test_watchdog_bump_prevents_dump(self, tmp_path, recorder):
        wd = worker_base.HangWatchdog(
            "unit", timeout_s=0.2, poll_interval=0.02
        )
        wd.start()
        try:
            for _ in range(10):
                wd.bump()
                time.sleep(0.03)
        finally:
            wd.stop()
        assert wd.dumps == 0
        assert not _dumps(tmp_path)
