"""Packing + TrainEngine tests on the 8-device virtual CPU mesh.

Counterpart of the reference's CPU ``mock_train`` backend tests: real pjit
sharding (d2×f2×m2 = 8 devices), tiny model, real optimizer steps.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.models.config import ModelConfig
from areal_tpu.ops import ppo as ppo_ops
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.train import batching
from areal_tpu.train.engine import OptimizerConfig, TrainEngine, vmapped_forward

TINY = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


def _make_sample(rng, n_items=6, with_reward=False):
    seqlens = [int(n) for n in rng.integers(4, 12, size=n_items)]
    data = {
        "packed_input_ids": np.concatenate(
            [rng.integers(0, 128, size=n).astype(np.int64) for n in seqlens]
        ),
        "prompt_mask": np.concatenate(
            [
                np.r_[np.ones(2, np.bool_), np.zeros(n - 2, np.bool_)]
                for n in seqlens
            ]
        ),
    }
    if with_reward:
        data["rewards"] = rng.normal(size=n_items).astype(np.float32)
    return SequenceSample.from_default(
        ids=list(range(n_items)), seqlens=seqlens, data=data
    )


def test_pack_roundtrip(rng):
    sample = _make_sample(rng, with_reward=True)
    pb = batching.pack_sequences(sample, n_rows=4, pad_multiple=16)
    assert pb.arrays["input_ids"].shape == pb.arrays["segment_ids"].shape
    # every sequence present exactly once, token-aligned
    outs = pb.unpack(pb.arrays["input_ids"])
    full = sample.data["packed_input_ids"]
    offsets = np.cumsum([0] + [l[0] for l in sample.seqlens["packed_input_ids"]])
    for p, got in zip(pb.placements, outs):
        np.testing.assert_array_equal(
            got, full[offsets[p.item_idx] : offsets[p.item_idx] + p.length]
        )
    # scalar broadcast: rewards constant over each segment
    for p in pb.placements:
        seg = pb.arrays["rewards"][p.row, p.start : p.start + p.length]
        assert np.all(seg == sample.data["rewards"][p.item_idx])
    # padding rows zero
    assert np.all(
        pb.arrays["input_ids"][pb.arrays["segment_ids"] == 0] == 0
    )


def test_pack_balance(rng):
    lens = [100, 1, 1, 1, 50, 50, 1, 1]
    rows = batching.plan_rows(lens, 2)
    loads = [sum(l for l, r in zip(lens, rows) if r == j) for j in range(2)]
    assert abs(loads[0] - loads[1]) <= 100 - 50  # LPT puts 100 alone-ish
    assert max(loads) <= 104


def _sft_loss(params, cfg, arrays):
    logits = vmapped_forward(params, cfg, arrays)
    lp = jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
        logits, arrays["input_ids"], arrays["segment_ids"]
    )
    seg = arrays["segment_ids"]
    has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
    mask = has_next & ~arrays["prompt_mask"]
    n = jnp.maximum(mask.sum(), 1)
    loss = -jnp.sum(jnp.where(mask, lp, 0.0)) / n
    return loss, {"n_tokens": n}


@pytest.fixture(scope="module")
def engine():
    eng = TrainEngine(
        TINY,
        parallel=ParallelConfig(data=2, fsdp=2, model=2),
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="cosine"),
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=50)
    return eng


def test_sharded_init(engine):
    # wq is [L, E, H*D]: embed axis sharded over fsdp, heads over model
    spec = engine.params["layers"]["attn"]["wq"].sharding.spec
    assert spec == jax.sharding.PartitionSpec(None, "fsdp", "model")


def test_train_batch_loss_decreases(engine, rng):
    sample = _make_sample(rng, n_items=8)
    spec = MicroBatchSpec(n_mbs=2, max_tokens_per_mb=64)
    losses = []
    for _ in range(8):
        stats = engine.train_batch(sample, spec, _sft_loss)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0]
    assert stats["grad_norm"] > 0
    assert stats["lr"] > 0


@pytest.mark.parametrize(
    "par", [ParallelConfig(), ParallelConfig(data=2, fsdp=2, model=2)],
    ids=["single", "d2f2m2"],
)
def test_no_recompile_across_rounds(rng, par):
    """Identical-shape train rounds must backend-compile exactly once
    (VERDICT r3 weak #1). Two past offenders: (a) jit(tx.init) left the
    optax count scalars SingleDeviceSharding while the train step emitted
    NamedSharding(mesh, P()) — the sharding-in-types aval mismatch forced
    a FULL second train-step compile on round 2 of every run (64.7 s at
    bench shape on the chip); (b) on multi-device meshes GSPMD's inferred
    output shardings for the opt state drifted from the init-time ones —
    a trace-cache HIT but a second backend compile (now pinned via
    out_shardings)."""
    from jax._src import monitoring

    eng = TrainEngine(
        TINY, parallel=par,
        optimizer=OptimizerConfig(lr=1e-3),
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=50)
    sample = _make_sample(rng, n_items=8)
    spec = MicroBatchSpec(n_mbs=1, max_tokens_per_mb=256)
    compiles = []

    def on_dur(key, dur, **kw):
        if key == "/jax/core/compile/backend_compile_duration":
            compiles.append(dur)

    monitoring.register_event_duration_secs_listener(on_dur)
    try:
        eng.train_batch(sample, spec, _sft_loss, fetch_stats=False)
        n_round1 = len(compiles)
        assert n_round1 >= 1  # round 1 really compiled the step
        for _ in range(3):
            eng.train_batch(sample, spec, _sft_loss, fetch_stats=False)
        assert len(compiles) == n_round1, (
            f"rounds 2-4 backend-compiled {len(compiles) - n_round1} more "
            "program(s) at identical shapes"
        )
    finally:
        # the public unregister name moved across jax versions; fall back to
        # the by-callback private API so the listener never leaks into
        # subsequent tests
        unreg = getattr(
            monitoring, "unregister_event_duration_listener",
            getattr(
                monitoring, "_unregister_event_duration_listener_by_callback",
            ),
        )
        unreg(on_dur)


def test_forward_unpacks_per_sequence(engine, rng):
    sample = _make_sample(rng, n_items=5)

    def logprob_fn(params, cfg, arrays):
        logits = vmapped_forward(params, cfg, arrays)
        return jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
            logits, arrays["input_ids"], arrays["segment_ids"]
        )

    outs = engine.forward(sample, MicroBatchSpec(n_mbs=2), logprob_fn)
    lens = [l[0] for l in sample.seqlens["packed_input_ids"]]
    assert len(outs) == 5
    # outputs come back in the sample's original item order despite the
    # reordering micro-batch split
    assert [o.shape[0] for o in outs] == lens


def test_checkpoint_roundtrip(engine, rng, tmp_path):
    sample = _make_sample(rng, n_items=4)
    path = str(tmp_path / "ckpt")
    engine.save_checkpoint(path)
    before = engine.eval_batch(sample, MicroBatchSpec(), _sft_loss)["loss"]
    engine.train_batch(sample, MicroBatchSpec(), _sft_loss)
    engine.load_checkpoint(path)
    after = engine.eval_batch(sample, MicroBatchSpec(), _sft_loss)["loss"]
    assert before == pytest.approx(after, rel=1e-6)


def test_micro_batch_split_respects_row_capacity():
    """ADVICE round 1 (medium): the token budget only bounded the average;
    a [16000, 500, 16000] batch with budget 16384 crashed the packer."""
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.train import batching

    lens = [16000, 500, 16000]
    sample = SequenceSample.from_default(
        ids=[0, 1, 2],
        seqlens=lens,
        data={"packed_input_ids": np.zeros(sum(lens), np.int64)},
    )
    parts = batching.split_into_micro_batches(
        sample, n_mbs=1, max_tokens_per_mb=16384, n_rows=1
    )
    for part in parts:
        pb = batching.pack_sequences(part, n_rows=1, capacity=16384)
        assert pb.capacity == 16384

    # a single over-long sequence is rejected at intake with a clear error
    big = SequenceSample.from_default(
        ids=[0],
        seqlens=[20000],
        data={"packed_input_ids": np.zeros(20000, np.int64)},
    )
    with pytest.raises(ValueError, match="can never be packed"):
        batching.split_into_micro_batches(
            big, n_mbs=1, max_tokens_per_mb=16384, n_rows=1
        )


def test_remat_policy_and_unroll_grad_parity(rng):
    """remat_policy / layer_scan_unroll are pure execution knobs: losses and
    gradients are identical across every combination."""
    import dataclasses

    from areal_tpu.models import transformer as tfm

    base = dataclasses.replace(TINY)
    T = 32
    ids = jnp.asarray(rng.integers(0, 128, T).astype(np.int32))
    seg = jnp.asarray(np.r_[np.ones(20, np.int32) * 1, np.ones(12, np.int32) * 2])
    pos = jnp.asarray(np.r_[np.arange(20), np.arange(12)].astype(np.int32))
    params = tfm.init_params(base, jax.random.key(0))

    def loss(cfg):
        def f(p):
            out = tfm.forward_packed(p, cfg, ids, seg, pos)
            return jnp.sum(out.astype(jnp.float32) ** 2) * 1e-4
        return jax.value_and_grad(f)(params)

    ref_l, ref_g = loss(base)
    for policy in ("full", "dots", "dots_attn", "none"):
        for unroll in (1, 2):
            cfg = dataclasses.replace(
                base, remat_policy=policy, layer_scan_unroll=unroll
            )
            l, g = loss(cfg)
            assert jnp.allclose(l, ref_l, atol=1e-6), (policy, unroll)
            for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(ref_g)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-5,
                    err_msg=f"{policy}/{unroll}",
                )


def test_chunked_loss_matches_dense(rng):
    """cfg.loss_chunk_size (blockwise LM-head cross-entropy, the 32k-logit
    memory saver) must match the dense loss in value AND gradients — incl.
    a chunk size that does not divide T (rounded down to a divisor)."""
    import dataclasses

    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.models import transformer as tfm

    cfg = TINY
    params = tfm.init_params(cfg, jax.random.key(3))
    T = 64
    arrays = {
        "input_ids": jnp.asarray(rng.integers(1, 128, (2, T)), jnp.int32),
        "segment_ids": jnp.asarray(
            np.tile(np.r_[np.ones(50), np.zeros(T - 50)], (2, 1)), jnp.int32
        ),
        "positions": jnp.asarray(np.tile(np.arange(T), (2, 1)), jnp.int32),
        "prompt_mask": jnp.asarray(
            np.tile(np.r_[np.ones(5), np.zeros(T - 5)], (2, 1)), bool
        ),
    }
    l_dense, _ = sft_loss_fn(params, cfg, arrays)
    g_dense = jax.grad(lambda p: sft_loss_fn(p, cfg, arrays)[0])(params)
    for chunk in (16, 24):  # 24 does not divide 64 -> rounds down to 16
        cfgc = dataclasses.replace(cfg, loss_chunk_size=chunk)
        l_c, _ = sft_loss_fn(params, cfgc, arrays)
        np.testing.assert_allclose(float(l_dense), float(l_c), atol=1e-5)
        g_c = jax.grad(lambda p: sft_loss_fn(p, cfgc, arrays)[0])(params)
        for a, b in zip(jax.tree.leaves(g_dense), jax.tree.leaves(g_c)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )


def _nan_on_empty_loss(params, cfg, arrays):
    """SFT-style loss WITHOUT the max(n, 1) clamp: an empty action mask
    yields 0/0 = nan — the loss-fn shape the engine must tolerate on
    all-padding micro-batches (engine comment in eval_batch: nan means the
    mb's weight is 0)."""
    logits = vmapped_forward(params, cfg, arrays)
    lp = jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
        logits, arrays["input_ids"], arrays["segment_ids"]
    )
    seg = arrays["segment_ids"]
    has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
    mask = has_next & ~arrays["prompt_mask"]
    loss = -jnp.sum(jnp.where(mask, lp, 0.0)) / mask.sum()
    return loss, {"n_tokens": mask.sum()}


def _fresh_tiny_engine():
    eng = TrainEngine(
        TINY, parallel=ParallelConfig(), optimizer=OptimizerConfig(lr=1e-3)
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=50)
    return eng


class TestTrainGuard:
    """On-device finite-ness guard (trainer survivability, PR 3)."""

    def test_injected_nan_step_skips_update_params_byte_identical(self, rng):
        from areal_tpu.base import faults

        eng = _fresh_tiny_engine()
        sample = _make_sample(rng, n_items=6)
        spec = MicroBatchSpec(n_mbs=2, max_tokens_per_mb=64)
        eng.train_batch(sample, spec, _sft_loss)  # warm; params move
        before = [np.asarray(l).copy() for l in jax.tree.leaves(eng.params)]
        opt_before = [
            np.asarray(l).copy() for l in jax.tree.leaves(eng.opt_state)
        ]
        try:
            faults.inject("train.step", action="trip", times=1)
            stats = eng.train_batch(sample, spec, _sft_loss)
        finally:
            faults.reset()
        # the poisoned update was selected away: params AND opt state
        # (Adam moments + count) byte-identical to the pre-step values
        assert stats["guard/step_ok"] == 0.0
        for a, b in zip(before, jax.tree.leaves(eng.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        for a, b in zip(opt_before, jax.tree.leaves(eng.opt_state)):
            np.testing.assert_array_equal(a, np.asarray(b))
        # next (clean) step trains normally
        stats = eng.train_batch(sample, spec, _sft_loss)
        assert stats["guard/step_ok"] == 1.0
        assert any(
            not np.array_equal(a, np.asarray(b))
            for a, b in zip(before, jax.tree.leaves(eng.params))
        )

    def test_empty_microbatch_nan_does_not_misfire_guard(self, rng):
        """A zero-weight (all-padding / all-prompt) micro-batch whose loss
        is 0/0 = nan must be SELECTED out, not scaled out — the guard must
        see a finite step and the other micro-batch must still train."""
        eng = _fresh_tiny_engine()
        lens = [10, 10, 10]
        data = {
            "packed_input_ids": rng.integers(
                0, 128, sum(lens)
            ).astype(np.int64),
            # one item is ALL prompt: zero action tokens -> its micro-batch
            # (forced by the tiny token budget) carries loss weight 0 and a
            # nan loss under _nan_on_empty_loss
            "prompt_mask": np.concatenate([
                np.r_[np.ones(2, np.bool_), np.zeros(8, np.bool_)],
                np.ones(10, np.bool_),
                np.r_[np.ones(2, np.bool_), np.zeros(8, np.bool_)],
            ]),
        }
        sample = SequenceSample.from_default(
            ids=[0, 1, 2], seqlens=lens, data=data
        )
        # one warm step so the lr warmup is past 0 (step-0 updates are
        # all-zero by schedule, which would mask the thing under test)
        eng.train_batch(
            sample, MicroBatchSpec(n_mbs=3, max_tokens_per_mb=16),
            _nan_on_empty_loss,
        )
        before = [np.asarray(l).copy() for l in jax.tree.leaves(eng.params)]
        stats = eng.train_batch(
            sample, MicroBatchSpec(n_mbs=3, max_tokens_per_mb=16),
            _nan_on_empty_loss,
        )
        assert stats["guard/step_ok"] == 1.0, "guard misfired on empty mb"
        assert np.isfinite(stats["loss"]) and np.isfinite(stats["grad_norm"])
        assert any(
            not np.array_equal(a, np.asarray(b))
            for a, b in zip(before, jax.tree.leaves(eng.params))
        )

    def test_eval_all_padding_mb_nan_has_zero_weight(self, rng):
        """Pins the engine comment in eval_batch: an all-padding packed
        buffer can evaluate to a nan loss, and the host-side weighting must
        zero it out rather than poison the epoch mean."""
        eng = _fresh_tiny_engine()
        sample = _make_sample(rng, n_items=4)
        _, packed, _ = eng._make_micro_batches(sample, MicroBatchSpec())
        empty = batching.empty_like(packed[0])
        ev = eng._get_jitted("eval", _nan_on_empty_loss)
        loss = np.asarray(
            jax.device_get(ev(eng.params, eng._put_batch(empty))[0])
        )
        assert np.isnan(loss)  # the raw all-padding loss IS nan...
        out = eng.eval_batch(sample, MicroBatchSpec(), _nan_on_empty_loss)
        assert np.isfinite(out["loss"])  # ...but the weighted mean is not


class TestAsyncSaveHF:
    def test_async_write_lands_and_runs_post_write(self, engine, tmp_path):
        import os

        path = str(tmp_path / "ckpt_async")
        flag = []
        t = engine.save_hf(
            path, "qwen2", async_write=True,
            post_write=lambda: flag.append(1),
        )
        assert t is not None
        t.join()
        assert t._areal_exc is None
        assert flag == [1]
        assert os.path.exists(os.path.join(path, "model.safetensors"))

    def test_async_write_failure_is_stored_not_swallowed(
        self, engine, monkeypatch
    ):
        """Review finding r5: a failed background write must surface to
        the joiner (trainer's _join_publish raises), not die silently."""
        from areal_tpu.models import hf as hf_conv

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(hf_conv, "save_hf_checkpoint", boom)
        t = engine.save_hf("/tmp/nowhere_ckpt", "qwen2", async_write=True)
        t.join()
        assert isinstance(t._areal_exc, OSError)
