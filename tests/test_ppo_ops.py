"""PPO math tests vs straightforward numpy references.

Counterpart of the reference's ``tests/cpp_extensions/test_cugae.py`` (CUDA
GAE vs python loop) and ``tests/data/test_dual_clip.py``.
"""

import numpy as np
import jax.numpy as jnp

from areal_tpu.ops import ppo


def _pack_segments(lens):
    T = sum(lens)
    seg = np.zeros(T, np.int32)
    off = 0
    for i, n in enumerate(lens):
        seg[off : off + n] = i + 1
        off += n
    return seg


def _numpy_gae(rewards, values, next_values, lens, gamma, lam):
    """Per-sequence reverse loop (the reference's pygae semantics with an
    aligned layout)."""
    adv = np.zeros_like(rewards)
    off = 0
    for n in lens:
        lastgaelam = 0.0
        for t in reversed(range(n)):
            i = off + t
            nv = next_values[i] if t == n - 1 else values[i + 1]
            delta = rewards[i] + gamma * nv - values[i]
            lastgaelam = delta + gamma * lam * (lastgaelam if t < n - 1 else 0.0)
            adv[i] = lastgaelam
        off += n
    return adv, adv + values


def test_segment_gae_matches_numpy(rng):
    lens = [5, 1, 9, 3]
    T = sum(lens) + 4  # trailing padding
    seg = np.zeros(T, np.int32)
    seg[: sum(lens)] = _pack_segments(lens)
    rewards = rng.normal(size=T).astype(np.float32)
    values = rng.normal(size=T).astype(np.float32)
    bootstrap = rng.normal(size=T).astype(np.float32)  # truncation bootstrap
    next_values = np.asarray(
        ppo.segment_next_values(jnp.asarray(values), jnp.asarray(seg), jnp.asarray(bootstrap))
    )
    adv, ret = ppo.segment_gae(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(next_values),
        jnp.asarray(seg), gamma=0.99, lam=0.95,
    )
    ref_adv, ref_ret = _numpy_gae(
        rewards[: sum(lens)], values[: sum(lens)], next_values[: sum(lens)],
        lens, 0.99, 0.95,
    )
    np.testing.assert_allclose(np.asarray(adv)[: sum(lens)], ref_adv, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret)[: sum(lens)], ref_ret, atol=1e-5)
    assert np.all(np.asarray(adv)[sum(lens):] == 0)
    assert np.all(np.asarray(ret)[sum(lens):] == 0)


def test_actor_loss_clip_and_dual_clip(rng):
    T = 64
    lp = rng.normal(size=T).astype(np.float32) * 0.1
    old = rng.normal(size=T).astype(np.float32) * 0.1
    adv = rng.normal(size=T).astype(np.float32)
    mask = rng.random(T) > 0.2

    loss, stat = ppo.actor_loss_fn(
        jnp.asarray(lp), jnp.asarray(old), jnp.asarray(adv), 0.2, jnp.asarray(mask)
    )
    # numpy reference
    ratio = np.where(mask, np.exp(lp - old), 0.0)
    clipped = np.clip(ratio, 0.8, 1.2)
    pg = np.maximum(-adv * ratio, -adv * clipped)
    ref = np.where(mask, pg, 0).sum() / mask.sum()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    # dual clip lower-bounds the loss for negative advantages with huge ratios
    lp2 = old + 3.0  # ratio e^3
    loss_noc, _ = ppo.actor_loss_fn(
        jnp.asarray(lp2), jnp.asarray(old), jnp.asarray(adv), 0.2, jnp.asarray(mask)
    )
    loss_c, stat_c = ppo.actor_loss_fn(
        jnp.asarray(lp2), jnp.asarray(old), jnp.asarray(adv), 0.2,
        jnp.asarray(mask), c_clip=3.0,
    )
    assert float(loss_c) <= float(loss_noc)
    assert bool(np.asarray(stat_c["dual_clip_mask"]).any())


def test_actor_loss_decoupled(rng):
    T = 32
    old = rng.normal(size=T).astype(np.float32) * 0.1      # behavior policy
    prox = old + rng.normal(size=T).astype(np.float32) * 0.05  # proximal
    lp = prox + rng.normal(size=T).astype(np.float32) * 0.05
    adv = rng.normal(size=T).astype(np.float32)
    mask = np.ones(T, bool)
    loss, stat = ppo.actor_loss_fn(
        jnp.asarray(lp), jnp.asarray(old), jnp.asarray(adv), 0.2,
        jnp.asarray(mask), proximal_logprobs=jnp.asarray(prox),
    )
    ratio = np.exp(lp - prox)
    clipped = np.clip(ratio, 0.8, 1.2)
    pg = np.maximum(-adv * ratio, -adv * clipped)
    behav_w = np.exp(prox - old)
    ref = (pg * behav_w).sum() / T
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
    # capping excludes tokens with large behavior drift
    loss_cap, stat_cap = ppo.actor_loss_fn(
        jnp.asarray(lp), jnp.asarray(old), jnp.asarray(adv), 0.2,
        jnp.asarray(mask), proximal_logprobs=jnp.asarray(prox),
        behav_imp_weight_cap=1.01,
    )
    assert np.asarray(stat_cap["behave_mask"]).sum() < T


def test_critic_loss(rng):
    T = 16
    v = rng.normal(size=T).astype(np.float32)
    old = v + rng.normal(size=T).astype(np.float32) * 0.01
    tgt = rng.normal(size=T).astype(np.float32)
    mask = np.ones(T, bool)
    loss, stat = ppo.critic_loss_fn(
        jnp.asarray(v), jnp.asarray(old), jnp.asarray(tgt), 0.2, jnp.asarray(mask)
    )
    clipped = old + np.clip(v - old, -0.2, 0.2)
    ref = np.maximum(0.5 * (v - tgt) ** 2, 0.5 * (clipped - tgt) ** 2).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_get_packed_rewards():
    seg = jnp.asarray(_pack_segments([3, 2]))
    lp = jnp.asarray(np.array([0.1, 0.2, 0.3, 0.4, 0.5], np.float32))
    ref_lp = jnp.zeros(5, jnp.float32)
    score = jnp.asarray(np.array([0, 0, 7.0, 0, -30.0], np.float32))
    no_eos = jnp.asarray(np.array([False] * 3 + [True] * 2))
    kl_r, tot = ppo.get_packed_rewards(
        kl_ctl=0.1, clip_reward_value=5.0, log_probs=lp, ref_log_probs=ref_lp,
        reward_score=score, segment_ids=seg, seq_no_eos_mask=no_eos,
    )
    np.testing.assert_allclose(np.asarray(kl_r), -0.1 * np.asarray(lp), atol=1e-6)
    # reward clipped to ±5, added at positions 2 and 4
    np.testing.assert_allclose(float(tot[2] - kl_r[2]), 5.0, atol=1e-6)
    np.testing.assert_allclose(float(tot[4] - kl_r[4]), -5.0, atol=1e-6)
    # masking truncated sequences zeroes their end reward
    _, tot2 = ppo.get_packed_rewards(
        kl_ctl=0.1, clip_reward_value=5.0, log_probs=lp, ref_log_probs=ref_lp,
        reward_score=score, segment_ids=seg, seq_no_eos_mask=no_eos,
        mask_no_eos_with_zero=True,
    )
    np.testing.assert_allclose(float(tot2[4] - kl_r[4]), 0.0, atol=1e-6)


def test_gather_packed_shifted_log_probs(rng):
    T, V = 8, 11
    logits = rng.normal(size=(T, V)).astype(np.float32)
    ids = rng.integers(0, V, size=T).astype(np.int32)
    seg = np.array([1, 1, 1, 2, 2, 0, 0, 0], np.int32)
    out = np.asarray(
        ppo.gather_packed_shifted_log_probs(
            jnp.asarray(logits), jnp.asarray(ids), jnp.asarray(seg)
        )
    )
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    for t in [0, 1, 3]:
        np.testing.assert_allclose(out[t], logp[t, ids[t + 1]], rtol=1e-5)
    assert out[2] == 0 and out[4] == 0 and np.all(out[5:] == 0)


def test_masked_normalization(rng):
    x = rng.normal(size=100).astype(np.float32) * 5 + 3
    mask = rng.random(100) > 0.3
    out = np.asarray(ppo.masked_normalization(jnp.asarray(x), jnp.asarray(mask)))
    sel = out[mask]
    assert abs(sel.mean()) < 1e-4
    assert abs(sel.std() - 1.0) < 1e-2
    np.testing.assert_array_equal(out[~mask], x[~mask])


def test_group_normalization(rng):
    x = rng.normal(size=12).astype(np.float32)
    gid = np.repeat(np.arange(3), 4)
    mask = np.ones(12, bool)
    out = np.asarray(
        ppo.group_normalization(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(gid), num_groups=3
        )
    )
    for g in range(3):
        assert abs(out[gid == g].mean()) < 1e-4


def test_adaptive_kl_controller():
    ctl = ppo.AdaptiveKLController(0.1, target=1.0, horizon=100)
    ctl.update(current=2.0, n_steps=10)
    assert ctl.value > 0.1  # KL above target -> coef grows
    ctl2 = ppo.AdaptiveKLController(0.1, target=1.0, horizon=100)
    ctl2.update(current=0.1, n_steps=10)
    assert ctl2.value < 0.1


def test_dpo_loss_matches_reference_semantics():
    """jnp dpo_loss vs a numpy transcription of the reference torch math
    (``dpo_functional.py``): same loss/scores/kl, and the loss gradient
    pushes win-logps up and lose-logps down."""
    import numpy as np

    import jax

    from areal_tpu.ops.dpo import dpo_loss

    rng = np.random.default_rng(0)
    pi = rng.normal(size=8).astype(np.float32)
    ref = rng.normal(size=8).astype(np.float32)
    beta = 0.3

    loss, pos, neg, kl = jax.jit(dpo_loss, static_argnums=2)(
        jnp.asarray(pi), jnp.asarray(ref), beta
    )
    p2, r2 = pi.reshape(-1, 2), ref.reshape(-1, 2)
    logits = beta * ((p2[:, 0] - p2[:, 1]) - (r2[:, 0] - r2[:, 1]))
    want_loss = float(np.mean(np.log1p(np.exp(-logits))))
    np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
    np.testing.assert_allclose(float(pos), beta * np.sum(p2[:, 0] - r2[:, 0]), rtol=1e-5)
    np.testing.assert_allclose(float(neg), beta * np.sum(p2[:, 1] - r2[:, 1]), rtol=1e-5)
    np.testing.assert_allclose(float(kl), -np.sum(pi - ref), rtol=1e-5)

    g = jax.grad(lambda p: dpo_loss(p, jnp.asarray(ref), beta)[0])(jnp.asarray(pi))
    g = np.asarray(g).reshape(-1, 2)
    assert (g[:, 0] < 0).all() and (g[:, 1] > 0).all()  # ascend win, descend lose
