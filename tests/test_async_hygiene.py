"""Tier-1 static async-hygiene pass (tools/check_async_hygiene.py).

Keeps ``areal_tpu/system/`` and ``areal_tpu/train/`` free of the bug
classes the fault-tolerance subsystems fixed: bare ``asyncio.gather(``
without ``return_exceptions`` (one dead peer aborts the whole fan-out),
discarded ``create_task`` results (unreferenced tasks can be GC'd; their
exceptions vanish), ``shutil.rmtree`` on checkpoint-capable paths outside
the commit helper (a crash mid-save destroys the only restore point), and
``time.sleep`` inside ``async def`` (blocks the event loop).
"""

import importlib.util
import os
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_async_hygiene",
        os.path.join(REPO, "tools", "check_async_hygiene.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_system_layer_is_clean():
    mod = _checker()
    findings = mod.scan_paths([
        os.path.join(REPO, "areal_tpu", "system"),
        os.path.join(REPO, "areal_tpu", "train"),
    ])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_checker_flags_bare_gather_and_discarded_task():
    mod = _checker()
    src = textwrap.dedent(
        """
        import asyncio

        async def bad():
            await asyncio.gather(one(), two())
            asyncio.get_event_loop().create_task(three())

        async def good():
            await asyncio.gather(one(), two(), return_exceptions=True)
            t = asyncio.get_event_loop().create_task(three())
            await t
        """
    )
    rules = sorted(f.rule for f in mod.scan_source(src))
    assert rules == ["bare-gather", "discarded-task"]


def test_checker_suppression_and_non_asyncio_gather():
    mod = _checker()
    src = textwrap.dedent(
        """
        import asyncio

        async def deliberate():
            await asyncio.gather(one(), two())  # async-hygiene: ok

        def data_join(batch):
            return SequenceSample.gather(batch)  # not asyncio: ignored
        """
    )
    assert mod.scan_source(src) == []


def test_checker_flags_live_checkpoint_rmtree():
    mod = _checker()
    src = textwrap.dedent(
        """
        import shutil
        from shutil import rmtree

        def clean(path):
            shutil.rmtree(path)
            rmtree(path)
            shutil.rmtree(path)  # async-hygiene: ok
        """
    )
    rules = [f.rule for f in mod.scan_source(src, "areal_tpu/train/x.py")]
    assert rules == ["live-checkpoint-rmtree", "live-checkpoint-rmtree"]
    # the commit helper itself is the one sanctioned deletion site
    assert mod.scan_source(src, "areal_tpu/base/recover.py") == []


def test_checker_flags_time_sleep_in_async():
    mod = _checker()
    src = textwrap.dedent(
        """
        import asyncio
        import time

        async def bad():
            time.sleep(1.0)
            if True:
                time.sleep(2.0)

        async def bad_from_import():
            from time import sleep
            sleep(3.0)

        async def fine():
            await asyncio.sleep(1.0)
            time.sleep(0.1)  # async-hygiene: ok

            def sync_helper():
                time.sleep(0.5)  # runs where called (executor thread): ok

        async def fine_awaited_bare():
            from asyncio import sleep
            await sleep(1.0)  # asyncio's sleep via from-import: awaited

        def also_fine():
            time.sleep(1.0)
        """
    )
    findings = [f for f in mod.scan_source(src) if f.rule == "sleep-in-async"]
    assert len(findings) == 3
    assert all("blocks the event loop" in f.message for f in findings)


def test_stub_is_deprecated_but_forwards():
    """The retired entry point still works (forwards to arealint's four
    migrated rules) and says so: a deprecation notice on stderr, findings
    + exit codes unchanged. Deleted one release after arealint v2."""
    import subprocess
    import sys

    clean = os.path.join(REPO, "areal_tpu", "base", "faults.py")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_async_hygiene.py"),
         clean],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "deprecated" in r.stderr
    assert "python -m tools.arealint" in r.stderr
