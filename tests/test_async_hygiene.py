"""Tier-1 static async-hygiene pass (tools/check_async_hygiene.py).

Keeps ``areal_tpu/system/`` free of the exact bug class the fault-tolerance
subsystem fixed: bare ``asyncio.gather(`` without ``return_exceptions``
(one dead peer aborts the whole fan-out) and discarded ``create_task``
results (unreferenced tasks can be GC'd; their exceptions vanish).
"""

import importlib.util
import os
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_async_hygiene",
        os.path.join(REPO, "tools", "check_async_hygiene.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_system_layer_is_clean():
    mod = _checker()
    findings = mod.scan_paths([os.path.join(REPO, "areal_tpu", "system")])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_checker_flags_bare_gather_and_discarded_task():
    mod = _checker()
    src = textwrap.dedent(
        """
        import asyncio

        async def bad():
            await asyncio.gather(one(), two())
            asyncio.get_event_loop().create_task(three())

        async def good():
            await asyncio.gather(one(), two(), return_exceptions=True)
            t = asyncio.get_event_loop().create_task(three())
            await t
        """
    )
    rules = sorted(f.rule for f in mod.scan_source(src))
    assert rules == ["bare-gather", "discarded-task"]


def test_checker_suppression_and_non_asyncio_gather():
    mod = _checker()
    src = textwrap.dedent(
        """
        import asyncio

        async def deliberate():
            await asyncio.gather(one(), two())  # async-hygiene: ok

        def data_join(batch):
            return SequenceSample.gather(batch)  # not asyncio: ignored
        """
    )
    assert mod.scan_source(src) == []
