"""Sync-PPO recipe: on-mesh generation, generate→verify→train loop, evaluator.

Counterpart of the reference's sync PPO experiment tests
(``realhf/experiments/common/ppo_math_exp.py:29``) and the checkpoint
evaluator (``realhf/scheduler/evaluator.py:160``).
"""

import json
import os

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import GenerationHyperparameters, PPOHyperparameters
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.system.evaluator import AutomaticEvaluator, discover_checkpoints
from areal_tpu.system.sync_trainer import SyncPPOTrainerWorker, build_group_sample
from areal_tpu.system.trainer_worker import TrainerControl
from areal_tpu.train.engine import OptimizerConfig, TrainEngine
from areal_tpu.train.generation import SyncGenerator

TINY = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def actor():
    eng = TrainEngine(
        TINY, ParallelConfig(data=2, fsdp=2, model=2),
        OptimizerConfig(lr=1e-3),
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=20)
    return eng


class FakePromptDataset:
    """Minimal prompt dataset: qid -> fixed token prompt + metadata."""

    def __init__(self, n=4, plen=5):
        self.n, self.plen = n, plen
        self.metadata = {str(i): {"solutions": ["42"]} for i in range(n)}

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        ids = np.arange(1, self.plen + 1, dtype=np.int64) + i
        return SequenceSample(
            keys={"packed_prompts"},
            ids=[str(i)],
            seqlens={"packed_prompts": [[self.plen]]},
            data={"packed_prompts": ids},
        )


class TestSyncGenerator:
    def test_group_generation_shapes(self, actor):
        gen = SyncGenerator(actor)
        ghp = GenerationHyperparameters(n=3, max_new_tokens=8)
        groups = gen.generate([[1, 2, 3], [4, 5, 6, 7]], ghp, seed=0)
        assert len(groups) == 2 and all(len(g) == 3 for g in groups)
        for plist, group in zip([[1, 2, 3], [4, 5, 6, 7]], groups):
            for o in group:
                assert 1 <= len(o.gen_logprobs) <= 8
                assert len(o.tokens) == len(plist) + len(o.gen_logprobs)
                np.testing.assert_array_equal(o.tokens[: len(plist)], plist)

    def test_greedy_is_deterministic(self, actor):
        gen = SyncGenerator(actor)
        ghp = GenerationHyperparameters(n=2, max_new_tokens=6, greedy=True)
        (g1,) = gen.generate([[1, 2, 3]], ghp, seed=0)
        (g2,) = gen.generate([[1, 2, 3]], ghp, seed=123)
        np.testing.assert_array_equal(g1[0].tokens, g2[0].tokens)
        np.testing.assert_array_equal(g1[0].tokens, g1[1].tokens)

    def test_stop_token_terminates(self, actor):
        gen = SyncGenerator(actor)
        # stopping on every token id: generation ends after one token
        ghp = GenerationHyperparameters(
            n=1, max_new_tokens=8, stop_token_ids=list(range(128))
        )
        (group,) = gen.generate([[1, 2, 3]], ghp, seed=0)
        assert len(group[0].gen_logprobs) == 1
        assert not group[0].no_eos
        # no stopping: runs to max_new_tokens and reports truncation
        ghp2 = GenerationHyperparameters(n=1, max_new_tokens=8)
        (group2,) = gen.generate([[1, 2, 3]], ghp2, seed=0)
        assert len(group2[0].gen_logprobs) == 8
        assert group2[0].no_eos


def test_build_group_sample_layout():
    from areal_tpu.train.generation import SyncGenOutput

    outs = [
        SyncGenOutput(
            tokens=np.asarray([1, 2, 3, 10, 11], np.int64),
            gen_logprobs=np.asarray([-0.5, -0.7], np.float32),
            no_eos=False,
        ),
        SyncGenOutput(
            tokens=np.asarray([1, 2, 3, 20], np.int64),
            gen_logprobs=np.asarray([-0.2], np.float32),
            no_eos=True,
        ),
    ]
    s = build_group_sample("q0", outs, prompt_len=3, rewards=[1.0, -1.0])
    assert s.seqlens["packed_input_ids"] == [[5, 4]]
    lp = s.data["packed_logprobs"]
    # token-aligned: logprob of token t at position t-1, zero elsewhere
    np.testing.assert_allclose(lp[:5], [0, 0, -0.5, -0.7, 0])
    np.testing.assert_allclose(lp[5:], [0, 0, -0.2, 0])
    np.testing.assert_array_equal(s.data["seq_no_eos_mask"], [False, True])


class TestSyncPPOWorker:
    def test_e2e_steps(self, actor, tmp_path):
        def reward_fn(qid, answers, metadata):
            # deterministic rule exercising the full verify plumbing
            return [1.0 if "7" in a.split() else -1.0 for a in answers]

        worker = SyncPPOTrainerWorker(
            "test_sync", "trial0",
            actor_engine=actor,
            dataset=FakePromptDataset(),
            hp=PPOHyperparameters(
                disable_value=True,
                use_decoupled_loss=False,
                recompute_logprob=False,
                kl_ctl=0.0,
            ),
            ghp=GenerationHyperparameters(n=2, max_new_tokens=8),
            control=TrainerControl(total_train_steps=2),
            batch_size=2,
            mb_spec=MicroBatchSpec(),
        )
        # the sync graph has no inference nodes: fresh logprobs ARE proximal
        assert worker.executor.graph.names == ["actor_train"]
        s1 = worker.run_step()
        s2 = worker.run_step()
        assert np.isfinite(s1["actor_loss"]) and np.isfinite(s2["actor_loss"])
        assert -1.0 <= s1["reward_mean"] <= 1.0
        assert s1["n_seqs_consumed"] == 4
        assert worker.step == 2


class TestSyncPPOConvergence:
    def test_reward_rises_over_training(self):
        """VERDICT r5 'Missing #2': a real learning signal, not just
        finiteness. Tiny model + synthetic verifiable reward (fraction of
        generated token ids < 64, mapped to [-1, 1]) — 20 sync-PPO steps
        must RAISE the mean reward. Single-device engine keeps the whole
        run a few seconds of CPU after compile."""

        def reward_fn(qid, answers, metadata):
            out = []
            for a in answers:
                toks = [int(t) for t in a.split()] or [0]
                out.append(2.0 * float(np.mean([t < 64 for t in toks])) - 1.0)
            return out

        eng = TrainEngine(TINY, ParallelConfig(), OptimizerConfig(lr=3e-2))
        eng.init_random(0)
        eng.setup_optimizer(30)
        worker = SyncPPOTrainerWorker(
            "conv", "t0",
            actor_engine=eng,
            dataset=FakePromptDataset(n=4, plen=4),
            hp=PPOHyperparameters(
                disable_value=True, use_decoupled_loss=False,
                recompute_logprob=False, kl_ctl=0.0, adv_norm=True,
                ppo_n_minibatches=1,
            ),
            ghp=GenerationHyperparameters(n=4, max_new_tokens=6),
            control=TrainerControl(
                total_train_steps=20, ckpt_freq_steps=None,
                ckpt_freq_secs=None,
            ),
            batch_size=4,
            mb_spec=MicroBatchSpec(),
            reward_fn=reward_fn,
            seed=3,
        )
        rewards = [worker.run_step()["reward_mean"] for _ in range(20)]
        first, last = np.mean(rewards[:5]), np.mean(rewards[-5:])
        assert last > first + 0.3, (
            f"mean reward did not rise: first5={first:.3f} last5={last:.3f} "
            f"trace={np.round(rewards, 3).tolist()}"
        )


class TestEvaluator:
    def _fake_ckpt(self, root, step):
        d = os.path.join(root, f"step{step}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "config.json"), "w") as f:
            f.write("{}")
        return d

    def test_discovers_evaluates_once_in_order(self, tmp_path):
        root = str(tmp_path / "save")
        calls = []

        def eval_fn(path):
            calls.append(path)
            return {"score": float(len(calls))}

        ev = AutomaticEvaluator(
            root, eval_fn, str(tmp_path / "eval.jsonl"), poll_interval=0.01
        )
        assert ev.step_once() == []          # nothing yet
        self._fake_ckpt(root, 20)
        self._fake_ckpt(root, 10)
        assert ev.step_once() == [10, 20]    # ascending step order
        assert ev.step_once() == []          # never re-evaluated
        self._fake_ckpt(root, 30)
        assert ev.step_once() == [30]
        assert len(calls) == 3

    def test_incomplete_ckpt_ignored(self, tmp_path):
        root = str(tmp_path / "save")
        os.makedirs(os.path.join(root, "step5"))  # no config.json yet
        assert discover_checkpoints(root) == {}

    def test_recovery_skips_done(self, tmp_path):
        root = str(tmp_path / "save")
        out = str(tmp_path / "eval.jsonl")
        self._fake_ckpt(root, 1)
        with open(out, "w") as f:
            f.write(json.dumps({"step": 1, "ckpt": "x", "score": 0.5}) + "\n")
        calls = []
        ev = AutomaticEvaluator(root, lambda p: calls.append(p) or {}, out)
        assert ev.done == {1: {"score": 0.5}}
        assert ev.step_once() == []
        assert calls == []

    def test_failed_eval_retries_after_restart_only(self, tmp_path):
        root = str(tmp_path / "save")
        out = str(tmp_path / "eval.jsonl")
        self._fake_ckpt(root, 1)
        calls = []

        def eval_fn(path):
            calls.append(path)
            raise RuntimeError("boom")

        ev = AutomaticEvaluator(root, eval_fn, out)
        assert ev.step_once() == [1]
        assert ev.done[1] == {"eval_failed": 1.0}
        assert ev.step_once() == []          # no in-process retry storm
        assert len(calls) == 1
        # failures are NOT persisted: a restarted evaluator retries the step
        assert not os.path.exists(out)
        ev2 = AutomaticEvaluator(root, lambda p: {"score": 1.0}, out)
        assert ev2.step_once() == [1]
        assert ev2.done[1] == {"score": 1.0}
