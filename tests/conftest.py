"""Test harness: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's CPU-only test strategy (``realhf/base/testing.py``):
the whole stack must be testable without TPU hardware. An 8-device host
platform replaces the reference's 8-process gloo trick (SURVEY.md §4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon/tpu: tests always run CPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("AREAL_FILEROOT", "/tmp/areal_tpu_test")
# Data-plane pipelining (docs/pipelined_data_plane.md) defaults OFF under
# the CPU harness: with JAX_PLATFORMS=cpu the "device" IS the host, so
# dispatch-ahead depth and the background packer thread only oversubscribe
# the cores the multi-process e2e worlds already share (~35% wall-time
# regression measured on test_experiment_e2e). Production (TPU) keeps the
# ON defaults; tests/test_data_pipeline.py turns the knobs on explicitly
# to exercise both paths.
os.environ.setdefault("AREAL_FWD_PIPELINE", "0")
os.environ.setdefault("AREAL_TRAIN_PREFETCH", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The axon sitecustomize force-registers the TPU plugin and overrides
# JAX_PLATFORMS; the config update wins over both.
jax.config.update("jax_platforms", "cpu")

import asyncio
import contextlib
import inspect
import tempfile

import numpy as np
import pytest


@contextlib.contextmanager
def multihost_world_lock():
    """Serialize multi-process CPU worlds ACROSS pytest processes.

    An N-process gloo world is timing-sensitive (bounded collectives,
    coordinator rendezvous); two suites launching worlds concurrently on
    a shared CI box starve each other into spurious timeouts — the
    standalone test_multihost failures noted in the PR-8 log. A
    system-wide flock makes world launches mutually exclusive; the lock
    file lives in the shared tempdir so unrelated pytest invocations
    contend on the same lock."""
    import fcntl

    path = os.path.join(tempfile.gettempdir(), "areal_tpu_multihost.lock")
    with open(path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            k: pyfuncitem.funcargs[k] for k in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _seed():
    from areal_tpu.base import seeding

    seeding.set_random_seed(1, "test")
    yield
