"""Generation engine tests: greedy parity vs the packed forward, continuous
batching with slot turnover, stop tokens, interruption protocol.

Counterpart of the reference's generation tests (in-house engine +
``test_partial_rollout.py`` chunked regeneration semantics).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


def _greedy_reference(params, prompt, n_new):
    """Teacher-forcing argmax chain via the packed forward."""
    ids = list(prompt)
    for _ in range(n_new):
        T = len(ids)
        pad = ((T + 127) // 128) * 128
        seg = np.r_[np.ones(T, np.int32), np.zeros(pad - T, np.int32)]
        inp = np.r_[np.asarray(ids, np.int32), np.zeros(pad - T, np.int32)]
        pos = np.r_[np.arange(T, dtype=np.int32), np.zeros(pad - T, np.int32)]
        logits = tfm.forward_packed(
            params, CFG, jnp.asarray(inp), jnp.asarray(seg), jnp.asarray(pos),
            remat=False,
        )
        ids.append(int(np.argmax(np.asarray(logits)[T - 1])))
    return ids[len(prompt):]


def test_greedy_matches_forward(params, rng):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    prompt = [int(x) for x in rng.integers(1, 128, size=5)]
    eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=8, greedy=True))
    outs = eng.run_until_done(decode_steps=4)
    assert len(outs) == 1
    ref = _greedy_reference(params, prompt, 8)
    assert outs[0].output_ids == ref
    assert outs[0].finish_reason == "length"
    assert len(outs[0].output_logprobs) == 8


def test_continuous_batching_slot_turnover(params, rng):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    prompts = {
        f"r{i}": [int(x) for x in rng.integers(1, 128, size=int(n))]
        for i, n in enumerate(rng.integers(3, 9, size=5))
    }
    for rid, p in prompts.items():
        eng.submit(GenRequest(rid=rid, input_ids=p, max_new_tokens=6, greedy=True))
    outs = {o.rid: o for o in eng.run_until_done(decode_steps=4)}
    assert set(outs) == set(prompts)
    for rid, p in prompts.items():
        assert outs[rid].output_ids == _greedy_reference(params, p, 6), rid


def test_stop_tokens(params, rng):
    prompt = [int(x) for x in rng.integers(1, 128, size=5)]
    ref = _greedy_reference(params, prompt, 12)
    stop = ref[3]  # force a stop at the 4th generated token
    eng = GenerationEngine(
        CFG, params, max_slots=2, max_seqlen=128, stop_token_ids=[stop]
    )
    eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=12, greedy=True))
    outs = eng.run_until_done(decode_steps=2)
    assert outs[0].finish_reason == "stop"
    assert outs[0].output_ids == ref[:4]  # stop token included


def test_interrupt_and_resume_protocol(params, rng):
    """Pause mid-generation, resubmit with accumulated tokens (the partial
    rollout protocol): concatenated output must equal the uninterrupted run."""
    prompt = [int(x) for x in rng.integers(1, 128, size=5)]
    ref = _greedy_reference(params, prompt, 10)

    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=10, greedy=True))
    eng.step(decode_steps=4)   # partial progress
    parts = eng.pause()
    assert len(parts) == 1 and parts[0].finish_reason == "interrupted"
    got = parts[0].output_ids
    assert 0 < len(got) < 10

    eng.resume()
    eng.submit(
        GenRequest(
            rid="a2", input_ids=prompt + got,
            max_new_tokens=10 - len(got), greedy=True,
        )
    )
    outs = eng.run_until_done(decode_steps=4)
    assert got + outs[0].output_ids == ref


def test_per_request_stop_tokens(params, rng):
    prompt = [int(x) for x in rng.integers(1, 128, size=5)]
    ref = _greedy_reference(params, prompt, 12)
    stop = ref[2]
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)  # no global stop
    eng.submit(GenRequest(
        rid="a", input_ids=prompt, max_new_tokens=12, greedy=True,
        stop_token_ids=[stop],
    ))
    eng.submit(GenRequest(rid="b", input_ids=prompt, max_new_tokens=12, greedy=True))
    outs = {o.rid: o for o in eng.run_until_done(decode_steps=2)}
    assert outs["a"].finish_reason == "stop" and outs["a"].output_ids == ref[:3]
    assert outs["b"].finish_reason == "length" and outs["b"].output_ids == ref


def test_update_params_tags_version(params):
    eng = GenerationEngine(CFG, params, max_slots=1, max_seqlen=128)
    eng.submit(GenRequest(rid="a", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True))
    outs = eng.run_until_done(decode_steps=2)
    assert outs[0].version == 0
    new_params = tfm.init_params(CFG, jax.random.key(9))
    eng.update_params(new_params, version=3)
    eng.submit(GenRequest(rid="b", input_ids=[1, 2, 3], max_new_tokens=2, greedy=True))
    outs = eng.run_until_done(decode_steps=2)
    assert outs[0].version == 3


def test_sampling_reproducible_and_diverse(params):
    eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=128, seed=0)
    for i in range(4):
        eng.submit(GenRequest(
            rid=f"s{i}", input_ids=[5, 6, 7], max_new_tokens=8,
            temperature=1.0, top_p=0.95,
        ))
    outs = {o.rid: o.output_ids for o in eng.run_until_done(decode_steps=4)}
    assert len(set(map(tuple, outs.values()))) > 1  # samples differ across slots


def test_step_harvest_batches_device_pulls(params, rng, monkeypatch):
    """step() makes at most TWO device pulls per chunk — one sync of the
    small per-slot scalars, one batched fetch of every finished slot's
    outputs — no matter how many slots finish inside the chunk, and no
    per-slot scatter back (VERDICT r3 weak #2: 32 finishing slots used to
    cost ~64 round trips on a tunneled chip)."""
    eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=64)
    for i, n_new in enumerate((3, 4, 9, 12)):  # staggered finishes
        eng.submit(GenRequest(
            rid=f"r{i}",
            input_ids=[int(x) for x in rng.integers(1, 128, size=5)],
            max_new_tokens=n_new, greedy=True,
        ))
    calls = []
    real_get = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda x: calls.append(1) or real_get(x))
    outs = []
    for _ in range(40):
        calls.clear()
        outs.extend(eng.step(decode_steps=4))
        assert len(calls) <= 2, f"{len(calls)} device pulls in one step"
        if eng.free_slots() == 4 and not eng._pending:
            break
    assert sorted(o.rid for o in outs) == ["r0", "r1", "r2", "r3"]
    assert {o.rid: len(o.output_ids) for o in outs} == {
        "r0": 3, "r1": 4, "r2": 9, "r3": 12,
    }


class TestWarpContract:
    """The sampling layer's static-``warp`` split: engines that know no
    slot warps (host-side ``_warp_host``) skip the ``[B, V]`` sort — the
    dominant cost of a decode step at a 152k vocab — and the result must
    be EXACT either way. The spec-decode verify path leans on the same
    contract plus a single flattened sort for all K+1 positions."""

    def test_warp_false_exactness(self, rng):
        from areal_tpu.gen.sampling import SamplingParams, sample_tokens

        B, V = 6, 64
        logits = jnp.asarray(rng.normal(size=(B, V)), jnp.float32)
        # no slot actually warps: top_p=1, top_k >= V, mixed temperatures
        sp = SamplingParams(
            temperature=jnp.asarray([1.0, 0.7, 1.3, 0.0, 1.0, 2.0]),
            top_p=jnp.ones((B,)),
            top_k=jnp.full((B,), 1 << 30, jnp.int32),
        )
        key = jax.random.key(3)
        t1, lp1 = sample_tokens(key, logits, sp, warp=True)
        t2, lp2 = sample_tokens(key, logits, sp, warp=False)
        assert t1.tolist() == t2.tolist()
        np.testing.assert_allclose(np.asarray(lp1), np.asarray(lp2),
                                   atol=1e-6)

    def test_warp_multi_matches_per_position(self, rng):
        """One flattened sort over [B*C, V] (the spec-verify warp) must
        equal warping each position independently."""
        from areal_tpu.gen.sampling import (
            SamplingParams, warp_logits, warp_logits_multi,
        )

        B, C, V = 4, 3, 64
        logits = jnp.asarray(rng.normal(size=(B, C, V)), jnp.float32)
        sp = SamplingParams(
            temperature=jnp.asarray([1.0, 0.5, 1.2, 0.9]),
            top_p=jnp.asarray([0.9, 1.0, 0.5, 0.8]),
            top_k=jnp.asarray([5, 1 << 30, 20, 3], jnp.int32),
        )
        got = warp_logits_multi(logits, sp)
        for c in range(C):
            np.testing.assert_allclose(
                np.asarray(got[:, c]),
                np.asarray(warp_logits(logits[:, c], sp)),
                atol=1e-6,
            )

    def test_warp_rows_matches_full_warp(self, rng):
        """Per-slot warp narrowing (``warp_rows``): a mixed batch where
        only some slots warp must sample exactly what the full-batch warp
        samples — greedy/plain slots get the warp=False arm, warping
        slots their warped rows, padding indices drop."""
        from areal_tpu.gen.sampling import SamplingParams, sample_tokens

        B, V = 6, 64
        logits = jnp.asarray(rng.normal(size=(B, V)), jnp.float32)
        sp = SamplingParams(
            temperature=jnp.asarray([0.0, 1.0, 0.7, 0.0, 1.3, 1.0]),
            top_p=jnp.asarray([1.0, 0.9, 1.0, 1.0, 0.8, 1.0]),
            top_k=jnp.asarray(
                [1 << 30, 1 << 30, 5, 1 << 30, 7, 1 << 30], jnp.int32
            ),
        )
        rows = jnp.asarray([1, 2, 4, B], jnp.int32)  # B = padding -> drop
        key = jax.random.key(7)
        t1, lp1 = sample_tokens(key, logits, sp, warp=True)
        t2, lp2 = sample_tokens(key, logits, sp, warp=True, warp_rows=rows)
        assert t1.tolist() == t2.tolist()
        np.testing.assert_allclose(np.asarray(lp1), np.asarray(lp2),
                                   atol=1e-5)

    def test_warp_rows_multi_matches_full(self, rng):
        """The spec-verify [B, C, V] shape through warp_logits_rows."""
        from areal_tpu.gen.sampling import (
            SamplingParams, warp_logits_multi, warp_logits_rows,
        )

        B, C, V = 4, 3, 64
        logits = jnp.asarray(rng.normal(size=(B, C, V)), jnp.float32)
        sp = SamplingParams(
            temperature=jnp.asarray([1.0, 0.5, 1.2, 0.9]),
            top_p=jnp.asarray([0.9, 1.0, 0.5, 0.8]),
            top_k=jnp.asarray([5, 1 << 30, 20, 3], jnp.int32),
        )
        rows = jnp.asarray([0, 2, 3, B], jnp.int32)
        full = warp_logits_multi(logits, sp)
        sparse = warp_logits_rows(logits, sp, rows)
        for b in (0, 2, 3):
            np.testing.assert_allclose(
                np.asarray(sparse[b]), np.asarray(full[b]), atol=1e-6
            )

    def test_mixed_batch_one_warper_engine_exactness(self, params, rng):
        """Engine-level pin: a batch of greedy requests plus ONE top-p
        request must give the greedy slots exactly the tokens an all-greedy
        engine gives them — the warping request no longer changes (or
        slows) anyone else's path."""
        prompts = [
            [int(x) for x in rng.integers(1, 128, n)] for n in (5, 9, 7)
        ]
        ref = GenerationEngine(CFG, params, max_slots=4, max_seqlen=64,
                               seed=0)
        for i, p in enumerate(prompts):
            ref.submit(GenRequest(
                rid=f"g{i}", input_ids=p, max_new_tokens=8, greedy=True,
            ))
        want = {o.rid: o.output_ids
                for o in ref.run_until_done(decode_steps=3)}
        eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=64,
                               seed=0)
        for i, p in enumerate(prompts):
            eng.submit(GenRequest(
                rid=f"g{i}", input_ids=p, max_new_tokens=8, greedy=True,
            ))
        eng.submit(GenRequest(
            rid="warp", input_ids=prompts[0], max_new_tokens=8,
            temperature=1.0, top_p=0.9,
        ))
        got = {o.rid: o.output_ids
               for o in eng.run_until_done(decode_steps=3)}
        for rid, ids in want.items():
            assert got[rid] == ids, rid
        # the chunk specialized on the warp bucket, not a batch-wide bool
        assert any(k[2] == 1 for k in eng._jit_chunk)  # bucket-1 program


# --------------------------------------------------------------------------- #
# Tensor-parallel serving (VERDICT r2 #1): engine over a `model` mesh
# --------------------------------------------------------------------------- #


def _tp_mesh(n):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("model",))


class TestTensorParallelEngine:
    def test_tp2_greedy_matches_single_device(self, params, rng):
        """A 2-way TP engine must generate the same greedy chains as the
        unsharded engine (counterpart of the reference's per-TP-group SGLang
        servers, realhf/system/generation_server.py:150)."""
        prompts = [
            [int(x) for x in rng.integers(1, 128, size=n)] for n in (5, 9, 3)
        ]
        eng1 = GenerationEngine(CFG, params, max_slots=4, max_seqlen=128)
        eng2 = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=128, mesh=_tp_mesh(2)
        )
        for eng in (eng1, eng2):
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=8, greedy=True
                ))
        o1 = {o.rid: o for o in eng1.run_until_done(decode_steps=4)}
        o2 = {o.rid: o for o in eng2.run_until_done(decode_steps=4)}
        assert set(o1) == set(o2)
        for rid in o1:
            assert o1[rid].output_ids == o2[rid].output_ids, rid
            np.testing.assert_allclose(
                o1[rid].output_logprobs, o2[rid].output_logprobs, atol=1e-4
            )

    def test_tp_pool_is_sharded_and_weight_swap_reshards(self, params):
        mesh = _tp_mesh(2)
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=128, mesh=mesh
        )
        # KV pool shards over the kv-head axis: each device holds half
        kshard = eng.state.cache.pages.sharding
        assert kshard.spec == jax.sharding.PartitionSpec(
            None, None, None, "model", None, None
        )
        # wq shards on its head-output column axis
        wq = eng.params["layers"]["attn"]["wq"]
        assert wq.sharding.spec[-1] == "model"
        # hot swap from UNSHARDED host params lands back on the mesh
        host = jax.tree.map(np.asarray, tfm.init_params(CFG, jax.random.key(9)))
        eng.update_params(eng.prepare_params(host), version=2)
        assert eng.params["layers"]["attn"]["wq"].sharding.spec[-1] == "model"
        eng.submit(GenRequest(rid="a", input_ids=[1, 2, 3], max_new_tokens=2))
        outs = eng.run_until_done(decode_steps=2)
        assert outs[0].version == 2

    def test_tp_prefix_sharing_and_sampling(self, params):
        """Radix prefix sharing + stochastic sampling still work sharded."""
        mesh = _tp_mesh(2)
        eng = GenerationEngine(
            CFG, params, max_slots=4, max_seqlen=256, page_size=4, seed=0,
            mesh=mesh,
        )
        prompt = [5, 6, 7, 8, 9, 10, 11]  # 1 full page shared
        for i in range(4):
            eng.submit(GenRequest(
                rid=f"s{i}", input_ids=prompt, max_new_tokens=8,
                temperature=1.0, top_p=0.95,
            ))
        outs = {o.rid: o.output_ids for o in eng.run_until_done(decode_steps=4)}
        assert len(outs) == 4
        assert eng.stats["prefix_hits"] >= 3
        assert len(set(map(tuple, outs.values()))) > 1

    def test_tp_decode_stays_on_auto_dispatch(self, params):
        """r5 (VERDICT r4 weak #7): TP serving no longer pins the XLA
        gather path — the Pallas kernel runs under shard_map over the
        kv-head axis, so auto-dispatch stays in charge on every mesh."""
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=128, mesh=_tp_mesh(2)
        )
        assert eng._decode_use_pallas is None
        eng1 = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
        assert eng1._decode_use_pallas is None  # platform auto-dispatch

    def test_tp_shard_map_pallas_decode_matches_gather(self, params, rng):
        """The shard_map'd Pallas decode (forced on, interpret mode) must
        match the XLA gather path on a kv-head-sharded pool."""
        from areal_tpu.ops.paged_attention import paged_decode_attention

        mesh = _tp_mesh(2)
        L, P_, Hkv, page, D = 2, 8, 2, 8, 16
        B, H = 4, 4
        q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
        k_self = jnp.asarray(rng.normal(size=(B, Hkv, D)), jnp.float32)
        v_self = jnp.asarray(rng.normal(size=(B, Hkv, D)), jnp.float32)
        pages = jnp.asarray(
            rng.normal(size=(L, P_, 2, Hkv, page, D)), jnp.float32
        )
        table = jnp.asarray(
            rng.permutation(P_).reshape(B, 2), jnp.int32
        )
        lens = jnp.asarray([3, 9, 16, 0], jnp.int32)
        ref = paged_decode_attention(
            q, k_self, v_self, pages, jnp.int32(1), table, lens,
            use_pallas=False,
        )
        got = paged_decode_attention(
            q, k_self, v_self, pages, jnp.int32(1), table, lens,
            use_pallas=True, mesh=mesh,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_tp_rejects_indivisible_heads(self, params):
        bad = dataclasses.replace(CFG, n_kv_heads=3, n_q_heads=3)
        p3 = tfm.init_params(bad, jax.random.key(0))
        with pytest.raises(ValueError, match="divisible"):
            GenerationEngine(bad, p3, max_slots=2, mesh=_tp_mesh(2))


# --------------------------------------------------------------------------- #
# Chunk pipelining (r5, VERDICT r4 #5): harvest one chunk late so the
# per-chunk host sync overlaps the next chunk's compute
# --------------------------------------------------------------------------- #


class TestPipelinedChunks:
    def test_pipelined_matches_unpipelined_greedy(self, params, rng):
        prompts = [
            [int(x) for x in rng.integers(1, 128, size=n)]
            for n in (5, 9, 3, 7)
        ]
        outs = []
        for pipelined in (False, True):
            eng = GenerationEngine(
                CFG, params, max_slots=4, max_seqlen=128,
                pipeline_chunks=pipelined,
            )
            for i, p in enumerate(prompts):
                eng.submit(GenRequest(
                    rid=f"r{i}", input_ids=p, max_new_tokens=10 + i,
                    greedy=True,
                ))
            outs.append({
                o.rid: o for o in eng.run_until_done(decode_steps=4)
            })
        assert set(outs[0]) == set(outs[1])
        for rid in outs[0]:
            assert outs[0][rid].output_ids == outs[1][rid].output_ids, rid
            assert outs[0][rid].finish_reason == outs[1][rid].finish_reason
            np.testing.assert_allclose(
                outs[0][rid].output_logprobs, outs[1][rid].output_logprobs,
                atol=1e-5,
            )

    def test_pipelined_staggered_admission(self, params, rng):
        """New requests admitted mid-flight (slots freed by late harvests)
        must complete correctly — the fresh slot's lens/harvest state must
        not be clobbered by the stale previous-chunk flags."""
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, pipeline_chunks=True,
        )
        for i in range(5):  # 5 requests through 2 slots
            eng.submit(GenRequest(
                rid=f"s{i}",
                input_ids=[int(x) for x in rng.integers(1, 128, size=4 + i)],
                max_new_tokens=6, greedy=True,
            ))
        outs = {o.rid: o for o in eng.run_until_done(decode_steps=3)}
        assert set(outs) == {f"s{i}" for i in range(5)}
        assert all(len(o.output_ids) == 6 for o in outs.values())

    def test_pause_classifies_unharvested_finishes(self, params, rng):
        """A slot that FINISHED in the in-flight chunk must come out of
        pause() as stop/length, not 'interrupted' (a client would
        resubmit a complete sample)."""
        eng = GenerationEngine(
            CFG, params, max_slots=2, max_seqlen=64, pipeline_chunks=True,
        )
        eng.submit(GenRequest(
            rid="short", input_ids=[3, 4, 5], max_new_tokens=2, greedy=True,
        ))
        eng.submit(GenRequest(
            rid="long", input_ids=[6, 7, 8], max_new_tokens=40, greedy=True,
        ))
        # one step: dispatches a 4-step chunk; 'short' finishes ON DEVICE
        # inside it but its harvest is deferred (pipelined)
        outs = eng.step(decode_steps=4)
        assert outs == []
        assert eng.has_inflight
        harvested = {o.rid: o for o in eng.pause()}
        assert harvested["short"].finish_reason == "length"
        assert len(harvested["short"].output_ids) == 2
        assert harvested["long"].finish_reason == "interrupted"
