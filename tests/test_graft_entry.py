"""Regression tests for the driver entry points (``__graft_entry__``).

Round-1 threw away a whole round of multi-chip signal because
``dryrun_multichip`` never forced the virtual CPU platform (VERDICT.md
"Next round" #1). These tests pin both entry points so they can't silently
regress. Mirrors the reference's CPU-testability doctrine
(``realhf/base/testing.py:48,137``).
"""

import numpy as np

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    # conftest already forces an 8-device CPU platform; dryrun must also
    # work when run under it (idempotent env setup).
    graft.dryrun_multichip(8)


def test_entry_compiles_and_runs():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    logits = jax.device_get(out)
    assert np.all(np.isfinite(np.asarray(logits, np.float32)))
