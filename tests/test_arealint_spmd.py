"""Tier-1 tests for arealint v3's SPMD/sharding-safety families
(docs/static_analysis.md "SPMD rules"):

1. **Mesh model** — the axis catalog parsed from parallel/mesh.py (ast,
   never imported) matches the tuple ``make_mesh`` actually builds at
   runtime, so catalog drift fails loudly.
2. **Rule fixtures** — every new rule has at least one positive fixture
   (fires on the bug) and one negative (quiet on the idiom / on an
   unresolvable pattern: propagation degrades, never guesses).
3. **Runtime twin** — ``logical_to_pspec``/``param_shardings`` raise on
   logical-axis typos instead of silently replicating.
4. **--changed-only** — the CI fast path scans exactly what passing the
   surviving files as explicit paths would scan, and a 3-file diff
   completes in under 2 s.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import (  # noqa: E402
    Config,
    MeshModel,
    PROJECT_RULES,
    RULES,
    parse_mesh_module,
    scan_source,
    scan_sources,
)

pytestmark = pytest.mark.arealint

MESH = MeshModel(
    axes=("data", "fsdp", "ctx", "model"),
    logical_rules={"embed": "fsdp", "heads": "model", "layer": None},
)
CFG = Config(mesh=MESH)


def rules_of(src, path="areal_tpu/some/module.py", rules=None):
    return [
        f.rule for f in scan_source(src, path, rules=rules, config=CFG)
    ]


def findings_of(src, path="areal_tpu/some/module.py", rules=None):
    return scan_source(src, path, rules=rules, config=CFG)


def project_of(sources, rules):
    return scan_sources(sources, rules=rules, config=CFG)


# ------------------------------------------------------------------ #
# mesh model provenance
# ------------------------------------------------------------------ #


class TestMeshModel:
    def test_parsed_axes_match_runtime_make_mesh(self):
        """The statically-parsed axis catalog IS the tuple make_mesh
        builds — if someone renames/reorders mesh axes, this fails and
        forces the catalog (and every spec in the tree) to follow."""
        parsed = parse_mesh_module(
            os.path.join(REPO, "areal_tpu", "parallel", "mesh.py")
        )
        assert parsed is not None

        from areal_tpu.parallel.mesh import ParallelConfig, make_mesh

        mesh = make_mesh(ParallelConfig())  # 1x1x1x1: any device count
        assert parsed.axes == tuple(mesh.axis_names)

    def test_parsed_logical_rules_match_runtime(self):
        from areal_tpu.parallel.mesh import DEFAULT_RULES

        parsed = parse_mesh_module(
            os.path.join(REPO, "areal_tpu", "parallel", "mesh.py")
        )
        assert parsed.logical_rules == DEFAULT_RULES

    def test_default_config_carries_the_model(self):
        cfg = Config.from_repo()
        assert cfg.mesh is not None
        assert cfg.mesh.axes == ("data", "fsdp", "ctx", "model")

    def test_unparsable_module_degrades_to_none(self, tmp_path):
        p = tmp_path / "mesh.py"
        p.write_text("def make_mesh():\n    return None\n")
        assert parse_mesh_module(p) is None
        p.write_text("def f(:\n")  # syntax error
        assert parse_mesh_module(p) is None

    def test_falls_back_to_module_level_mesh_call(self, tmp_path):
        """Review regression: a make_mesh without a literal axis tuple
        must not mask a module-level Mesh(...) literal."""
        p = tmp_path / "mesh.py"
        p.write_text(textwrap.dedent(
            """
            AXES = ("data", "model")

            def make_mesh(devs):
                return Mesh(devs, AXES)

            _DEFAULT = Mesh(None, ("data", "model"))
            """
        ))
        parsed = parse_mesh_module(p)
        assert parsed is not None and parsed.axes == ("data", "model")


# ------------------------------------------------------------------ #
# unknown-mesh-axis
# ------------------------------------------------------------------ #


class TestUnknownMeshAxis:
    def test_fires_on_typo_including_tuple_entries(self):
        src = textwrap.dedent(
            """
            from jax.sharding import NamedSharding, PartitionSpec as P

            a = NamedSharding(mesh, P("modle"))
            b = P(None, ("data", "fspd"))
            """
        )
        fs = findings_of(src, rules=["unknown-mesh-axis"])
        assert [f.line for f in fs] == [4, 5]
        assert "'modle'" in fs[0].message and "data, fsdp" in fs[0].message

    def test_quiet_on_valid_axes_and_dynamic_entries(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            a = P(("data", "fsdp"), "ctx")
            b = P(None, axis_var, "model")     # dynamic entry skipped
            c = P(*computed)                   # fully dynamic
            """
        )
        assert rules_of(src, rules=["unknown-mesh-axis"]) == []

    def test_degrades_without_a_mesh_model(self):
        src = (
            "from jax.sharding import PartitionSpec as P\n"
            "a = P('definitely_wrong')\n"
        )
        fs = scan_source(
            src, "areal_tpu/x.py", rules=["unknown-mesh-axis"],
            config=Config(),  # no mesh catalog: degrade, never guess
        )
        assert fs == []

    def test_suppression_with_reason(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            # arealint: ok(spec for the bench-only toy mesh)
            a = P("rows")
            """
        )
        assert rules_of(src, rules=["unknown-mesh-axis"]) == []


# ------------------------------------------------------------------ #
# mesh-axis-reuse
# ------------------------------------------------------------------ #


class TestMeshAxisReuse:
    def test_fires_on_reuse_direct_and_through_tuple(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            a = P("model", None, "model")
            b = P(("data", "fsdp"), "data")
            """
        )
        fs = findings_of(src, rules=["mesh-axis-reuse"])
        assert [f.line for f in fs] == [4, 5]

    def test_quiet_on_distinct_axes(self):
        src = (
            "from jax.sharding import PartitionSpec as P\n"
            "a = P(('data', 'fsdp'), 'ctx', 'model')\n"
        )
        assert rules_of(src, rules=["mesh-axis-reuse"]) == []


# ------------------------------------------------------------------ #
# shard-map-spec-arity
# ------------------------------------------------------------------ #


class TestShardMapArity:
    def test_fires_on_signature_mismatch(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def body(q, k, v):
                return q

            def run(mesh, q, k, v):
                f = shard_map(
                    body, mesh=mesh,
                    in_specs=(P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )
                return f(q, k)
            """
        )
        fs = findings_of(src, rules=["shard-map-spec-arity"])
        assert len(fs) == 1
        assert "2 entries but body() takes 3" in fs[0].message

    def test_fires_on_invocation_mismatch_when_body_unresolvable(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def run(mesh, external_fn, q, k, v):
                return shard_map(
                    external_fn, mesh=mesh,
                    in_specs=(P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )(q, k, v)
            """
        )
        fs = findings_of(src, rules=["shard-map-spec-arity"])
        assert len(fs) == 1 and "passes 3 operand(s)" in fs[0].message

    def test_fires_on_out_specs_vs_return_tuple(self):
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def body(q, k):
                return q, k

            def run(mesh, q, k):
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(P("ctx"), P("ctx")),
                    out_specs=(P("ctx"), P("ctx"), P("ctx")),
                )(q, k)
            """
        )
        fs = findings_of(src, rules=["shard-map-spec-arity"])
        assert len(fs) == 1
        assert "out_specs has 3 entries but body() returns a 2-tuple" in (
            fs[0].message
        )

    def test_quiet_on_correct_arity_partial_and_shadowed_names(self):
        src = textwrap.dedent(
            """
            import functools
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def _shard(q, k, v, seg, *, scale):
                return q

            def scan_user(q):
                def body(carry, x):      # unrelated 2-arg scan body
                    return carry, x
                return body

            def run(mesh, q, k, v, seg):
                fn = functools.partial(_shard, scale=1.0)
                out = shard_map(
                    fn, mesh=mesh,
                    in_specs=(P("ctx"), P("ctx"), P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )(q, k, v, seg)
                # `body` here is a local VARIABLE shadowing the scan
                # body def above — resolution must degrade, not match
                body = functools.partial(_shard, scale=2.0)
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(P("ctx"), P("ctx"), P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )(q, k, v, seg)
            """
        )
        assert rules_of(src, rules=["shard-map-spec-arity"]) == []

    def test_callable_parameter_never_resolves_to_module_def(self):
        """Review regression: a callable PARAMETER named like an
        unrelated module-level def must degrade, not resolve."""
        src = textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def kernel(a, b, c):
                return a

            def outer(kernel, mesh, x):
                return shard_map(
                    kernel, mesh=mesh,
                    in_specs=(P("data"),),
                    out_specs=P("data"),
                )(x)
            """
        )
        assert rules_of(src, rules=["shard-map-spec-arity"]) == []

    def test_partial_keyword_over_positional_param_degrades(self):
        """Review regression: binding a POSITIONAL-or-keyword param by
        keyword shrinks the callable's positional surface in a way
        subtraction can't model — must degrade, not fire."""
        src = textwrap.dedent(
            """
            import functools
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def body(q, k, scale):
                return q

            def run(mesh, q, k):
                return shard_map(
                    functools.partial(body, scale=0.5), mesh=mesh,
                    in_specs=(P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )(q, k)
            """
        )
        assert rules_of(src, rules=["shard-map-spec-arity"]) == []

    def test_partial_positional_args_reduce_arity(self):
        src = textwrap.dedent(
            """
            import functools
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def body(cfg, q, k):
                return q

            def run(mesh, cfg, q, k):
                return shard_map(
                    functools.partial(body, cfg), mesh=mesh,
                    in_specs=(P("ctx"), P("ctx"), P("ctx")),
                    out_specs=P("ctx"),
                )(q, k)
            """
        )
        fs = findings_of(src, rules=["shard-map-spec-arity"])
        assert len(fs) == 1 and "takes 2 positional" in fs[0].message


# ------------------------------------------------------------------ #
# donation-sharding-mismatch
# ------------------------------------------------------------------ #


class TestDonationShardingMismatch:
    SRC = textwrap.dedent(
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def train(mesh, params, batch):
            sh_p = NamedSharding(mesh, P("fsdp"))
            sh_r = NamedSharding(mesh, P())
            params = jax.device_put(params, sh_p)
            step = jax.jit(
                train_step, donate_argnums=(0,), out_shardings=(OUT,)
            )
            return step(params, batch)
        """
    )

    def test_fires_when_no_output_matches_donated_sharding(self):
        fs = findings_of(
            self.SRC.replace("OUT", "sh_r"),
            rules=["donation-sharding-mismatch"],
        )
        assert len(fs) == 1 and fs[0].severity == "warn"
        assert "'params'" in fs[0].message

    def test_quiet_when_an_output_matches(self):
        assert rules_of(
            self.SRC.replace("OUT", "sh_p"),
            rules=["donation-sharding-mismatch"],
        ) == []

    def test_degrades_on_unresolvable_out_entry(self):
        # None entry = "let XLA choose": the output COULD alias
        assert rules_of(
            self.SRC.replace("OUT", "None"),
            rules=["donation-sharding-mismatch"],
        ) == []


# ------------------------------------------------------------------ #
# hot-path-reshard (propagation lite)
# ------------------------------------------------------------------ #


class TestHotPathReshard:
    def test_fires_inside_hot_root(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/step.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                def step(mesh, batch):  # arealint: hot
                    sh_b = NamedSharding(mesh, P(("data", "fsdp")))
                    sh_r = NamedSharding(mesh, P())
                    x = jax.device_put(batch, sh_b)
                    return jax.lax.with_sharding_constraint(x, sh_r)
                """
            ),
        }, rules=["hot-path-reshard"])
        assert [f.rule for f in fs] == ["hot-path-reshard"]
        assert "'x'" in fs[0].message and "P()" in fs[0].message

    def test_fires_cross_module_from_hot_root(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/a.py": textwrap.dedent(
                """
                from pkg.b import helper

                def step(mesh, batch):  # arealint: hot
                    return helper(mesh, batch)
                """
            ),
            "pkg/b.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                def helper(mesh, batch):
                    x = jax.device_put(
                        batch, NamedSharding(mesh, P("data"))
                    )
                    return jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, P("model"))
                    )
                """
            ),
        }, rules=["hot-path-reshard"])
        assert [(f.path, f.rule) for f in fs] == [
            ("pkg/b.py", "hot-path-reshard")
        ]
        assert "step" in fs[0].message  # names the hot root

    def test_quiet_off_hot_path_and_on_unresolved_specs(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/cold.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                def cold(mesh, batch):
                    x = jax.device_put(
                        batch, NamedSharding(mesh, P("data"))
                    )
                    return jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, P())
                    )

                def hot(mesh, batch, sh):  # arealint: hot
                    # operand spec unknown -> constraint establishes,
                    # not reshards; dynamic sharding arg -> degrade
                    y = jax.lax.with_sharding_constraint(batch, sh)
                    return jax.lax.with_sharding_constraint(
                        y, NamedSharding(mesh, P("data"))
                    )
                """
            ),
        }, rules=["hot-path-reshard"])
        assert fs == []

    def test_suppression_with_reason(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/step.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                def step(mesh, batch):  # arealint: hot
                    sh_b = NamedSharding(mesh, P(("data", "fsdp")))
                    sh_r = NamedSharding(mesh, P())
                    x = jax.device_put(batch, sh_b)
                    # arealint: ok(one deliberate all-gather for sampling)
                    return jax.lax.with_sharding_constraint(x, sh_r)
                """
            ),
        }, rules=["hot-path-reshard"])
        assert fs == []

    def test_attr_rebound_to_unresolvable_value_degrades(self):
        """Review regression: a self-attr with one literal NamedSharding
        binding AND one opaque rebinding (a forwarded parameter) has an
        unknowable spec — it must not anchor a reshard finding."""
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/eng.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                class Eng:
                    def __init__(self, mesh):
                        self._sh = NamedSharding(mesh, P("model"))

                    def set_sharding(self, sh):
                        self._sh = sh          # opaque rebinding

                    def step(self, mesh, x):  # arealint: hot
                        x = jax.device_put(
                            x, NamedSharding(mesh, P("data"))
                        )
                        return jax.device_put(x, self._sh)
                """
            ),
        }, rules=["hot-path-reshard"])
        assert fs == []

    def test_rebind_through_unmodeled_forms_invalidates(self):
        """Review regression: AnnAssign/AugAssign/for/with rebinds drop
        the inferred spec — a constraint on the FRESH value is not a
        reshard of the old one."""
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/step.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                def step(mesh, batch, items):  # arealint: hot
                    sh_b = NamedSharding(mesh, P("data"))
                    sh_r = NamedSharding(mesh, P())
                    x = jax.device_put(batch, sh_b)
                    x: object = compute(batch)       # annotated rebind
                    a = jax.device_put(batch, sh_b)
                    a += 1                           # augmented rebind
                    for b in items:                  # loop rebind
                        pass
                    y1 = jax.lax.with_sharding_constraint(x, sh_r)
                    y2 = jax.lax.with_sharding_constraint(a, sh_r)
                    return y1, y2
                """
            ),
        }, rules=["hot-path-reshard"])
        assert fs == []


# ------------------------------------------------------------------ #
# jit-sharding-disagreement
# ------------------------------------------------------------------ #


class TestJitShardingDisagreement:
    def test_fires_when_sites_disagree(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/f.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                @jax.jit
                def apply(x):
                    return x

                def a(mesh, v):
                    v = jax.device_put(v, NamedSharding(mesh, P("data")))
                    return apply(v)

                def b(mesh, v):
                    v = jax.device_put(v, NamedSharding(mesh, P("model")))
                    return apply(v)
                """
            ),
        }, rules=["jit-sharding-disagreement"])
        # one defect ("pick one sharding"), ONE finding — the sibling
        # site is named in the message, not double-reported
        assert len(fs) == 1 and fs[0].severity == "warn"
        assert "P('model')" in fs[0].message or "P('data')" in fs[0].message

    def test_quiet_when_sites_agree_or_specs_unknown(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/f.py": textwrap.dedent(
                """
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                @jax.jit
                def apply(x):
                    return x

                def a(mesh, v):
                    v = jax.device_put(v, NamedSharding(mesh, P("data")))
                    return apply(v)

                def b(mesh, v):
                    v = jax.device_put(v, NamedSharding(mesh, P("data")))
                    return apply(v)

                def c(v):
                    return apply(v)   # unknown spec: degrade
                """
            ),
        }, rules=["jit-sharding-disagreement"])
        assert fs == []


# ------------------------------------------------------------------ #
# host-divergence-collective
# ------------------------------------------------------------------ #

MULTIHOST_FIXTURE = textwrap.dedent(
    """
    from jax.experimental import multihost_utils

    def barrier(name="b"):
        multihost_utils.sync_global_devices(name)

    def main_decides(flag):
        return flag
    """
)


class TestHostDivergence:
    def test_fires_on_time_branch_guarding_collective(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/loop.py": textwrap.dedent(
                """
                import time
                from pkg import multihost

                def train(deadline):
                    if time.monotonic() > deadline:
                        multihost.barrier()
                """
            ),
        }, rules=["host-divergence-collective"])
        assert [f.rule for f in fs] == ["host-divergence-collective"]
        assert "time.monotonic()" in fs[0].message
        assert "multihost.barrier()" in fs[0].message

    def test_quiet_when_gated_through_main_decides(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/loop.py": textwrap.dedent(
                """
                import time
                from pkg import multihost

                def train(deadline):
                    if multihost.main_decides(
                        time.monotonic() > deadline
                    ):
                        multihost.barrier()
                """
            ),
        }, rules=["host-divergence-collective"])
        assert fs == []

    def test_fires_through_cross_module_return_taint(self):
        """is_main()-style: the divergent value flows through a helper's
        RETURN, across a module boundary, into the branch."""
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/timerlib.py": textwrap.dedent(
                """
                import time

                def expired(deadline):
                    return time.monotonic() > deadline
                """
            ),
            "pkg/loop.py": textwrap.dedent(
                """
                from pkg import multihost
                from pkg.timerlib import expired

                def train(deadline):
                    flag = expired(deadline)
                    if flag:
                        multihost.barrier()
                """
            ),
        }, rules=["host-divergence-collective"])
        assert len(fs) == 1 and fs[0].path == "pkg/loop.py"
        assert "expired()" in fs[0].message

    def test_fires_on_control_dependent_taint(self):
        """The EpochStepTimeFreqCtl.check() shape: the returned flag is
        a CONSTANT assigned under a time-divergent branch."""
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/timerlib.py": textwrap.dedent(
                """
                import time

                class Timer:
                    def check(self):
                        fire = False
                        if time.monotonic() > self.next_at:
                            fire = True
                        return fire
                """
            ),
            "pkg/loop.py": textwrap.dedent(
                """
                from pkg import multihost
                from pkg.timerlib import Timer

                def train():
                    t = Timer()
                    if t.check():
                        multihost.barrier()
                """
            ),
        }, rules=["host-divergence-collective"])
        assert len(fs) == 1 and fs[0].path == "pkg/loop.py"

    def test_fires_on_process_index_guarding_jitted_psum(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/ops.py": textwrap.dedent(
                """
                import jax

                @jax.jit
                def reduce_all(x):
                    return jax.lax.psum(x, "data")
                """
            ),
            "pkg/loop.py": textwrap.dedent(
                """
                import jax
                from pkg.ops import reduce_all

                def step(x):
                    if jax.process_index() == 0:
                        return reduce_all(x)
                    return x
                """
            ),
        }, rules=["host-divergence-collective"])
        assert len(fs) == 1
        assert "process_index()" in fs[0].message
        assert "lax.psum()" in fs[0].message

    def test_fires_on_signal_poll_guarding_mesh_entry(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/loop.py": textwrap.dedent(
                """
                def run(mesh, shutdown):
                    if shutdown.should_stop():
                        with mesh:
                            pass
                """
            ),
        }, rules=["host-divergence-collective"])
        assert len(fs) == 1
        assert "mesh context entry" in fs[0].message

    def test_quiet_on_uniform_branch_and_collective_free_branch(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/loop.py": textwrap.dedent(
                """
                import time
                from pkg import multihost

                def train(step, total, log, deadline):
                    if step % 10 == 0:          # host-uniform test
                        multihost.barrier()
                    if time.monotonic() > deadline:
                        log.info("late")        # no collective guarded
                """
            ),
        }, rules=["host-divergence-collective"])
        assert fs == []

    def test_suppression_with_reason(self):
        fs = project_of({
            "pkg/__init__.py": "",
            "pkg/multihost.py": MULTIHOST_FIXTURE,
            "pkg/loop.py": textwrap.dedent(
                """
                import time
                from pkg import multihost

                def train(deadline):
                    # arealint: ok(single-process tool, never on a pod)
                    if time.monotonic() > deadline:
                        multihost.barrier()
                """
            ),
        }, rules=["host-divergence-collective"])
        assert fs == []


# ------------------------------------------------------------------ #
# runtime twin: logical-axis validation in mesh.py
# ------------------------------------------------------------------ #


class TestRuntimeLogicalAxisValidation:
    def test_typo_raises_instead_of_replicating(self):
        from areal_tpu.parallel.mesh import logical_to_pspec

        with pytest.raises(ValueError, match="vocag"):
            logical_to_pspec(("layer", "vocag"))

    def test_valid_axes_and_none_pass(self):
        from areal_tpu.parallel.mesh import logical_to_pspec

        spec = logical_to_pspec(("layer", "embed", "heads"))
        assert tuple(spec) == (None, "fsdp", "model")
        assert tuple(logical_to_pspec(None)) == ()

    def test_param_shardings_validates_tree_leaves(self):
        from areal_tpu.parallel.mesh import (
            ParallelConfig, make_mesh, param_shardings,
        )

        mesh = make_mesh(ParallelConfig())
        with pytest.raises(ValueError, match="embedd"):
            param_shardings(mesh, {"w": ("embedd",)})

    def test_custom_rules_still_validate(self):
        from areal_tpu.parallel.mesh import logical_to_pspec

        with pytest.raises(ValueError, match="embed"):
            logical_to_pspec(("embed",), rules={"tokens": "ctx"})


# ------------------------------------------------------------------ #
# registry + --changed-only
# ------------------------------------------------------------------ #


class TestRegistry:
    def test_spmd_families_registered(self):
        assert {"unknown-mesh-axis", "mesh-axis-reuse",
                "shard-map-spec-arity",
                "donation-sharding-mismatch"} <= set(RULES)
        assert {"hot-path-reshard", "jit-sharding-disagreement",
                "host-divergence-collective"} <= set(PROJECT_RULES)


class TestChangedOnly:
    def _run(self, *args, stdin=None):
        return subprocess.run(
            [sys.executable, "-m", "tools.arealint", *args],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            input=stdin,
        )

    def test_same_findings_as_explicit_paths(self, tmp_path):
        """The pinned property: --changed-only with a file list on
        stdin produces byte-identical findings to passing the SAME
        surviving files as explicit CLI paths."""
        bad = tmp_path / "bad.py"
        bad.write_text("import os\nx = os.environ.get('AREAL_X')\n")
        clean = tmp_path / "clean.py"
        clean.write_text("def f():\n    return 1\n")
        excluded = tmp_path / "excluded.py"  # NOT in the stdin list
        excluded.write_text("import os\ny = os.getenv('AREAL_Y')\n")
        gone = tmp_path / "gone.py"          # in the list, not on disk

        stdin = f"{bad}\n{clean}\n{gone}\nnot_python.txt\n"
        r_changed = self._run(
            str(tmp_path), "--changed-only", "--no-baseline",
            "--format", "json", stdin=stdin,
        )
        r_explicit = self._run(
            str(bad), str(clean), "--no-baseline", "--format", "json",
        )
        assert r_changed.returncode == r_explicit.returncode == 1
        changed = json.loads(r_changed.stdout)
        explicit = json.loads(r_explicit.stdout)
        assert changed["findings"] == explicit["findings"]
        assert changed["errors"] == 1
        # the excluded file's finding appears in neither
        assert all(
            "excluded.py" not in f["path"] for f in changed["findings"]
        )

    def test_outside_scan_set_is_dropped(self, tmp_path):
        inside = tmp_path / "scanned"
        inside.mkdir()
        bad = inside / "bad.py"
        bad.write_text("import os\nx = os.environ.get('AREAL_X')\n")
        outside = tmp_path / "other"
        outside.mkdir()
        also_bad = outside / "also_bad.py"
        also_bad.write_text("import os\ny = os.getenv('AREAL_Y')\n")
        r = self._run(
            str(inside), "--changed-only", "--no-baseline",
            "--format", "json", stdin=f"{bad}\n{also_bad}\n",
        )
        payload = json.loads(r.stdout)
        assert [os.path.basename(f["path"]) for f in payload["findings"]
                ] == ["bad.py"]

    def test_empty_diff_exits_clean(self):
        r = self._run("--changed-only", "--since", "HEAD", stdin="")
        assert r.returncode == 0
        assert "no changed Python files" in r.stdout
        assert "HEAD" in r.stdout

    def test_empty_diff_keeps_machine_formats_parseable(self):
        """Review regression: docs-only diffs must still emit the
        stable json/sarif documents, not a plain-text note."""
        r = self._run(
            "--changed-only", "--format", "json", stdin="README.md\n"
        )
        assert r.returncode == 0
        payload = json.loads(r.stdout)
        assert payload["findings"] == [] and payload["errors"] == 0
        r = self._run("--changed-only", "--format", "sarif", stdin="")
        assert r.returncode == 0
        log = json.loads(r.stdout)
        assert log["version"] == "2.1.0"
        assert log["runs"][0]["results"] == []

    def test_since_requires_changed_only(self):
        r = self._run("--since", "HEAD")
        assert r.returncode == 2

    def test_three_file_diff_under_two_seconds(self):
        files = [
            "areal_tpu/parallel/mesh.py",
            "areal_tpu/parallel/multihost.py",
            "areal_tpu/base/timeutil.py",
        ]
        start = time.monotonic()
        r = self._run("--changed-only", stdin="\n".join(files) + "\n")
        elapsed = time.monotonic() - start
        assert r.returncode == 0, r.stdout + r.stderr
        assert elapsed < 2.0, f"changed-only scan took {elapsed:.2f}s"
