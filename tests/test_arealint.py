"""Tier-1 tests for tools/arealint — the repo's static-analysis framework
(docs/static_analysis.md).

Three layers:

1. **Rule fixtures** — every JAX/TPU rule has at least one positive
   fixture (it fires on the bug pattern) and one negative fixture (it
   stays quiet on the idiomatic pattern).
2. **Framework semantics** — inline suppressions require reasons,
   baseline entries suppress exactly their findings and expire (report
   stale) when the violation is fixed, severities split errors/warns.
3. **The tree itself** — ``areal_tpu/`` stays clean at error severity
   (warn findings are reported but non-fatal), and the CLI exit codes
   are stable (0 clean / 1 errors / 2 usage).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import (  # noqa: E402
    Config,
    RULES,
    apply_baseline,
    has_errors,
    scan_paths,
    scan_source,
)

pytestmark = pytest.mark.arealint

# Fixture scans use an explicit empty-catalog Config so catalog rules
# behave deterministically regardless of the repo checkout state.
FIXTURE_CFG = Config(
    counter_values=frozenset({"ft/evictions", "fwd_pipe/dispatched"}),
    counter_names=frozenset({"FT_EVICTIONS", "PIPE_FWD_DISPATCHED"}),
    fault_points=frozenset({"gen.http", "train.step"}),
)


def rules_of(src, path="areal_tpu/some/module.py", rules=None):
    return [
        f.rule
        for f in scan_source(src, path, rules=rules, config=FIXTURE_CFG)
    ]


def findings_of(src, path="areal_tpu/some/module.py", rules=None):
    return scan_source(src, path, rules=rules, config=FIXTURE_CFG)


# ------------------------------------------------------------------ #
# host-sync-in-hot-path
# ------------------------------------------------------------------ #


class TestHostSyncRule:
    def test_fires_inside_hot_annotated_function(self):
        src = textwrap.dedent(
            """
            import jax

            def step(batch):  # arealint: hot
                out = dispatch(batch)
                loss = float(fetch(out))
                return out.grads.item()
            """
        )
        rules = rules_of(src, rules=["host-sync-in-hot-path"])
        assert rules == ["host-sync-in-hot-path"] * 2

    def test_fires_transitively_through_call_graph(self):
        src = textwrap.dedent(
            """
            import jax

            def outer(batch):  # arealint: hot
                return helper(batch)

            def helper(batch):
                return jax.device_get(batch)
            """
        )
        fs = findings_of(src, rules=["host-sync-in-hot-path"])
        assert [f.rule for f in fs] == ["host-sync-in-hot-path"]
        assert "helper()" in fs[0].message

    def test_fires_inside_jitted_function(self):
        src = textwrap.dedent(
            """
            import jax

            def build():
                def step(x):
                    return x.sum().item()
                return jax.jit(step)
            """
        )
        assert rules_of(src, rules=["host-sync-in-hot-path"]) == [
            "host-sync-in-hot-path"
        ]

    def test_quiet_off_the_hot_path_and_on_host_scalars(self):
        src = textwrap.dedent(
            """
            import jax
            import numpy as np

            def cold_eval(batch):
                # not hot-annotated, not jitted, not reachable from hot
                return jax.device_get(batch)

            def hot_driver(batch):  # arealint: hot
                w = float(total)          # float(name): host scalar
                arr = np.asarray(rows)    # np.asarray(name): host data
                return w, arr
            """
        )
        assert rules_of(src, rules=["host-sync-in-hot-path"]) == []

    def test_ok_annotation_with_reason_suppresses(self):
        src = textwrap.dedent(
            """
            import jax

            def step(batch):  # arealint: hot
                # arealint: ok(single deferred stats pull per interval)
                return jax.device_get(batch)
            """
        )
        assert rules_of(src, rules=["host-sync-in-hot-path"]) == []


# ------------------------------------------------------------------ #
# retrace-hazard
# ------------------------------------------------------------------ #


class TestRetraceRule:
    def test_fires_on_jit_in_loop(self):
        src = textwrap.dedent(
            """
            import jax

            def sweep(fns, xs):
                outs = []
                for fn in fns:
                    outs.append(jax.jit(fn)(xs))
                return outs
            """
        )
        rules = rules_of(src, rules=["retrace-hazard"])
        assert "retrace-hazard" in rules

    def test_fires_on_immediate_invoke(self):
        src = textwrap.dedent(
            """
            import jax

            def step(params, x):
                return jax.jit(apply)(params, x)
            """
        )
        fs = findings_of(src, rules=["retrace-hazard"])
        assert len(fs) == 1 and "immediately invoked" in fs[0].message

    def test_fires_on_nonhashable_static_operand(self):
        src = textwrap.dedent(
            """
            import jax

            def run(x):
                return jax.jit(f, static_argnums=(1,))(x, [1, 2, 3])
            """
        )
        msgs = [f.message for f in findings_of(src, rules=["retrace-hazard"])]
        assert any("non-hashable operand" in m for m in msgs)

    def test_fires_on_closure_captured_jnp_array(self):
        src = textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            def build(cfg):
                table = jnp.arange(1024)

                def step(x):
                    return x + table

                return jax.jit(step)
            """
        )
        msgs = [f.message for f in findings_of(src, rules=["retrace-hazard"])]
        assert any("closes over jnp array 'table'" in m for m in msgs)

    def test_one_finding_for_immediate_invoke_inside_loop(self):
        src = textwrap.dedent(
            """
            import jax

            def sweep(xs):
                for x in xs:
                    y = jax.jit(apply)(x)
                return y
            """
        )
        fs = findings_of(src, rules=["retrace-hazard"])
        assert len(fs) == 1 and "inside a loop" in fs[0].message

    def test_quiet_on_cached_module_level_and_assigned_jit(self):
        src = textwrap.dedent(
            """
            import jax

            jitted = jax.jit(apply)

            def build(self):
                if "k" not in self._cache:
                    self._cache["k"] = jax.jit(apply)
                return self._cache["k"]

            def step(params, x):
                fn = jax.jit(apply, static_argnums=(1,))
                return fn
            """
        )
        assert rules_of(src, rules=["retrace-hazard"]) == []

    def test_is_warn_severity(self):
        src = "import jax\ndef f(x):\n    return jax.jit(g)(x)\n"
        fs = findings_of(src, rules=["retrace-hazard"])
        assert fs and all(f.severity == "warn" for f in fs)
        assert not has_errors(fs)


# ------------------------------------------------------------------ #
# donation-after-use
# ------------------------------------------------------------------ #


class TestDonationRule:
    def test_fires_on_read_after_donating_call(self):
        src = textwrap.dedent(
            """
            import jax

            def train(params, opt_state, batch):
                step = jax.jit(train_step, donate_argnums=(0, 1))
                new_params, new_opt = step(params, opt_state, batch)
                norm = global_norm(params)   # donated buffer!
                return new_params, new_opt, norm
            """
        )
        fs = findings_of(src, rules=["donation-after-use"])
        assert [f.rule for f in fs] == ["donation-after-use"]
        assert "'params'" in fs[0].message

    def test_fires_for_immediate_invoke_donation(self):
        src = textwrap.dedent(
            """
            import jax

            def train(params, batch):
                out = jax.jit(train_step, donate_argnums=(0,))(params, batch)
                return params.mean(), out
            """
        )
        assert rules_of(src, rules=["donation-after-use"]) == [
            "donation-after-use"
        ]

    def test_quiet_when_rebound_at_call_or_before_use(self):
        src = textwrap.dedent(
            """
            import jax

            def train(self, batch):
                step = jax.jit(train_step, donate_argnums=(0, 1))
                # rebinding at the call keeps the names valid
                self.params, self.opt_state = step(
                    self.params, self.opt_state, batch
                )
                return global_norm(self.params)

            def other(params, batch):
                step = jax.jit(train_step, donate_argnums=(0,))
                out = step(params, batch)
                params = out          # rebound before any read
                return params
            """
        )
        assert rules_of(src, rules=["donation-after-use"]) == []


# ------------------------------------------------------------------ #
# env-knob
# ------------------------------------------------------------------ #


class TestEnvKnobRule:
    def test_fires_on_reads_outside_catalog(self):
        src = textwrap.dedent(
            """
            import os

            LEVEL = os.environ.get("AREAL_LOG_LEVEL", "INFO")
            DEPTH = os.getenv("AREAL_DEPTH")
            RAW = os.environ["AREAL_RAW"]
            HAS = "AREAL_X" in os.environ
            """
        )
        assert rules_of(src, rules=["env-knob"]) == ["env-knob"] * 4

    def test_fires_on_from_import_forms(self):
        src = textwrap.dedent(
            """
            from os import environ, getenv

            DEPTH = getenv("AREAL_DEPTH")
            RAW = environ["AREAL_RAW"]
            LEVEL = environ.get("AREAL_LOG_LEVEL", "INFO")
            HAS = "AREAL_X" in environ
            """
        )
        assert rules_of(src, rules=["env-knob"]) == ["env-knob"] * 4

    def test_quiet_in_catalog_and_env_helpers_and_on_writes(self):
        src = textwrap.dedent(
            """
            import os

            def log_level():
                return os.environ.get("AREAL_LOG_LEVEL", "INFO")
            """
        )
        assert rules_of(
            src, path="areal_tpu/base/constants.py", rules=["env-knob"]
        ) == []

        helper = textwrap.dedent(
            """
            import os

            def _env_float(name, default):
                raw = os.environ.get(name)
                return float(raw) if raw else default

            def not_a_helper():
                return os.environ.get("AREAL_X")
            """
        )
        rules = rules_of(
            helper, path="areal_tpu/system/worker_base.py",
            rules=["env-knob"],
        )
        assert rules == ["env-knob"]  # only the non-_env_* read

        writes = textwrap.dedent(
            """
            import os

            os.environ["AREAL_FILEROOT"] = "/tmp/x"
            os.environ.setdefault("AREAL_ROOT", "/tmp/y")
            os.environ.pop("JAX_PLATFORMS", None)
            """
        )
        assert rules_of(writes, rules=["env-knob"]) == []


# ------------------------------------------------------------------ #
# registry rules
# ------------------------------------------------------------------ #


class TestRegistryRules:
    def test_counter_literal_must_be_registered(self):
        src = textwrap.dedent(
            """
            from areal_tpu.base import metrics as metrics_mod

            metrics_mod.counters.add("ft/evictions")
            metrics_mod.counters.add("ft/not_in_catalog")
            metrics_mod.counters.peak("fwd_pipe/dispatched", 3)
            """
        )
        fs = findings_of(src, rules=["unregistered-counter"])
        assert len(fs) == 1 and "ft/not_in_catalog" in fs[0].message

    def test_counter_constant_must_be_defined(self):
        src = textwrap.dedent(
            """
            from areal_tpu.base import metrics as metrics_mod

            metrics_mod.counters.add(metrics_mod.FT_EVICTIONS)
            metrics_mod.counters.add(metrics_mod.FT_TYPO_NAME)
            metrics_mod.counters.get(local_variable_name)
            """
        )
        fs = findings_of(src, rules=["unregistered-counter"])
        assert len(fs) == 1 and "FT_TYPO_NAME" in fs[0].message

    def test_histogram_observe_must_be_registered(self):
        """The telemetry plane's histogram kind goes through the same
        catalog: counters.observe with an uncataloged key is flagged, a
        cataloged literal or constant passes, and the repo's REAL catalog
        carries the histogram constants (parsed, not imported)."""
        src = textwrap.dedent(
            """
            from areal_tpu.base import metrics as metrics_mod

            metrics_mod.counters.observe("ft/evictions", 1.0)
            metrics_mod.counters.observe("staleness_not_in_catalog", 2)
            metrics_mod.counters.observe(metrics_mod.FT_EVICTIONS, 3)
            """
        )
        fs = findings_of(src, rules=["unregistered-counter"])
        assert len(fs) == 1 and "staleness_not_in_catalog" in fs[0].message
        # the real catalog registers the trajectory histogram keys
        real = Config.from_repo()
        for name, value in [
            ("STALENESS_VERSIONS", "staleness_versions"),
            ("QUEUE_WAIT_S", "queue_wait_s"),
            ("E2E_LATENCY_S", "e2e_latency_s"),
        ]:
            assert name in real.counter_names
            assert value in real.counter_values

    def test_fault_point_must_be_registered(self):
        src = textwrap.dedent(
            """
            from areal_tpu.base import faults

            faults.maybe_fail("gen.http", url=url)
            faults.maybe_trip("train.step", step=3)
            faults.maybe_fail("gen.htpp", url=url)
            """
        )
        fs = findings_of(src, rules=["unregistered-fault-point"])
        assert len(fs) == 1 and "gen.htpp" in fs[0].message

    def test_registry_rules_skip_without_catalog(self):
        cfg = Config()  # no catalogs loaded
        src = 'counters.add("whatever")\nmaybe_fail("nope")\n'
        fs = scan_source(
            src, "areal_tpu/x.py",
            rules=["unregistered-counter", "unregistered-fault-point"],
            config=cfg,
        )
        assert fs == []


# ------------------------------------------------------------------ #
# suppression semantics
# ------------------------------------------------------------------ #


class TestSuppression:
    def test_reason_required(self):
        src = textwrap.dedent(
            """
            import os

            a = os.environ.get("AREAL_A")  # arealint: ok
            b = os.environ.get("AREAL_B")  # arealint: ok()
            c = os.environ.get("AREAL_C")  # arealint: ok(read by ops tooling)
            """
        )
        fs = findings_of(
            src, rules=["env-knob", "suppression-missing-reason"]
        )
        by_rule = {}
        for f in fs:
            by_rule.setdefault(f.rule, []).append(f.line)
        # the two reason-less suppressions do NOT suppress...
        assert by_rule["env-knob"] == [4, 5]
        # ...and are themselves flagged (warn)
        assert by_rule["suppression-missing-reason"] == [4, 5]

    def test_comment_line_above_suppresses(self):
        src = textwrap.dedent(
            """
            import os

            # arealint: ok(documented legacy read)
            a = os.environ.get("AREAL_A")
            """
        )
        assert rules_of(src, rules=["env-knob"]) == []

    def test_legacy_token_only_covers_migrated_rules(self):
        src = textwrap.dedent(
            """
            import asyncio
            import os

            async def f():
                await asyncio.gather(a(), b())  # async-hygiene: ok

            x = os.environ.get("AREAL_X")  # async-hygiene: ok
            """
        )
        fs = findings_of(src, rules=["bare-gather", "env-knob"])
        assert [f.rule for f in fs] == ["env-knob"]


# ------------------------------------------------------------------ #
# baseline semantics
# ------------------------------------------------------------------ #


class TestBaseline:
    SRC = textwrap.dedent(
        """
        import os

        a = os.environ.get("AREAL_A")
        b = os.environ.get("AREAL_B")
        """
    )

    def test_entry_suppresses_up_to_max_and_stale_entries_reported(self):
        fs = findings_of(self.SRC, path="areal_tpu/mod.py",
                         rules=["env-knob"])
        assert len(fs) == 2
        entries = [
            {"rule": "env-knob", "path": "areal_tpu/mod.py",
             "reason": "legacy knobs, migration tracked", "max": 2},
            {"rule": "env-knob", "path": "areal_tpu/gone.py",
             "reason": "was fixed — this entry is now stale"},
        ]
        remaining, stale = apply_baseline(fs, entries)
        assert remaining == []
        assert [e["path"] for e in stale] == ["areal_tpu/gone.py"]

    def test_default_max_is_one_finding(self):
        fs = findings_of(self.SRC, path="areal_tpu/mod.py",
                         rules=["env-knob"])
        entries = [{
            "rule": "env-knob", "path": "areal_tpu/mod.py",
            "reason": "one legacy knob",
        }]
        remaining, stale = apply_baseline(fs, entries)
        assert len(remaining) == 1 and stale == []

    def test_malformed_baseline_rejected(self):
        from tools.arealint import BaselineError, load_baseline

        import tempfile

        with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False
        ) as f:
            json.dump({"entries": [{"rule": "env-knob",
                                    "path": "x.py"}]}, f)  # no reason
        with pytest.raises(BaselineError):
            load_baseline(f.name)
        os.unlink(f.name)


# ------------------------------------------------------------------ #
# the tree itself + CLI
# ------------------------------------------------------------------ #


class TestRepoIsClean:
    def test_rule_registry_has_the_required_families(self):
        migrated = {"bare-gather", "discarded-task",
                    "live-checkpoint-rmtree", "sleep-in-async"}
        jax_tpu = {"host-sync-in-hot-path", "retrace-hazard",
                   "donation-after-use", "env-knob",
                   "unregistered-counter", "unregistered-fault-point"}
        assert migrated <= set(RULES)
        assert jax_tpu <= set(RULES)
        assert len(RULES) >= 8

    # (the tree-clean gate itself is TestFullTreeGate below: one CLI run
    # covers areal_tpu/ tools/ tests/ with the baseline AND the runtime
    # budget — a second in-process scan of areal_tpu/ would just re-parse
    # the tree for ~14 s of tier-1 time)

    def test_baseline_has_no_hot_path_entries_for_train(self):
        """Acceptance: host-sync/donation findings in areal_tpu/train are
        FIXED or inline-annotated — never baselined away."""
        from tools.arealint import DEFAULT_BASELINE, load_baseline

        bl = os.path.join(REPO, DEFAULT_BASELINE)
        entries = load_baseline(bl) if os.path.exists(bl) else []
        offenders = [
            e for e in entries
            if e["rule"] in ("host-sync-in-hot-path", "donation-after-use")
            and e["path"].startswith("areal_tpu/train/")
        ]
        assert offenders == []


class TestCLI:
    def _run(self, *args, **kw):
        return subprocess.run(
            [sys.executable, "-m", "tools.arealint", *args],
            cwd=REPO, capture_output=True, text=True, timeout=120, **kw,
        )

    def test_json_scan_of_tree_exits_0(self):
        # base/ only: the full-tree error gate is the in-process
        # TestRepoIsClean scan; this checks the CLI+JSON plumbing without
        # paying for a second whole-tree parse
        r = self._run(
            os.path.join(REPO, "areal_tpu", "base"), "--format", "json"
        )
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["errors"] == 0
        assert {"findings", "stale_baseline", "warnings"} <= set(payload)

    def test_errors_exit_1(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import os\nx = os.environ.get('AREAL_X')\n"
        )
        r = self._run(str(bad), "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "env-knob" in r.stdout

    def test_warn_only_exits_0(self, tmp_path):
        warn = tmp_path / "warn.py"
        warn.write_text(
            "import jax\ndef f(x):\n    return jax.jit(g)(x)\n"
        )
        r = self._run(str(warn), "--no-baseline")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "retrace-hazard" in r.stdout

    def test_usage_errors_exit_2(self):
        assert self._run("--definitely-not-a-flag").returncode == 2
        r = self._run("--rules", "no-such-rule")
        assert r.returncode == 2
        assert "unknown rule" in r.stderr

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        assert "host-sync-in-hot-path" in r.stdout


class TestSarif:
    """SARIF output is a determinism contract: the same findings render
    byte-identical SARIF everywhere (golden-file), and the CLI path
    round-trips through real findings."""

    GOLDEN = os.path.join(REPO, "tests", "data", "arealint_golden.sarif")

    def test_golden_file(self):
        from tools.arealint import Finding, sarif

        findings = [
            Finding(
                "areal_tpu/system/demo.py", 12, "bare-gather",
                "asyncio.gather(...) without return_exceptions=True",
                "error",
            ),
            Finding(
                "areal_tpu/train/demo.py", 40, "host-sync-cross-module",
                "jax.device_get(...) in helper() forces a host<->device "
                "sync on a hot path — reachable from hot root "
                "Engine.step()",
                "error",
            ),
            Finding(
                "tools/demo.py", 7, "jit-weak-type-drift",
                "jitted f() receives an int literal at position 0 here "
                "but a non-literal at another site",
                "warn",
            ),
            # one finding per v3 SPMD family (docs/static_analysis.md
            # "SPMD rules")
            Finding(
                "areal_tpu/train/demo.py", 12, "unknown-mesh-axis",
                "unknown mesh axis 'modle' in PartitionSpec — the mesh "
                "built by make_mesh has axes (data, fsdp, ctx, model)",
                "error",
            ),
            Finding(
                "areal_tpu/ops/demo.py", 21, "shard-map-spec-arity",
                "shard_map in_specs has 2 entries but body() takes 3 "
                "positional argument(s) — every operand needs exactly "
                "one spec",
                "error",
            ),
            Finding(
                "areal_tpu/gen/demo.py", 33, "hot-path-reshard",
                "with_sharding_constraint() changes the inferred "
                "sharding of 'x' from P(('data','fsdp')) to P() in "
                "decode() (reachable from hot root Engine.step()) — an "
                "implicit reshard on the hot path",
                "error",
            ),
            Finding(
                "areal_tpu/system/demo.py", 48,
                "host-divergence-collective",
                "branch in run() depends on host-local time.monotonic() "
                "but guards collective multihost.barrier() via "
                "save_recover_checkpoint()",
                "error",
            ),
            # one finding per v4 lifecycle rule (docs/static_analysis.md
            # "Lifecycle rules")
            Finding(
                "areal_tpu/gen/demo.py", 12, "leak-on-exception-path",
                "gen.kv-pages acquired by pool.alloc() is not released "
                "on every path out of admit() — release it in a finally "
                "/ context manager, or annotate the deliberate handoff "
                "with '# arealint: owns(gen.kv-pages, <reason>)'",
                "error",
            ),
            Finding(
                "areal_tpu/gen/demo.py", 55, "leak-on-cancellation",
                "this await can be cancelled while gen.kv-pages "
                "(acquired line 52 by pool.alloc()) is held — a "
                "CancelledError skips the release on line 57; wrap the "
                "window in try/finally (note: 'except Exception' does "
                "not catch CancelledError)",
                "error",
            ),
            Finding(
                "areal_tpu/gen/demo.py", 80, "double-release",
                "gen.kv-pages ('pages') is released again here — "
                "already released on line 78 with no re-acquire in "
                "between; the second release underflows the refcount "
                "(double free)",
                "error",
            ),
            Finding(
                "areal_tpu/gateway/demo.py", 31, "release-without-acquire",
                "gateway.token-bucket is released here on every path, "
                "but the matching acquire (line 24) happens only on "
                "some — the no-acquire path releases a resource it "
                "never held; guard the release with the same condition "
                "(or the handle's truthiness)",
                "error",
            ),
            Finding(
                "areal_tpu/gateway/demo.py", 24, "charge-refund-asymmetry",
                "gateway.token-bucket charged by bucket.try_acquire() "
                "is not released on every path out of submit() — refund "
                "it on every exit (try/finally), hand it to a callee "
                "that settles it, or annotate the deliberate handoff "
                "with '# arealint: owns(gateway.token-bucket, <reason>)'",
                "error",
            ),
            # one finding per v5 wire rule (docs/static_analysis.md
            # "Wire rules")
            Finding(
                "areal_tpu/gateway/demo.py", 18, "unknown-endpoint",
                "GenAPIClient.pause calls POST /pause, which no server "
                "module registers — the request can only 404",
                "error",
            ),
            Finding(
                "areal_tpu/system/demo.py", 61, "request-field-drift",
                "session.post posts /allocate_rollout without field "
                "'qid', which the handler "
                "(areal_tpu/system/gserver_manager.py:_allocate) reads "
                "unconditionally — guaranteed KeyError -> 500",
                "error",
            ),
            Finding(
                "areal_tpu/gateway/demo.py", 74, "response-field-drift",
                "GenAPIClient.metrics reads response key "
                "'slot_capacity' from /metrics_json, which no producer "
                "(areal_tpu/gen/server.py:_metrics) emits",
                "error",
            ),
            Finding(
                "areal_tpu/gen/demo.py", 92, "status-code-drift",
                "_generate emits HTTP 429 for POST /generate, but no "
                "caller branches on it or guards with raise_for_status "
                "— it surfaces as an unhandled exception",
                "warn",
            ),
            Finding(
                "areal_tpu/system/demo.py", 130, "retry-unbounded-status",
                "GenAPIClient.generate retries POST /generate on "
                "transient HTTP statuses, but the endpoint is "
                "non-idempotent — a timed-out request may still be "
                "running server-side and a re-send double-executes it "
                "(pass retry_connection_only=True)",
                "error",
            ),
        ]
        rendered = sarif.dumps(
            findings,
            root="/checkout",
            rule_ids=[
                "bare-gather", "host-sync-cross-module",
                "jit-weak-type-drift", "unknown-mesh-axis",
                "shard-map-spec-arity", "hot-path-reshard",
                "host-divergence-collective",
                "leak-on-exception-path", "leak-on-cancellation",
                "double-release", "release-without-acquire",
                "charge-refund-asymmetry",
                "unknown-endpoint", "request-field-drift",
                "response-field-drift", "status-code-drift",
                "retry-unbounded-status",
            ],
        ) + "\n"
        with open(self.GOLDEN, encoding="utf-8") as f:
            golden = f.read()
        assert rendered == golden, (
            "SARIF output drifted from tests/data/arealint_golden.sarif — "
            "if the change is deliberate (schema/rule-doc update), "
            "regenerate the golden file"
        )

    def test_cli_sarif_of_findings(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import os\nx = os.environ.get('AREAL_X')\n")
        r = subprocess.run(
            [sys.executable, "-m", "tools.arealint", str(bad),
             "--no-baseline", "--format", "sarif"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        log = json.loads(r.stdout)
        assert log["version"] == "2.1.0"
        (run,) = log["runs"]
        assert run["tool"]["driver"]["name"] == "arealint"
        assert any(
            res["ruleId"] == "env-knob" and res["level"] == "error"
            for res in run["results"]
        )
        rule_ids = [ru["id"] for ru in run["tool"]["driver"]["rules"]]
        assert rule_ids == sorted(rule_ids)


class TestFullTreeGate:
    """Acceptance + runtime budget in one pass: the DEFAULT scan
    (areal_tpu/ tools/ tests/, parallel jobs, project rules on) exits 0
    on this tree AND completes under a fixed wall-clock bound on CPU —
    the lint gate must stay cheap enough to run on every PR."""

    BUDGET_S = 180.0

    def test_default_tree_clean_and_under_budget(self):
        import time

        start = time.monotonic()
        # subprocess timeout sits ABOVE the budget so a breach fails via
        # the diagnostic assert below, not a raw TimeoutExpired traceback
        r = subprocess.run(
            [sys.executable, "-m", "tools.arealint"],
            cwd=REPO, capture_output=True, text=True,
            timeout=self.BUDGET_S * 2,
        )
        elapsed = time.monotonic() - start
        # exit 0 == no error-severity findings; warn findings are
        # reported but non-fatal by policy (docs/static_analysis.md), so
        # the gate must NOT require a completely silent scan
        assert r.returncode == 0, r.stdout + r.stderr
        assert elapsed < self.BUDGET_S, (
            f"full-tree scan took {elapsed:.1f}s "
            f"(budget {self.BUDGET_S:.0f}s)"
        )
