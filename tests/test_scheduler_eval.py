"""Scheduler clients + offline evaluation harness.

Counterpart of the reference's scheduler layer tests
(``realhf/scheduler/client.py`` contract, local subprocess + slurm sbatch
backends) and its ``evaluation/eval_and_aggregate.py`` math harness.
"""

import json
import os
import shutil
import sys

import numpy as np
import pytest

from areal_tpu.scheduler import (
    JobException,
    JobState,
    LocalSchedulerClient,
    SlurmSchedulerClient,
    make_scheduler,
)


class TestLocalScheduler:
    def test_submit_wait_completed(self):
        s = make_scheduler("local", "sched-test", "t0")
        s.submit("ok", [sys.executable, "-c", "print('hi')"])
        infos = s.wait(timeout=30)
        assert [i.state for i in infos] == [JobState.COMPLETED]

    def test_failure_raises_and_stops_world(self):
        s = LocalSchedulerClient("sched-test", "t1")
        s.submit("bad", [sys.executable, "-c", "raise SystemExit(3)"])
        s.submit("slow", [sys.executable, "-c", "import time; time.sleep(60)"])
        with pytest.raises(JobException) as e:
            s.wait(timeout=30, poll=0.2)
        assert e.value.reason == JobState.FAILED
        # the surviving job was stopped with the world
        assert s.find("slow").state in (JobState.CANCELLED, JobState.FAILED)

    def test_stop_and_states(self):
        s = LocalSchedulerClient("sched-test", "t2")
        s.submit("j", [sys.executable, "-c", "import time; time.sleep(60)"])
        assert s.find("j").state == JobState.RUNNING
        s.stop("j")
        assert s.find("j").state == JobState.CANCELLED
        assert s.find("ghost").state == JobState.NOT_FOUND

    def test_submit_array(self):
        s = LocalSchedulerClient("sched-test", "t3")
        s.submit_array("w", [sys.executable, "-c", "import sys; print(sys.argv)"], 3)
        infos = s.wait(timeout=30)
        assert len(infos) == 3
        assert {i.name for i in infos} == {"w/0", "w/1", "w/2"}


class TestSlurmCommands:
    def test_sbatch_command_shape(self):
        s = SlurmSchedulerClient(
            "exp", "t0", partition="tpu", container_image="areal:latest",
            log_dir="/logs", extra_sbatch_args=["--qos=high"],
        )
        cmd = s.build_sbatch_cmd(
            "trainer/0", ["python", "-m", "areal_tpu.apps.main", "async-ppo"],
            nodes=4, cpus_per_task=16, mem_gb=64, time_limit="12:00:00",
        )
        assert cmd[0] == "sbatch"
        assert "--job-name=exp_t0:trainer/0" in cmd
        assert "--nodes=4" in cmd and "--ntasks-per-node=1" in cmd
        assert "--partition=tpu" in cmd and "--qos=high" in cmd
        assert "--time=12:00:00" in cmd
        wrap = cmd[-1]
        assert wrap.startswith("--wrap=srun --container-image=areal:latest")
        assert "areal_tpu.apps.main async-ppo" in wrap

    @pytest.mark.skipif(shutil.which("sbatch") is not None,
                        reason="slurm present; gate test is for without")
    def test_no_slurm_is_loud(self):
        s = SlurmSchedulerClient("exp", "t0")
        with pytest.raises(RuntimeError, match="sbatch"):
            s.submit("x", ["true"])


def test_eval_offline_harness(tmp_path):
    """End-to-end offline eval on a tiny random model: samples + aggregate
    land with the right shape (scores ~0 on a random model)."""
    from areal_tpu.apps import eval_offline
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.models import hf as hf_conv, transformer as tfm

    import jax

    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, use_attention_bias=True,
        dtype="float32",
    )
    ckpt = str(tmp_path / "ckpt")
    hf_conv.save_hf_checkpoint(
        jax.tree.map(lambda x: np.asarray(x), tfm.init_params(cfg, jax.random.key(0))),
        cfg, "qwen2", ckpt,
    )
    data = str(tmp_path / "math.jsonl")
    rng = np.random.default_rng(0)
    with open(data, "w") as f:
        for i in range(4):
            f.write(json.dumps({
                "query_id": f"q{i}",
                "prompt_ids": [int(x) for x in rng.integers(1, 128, 6)],
                "task": "math",
                "solutions": ["\\boxed{7}"],
            }) + "\n")
    out = str(tmp_path / "eval")
    rc = eval_offline.main([
        "--model-path", ckpt, "--dataset", data, "--output-dir", out,
        "--n-sampling", "2", "--max-gen-tokens", "8", "--greedy",
        "--batch-prompts", "2", "--allow-token-id-answers",
    ])
    assert rc == 0
    agg = json.load(open(os.path.join(out, "aggregate.json")))
    assert agg["n_prompts"] == 4 and "pass@1" in agg and "pass@2" in agg
    lines = [json.loads(l) for l in open(os.path.join(out, "samples.jsonl"))]
    assert len(lines) == 4
    assert all(len(l["answers"]) == 2 for l in lines)
    # idempotence: a second run without --overwrite is a no-op
    assert eval_offline.main([
        "--model-path", ckpt, "--dataset", data, "--output-dir", out,
        "--n-sampling", "2", "--allow-token-id-answers",
    ]) == 0
