"""Scheduler clients + offline evaluation harness.

Counterpart of the reference's scheduler layer tests
(``realhf/scheduler/client.py`` contract, local subprocess + slurm sbatch
backends) and its ``evaluation/eval_and_aggregate.py`` math harness.
"""

import json
import os
import shutil
import sys

import numpy as np
import pytest

from areal_tpu.scheduler import (
    JobException,
    JobState,
    LocalSchedulerClient,
    SlurmSchedulerClient,
    make_scheduler,
)


class TestLocalScheduler:
    def test_submit_wait_completed(self):
        s = make_scheduler("local", "sched-test", "t0")
        s.submit("ok", [sys.executable, "-c", "print('hi')"])
        infos = s.wait(timeout=30)
        assert [i.state for i in infos] == [JobState.COMPLETED]

    def test_failure_raises_and_stops_world(self):
        s = LocalSchedulerClient("sched-test", "t1")
        s.submit("bad", [sys.executable, "-c", "raise SystemExit(3)"])
        s.submit("slow", [sys.executable, "-c", "import time; time.sleep(60)"])
        with pytest.raises(JobException) as e:
            s.wait(timeout=30, poll=0.2)
        assert e.value.reason == JobState.FAILED
        # the surviving job was stopped with the world
        assert s.find("slow").state in (JobState.CANCELLED, JobState.FAILED)

    def test_stop_and_states(self):
        s = LocalSchedulerClient("sched-test", "t2")
        s.submit("j", [sys.executable, "-c", "import time; time.sleep(60)"])
        assert s.find("j").state == JobState.RUNNING
        s.stop("j")
        assert s.find("j").state == JobState.CANCELLED
        assert s.find("ghost").state == JobState.NOT_FOUND

    def test_submit_array(self):
        s = LocalSchedulerClient("sched-test", "t3")
        s.submit_array("w", [sys.executable, "-c", "import sys; print(sys.argv)"], 3)
        infos = s.wait(timeout=30)
        assert len(infos) == 3
        assert {i.name for i in infos} == {"w/0", "w/1", "w/2"}


class TestSlurmCommands:
    def test_sbatch_command_shape(self):
        s = SlurmSchedulerClient(
            "exp", "t0", partition="tpu", container_image="areal:latest",
            log_dir="/logs", extra_sbatch_args=["--qos=high"],
        )
        cmd = s.build_sbatch_cmd(
            "trainer/0", ["python", "-m", "areal_tpu.apps.main", "async-ppo"],
            nodes=4, cpus_per_task=16, mem_gb=64, time_limit="12:00:00",
        )
        assert cmd[0] == "sbatch"
        assert "--job-name=exp_t0:trainer/0" in cmd
        assert "--nodes=4" in cmd and "--ntasks-per-node=1" in cmd
        assert "--partition=tpu" in cmd and "--qos=high" in cmd
        assert "--time=12:00:00" in cmd
        wrap = cmd[-1]
        assert wrap.startswith("--wrap=srun --container-image=areal:latest")
        assert "areal_tpu.apps.main async-ppo" in wrap

    @pytest.mark.skipif(shutil.which("sbatch") is not None,
                        reason="slurm present; gate test is for without")
    def test_no_slurm_is_loud(self):
        s = SlurmSchedulerClient("exp", "t0")
        with pytest.raises(RuntimeError, match="sbatch"):
            s.submit("x", ["true"])

    def test_array_submission_scripts(self):
        """A 16-worker trainer fleet over 4 hosts: ONE job, one jobstep per
        worker via srun -K --multi-prog, ranks pinned to hosts through a
        hostfile + --distribution=arbitrary, env exported in-script
        (VERDICT r3 missing #1 ≈ realhf/scheduler/slurm/utils.py:140-420)."""
        s = SlurmSchedulerClient(
            "exp", "t0", partition="tpu", log_dir="/logs",
            extra_sbatch_args=["--qos=high"],
        )
        hosts = [f"tpu-host-{i}" for i in range(4)]
        sub = s.build_array_submission(
            "trainer", ["python", "-m", "areal_tpu.apps.launcher_worker",
                        "--role=trainer"],
            count=16, cpus_per_task=16, mem_gb_per_task=32,
            hosts=hosts, tasks_per_host=4,
            env={"AREAL_NAME_RESOLVE": "rpc://ctrl:2379",
                 "TPU_FLAG": "a b"},
            time_limit="12:00:00",
        )
        script = sub.batch_script
        assert "#SBATCH --job-name=exp_t0:trainer" in script
        assert "#SBATCH --ntasks=16" in script
        assert "#SBATCH --partition=tpu" in script
        assert "#SBATCH --qos=high" in script
        assert "#SBATCH --time=12:00:00" in script
        assert "#SBATCH --distribution=arbitrary" in script
        assert "export AREAL_NAME_RESOLVE=rpc://ctrl:2379" in script
        assert "export TPU_FLAG='a b'" in script            # quoted
        # multiprog/hostfile self-materialize ON THE BATCH NODE (a submit-
        # host path would not exist there on node-local-/tmp clusters)
        assert "export SLURM_HOSTFILE=$AREAL_JOBDIR/hostfile" in script
        assert "cat > $AREAL_JOBDIR/multiprog <<'AREAL_EOF'" in script
        assert sub.multiprog_content.rstrip("\n") in script
        assert sub.hostfile_content.rstrip("\n") in script
        assert "srun -K -l --ntasks=16" in script
        assert "--multi-prog $AREAL_JOBDIR/multiprog" in script
        # multiprog: rank k runs the command with --worker-index=k
        lines = sub.multiprog_content.strip().splitlines()
        assert len(lines) == 16
        assert lines[0].startswith("0 python -m areal_tpu.apps.launcher_worker")
        assert lines[7].endswith("--worker-index=7")
        # hostfile: 4 ranks per host, in order
        hl = sub.hostfile_content.strip().splitlines()
        assert len(hl) == 16
        assert hl[:4] == ["tpu-host-0"] * 4 and hl[-1] == "tpu-host-3"

    def test_array_submission_validates_hosts(self):
        s = SlurmSchedulerClient("exp", "t0")
        with pytest.raises(ValueError, match="hosts"):
            s.build_array_submission(
                "w", ["true"], count=8, hosts=["h0"], tasks_per_host=2
            )

    def test_submit_array_writes_and_sbatches(self, tmp_path, monkeypatch):
        import subprocess as sp

        import areal_tpu.scheduler.client as sched_mod

        s = SlurmSchedulerClient("exp", "t0", log_dir=str(tmp_path))
        monkeypatch.setattr(sched_mod.shutil, "which", lambda _: "/usr/bin/sbatch")
        calls = []
        monkeypatch.setattr(
            sched_mod.subprocess, "check_output",
            lambda cmd, **kw: calls.append(cmd) or "4242\n",
        )
        ids = s.submit_array(
            "rollout", ["python", "-m", "x"], count=4,
            hosts=["h0", "h1"], tasks_per_host=2,
        )
        assert ids == ["4242"] and s._job_ids["rollout"] == "4242"
        assert calls[0][:2] == ["sbatch", "--parsable"]
        assert (tmp_path / "rollout.sbatch").exists()
        sp_script = (tmp_path / "rollout.sbatch").read_text()
        assert "srun -K -l --ntasks=4" in sp_script
        # the script carries its own multiprog/hostfile payload
        assert "cat > $AREAL_JOBDIR/multiprog" in sp_script
        assert "--worker-index=3" in sp_script


def test_eval_offline_harness(tmp_path):
    """End-to-end offline eval on a tiny random model: samples + aggregate
    land with the right shape (scores ~0 on a random model)."""
    from areal_tpu.apps import eval_offline
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.models import hf as hf_conv, transformer as tfm

    import jax

    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, use_attention_bias=True,
        dtype="float32",
    )
    ckpt = str(tmp_path / "ckpt")
    hf_conv.save_hf_checkpoint(
        jax.tree.map(lambda x: np.asarray(x), tfm.init_params(cfg, jax.random.key(0))),
        cfg, "qwen2", ckpt,
    )
    data = str(tmp_path / "math.jsonl")
    data2 = str(tmp_path / "more.jsonl")
    rng = np.random.default_rng(0)
    for path, n_prompts in ((data, 4), (data2, 2)):
        with open(path, "w") as f:
            for i in range(n_prompts):
                f.write(json.dumps({
                    "query_id": f"q{i}",
                    "prompt_ids": [int(x) for x in rng.integers(1, 128, 6)],
                    "task": "math",
                    "solutions": ["\\boxed{7}"],
                }) + "\n")
    # per-benchmark sampling override (the reference's per-benchmark configs)
    sampling_cfg = str(tmp_path / "sampling.json")
    with open(sampling_cfg, "w") as f:
        json.dump({"more": {"max_gen_tokens": 4, "temperature": 1.0}}, f)
    out = str(tmp_path / "eval")
    rc = eval_offline.main([
        "--model-path", ckpt, "--dataset", data,
        "--dataset", f"more={data2}", "--output-dir", out,
        "--n-sampling", "2", "--max-gen-tokens", "8", "--with-greedy",
        "--batch-prompts", "2", "--allow-token-id-answers",
        "--sampling-config", sampling_cfg,
    ])
    assert rc == 0
    agg = json.load(open(os.path.join(out, "aggregate.json")))
    assert set(agg["benchmarks"]) == {"math", "more"}
    m = agg["benchmarks"]["math"]
    assert m["n_prompts"] == 4 and "pass@1" in m and "pass@2" in m
    assert "greedy_acc" in m and "sample_length" in m
    assert agg["benchmarks"]["more"]["n_prompts"] == 2
    lines = [json.loads(l) for l in
             open(os.path.join(out, "math", "samples.jsonl"))]
    assert len(lines) == 4
    assert all(len(l["answers"]) == 2 for l in lines)
    assert all("greedy_answer" in l for l in lines)
    # the override capped generation length for the second benchmark
    lines2 = [json.loads(l) for l in
              open(os.path.join(out, "more", "samples.jsonl"))]
    assert all(max(l["gen_lens"]) <= 4 for l in lines2)
    # idempotence: a second run without --overwrite is a no-op
    assert eval_offline.main([
        "--model-path", ckpt, "--dataset", data, "--output-dir", out,
        "--n-sampling", "2", "--allow-token-id-answers",
    ]) == 0


def test_parse_datasets_rejects_stem_collisions():
    """Two dataset paths with the same basename must not silently collide
    (ADVICE r3) — only the last would be evaluated."""
    import pytest as _pytest

    from areal_tpu.apps.eval_offline import _parse_datasets

    assert _parse_datasets(["math=a/test.jsonl", "b/test.jsonl"]) == {
        "math": "a/test.jsonl", "test": "b/test.jsonl",
    }
    with _pytest.raises(ValueError, match="duplicate benchmark name"):
        _parse_datasets(["a/test.jsonl", "b/test.jsonl"])


def test_pass_at_k_estimator_and_majority():
    from areal_tpu.apps.eval_offline import (
        majority_score,
        unbiased_pass_at_k,
    )

    # exact combinatorial identities
    assert unbiased_pass_at_k(8, 8, 1) == 1.0
    assert unbiased_pass_at_k(8, 0, 8) == 0.0
    assert abs(unbiased_pass_at_k(8, 4, 1) - 0.5) < 1e-12
    # n=4, c=2, k=2: 1 - C(2,2)/C(4,2) = 1 - 1/6
    assert abs(unbiased_pass_at_k(4, 2, 2) - (1 - 1 / 6)) < 1e-12
    # majority voting groups equivalent answers ("0.5" with "\\frac{1}{2}")
    answers = ["\\boxed{0.5}", "\\boxed{\\frac{1}{2}}", "\\boxed{3}"]
    assert majority_score(answers, [1.0, 1.0, -1.0], 3) == 1.0
    assert majority_score(["\\boxed{3}", "\\boxed{3}", "\\boxed{0.5}"],
                          [-1.0, -1.0, 1.0], 3) == 0.0


# --------------------------------------------------------------------------- #
# Codeforces ELO estimation (≈ evaluation/cf_elo_caculator.py)
# --------------------------------------------------------------------------- #


def _synthetic_contest(n=300, n_problems=3):
    """Participants with ratings 1000..1000+10(n-1); points descend with
    rating so rank order == rating order."""
    rows = [
        {
            "party": {"members": [{"handle": f"h{i}"}]},
            "points": float(2 * (n - i)),
            "penalty": 0,
        }
        for i in range(n)
    ]
    changes = [
        {"handle": f"h{i}", "oldRating": 1000 + 10 * (n - 1 - i)}
        for i in range(n)
    ]
    problems = [
        {"contestId": 1700, "index": chr(ord("A") + j), "points": 500.0 * (j + 1)}
        for j in range(n_problems)
    ]
    return (
        {"result": {"rows": rows, "problems": problems}},
        {"result": changes},
    )


def test_cf_elo_score_and_rank_math():
    from areal_tpu.apps import cf_elo

    standings, _ = _synthetic_contest()
    problems = standings["result"]["problems"]
    # solve A on 1st attempt (500), B on 2nd (1000 - 50), miss C
    status = {"1700A": [True], "1700B": [False, True], "1700C": [False, False]}
    score, penalty = cf_elo.contest_score(status, problems)
    assert score == 500.0 + 950.0 and penalty == 0.0
    # rank: rows have points 600, 598, ... -> score 1450 beats rows with
    # points < 1450
    rank = cf_elo.rank_in_standings(standings["result"]["rows"], score, penalty)
    assert rank == 0  # 2*(300-i) max is 600 < 1450; 0-based like the reference

    # expected seed is monotone decreasing in rating
    old = [1200.0] * 100
    assert cf_elo.expected_seed(1500, old) < cf_elo.expected_seed(1000, old)
    assert cf_elo.rating_for_rank(1, old, 1200) > cf_elo.rating_for_rank(
        90, old, 1200
    )


def test_cf_elo_end_to_end(tmp_path):
    import json

    from areal_tpu.apps import cf_elo

    standings, changes = _synthetic_contest()
    (tmp_path / "1700.json").write_text(
        json.dumps({"standings": standings, "rating_changes": changes})
    )
    (tmp_path / "ratings.txt").write_text(
        "\n".join(str(900 + i) for i in range(0, 3000, 10))
    )

    strong = cf_elo.calculate_cf_elo(
        {"1700A": [True], "1700B": [True], "1700C": [True]},
        str(tmp_path),
        str(tmp_path / "ratings.txt"),
    )
    weak = cf_elo.calculate_cf_elo(
        {"1700A": [False, False], "1700B": [False], "1700C": [False]},
        str(tmp_path),
        str(tmp_path / "ratings.txt"),
    )
    assert strong["n_contests"] == 1 and weak["n_contests"] == 1
    assert strong["elo"] > weak["elo"]
    assert 0.0 <= weak["percentile"] <= strong["percentile"] <= 1.0

    # unusable contests (too few participants) are skipped, not crashed
    small_s, small_c = _synthetic_contest(n=50)
    (tmp_path / "1701.json").write_text(
        json.dumps({"standings": small_s, "rating_changes": small_c})
    )
    out = cf_elo.calculate_cf_elo({"1701A": [True]}, str(tmp_path))
    assert out["n_contests"] == 0.0


def test_profile_experiment_runs():
    """≈ the reference's null/profile experiment: timed steps on synthetic
    data through the real engine, reporting step time and TFLOP/s."""
    from areal_tpu.apps.profile import run_profile
    from areal_tpu.experiments.config import ModelSpec

    spec = ModelSpec(
        arch=dict(
            n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8, hidden_dim=16,
            intermediate_dim=32, vocab_size=64, dtype="float32",
        ),
        parallel="d2f2m2",
    )
    out = run_profile(spec, [12, 9, 14, 8], n_steps=2, n_warmup=1)
    assert out["step_time_s"] > 0
    assert out["tokens_per_s"] > 0
    assert out["n_params"] > 0


def test_ray_scheduler_gated():
    """The Ray backend exists in the registry; without the ray package (not
    bundled with this image) it raises a clear, actionable error instead of
    an opaque ModuleNotFoundError deep in a worker."""
    from areal_tpu.scheduler.client import make_scheduler

    try:
        import ray  # noqa: F401
        has_ray = True
    except ImportError:
        has_ray = False
    if has_ray:
        pytest.skip("ray installed; gate untestable")
    with pytest.raises(ImportError, match="pip install 'ray"):
        make_scheduler("ray", "e", "t")
