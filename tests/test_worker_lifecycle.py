"""Worker lifecycle: death watch + heartbeats (≈ reference worker_base poll
loop + the 300 s experiment_status timeout in rollout/generation workers)."""

import os
import subprocess
import sys
import time

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.system import worker_base
from areal_tpu.system.worker_base import (
    ExperimentStatusWatch,
    Heartbeat,
    last_heartbeat,
)

EXP, TRIAL = "lifecycle-test", "t0"


class TestStatusWatch:
    def test_running_keeps_alive(self):
        worker_base.mark_experiment_running(EXP, TRIAL)
        w = ExperimentStatusWatch(EXP, TRIAL, timeout=0.1, poll_interval=0.0)
        assert w.alive()
        time.sleep(0.2)
        assert w.alive()  # status present: timeout never starts

    def test_stopped_kills_immediately(self):
        worker_base.mark_experiment_running(EXP, TRIAL)
        w = ExperimentStatusWatch(EXP, TRIAL, timeout=300, poll_interval=0.0)
        assert w.alive()
        worker_base.mark_experiment_stopped(EXP, TRIAL)
        assert not w.alive()
        assert not w.alive()  # latched

    def test_missing_key_kills_after_timeout(self):
        key = worker_base.names.experiment_status(EXP, TRIAL)
        try:
            name_resolve.delete(key)
        except name_resolve.NameEntryNotFoundError:
            pass
        w = ExperimentStatusWatch(EXP, TRIAL, timeout=0.2, poll_interval=0.0)
        assert w.alive()          # grace period
        time.sleep(0.3)
        assert not w.alive()      # launcher never appeared / died silently

    def test_heartbeat_publishes(self):
        hb = Heartbeat(EXP, TRIAL, "unit_worker", interval=0.05).start()
        time.sleep(0.15)
        hb.stop()
        t = last_heartbeat(EXP, TRIAL, "unit_worker")
        assert t is not None and abs(time.time() - t) < 5


class TestHangWatchdog:
    def test_dumps_stacks_and_live_spans_for_hung_step(self, caplog):
        import logging as logging_mod
        import threading

        from areal_tpu.base import metrics as metrics_mod
        from areal_tpu.base import tracing
        from areal_tpu.system.worker_base import HangWatchdog

        release = threading.Event()
        started = threading.Event()

        def hung_step():
            # an artificially hung "step" holding a data-plane span open —
            # the dump must attribute the hang to it
            with tracing.span("train_pipe/dispatch_hung"):
                started.set()
                release.wait(10)

        t = threading.Thread(target=hung_step, name="hung-step", daemon=True)
        t.start()
        assert started.wait(5)
        before = metrics_mod.counters.get("guard/watchdog_dumps")
        dumps = []
        wd = HangWatchdog(
            "test", timeout_s=0.15, poll_interval=0.05,
            on_dump=lambda stalled: dumps.append(stalled),
        )
        with caplog.at_level(
            logging_mod.ERROR, logger="areal_tpu.worker_base"
        ):
            wd.start()
            deadline = time.time() + 5
            while not dumps and time.time() < deadline:
                time.sleep(0.02)
            wd.stop()
        release.set()
        t.join(timeout=5)
        assert wd.dumps >= 1
        assert (
            metrics_mod.counters.get("guard/watchdog_dumps")
            >= before + wd.dumps
        )
        log = caplog.text
        assert "no heartbeat" in log and "thread stacks" in log
        assert "hung-step" in log                  # the wedged thread
        assert "train_pipe/dispatch_hung" in log   # the open span

    def test_bump_keeps_watchdog_quiet(self):
        from areal_tpu.system.worker_base import HangWatchdog

        wd = HangWatchdog("quiet", timeout_s=0.2, poll_interval=0.02)
        wd.start()
        t0 = time.time()
        while time.time() - t0 < 0.5:
            wd.bump()
            time.sleep(0.02)
        wd.stop()
        assert wd.dumps == 0


_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["AREAL_NAME_RESOLVE_ROOT"] = {root!r}
from areal_tpu.base import name_resolve
name_resolve.reconfigure(
    name_resolve.NameResolveConfig(type="file", root={root!r})
)
from areal_tpu.system.worker_base import ExperimentStatusWatch, Heartbeat

hb = Heartbeat("killtest", "t0", "child", interval=0.05).start()
watch = ExperimentStatusWatch("killtest", "t0", timeout=2.0, poll_interval=0.0)
# the worker loop: spin while the experiment lives, exit 0 when it dies
while watch.alive():
    time.sleep(0.05)
hb.stop()
sys.exit(0)
"""


@pytest.mark.slow
def test_orphaned_worker_exits_when_experiment_dies(tmp_path):
    """Kill-the-trainer scenario across real processes: the launcher-side
    status flip (here: key deletion simulating launcher death after the
    grace window / explicit stop) makes every worker exit cleanly."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = str(tmp_path / "nr")
    script = _CHILD.format(repo=repo, root=root)

    # launcher-side name_resolve over the same file backend (a direct
    # repository instance — the module default stays in-memory for the
    # other tests in this process)
    ns = name_resolve.FileNameRecordRepository(root)
    from areal_tpu.base import names

    status_key = names.experiment_status("killtest", "t0")
    ns.add(status_key, "running", replace=True)

    procs = [
        subprocess.Popen([sys.executable, "-c", script])
        for _ in range(2)
    ]
    # wait for the workers to come up (heartbeat visible launcher-side)
    hb_key = names.worker_status("killtest", "t0", "child")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            ns.get(hb_key)
            break
        except name_resolve.NameEntryNotFoundError:
            time.sleep(0.1)
    else:
        pytest.fail("no heartbeat from child workers")
    assert all(p.poll() is None for p in procs)  # workers running

    ns.add(status_key, "stopped", replace=True)  # trainer/launcher death
    for p in procs:
        assert p.wait(timeout=15) == 0           # clean, prompt exit
