"""HBM observability + pressure action (VERDICT r3 missing #2).

Counterpart of the reference's GPU memory monitoring + kill threshold
(``realhf/system/model_worker.py:1507-1610``,
``REAL_GPU_MEMORY_KILL_THRESHOLD``).
"""

import logging

import pytest

from areal_tpu.base import hbm


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


GIB = 2**30


def _dev(used, limit=16 * GIB, peak=None):
    return _FakeDevice({
        "bytes_in_use": used,
        "peak_bytes_in_use": peak if peak is not None else used,
        "bytes_limit": limit,
    })


def test_stats_normalized_and_gauges():
    mon = hbm.HBMMonitor(
        device=_dev(4 * GIB, peak=5 * GIB), warn_threshold=0.9,
        kill_threshold=1.0,
    )
    out = mon.check()
    assert out["hbm_bytes_in_use"] == 4 * GIB
    assert out["hbm_peak_bytes_in_use"] == 5 * GIB
    assert out["hbm_util"] == pytest.approx(0.25)


def test_platform_without_stats_degrades_to_live_bytes():
    class _NoStats:
        def memory_stats(self):
            raise NotImplementedError

    out = hbm.HBMMonitor(device=_NoStats()).check()
    assert set(out) == {"hbm_live_array_bytes"}  # client-side lower bound
    assert hbm.device_memory_stats(_FakeDevice({})) is None
    import jax.numpy as jnp

    x = jnp.ones((1024,), jnp.float32)
    assert hbm.live_array_bytes() >= x.nbytes


def test_kill_threshold_raises(caplog):
    mon = hbm.HBMMonitor(
        device=_dev(15 * GIB), warn_threshold=0.8, kill_threshold=0.9,
        tag="trainer",
    )
    with pytest.raises(hbm.HBMPressureError, match="trainer.*kill threshold"):
        mon.check()
    # pull paths must not raise, still report the gauge
    out = mon.check(kill=False)
    assert out["hbm_util"] > 0.9


def test_warn_logs_once_per_crossing(caplog):
    dev = _dev(15 * GIB)
    mon = hbm.HBMMonitor(device=dev, warn_threshold=0.9, kill_threshold=1.1)
    with caplog.at_level(logging.WARNING, logger="areal_tpu.hbm"):
        mon.check()
        mon.check()
    assert sum("pressure" in r.message for r in caplog.records) == 1
    # drop below, then cross again -> one more warning
    dev._stats["bytes_in_use"] = 2 * GIB
    mon.check()
    dev._stats["bytes_in_use"] = 15 * GIB
    with caplog.at_level(logging.WARNING, logger="areal_tpu.hbm"):
        mon.check()
    assert sum("pressure" in r.message for r in caplog.records) == 2


def test_env_thresholds(monkeypatch):
    monkeypatch.setenv("AREAL_HBM_KILL_THRESHOLD", "0.5")
    mon = hbm.HBMMonitor(device=_dev(9 * GIB))
    with pytest.raises(hbm.HBMPressureError):
        mon.check()


# The worker-integration half (trainer workers folding HBM gauges into
# their per-step stats) is asserted end-to-end in
# tests/test_experiment_e2e.py::test_sft_experiment on the metrics.jsonl
# the real worker writes.
