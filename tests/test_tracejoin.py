"""Distributed-tracing plane (docs/observability.md "Distributed
tracing"): tracejoin's merge/resolve/render surface against synthetic
multi-worker flushes, the ``obs --trace`` CLI, and the flagship
end-to-end check — one streamed request through the real gateway → gen
server → engine stack produces spans from three worker identities
sharing one trace id, joined back into a single tree."""

import asyncio
import json
import os

import aiohttp
import pytest

import jax

from areal_tpu.apps import obs
from areal_tpu.base import network, tracing
from areal_tpu.gateway.api import (
    ByteFallbackCodec,
    GatewayConfig,
    GatewayServer,
    serve_gateway,
)
from areal_tpu.gateway.scheduler import ContinuousBatchScheduler
from areal_tpu.gen.engine import GenerationEngine
from areal_tpu.gen.server import serve
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.system import tracejoin

# --------------------------------------------------------------------- #
# synthetic spans
# --------------------------------------------------------------------- #


def _span(
    worker, name, trace_id, span_id, parent=None, start=1000.0, dur=0.01,
    attrs=None, error=False, exc=None,
):
    s = {
        "worker": worker, "name": name, "trace_id": trace_id,
        "span_id": span_id, "parent_id": parent, "start": start,
        "dur_s": dur, "thread": "MainThread", "pid": 1, "error": error,
    }
    if attrs:
        s["attrs"] = attrs
    if exc:
        s["exc"] = exc
    return s


TID = "a" * 32
OTHER = "b" * 32


def _write_world(root):
    """Three workers' flush files, one shared trace + one unrelated."""
    d = os.path.join(root, "trace_spans")
    os.makedirs(d, exist_ok=True)
    by_worker = {
        "gateway": [
            _span("gateway", "gw/request", TID, "1" * 16, start=1000.0,
                  dur=0.5, attrs={"rid": "gw-feedbeefcafe0123"}),
        ],
        "gen_server": [
            _span("gen_server", "gen_server/generate_stream", TID,
                  "2" * 16, parent="1" * 16, start=1000.1, dur=0.3,
                  attrs={"rid": "gw-feedbeefcafe0123-c0"}),
        ],
        "rollout": [
            _span("rollout", "rollout/group", OTHER, "3" * 16,
                  start=999.0, dur=1.0, attrs={"qid": "q42"}),
            _span("rollout", "rollout/reward", OTHER, "4" * 16,
                  parent="3" * 16, start=999.5, dur=0.1,
                  attrs={"qid": "q42"}, error=True, exc="TimeoutError"),
            # parent never flushed (ring overwrite): promoted to a root
            _span("rollout", "rollout/orphan", OTHER, "5" * 16,
                  parent="f" * 16, start=999.8, dur=0.05),
        ],
    }
    for worker, spans in by_worker.items():
        with open(os.path.join(d, f"{worker}.jsonl"), "a") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
    # a torn final line (crashed worker mid-write) must be skipped
    with open(os.path.join(d, "gateway.jsonl"), "a") as f:
        f.write('{"worker": "gateway", "name": "torn', )


class TestTracejoin:
    def test_scan_merges_and_skips_torn_lines(self, tmp_path):
        _write_world(str(tmp_path))
        spans = tracejoin.scan(str(tmp_path))
        assert len(spans) == 5
        assert [s["start"] for s in spans] == sorted(
            s["start"] for s in spans
        )

    def test_resolve_trace_id(self, tmp_path):
        _write_world(str(tmp_path))
        spans = tracejoin.scan(str(tmp_path))
        assert tracejoin.resolve_trace_id(spans, TID) == TID
        assert tracejoin.resolve_trace_id(spans, TID[:12]) == TID  # prefix
        assert tracejoin.resolve_trace_id(
            spans, "gw-feedbeefcafe0123"
        ) == TID  # exact rid AND the -c0 chunk rid's base
        assert tracejoin.resolve_trace_id(spans, "q42") == OTHER  # qid
        assert tracejoin.resolve_trace_id(spans, "nope") is None
        assert tracejoin.resolve_trace_id(spans, "") is None

    def test_chrome_trace_structure(self, tmp_path):
        _write_world(str(tmp_path))
        spans = tracejoin.scan(str(tmp_path))
        doc = tracejoin.chrome_trace(spans)
        evs = doc["traceEvents"]
        procs = [e for e in evs if e["name"] == "process_name"]
        assert {p["args"]["name"] for p in procs} == {
            "gateway", "gen_server", "rollout"
        }
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == 5
        # the shared trace's events span two distinct pids
        tids = {e["pid"] for e in xs if e["args"]["trace_id"] == TID}
        assert len(tids) == 2
        err = [e for e in xs if e["name"] == "rollout/reward"][0]
        assert err["cat"] == "span,error"
        assert err["args"]["error"] is True
        assert err["args"]["exc"] == "TimeoutError"
        assert err["dur"] == pytest.approx(0.1 * 1e6)

    def test_write_chrome_trace_atomic_and_filtered(self, tmp_path):
        _write_world(str(tmp_path))
        out = tmp_path / "trace.json"
        n = tracejoin.write_chrome_trace(str(out), str(tmp_path))
        assert n == 5 and out.exists()
        assert not (tmp_path / "trace.json.tmp").exists()
        n = tracejoin.write_chrome_trace(
            str(out), str(tmp_path), trace_id=TID
        )
        assert n == 2
        doc = json.loads(out.read_text())
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["args"]["trace_id"] for e in xs} == {TID}

    def test_span_tree_and_render(self, tmp_path):
        _write_world(str(tmp_path))
        spans = tracejoin.scan(str(tmp_path))
        roots = tracejoin.span_tree(spans, OTHER)
        # reward nests under group; the orphan is promoted, not dropped
        assert [r["name"] for r in roots] == [
            "rollout/group", "rollout/orphan"
        ]
        assert [c["name"] for c in roots[0]["children"]] == [
            "rollout/reward"
        ]
        out = tracejoin.render_tree(spans, OTHER)
        assert f"trace {OTHER}" in out and "1 worker(s)" in out
        assert "ERROR(TimeoutError)" in out
        assert "qid=q42" in out
        # child indented under its parent
        group_i = out.index("rollout/group")
        reward_i = out.index("rollout/reward")
        assert reward_i > group_i

    def test_cli(self, tmp_path, capsys):
        _write_world(str(tmp_path))
        out_json = tmp_path / "merged.json"
        assert tracejoin.main(
            [str(tmp_path), "--out", str(out_json)]
        ) == 0
        assert out_json.exists()
        assert tracejoin.main([str(tmp_path), "--trace", "q42"]) == 0
        assert "rollout/group" in capsys.readouterr().out
        assert tracejoin.main([str(tmp_path), "--trace", "zzz"]) == 1


class TestObsTraceCLI:
    def test_obs_trace_renders_tree(self, tmp_path, capsys):
        _write_world(str(tmp_path))
        assert obs.main([str(tmp_path), "--trace", "q42"]) == 0
        out = capsys.readouterr().out
        assert "rollout/group" in out and "rollout/reward" in out
        assert obs.main(
            [str(tmp_path), "--trace", "gw-feedbeefcafe0123"]
        ) == 0
        assert "gw/request" in capsys.readouterr().out

    def test_obs_trace_no_match(self, tmp_path, capsys):
        os.makedirs(tmp_path / "trace_spans", exist_ok=True)
        assert obs.main([str(tmp_path), "--trace", "missing"]) == 1
        assert "no trace matches" in capsys.readouterr().err


# --------------------------------------------------------------------- #
# end-to-end: one streamed request through the real serving stack
# --------------------------------------------------------------------- #

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)

# the per-hop span names, keyed by the worker identity each would flush
# under in a real deployment (here everything shares one test process, so
# the drained ring is partitioned by name prefix before flushing)
WORKER_PREFIXES = {
    "gateway": ("gw/",),
    "gen_server": ("gen_server/", "gen_client/"),
    "gen_engine": ("gen_engine/",),
}


async def test_stream_propagates_one_trace_across_three_workers(tmp_path):
    """ISSUE acceptance: a streamed /v1/completions request yields merged
    trace JSON with spans from >=3 distinct worker identities sharing one
    trace id, and obs --trace renders the joined tree."""
    tracing.drain()
    params = tfm.init_params(CFG, jax.random.key(5))
    eng = GenerationEngine(CFG, params, max_slots=4, max_seqlen=128)
    gen_port = network.find_free_port()
    gen_runner = await serve(eng, "127.0.0.1", gen_port, decode_steps=2)
    scheduler = ContinuousBatchScheduler(
        [f"http://127.0.0.1:{gen_port}"], {}, max_queue=16,
    )
    await scheduler.start()
    gw = GatewayServer(
        scheduler, ByteFallbackCodec(CFG.vocab_size),
        GatewayConfig(max_tokens_cap=256),
    )
    gw_port = network.find_free_port()
    gw_runner = await serve_gateway(gw, "127.0.0.1", gw_port)
    try:
        async with aiohttp.ClientSession() as sess:
            resp = await sess.post(
                f"http://127.0.0.1:{gw_port}/v1/completions",
                json={
                    "prompt": [1, 2, 3], "max_tokens": 4, "stream": True,
                },
            )
            assert resp.status == 200
            rid = None
            async for raw in resp.content:
                line = raw.strip()
                if not line.startswith(b"data:"):
                    continue
                payload = line[len(b"data:"):].strip()
                if payload == b"[DONE]":
                    break
                frame = json.loads(payload)
                rid = frame["id"][len("cmpl-"):]
            assert rid and rid.startswith("gw-")
    finally:
        await scheduler.stop()
        await gw_runner.cleanup()
        await gen_runner.cleanup()

    spans = tracing.drain()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    gw_req = [
        s for s in by_name.get("gw/request", [])
        if (s.get("attrs") or {}).get("rid") == rid
    ]
    assert gw_req, sorted(by_name)
    tid = gw_req[0]["trace_id"]
    # every serving hop joined THIS trace
    for name in (
        "gw/dispatch", "gen_server/generate_stream", "gen_engine/submit"
    ):
        assert any(
            s["trace_id"] == tid for s in by_name.get(name, [])
        ), (name, sorted(by_name))
    # parenting: dispatch under request, server stream under dispatch
    dispatch = next(
        s for s in by_name["gw/dispatch"] if s["trace_id"] == tid
    )
    assert dispatch["parent_id"] == gw_req[0]["span_id"]
    server_stream = next(
        s for s in by_name["gen_server/generate_stream"]
        if s["trace_id"] == tid
    )
    assert server_stream["parent_id"] == dispatch["span_id"]

    # flush the ring partitioned into the three worker identities the
    # spans would have come from in a real (multi-process) deployment
    d = tmp_path / "trace_spans"
    d.mkdir()
    for worker, prefixes in WORKER_PREFIXES.items():
        mine = [
            s for s in spans if s["name"].startswith(prefixes)
        ]
        assert mine, worker
        with open(d / f"{worker}.jsonl", "w") as f:
            for s in mine:
                f.write(json.dumps({"worker": worker, **s}) + "\n")

    merged = tracejoin.scan(str(tmp_path))
    assert tracejoin.resolve_trace_id(merged, rid) == tid
    doc = tracejoin.chrome_trace(tracejoin.trace_spans(merged, tid))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len({e["pid"] for e in xs}) >= 3  # >=3 distinct processes
    assert {e["args"]["trace_id"] for e in xs} == {tid}

    tree = obs.render_trace(str(tmp_path), rid)
    assert tree is not None
    assert f"trace {tid}" in tree
    assert "3 worker(s)" in tree
    assert "gw/request" in tree and "gen_server/generate_stream" in tree
