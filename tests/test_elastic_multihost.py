"""Elastic multihost: the REAL N-process worlds (slow).

The acceptance proofs of docs/fault_tolerance.md "Elastic multihost",
driven through the chaos harness (`tools/chaos.py`) on the same CPU
fault world as tests/test_multihost.py:

- kill one rank mid-step on the 4-process world -> detection,
  surviving-rank rollback, relaunch, rejoin — the loss trajectory equals
  an unfaulted single-process run from the same committed checkpoint,
  the gen side keeps answering throughout, and ft/rank_restarts == 1;
- a rank *hang* (not exit) is detected by the collective-timeout
  watchdog and recovered the same way;
- a rank that calls `multihost.barrier` with a dead/wedged peer raises
  the bounded-timeout error within the configured deadline (2-process
  world), instead of hanging;
- the randomized-but-seeded multi-fault soak holds every end-state
  invariant (`make chaos` runs the shorter CI flavor of the same
  harness).
"""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import multihost_world_lock
from tools import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cfg: chaos.ChaosConfig) -> dict:
    with multihost_world_lock():
        return chaos.run_scenario(cfg)


@pytest.mark.slow
def test_kill_rank_mid_step_recovers_surgically(tmp_path):
    report = _run(chaos.ChaosConfig(
        seed=101,
        schedule=[{"kind": "kill", "rank": 2, "epoch": 0, "step": 2}],
        num_ranks=4, steps=8, ckpt_every=3,
        collective_timeout_s=30.0,
        with_gen=True,
        root=str(tmp_path),
    ))
    assert report["ok"], report["violations"]
    # exactly ONE rank relaunch and ONE world epoch for one kill
    assert report["rank_restarts"] == 1
    assert report["world_epochs"] == 1
    assert report["counters"]["ft/rank_restarts"] == 1
    # every rank rejoined and reached the final step (loss continuity vs
    # the unfaulted baseline is asserted inside the harness invariants)
    assert report["ranks_reported"] == [0, 1, 2, 3]
    # the serving side never stopped answering and leaked nothing
    gen = report["gen"]
    assert gen["ok"] >= 1 and gen["failed"] == 0
    assert gen["slots_running"] == 0 and gen["pages_leaked"] == 0
    assert not gen["version_regressed"]


@pytest.mark.slow
def test_hang_rank_detected_by_collective_watchdog(tmp_path):
    """A rank that wedges WITHOUT exiting is invisible to process-level
    supervision; only the bounded-collective watchdog surfaces it."""
    report = _run(chaos.ChaosConfig(
        seed=102,
        schedule=[{"kind": "hang", "rank": 1, "epoch": 0, "step": 3}],
        num_ranks=4, steps=8, ckpt_every=3,
        collective_timeout_s=25.0,
        with_gen=False,
        root=str(tmp_path),
    ))
    assert report["ok"], report["violations"]
    assert report["rank_restarts"] == 1
    assert report["world_epochs"] == 1
    # detection cannot be faster than the collective timeout, and must be
    # bounded well under the harness recovery bound
    assert report["recovery_times_s"][0] < 240.0


@pytest.mark.slow
def test_collective_timeout_raises_within_deadline(tmp_path):
    """Satellite contract: `multihost.barrier` with a wedged peer raises
    CollectiveTimeoutError within the configured deadline on the
    2-process world — not a hang, not a crash."""
    from areal_tpu.base import name_resolve, network
    from areal_tpu.parallel import elastic

    nr_root = str(tmp_path / "nr")
    timeout_s = 6.0
    prev = name_resolve.default_repository()
    name_resolve.set_repository(
        name_resolve.make_repository(
            name_resolve.NameResolveConfig(type="file", root=nr_root)
        )
    )
    try:
        port = network.find_free_port()
        elastic.host_service(port, 2)
        elastic.write_world(
            "etimeout", "t0",
            elastic.WorldState(0, f"127.0.0.1:{port}", 2),
        )
    finally:
        name_resolve.set_repository(prev)

    script = os.path.join(os.path.dirname(__file__),
                          "elastic_timeout_script.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    out0 = str(tmp_path / "r0.json")
    with multihost_world_lock():
        procs = [
            subprocess.Popen(
                [sys.executable, script, "--rank", str(r),
                 "--nr-root", nr_root, "--timeout-s", str(timeout_s),
                 "--out", str(tmp_path / f"r{r}.json")],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            for r in range(2)
        ]
        try:
            log0 = procs[0].communicate(timeout=180)[0].decode()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    assert procs[0].returncode == 0, log0[-3000:]
    with open(out0) as f:
        outcome = json.load(f)
    assert outcome["raised"] == "CollectiveTimeoutError", outcome
    # raised near the deadline: after it, but not hanging far past it
    assert timeout_s <= outcome["elapsed_s"] < timeout_s + 30.0, outcome
    assert outcome["timeouts_counted"] >= 1


@pytest.mark.slow
def test_chaos_soak_seeded_multi_fault(tmp_path):
    """The long(er) soak `make chaos` is the short flavor of: a seeded
    hang + kill across consecutive world epochs, every end-state
    invariant asserted."""
    report = _run(chaos.ChaosConfig(
        seed=8, n_faults=2,
        num_ranks=4, steps=10, ckpt_every=3,
        collective_timeout_s=25.0,
        with_gen=True,
        root=str(tmp_path),
    ))
    assert report["ok"], report["violations"]
    assert report["rank_restarts"] == len(report["schedule"]) == 2
    assert report["world_epochs"] == 2
