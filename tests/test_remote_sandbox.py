"""Remote reward-sandbox client (VERDICT r3 missing #3): batch fan-out
against a real aiohttp mock server with injected failures, timeouts, and
system errors — semantics ≈ ``functioncall/base/call.py``.
"""

import asyncio
import json
import threading

import pytest

from areal_tpu.rewards import remote

aiohttp = pytest.importorskip("aiohttp")
from aiohttp import web  # noqa: E402


class MockSandbox:
    """Scriptable verifier: behavior keyed by payload['mode'].

    - ok: 200 success
    - flaky: fail with HTTP 500 until the Nth attempt for that uid
    - hang: sleep past the client timeout
    - syserr: SystemError result on first attempt, success after
    - reject: always HTTP 400
    """

    def __init__(self):
        self.attempts = {}
        self.in_flight = 0
        self.max_in_flight = 0
        self._lock = asyncio.Lock()

    async def handle(self, request: web.Request) -> web.Response:
        d = await request.json()
        uid, mode = d.get("uid", ""), d.get("mode", "ok")
        async with self._lock:
            self.attempts[uid] = self.attempts.get(uid, 0) + 1
            self.in_flight += 1
            self.max_in_flight = max(self.max_in_flight, self.in_flight)
        try:
            await asyncio.sleep(0.01)
            n = self.attempts[uid]
            if mode == "hang":
                await asyncio.sleep(5.0)
            if mode == "flaky" and n < 2:
                return web.Response(status=500, text="transient")
            if mode == "reject":
                return web.Response(status=400, text="bad payload")
            if mode == "syserr" and n < 2:
                return web.json_response({
                    "uid": uid, "success": True,
                    "results": [{"success": False, "errorType": "SystemError"}],
                })
            return web.json_response({
                "uid": uid, "success": True,
                "results": [{"success": True}],
            })
        finally:
            async with self._lock:
                self.in_flight -= 1


@pytest.fixture()
def sandbox(event_loop_or_none=None):
    box = MockSandbox()
    app = web.Application()
    app.router.add_post("/{task}_verify", box.handle)
    loop = asyncio.new_event_loop()
    runner = web.AppRunner(app)

    async def _start():
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner.addresses[0][1]

    port_holder = {}
    ready = threading.Event()

    def _run():
        asyncio.set_event_loop(loop)
        try:
            port_holder["port"] = loop.run_until_complete(_start())
        except Exception as e:  # noqa: BLE001 — surfaced to the main thread
            port_holder["error"] = e
            ready.set()
            return
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    assert ready.wait(timeout=10), "mock sandbox server did not start"
    if "error" in port_holder:
        raise port_holder["error"]
    box.url = f"http://127.0.0.1:{port_holder['port']}/test_verify"
    yield box
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)


def _run_batch(payloads, url, **kw):
    return asyncio.run(
        remote.batch_function_call_async(payloads, url, **kw)
    )


def test_hundred_call_batch_with_injected_faults(sandbox):
    """100 calls: 80 ok, 10 flaky (retry succeeds), 5 hang (timeout ->
    failure shape), 5 syserr (retried to success). Order preserved, no
    exceptions escape, concurrency cap respected."""
    modes = ["ok"] * 80 + ["flaky"] * 10 + ["hang"] * 5 + ["syserr"] * 5
    payloads = [
        {"uid": f"u{i}", "mode": m, "code": "x"} for i, m in enumerate(modes)
    ]
    out = _run_batch(
        payloads, sandbox.url, timeout=1.0, concurrency=16,
        max_retries=3, initial_retry_interval=0.01,
    )
    assert len(out) == 100
    by_uid = {r["uid"]: r for r in out}
    assert [r["uid"] for r in out] == [p["uid"] for p in payloads]  # order
    for i, m in enumerate(modes):
        r = by_uid[f"u{i}"]
        if m == "hang":
            assert not r["success"]
            assert "timed out" in r["results"][0]["reason"]
        else:
            assert r["success"], (m, r)
    # flaky + syserr really were retried
    assert all(sandbox.attempts[f"u{i}"] == 2 for i in range(80, 90))
    assert all(sandbox.attempts[f"u{i}"] == 2 for i in range(95, 100))
    # hangs are NOT retried (budget already spent, call.py:117-131)
    assert all(sandbox.attempts[f"u{i}"] == 1 for i in range(90, 95))
    assert sandbox.max_in_flight <= 16


def test_retries_exhausted_and_payload_validation(sandbox):
    payloads = [
        {"uid": "r0", "mode": "reject"},      # always 400 -> retries exhausted
        {},                                    # empty payload
        {"uid": "c0", "code": "", "mode": "ok"},  # empty code
    ]
    out = _run_batch(
        payloads, sandbox.url, timeout=1.0, concurrency=4,
        max_retries=2, initial_retry_interval=0.01,
    )
    assert not out[0]["success"]
    assert "max retries" in out[0]["results"][0]["reason"]
    assert sandbox.attempts["r0"] == 2
    assert not out[1]["success"] and "Empty payload" in out[1]["results"][0]["reason"]
    assert not out[2]["success"] and "Empty code" in out[2]["results"][0]["reason"]
    # invalid payloads never reach the server
    assert "c0" not in sandbox.attempts


def test_default_concurrency_env(monkeypatch):
    monkeypatch.setenv("AREAL_FUNCTIONCALL_CONCURRENCY", "7")
    assert remote.default_concurrency() == 7
    monkeypatch.delenv("AREAL_FUNCTIONCALL_CONCURRENCY")
    monkeypatch.setenv("AREAL_FUNCTIONCALL_DP", "100")
    assert remote.default_concurrency() == 50


def test_math_code_wrappers_hit_domain(sandbox, monkeypatch):
    base = sandbox.url.rsplit("/", 1)[0]
    monkeypatch.setenv("AREAL_FUNCTIONCALL_SERVICE_DOMAIN", base)

    async def go():
        ok = await remote.math_verify_remote(
            ["42"], [["42"]], ["q1"]
        )
        ok2 = await remote.code_verify_remote(["print(1)"], ["q2"])
        return ok, ok2

    ok, ok2 = asyncio.run(go())
    assert ok == [True] and ok2 == [True]
    assert sandbox.attempts == {"q1": 1, "q2": 1}
