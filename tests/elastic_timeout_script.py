"""Subprocess body for the collective-timeout semantics test
(tests/test_elastic_multihost.py): a 2-process world where rank 1 wedges
BEFORE entering the barrier, and rank 0's `multihost.barrier` must raise
`CollectiveTimeoutError` within the configured deadline instead of
hanging forever.

Run as one rank:
    python elastic_timeout_script.py --rank 0 --nr-root /tmp/x \
        --timeout-s 5 --out r0.json
"""

import argparse
import json
import os
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nr-root", required=True)
    ap.add_argument("--timeout-s", type=float, default=5.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from areal_tpu.base import name_resolve
    from areal_tpu.parallel import elastic, multihost

    multihost.enable_cpu_collectives()
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(type="file", root=args.nr_root)
    )
    mgr = elastic.WorldEpochManager(
        elastic.ElasticConfig(
            experiment_name="etimeout", trial_name="t0",
            num_processes=2, process_id=args.rank,
            collective_timeout_s=args.timeout_s,
        )
    )
    mgr.join()

    # one successful warm-up barrier proves the guarded path works at all
    multihost.barrier("warmup")

    if args.rank == 1:
        time.sleep(600)  # wedged in "user code", never reaches the barrier

    t0 = time.monotonic()
    try:
        multihost.barrier("dead_peer")
        outcome = {"raised": None, "elapsed_s": time.monotonic() - t0}
    except elastic.CollectiveTimeoutError as e:
        outcome = {
            "raised": "CollectiveTimeoutError",
            "message": str(e)[:200],
            "elapsed_s": time.monotonic() - t0,
            "timeouts_counted": mgr.guard.timeouts,
        }
    except Exception as e:  # noqa: BLE001 — recorded for the test to judge
        outcome = {
            "raised": type(e).__name__,
            "message": str(e)[:200],
            "elapsed_s": time.monotonic() - t0,
        }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(outcome, f)
    mgr.stop()
    elastic.hard_exit(0)


if __name__ == "__main__":
    sys.exit(main())
