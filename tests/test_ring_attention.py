"""Ring attention (context parallelism): parity vs the single-device flash
path — forward AND gradients — on an 8-virtual-device mesh.

The long-context bar (SURVEY §2.2 "SP" / brief: "ring attention or
all-to-all sequence parallelism"): per-device attention memory scales with
T/cp while results match the unsharded computation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from areal_tpu.ops import attention as attn_ops
from areal_tpu.ops.ring_attention import ring_attention


def _ctx_mesh(cp):
    devs = np.asarray(jax.devices()[:cp])
    return Mesh(devs.reshape(cp), ("ctx",))


def _packed_inputs(rng, T, H, Hkv, D, seqlens):
    assert sum(seqlens) <= T
    seg = np.zeros(T, np.int32)
    pos = 0
    for i, n in enumerate(seqlens):
        seg[pos : pos + n] = i + 1
        pos += n
    q = rng.normal(size=(T, H, D)).astype(np.float32)
    k = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    v = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(seg)


def _reference(q, k, v, seg, **kw):
    # the packed dense/XLA path is the numerics oracle
    kw.setdefault("softmax_scale", q.shape[-1] ** -0.5)
    return attn_ops._attention_xla(q, k, v, seg, **kw)


@pytest.mark.parametrize("cp", [2, 4, 8])
def test_forward_parity(cp, rng):
    T, H, Hkv, D = 256, 4, 2, 16
    q, k, v, seg = _packed_inputs(rng, T, H, Hkv, D, [100, 60, 40])
    mesh = _ctx_mesh(cp)
    out = ring_attention(q, k, v, seg, mesh, block_k=32)
    ref = _reference(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_parity_softcap_window(rng):
    T, H, Hkv, D = 256, 4, 2, 16
    q, k, v, seg = _packed_inputs(rng, T, H, Hkv, D, [120, 90])
    mesh = _ctx_mesh(4)
    out = ring_attention(
        q, k, v, seg, mesh, soft_cap=8.0, sliding_window=48, block_k=64
    )
    ref = _reference(q, k, v, seg, soft_cap=8.0, sliding_window=48)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pad_rows_zero(rng):
    T, H, Hkv, D = 128, 4, 2, 16
    q, k, v, seg = _packed_inputs(rng, T, H, Hkv, D, [50])  # 78 pad tokens
    mesh = _ctx_mesh(4)
    out = np.asarray(ring_attention(q, k, v, seg, mesh, block_k=32))
    assert np.all(out[50:] == 0)


@pytest.mark.parametrize("cp", [2, 8])
def test_gradient_parity(cp, rng):
    """The backward ring (autodiff through ppermute) matches unsharded
    gradients for q, k, and v."""
    T, H, Hkv, D = 128, 4, 2, 8
    q, k, v, seg = _packed_inputs(rng, T, H, Hkv, D, [70, 33])
    mesh = _ctx_mesh(cp)
    tgt = jnp.asarray(rng.normal(size=(T, H, D)).astype(np.float32))

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, seg, mesh, block_k=32)
        return jnp.sum((o - tgt) ** 2)

    def loss_ref(q, k, v):
        o = _reference(q, k, v, seg)
        return jnp.sum((o - tgt) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, err_msg=f"d{name}"
        )


def test_under_jit_with_sharded_inputs(rng):
    """ring_attention composes with jit + GSPMD-sharded operands (the way
    the train engine calls it)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    T, H, Hkv, D = 256, 4, 2, 16
    q, k, v, seg = _packed_inputs(rng, T, H, Hkv, D, [200])
    mesh = _ctx_mesh(4)
    sh = NamedSharding(mesh, P("ctx"))
    q = jax.device_put(q, NamedSharding(mesh, P("ctx", None, None)))

    @jax.jit
    def f(q, k, v, seg):
        return ring_attention(q, k, v, seg, mesh, block_k=64)

    out = f(q, k, v, jax.device_put(seg, sh))
    ref = _reference(jax.device_put(q), k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestContextParallelTraining:
    """Full train step with the token axis ring-sharded: a d1f1c4m2 mesh
    reaches the same losses as d2f2m2 on the same global batch."""

    def _train(self, parallel, rng_seed=0, steps=4):
        from areal_tpu.api.data import MicroBatchSpec, SequenceSample
        from areal_tpu.api.model import make_interface
        from areal_tpu.models.config import ModelConfig
        from areal_tpu.ops import attention as attn_ops
        from areal_tpu.parallel.mesh import ParallelConfig
        from areal_tpu.train.engine import OptimizerConfig, TrainEngine

        cfg = ModelConfig(
            n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
            intermediate_dim=64, vocab_size=128, dtype="float32",
        )
        rng = np.random.default_rng(rng_seed)
        lens = [int(x) for x in rng.integers(10, 30, size=6)]
        sample = SequenceSample.from_default(
            ids=list(range(6)), seqlens=lens,
            data={
                "packed_input_ids": rng.integers(0, 128, sum(lens)).astype(np.int64),
                "prompt_mask": np.concatenate(
                    [np.r_[np.ones(2, bool), np.zeros(n - 2, bool)] for n in lens]
                ),
            },
        )
        try:
            eng = TrainEngine(
                cfg, ParallelConfig.from_str(parallel),
                OptimizerConfig(lr=1e-3),
            )
            eng.init_random(0)
            eng.setup_optimizer(total_train_steps=20)
            sft = make_interface("sft")
            return [
                sft.train_step(eng, sample, MicroBatchSpec())["loss"]
                for _ in range(steps)
            ]
        finally:
            attn_ops.clear_context_parallel()

    @pytest.mark.slow
    def test_ctx_parallel_matches_data_parallel(self):
        ring = self._train("d1f1c4m2")
        base = self._train("d2f2m2")
        for a, b in zip(ring, base):
            assert a == pytest.approx(b, rel=2e-4)

    def test_from_str_parses_ctx(self):
        from areal_tpu.parallel.mesh import ParallelConfig

        p = ParallelConfig.from_str("d2f2c2m1")
        assert (p.data, p.fsdp, p.ctx, p.model) == (2, 2, 2, 1)
        assert p.world_size == 8
        assert ParallelConfig.from_str("d2m2").ctx == 1


def test_ring_preserves_data_and_model_sharding(rng):
    """Review regression: under vmap with spmd_axis_name, the ring must not
    all-gather rows/heads — the output keeps the data-axis sharding and the
    compiled program contains zero all-gathers."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "ctx", "model"))
    R, T, H, Hkv, D = 2, 64, 4, 2, 8
    q = jax.device_put(
        jnp.asarray(rng.normal(size=(R, T, H, D)).astype(np.float32)),
        NamedSharding(mesh, P("data", "ctx", None, None)),
    )
    k = jax.device_put(
        jnp.asarray(rng.normal(size=(R, T, Hkv, D)).astype(np.float32)),
        NamedSharding(mesh, P("data", "ctx", None, None)),
    )
    seg = jax.device_put(
        jnp.asarray(np.ones((R, T), np.int32)),
        NamedSharding(mesh, P("data", "ctx")),
    )

    f = jax.jit(jax.vmap(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh, block_k=32),
        spmd_axis_name="data",
    ))
    out = f(q, k, k, seg)
    assert out.sharding.spec[0] == "data", out.sharding.spec
    hlo = f.lower(q, k, k, seg).compile().as_text()
    assert "all-gather" not in hlo
