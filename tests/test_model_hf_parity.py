"""Forward parity vs HuggingFace transformers on CPU.

Counterpart of the reference's ``tests/model/test_cpu_inference.py`` (ReaLModel
vs HF logits parity): build a tiny random HF model per family, convert its
state dict through ``areal_tpu.models.hf``, and compare packed-forward logits
token-for-token. Also checks prefill+decode against the packed forward.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from areal_tpu.models import hf as hf_conv
from areal_tpu.models import transformer as tfm

TINY = dict(
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    vocab_size=128,
    max_position_embeddings=128,
)


def _hf_model(family):
    import torch
    import transformers

    torch.manual_seed(0)
    if family == "llama":
        cfg = transformers.LlamaConfig(**TINY, rope_theta=10000.0)
        model = transformers.LlamaForCausalLM(cfg)
    elif family == "mistral":
        cfg = transformers.MistralConfig(**TINY, sliding_window=None)
        model = transformers.MistralForCausalLM(cfg)
    elif family == "qwen2":
        cfg = transformers.Qwen2Config(**TINY)
        model = transformers.Qwen2ForCausalLM(cfg)
    elif family == "qwen3":
        cfg = transformers.Qwen3Config(**TINY, head_dim=8)
        model = transformers.Qwen3ForCausalLM(cfg)
    elif family == "gemma":
        cfg = transformers.GemmaConfig(**TINY, head_dim=8, hidden_act="gelu_pytorch_tanh")
        model = transformers.GemmaForCausalLM(cfg)
    elif family == "mixtral":
        cfg = transformers.MixtralConfig(
            **TINY, num_local_experts=4, num_experts_per_tok=2
        )
        model = transformers.MixtralForCausalLM(cfg)
    elif family == "gpt2":
        cfg = transformers.GPT2Config(
            n_embd=32, n_layer=2, n_head=4, vocab_size=128, n_positions=128
        )
        model = transformers.GPT2LMHeadModel(cfg)
    else:
        raise ValueError(family)
    model.eval()
    return cfg, model


def _convert(family, hf_cfg, model):
    import dataclasses

    fam = hf_conv.HF_FAMILIES[family]
    cfg = fam.config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype="float32")
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    params = fam.params_from_hf(sd, cfg)
    return cfg, params


def _hf_logits(model, seqs):
    import torch

    outs = []
    with torch.no_grad():
        for s in seqs:
            ids = torch.tensor([s], dtype=torch.long)
            outs.append(model(ids).logits[0].float().numpy())
    return np.concatenate(outs, axis=0)


def _pack(seqs, pad_to=None):
    total = sum(len(s) for s in seqs)
    t = pad_to or total
    input_ids = np.zeros(t, np.int32)
    segment_ids = np.zeros(t, np.int32)
    positions = np.zeros(t, np.int32)
    off = 0
    for i, s in enumerate(seqs):
        input_ids[off : off + len(s)] = s
        segment_ids[off : off + len(s)] = i + 1
        positions[off : off + len(s)] = np.arange(len(s))
        off += len(s)
    return input_ids, segment_ids, positions


FAMILIES = ["llama", "mistral", "qwen2", "qwen3", "gemma", "gpt2", "mixtral"]


@pytest.mark.parametrize("family", FAMILIES)
def test_packed_forward_matches_hf(family, rng):
    hf_cfg, model = _hf_model(family)
    cfg, params = _convert(family, hf_cfg, model)
    seqs = [list(rng.integers(0, 128, size=n)) for n in (5, 9)]
    ref = _hf_logits(model, seqs)

    input_ids, segment_ids, positions = _pack(seqs, pad_to=16)
    out = tfm.forward_packed(
        params, cfg, jnp.asarray(input_ids), jnp.asarray(segment_ids),
        jnp.asarray(positions), remat=False,
    )
    got = np.asarray(out)[: ref.shape[0]]
    np.testing.assert_allclose(got, ref, atol=3e-3, rtol=2e-2)


@pytest.mark.parametrize("family", ["qwen2"])
def test_roundtrip_to_hf(family, rng):
    hf_cfg, model = _hf_model(family)
    cfg, params = _convert(family, hf_cfg, model)
    fam = hf_conv.HF_FAMILIES[family]
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    back = fam.params_to_hf(params, cfg)
    for k, v in back.items():
        np.testing.assert_array_equal(v, sd[k], err_msg=k)
    # config roundtrip preserves the fields we model
    cfg2 = fam.config_from_hf(fam.config_to_hf(cfg))
    assert cfg2.n_layers == cfg.n_layers
    assert cfg2.n_kv_heads == cfg.n_kv_heads
    assert cfg2.use_attention_bias == cfg.use_attention_bias


def test_disk_roundtrip_preserves_weights(rng, tmp_path):
    """Regression: safetensors writes raw buffers, so transposed views must
    be made contiguous before saving — otherwise disk silently holds
    transposed garbage that is self-consistent on reload but wrong."""
    hf_cfg, model = _hf_model("qwen2")
    cfg, params = _convert("qwen2", hf_cfg, model)
    path = str(tmp_path / "export")
    hf_conv.save_hf_checkpoint(params, cfg, "qwen2", path)
    cfg2, params2 = hf_conv.load_hf_checkpoint(path)
    import jax

    flat1 = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    flat2 = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_flatten_with_path(params2)[0]
    }
    assert flat1.keys() == flat2.keys()
    for k in flat1:
        np.testing.assert_array_equal(
            np.asarray(flat1[k]), np.asarray(flat2[k]), err_msg=k
        )


def test_prefill_decode_matches_packed(rng):
    hf_cfg, model = _hf_model("qwen2")
    cfg, params = _convert("qwen2", hf_cfg, model)
    prompt_lens = np.array([4, 6], np.int32)
    prompts = np.zeros((2, 6), np.int32)
    full = []
    for i, n in enumerate(prompt_lens):
        s = rng.integers(0, 128, size=n + 3)  # prompt + 3 continuation tokens
        prompts[i, :n] = s[:n]
        full.append(list(s))

    # Reference: packed forward over the full sequences.
    input_ids, segment_ids, positions = _pack(full)
    ref = np.asarray(
        tfm.forward_packed(
            params, cfg, jnp.asarray(input_ids), jnp.asarray(segment_ids),
            jnp.asarray(positions), remat=False,
        )
    )
    ref_rows = []
    off = 0
    for i, n in enumerate(prompt_lens):
        L = len(full[i])
        ref_rows.append(ref[off + n - 1 : off + L])  # logits from prompt end on
        off += L

    cache = tfm.KVCache.empty(cfg, batch=2, capacity=16)
    logits, cache = tfm.prefill(
        params, cfg, cache, jnp.asarray(prompts), jnp.asarray(prompt_lens)
    )
    got = [[np.asarray(logits)[i]] for i in range(2)]
    for step in range(3):
        toks = jnp.asarray(
            [full[i][prompt_lens[i] + step] for i in range(2)], jnp.int32
        )
        logits, cache = tfm.decode_step(params, cfg, cache, toks)
        for i in range(2):
            got[i].append(np.asarray(logits)[i])
    for i in range(2):
        np.testing.assert_allclose(
            np.stack(got[i][:-1]), ref_rows[i][:-1], atol=3e-3, rtol=2e-2
        )


def test_critic_head_shape(rng):
    import dataclasses
    import jax

    cfg = tfm.ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, is_critic=True, dtype="float32",
    )
    params = tfm.init_params(cfg, jax.random.key(0))
    ids, segs, pos = _pack([[1, 2, 3], [4, 5]], pad_to=8)
    out = tfm.forward_packed(
        params, cfg, jnp.asarray(ids), jnp.asarray(segs), jnp.asarray(pos)
    )
    assert out.shape == (8, 1)


def test_prefill_flash_path_matches_dense(rng):
    """The flattened varlen-flash prefill (the 32k-capable path used on TPU)
    must match the dense-mask prefill: same last-token logits, same cache."""
    import dataclasses

    import jax

    base = tfm.ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=16, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, dtype="float32",
        use_flash_attention=False,
    )
    params = tfm.init_params(base, jax.random.key(3))
    B, S = 2, 256
    prompt_lens = np.array([200, 256], np.int32)
    prompts = np.zeros((B, S), np.int32)
    for i, n in enumerate(prompt_lens):
        prompts[i, :n] = rng.integers(1, 128, size=n)

    outs = {}
    for flash in (False, True):
        cfg = dataclasses.replace(base, use_flash_attention=flash)
        cache = tfm.KVCache.empty(cfg, batch=B, capacity=S)
        logits, cache = tfm.prefill(
            params, cfg, cache, jnp.asarray(prompts), jnp.asarray(prompt_lens)
        )
        outs[flash] = (np.asarray(logits), np.asarray(cache.k),
                       np.asarray(cache.v))
    np.testing.assert_allclose(outs[True][0], outs[False][0], atol=2e-4,
                               rtol=2e-4)
    np.testing.assert_allclose(outs[True][1], outs[False][1], atol=2e-5,
                               rtol=2e-5)
    np.testing.assert_allclose(outs[True][2], outs[False][2], atol=2e-5,
                               rtol=2e-5)
