"""MoE dispatch parity: ragged (grouped-GEMM) vs dense.

The dense path is parity-tested against HF Mixtral in
``test_model_hf_parity.py``; here the ``lax.ragged_dot`` dispatch must match
the dense formulation in forward outputs, aux loss, and parameter gradients,
including under a sharded mesh. Counterpart of the reference's token
dispatcher tests (``realhf/impl/model/modules/moe/token_dispatcher.py``).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig, MoEConfig
from areal_tpu.ops import moe as moe_ops


def _cfg(dispatch, top_k=2, aux=0.01, z=0.001):
    return ModelConfig(
        n_layers=1,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=8,
        hidden_dim=16,
        intermediate_dim=32,
        vocab_size=64,
        mlp_type="moe",
        activation_function="silu",
        moe=MoEConfig(
            num_experts=4,
            top_k=top_k,
            aux_loss_coeff=aux,
            z_loss_coeff=z,
            dispatch=dispatch,
        ),
    )


def _params(rng, E=16, F=32, X=4):
    k = iter(jax.random.split(rng, 4))
    w = lambda shape: jax.random.normal(next(k), shape, jnp.float32) * 0.1
    return {
        "router": w((E, X)),
        "w_gate": w((X, E, F)),
        "w_up": w((X, E, F)),
        "w_down": w((X, F, E)),
    }


@pytest.mark.parametrize("top_k", [1, 2, 3])
def test_ragged_matches_dense_forward(top_k):
    p = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 17, 16), jnp.float32)
    out_d, aux_d = moe_ops.moe_mlp(_cfg("dense", top_k=top_k), p, x)
    out_r, aux_r = moe_ops.moe_mlp(_cfg("ragged", top_k=top_k), p, x)
    np.testing.assert_allclose(out_r, out_d, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(aux_r, aux_d, rtol=2e-5, atol=2e-6)


def test_ragged_matches_dense_grads():
    """Differentiated through a singleton vmap: the framework always
    differentiates the ragged path under vmap (see ops/moe.py docstring —
    un-vmapped reverse-mode AD is a known custom_vmap limitation)."""
    p = _params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 29, 16), jnp.float32)

    def loss(params, dispatch):
        out, aux = jax.vmap(
            lambda row: moe_ops.moe_mlp(_cfg(dispatch), params, row)
        )(x)
        return jnp.sum(out**2) + jnp.mean(aux)

    g_d = jax.grad(loss)(p, "dense")
    g_r = jax.grad(loss)(p, "ragged")
    for key in p:
        np.testing.assert_allclose(
            g_r[key], g_d[key], rtol=5e-4, atol=5e-5, err_msg=key
        )


def test_ragged_matches_dense_grads_under_vmap():
    """The train engine differentiates through vmap-over-rows; the ragged
    custom_vmap fold must produce the same parameter gradients as dense."""
    p = _params(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 13, 16), jnp.float32)

    # aux coeffs zeroed: under vmap the ragged fold computes one global aux
    # over all rows while dense averages per-row auxes — an intentionally
    # different (whole-batch) estimator; the main path must match exactly.
    def loss(params, dispatch):
        out, aux = jax.vmap(
            lambda row: moe_ops.moe_mlp(
                _cfg(dispatch, aux=0.0, z=0.0), params, row
            )
        )(x)
        return jnp.sum(out**2) + jnp.mean(aux)

    g_d = jax.jit(jax.grad(loss), static_argnums=1)(p, "dense")
    g_r = jax.jit(jax.grad(loss), static_argnums=1)(p, "ragged")
    for key in p:
        np.testing.assert_allclose(
            g_r[key], g_d[key], rtol=5e-4, atol=5e-4, err_msg=key
        )


def test_ragged_jits_and_runs_on_mesh():
    """The grouped-GEMM path must jit (static shapes) and execute under the
    8-device test mesh with data-sharded inputs and replicated experts."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("data", "model"))
    p = _params(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 16, 16), jnp.float32)
    cfg = _cfg("ragged")
    with mesh:
        xs = jax.device_put(x, NamedSharding(mesh, P("data", None, None)))
        out, aux = jax.jit(lambda pp, xx: moe_ops.moe_mlp(cfg, pp, xx))(p, xs)
    ref, _ = moe_ops.moe_mlp(_cfg("dense"), p, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_dispatch_is_a_config_switch():
    cfg = _cfg("dense")
    assert dataclasses.replace(
        cfg, moe=dataclasses.replace(cfg.moe, dispatch="ragged")
    ).moe.dispatch == "ragged"


def test_bad_dispatch_value_rejected():
    p = _params(jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9), (5, 16), jnp.float32)
    with pytest.raises(ValueError, match="dispatch"):
        moe_ops.moe_mlp(_cfg("megablox"), p, x)
