"""Scripted fault-tolerance scenarios (docs/fault_tolerance.md).

Acceptance scenarios for the fleet health & fault-tolerance subsystem:

(a) killing one gen server mid-run loses zero samples — its rollouts
    requeue and the run completes,
(b) a weight update with one dead server still bumps surviving servers to
    the new version and evicts the dead one,
(c) an evicted server is re-admitted after its health probe succeeds and
    serves at the current version,
(d) a trainer restarted from a recover checkpoint resumes with matching
    step counters and republishes ``model_version``.

Gen servers are scriptable HTTP stubs (no model) so scenarios are fast and
deterministic; failures come from ``areal_tpu.base.faults`` injection or
from flipping a stub into dead mode.
"""

import asyncio
import os

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from areal_tpu.api.agent import Agent, GenerationFailedError
from areal_tpu.api.data import SequenceSample
from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.base import faults, name_resolve, names
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gen.client import GenAPIClient, RetryPolicy
from areal_tpu.system.fleet import CLOSED, HALF_OPEN, OPEN, FleetHealth
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    serve_manager,
)
from areal_tpu.system.rollout_worker import RolloutWorker
from areal_tpu.base import network

EXP, TRIAL = "ft", "t0"


# --------------------------------------------------------------------- #
# scriptable stub gen server
# --------------------------------------------------------------------- #


class ScriptableGenServer:
    """HTTP stub with the gen-server surface. ``dead=True`` makes every
    endpoint return 500 (a crashed-but-listening process); closing the
    TestServer models a fully dead host (connection refused)."""

    def __init__(self, n_tokens: int = 4):
        self.n_tokens = n_tokens
        self.dead = False
        self.version = 0
        self.generate_calls = []
        self.update_calls = []
        self.app = web.Application()
        self.app.router.add_post("/generate", self._generate)
        self.app.router.add_post(
            "/update_weights_from_disk", self._update
        )
        self.app.router.add_get("/health", self._health)
        self.runner: TestServer = None
        self.url: str = None

    async def start(self):
        self.runner = TestServer(self.app)
        await self.runner.start_server()
        self.url = str(self.runner.make_url("")).rstrip("/")
        return self.url

    async def stop(self):
        await self.runner.close()

    async def _generate(self, request):
        d = await request.json()
        if self.dead:
            return web.json_response({"error": "dead"}, status=500)
        self.generate_calls.append(d)
        n = d["sampling_params"]["max_new_tokens"]
        n = min(n, self.n_tokens)
        return web.json_response(
            {
                "rid": d["rid"],
                "output_ids": list(range(1, n + 1)),
                "output_logprobs": [-0.1] * n,
                "finish_reason": "stop",
                "version": self.version,
            }
        )

    async def _update(self, request):
        d = await request.json()
        if self.dead:
            return web.json_response({"error": "dead"}, status=500)
        self.update_calls.append(d)
        self.version = d.get("version", self.version)
        return web.json_response(
            {"success": True, "message": "ok", "num_paused_requests": 0}
        )

    async def _health(self, request):
        if self.dead:
            return web.json_response({"status": "dead"}, status=500)
        return web.json_response({"status": "ok"})


class EchoAgent(Agent):
    """Minimal agent: one obs/act round trip, builds a trivial sample."""

    def __init__(self, n: int = 2, max_new_tokens: int = 8):
        self.gconfig = GenerationHyperparameters(
            n=n, max_new_tokens=max_new_tokens
        )

    async def collect_trajectory(self, prompt, env, obs_queue, act_queue):
        qid = prompt.ids[0]
        prompt_ids = np.asarray(prompt.data["packed_prompts"]).tolist()
        await obs_queue.put((qid, prompt_ids, self.gconfig))
        act = await act_queue.get()
        if act.error is not None:
            raise GenerationFailedError(act.error)
        seqlens = [len(s) for s in act.seqs]
        return [
            SequenceSample.from_default(
                ids=[qid],
                seqlens=[sum(seqlens)],
                data={
                    "packed_input_ids": np.concatenate(
                        [np.asarray(s, np.int64) for s in act.seqs]
                    )
                },
            )
        ]


class ListPusher:
    def __init__(self):
        self.items = []

    def push(self, data):
        self.items.append(data)
        return True


def _prompt(i: int) -> SequenceSample:
    return SequenceSample.from_default(
        ids=[f"q{i}"],
        seqlens=[4],
        data={"packed_prompts": np.asarray([1, 2, 3, 4], np.int64)},
    )


class ListDataset:
    def __init__(self, n):
        self.items = [_prompt(i) for i in range(n)]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]


class NullEnv:
    async def reset(self):
        pass

    async def step(self, action):
        return None, [1.0], None, None


@pytest.fixture(autouse=True)
def _ft_reset():
    faults.reset()
    name_resolve.reset()
    yield
    faults.reset()


def _mcfg(**kw) -> GserverManagerConfig:
    base = dict(
        experiment_name=EXP, trial_name=TRIAL, train_batch_size=4,
        max_head_offpolicyness=100, max_concurrent_rollouts=16,
        health_fail_threshold=3, health_probe_cooldown=0.1,
        health_check_interval=0.05, heartbeat_interval=1000.0,
    )
    base.update(kw)
    return GserverManagerConfig(**base)


# --------------------------------------------------------------------- #
# (a) kill one server mid-run: zero samples lost
# --------------------------------------------------------------------- #


async def test_kill_server_mid_run_loses_zero_samples(tmp_path):
    s0, s1 = ScriptableGenServer(), ScriptableGenServer()
    await s0.start()
    await s1.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url, s1.url])
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    n_samples = 8
    pusher = ListPusher()
    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=EchoAgent(), env=NullEnv(),
        dataset=ListDataset(n_samples), max_concurrent_tasks=4,
        pusher=pusher, manager_url=f"http://127.0.0.1:{mgr_port}",
    )
    # speed: tiny client backoff via the PRM's session default is fine; the
    # stub answers instantly. Kill s0 once the run is underway.
    run = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        for _ in range(100):
            await asyncio.sleep(0.02)
            if worker.accepted_cnt >= 2:
                break
        assert worker.accepted_cnt >= 2, "run never got underway"
        s0.dead = True  # kill mid-run: in-flight rollouts on s0 now fail

        for _ in range(1500):  # up to ~30s
            await asyncio.sleep(0.02)
            if worker.accepted_cnt >= n_samples:
                break
    finally:
        run.cancel()
        await asyncio.gather(run, return_exceptions=True)

    # zero samples lost: every prompt produced a trajectory despite the kill
    assert worker.accepted_cnt >= n_samples
    assert worker.dropped_cnt == 0
    assert len(pusher.items) >= n_samples
    pushed_qids = {d["ids"][0] for d in pusher.items}
    assert pushed_qids == {f"q{i}" for i in range(n_samples)}
    # the failure was observed and handled through the requeue machinery:
    # either whole-sample requeues or chunk-level re-scheduling (both routes
    # end with the dead server evicted from routing)
    assert manager.fleet.get(s0.url).total_failures > 0
    assert manager.fleet.is_healthy(s1.url)

    await mgr_runner.cleanup()
    await s0.stop()
    await s1.stop()


async def test_push_fault_requeues_without_duplicates():
    """The rollout.push injection point fires pre-delivery, so the requeue
    it triggers retries the sample without duplicating pushed samples."""
    s0 = ScriptableGenServer()
    await s0.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url])
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)
    pusher = ListPusher()
    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=EchoAgent(), env=NullEnv(),
        dataset=ListDataset(3), max_concurrent_tasks=2,
        pusher=pusher, manager_url=f"http://127.0.0.1:{mgr_port}",
    )
    # pin to ONE dataset epoch: load_next_data wraps epochs and clears
    # _used_qids, so a fast enough loop can legitimately re-roll q0 before
    # the accepted_cnt>=3 check below fires — that duplicate is epoch-wrap
    # behavior, not the requeue duplication this test is about
    orig_load = worker.load_next_data

    def _load_single_epoch():
        s = orig_load()  # the epoch wrap happens INSIDE load_next_data
        return None if worker._epoch > 0 else s

    worker.load_next_data = _load_single_epoch
    rule = faults.inject("rollout.push", qid="q1", times=1)
    run = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        for _ in range(500):
            await asyncio.sleep(0.02)
            if worker.accepted_cnt >= 3:
                break
    finally:
        run.cancel()
        await asyncio.gather(run, return_exceptions=True)
    assert rule.fired == 1
    assert worker.requeued_cnt == 1 and worker.dropped_cnt == 0
    qids = [d["ids"][0] for d in pusher.items]
    assert sorted(qids) == ["q0", "q1", "q2"]  # q1 exactly once
    await mgr_runner.cleanup()
    await s0.stop()


async def test_push_crash_still_releases_manager_slot():
    """An UNEXPECTED pusher crash (not the scripted fault point) must not
    skip finish_rollout: the manager's capacity slot is released, the
    undelivered sample is requeued, and the retry goes through — the
    lifecycle-rule triage fix for the allocate/finish pairing
    (rollout.manager-slot in tools/arealint/resources.py)."""
    s0 = ScriptableGenServer()
    await s0.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url])
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    class CrashOncePusher(ListPusher):
        def __init__(self):
            super().__init__()
            self.crashes = 0

        def push(self, data):
            if self.crashes == 0:
                self.crashes += 1
                raise RuntimeError("zmq push exploded")
            return super().push(data)

    pusher = CrashOncePusher()
    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=EchoAgent(), env=NullEnv(),
        dataset=ListDataset(2), max_concurrent_tasks=2,
        pusher=pusher, manager_url=f"http://127.0.0.1:{mgr_port}",
    )
    orig_load = worker.load_next_data

    def _load_single_epoch():
        s = orig_load()
        return None if worker._epoch > 0 else s

    worker.load_next_data = _load_single_epoch
    run = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        for _ in range(500):
            await asyncio.sleep(0.02)
            if worker.accepted_cnt >= 2 and not worker._tasks:
                break
    finally:
        run.cancel()
        await asyncio.gather(run, return_exceptions=True)
    assert pusher.crashes == 1
    # nothing was delivered before the crash, so the sample requeued and
    # retried (no duplicates), and every allocated slot was released
    assert worker.requeued_cnt == 1 and worker.dropped_cnt == 0
    assert worker.accepted_cnt >= 2
    qids = sorted(d["ids"][0] for d in pusher.items)
    assert qids == ["q0", "q1"]
    assert manager.rollout_stat.running == 0, (
        "a push-path crash leaked a manager capacity slot"
    )
    await mgr_runner.cleanup()
    await s0.stop()


async def test_deterministic_push_crash_exhausts_attempts():
    """A sample whose push ALWAYS crashes (e.g. unserializable metadata)
    must exhaust max_rollout_attempts and be dropped — the retry counter
    resets only after a fully delivered round, so a deterministic
    post-collect failure cannot requeue forever."""
    s0 = ScriptableGenServer()
    await s0.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url])
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    class AlwaysCrashPusher(ListPusher):
        def push(self, data):
            raise RuntimeError("metadata not serializable")

    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=EchoAgent(), env=NullEnv(),
        dataset=ListDataset(1), max_concurrent_tasks=1,
        pusher=AlwaysCrashPusher(),
        manager_url=f"http://127.0.0.1:{mgr_port}",
        max_rollout_attempts=3,
    )
    orig_load = worker.load_next_data

    def _load_single_epoch():
        s = orig_load()
        return None if worker._epoch > 0 else s

    worker.load_next_data = _load_single_epoch
    run = asyncio.get_event_loop().create_task(worker.run_async())
    try:
        for _ in range(500):
            await asyncio.sleep(0.02)
            if worker.dropped_cnt >= 1:
                break
    finally:
        run.cancel()
        await asyncio.gather(run, return_exceptions=True)
    assert worker.dropped_cnt == 1
    assert worker.requeued_cnt == 2  # attempts 1..2 requeued, 3rd dropped
    assert manager.rollout_stat.running == 0
    await mgr_runner.cleanup()
    await s0.stop()


# --------------------------------------------------------------------- #
# (b) weight update with one dead server: survivors bump, corpse evicted
# --------------------------------------------------------------------- #


async def test_weight_update_partial_failure_bumps_survivors(tmp_path):
    s0, s1, s2 = (ScriptableGenServer() for _ in range(3))
    for s in (s0, s1, s2):
        await s.start()
    manager = GserverManager(
        _mcfg(), server_urls=[s0.url, s1.url, s2.url]
    )
    await s1.stop()  # s1 is a dead host: connection refused

    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version(EXP, TRIAL, "actor"), f"1:{ckpt}", replace=True
    )
    path = await manager.check_new_params()
    assert path == str(ckpt)
    # version advanced despite the dead server
    assert manager.version == 1
    for s in (s0, s2):
        assert len(s.update_calls) == 1
        assert s.update_calls[0]["version"] == 1
    # the dead server was evicted and is out of routing + future fan-outs
    assert manager.fleet.get(s1.url).state == OPEN
    assert set(manager.fleet.healthy_urls()) == {s0.url, s2.url}
    assert manager.fleet.get(s0.url).acked_version == 1

    # no hot-loop: the next poll tick is a no-op (version already current)
    assert await manager.check_new_params() is None
    assert len(s0.update_calls) == 1

    await s0.stop()
    await s2.stop()


# --------------------------------------------------------------------- #
# (c) evicted server re-admitted after successful probe, at current version
# --------------------------------------------------------------------- #


async def test_evicted_server_readmitted_after_probe(tmp_path):
    s0, s1 = ScriptableGenServer(), ScriptableGenServer()
    await s0.start()
    await s1.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url, s1.url])

    # publish v1; s1 plays dead for the update → evicted
    s1.dead = True
    ckpt = tmp_path / "v1"
    ckpt.mkdir()
    name_resolve.add(
        names.model_version(EXP, TRIAL, "actor"), f"1:{ckpt}", replace=True
    )
    await manager.check_new_params()
    assert manager.fleet.get(s1.url).state == OPEN
    assert manager.fleet.healthy_urls() == [s0.url]
    assert s1.version == 0  # still stale

    # probe while still dead: breaker stays open, no re-admission
    await asyncio.sleep(0.15)  # past probe_cooldown
    await manager.run_health_checks(wait_probes=True)
    assert manager.fleet.get(s1.url).state == OPEN
    assert metrics_mod.counters.get("ft/probe_failures") >= 1

    # server comes back: probe + catch-up load → re-admitted at current v
    s1.dead = False
    await asyncio.sleep(0.15)
    await manager.run_health_checks(wait_probes=True)
    h = manager.fleet.get(s1.url)
    assert h.state == CLOSED
    assert h.acked_version == 1
    assert s1.version == 1  # catch-up update really reached the server
    assert set(manager.fleet.healthy_urls()) == {s0.url, s1.url}
    assert metrics_mod.counters.get("ft/readmissions") >= 1

    await s0.stop()
    await s1.stop()


# --------------------------------------------------------------------- #
# (d) trainer restart from recover checkpoint
# --------------------------------------------------------------------- #


def _tiny_trainer(eng=None):
    """A real (tiny) AsyncPPOTrainerWorker — engine checkpoints must round-
    trip through the actual save/load path."""
    from areal_tpu.api.model import PPOHyperparameters
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.system.trainer_worker import (
        AsyncPPOTrainerWorker,
        TrainerControl,
    )
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    if eng is None:
        cfg = ModelConfig(
            n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8, hidden_dim=16,
            intermediate_dim=32, vocab_size=64, dtype="float32",
            use_attention_bias=True,  # qwen2-exportable (publish_weights)
        )
        eng = TrainEngine(
            cfg, ParallelConfig(data=1, fsdp=1, model=1),
            OptimizerConfig(lr=1e-4),
        )
        eng.init_random(0)
        eng.setup_optimizer(10)

    class _EmptyStream:
        def get_batch(self, n, timeout=0.1):
            return []

        def clear(self):
            self.cleared = True
            return 3  # pretend 3 stale trajectories were buffered

    stream = _EmptyStream()
    worker = AsyncPPOTrainerWorker(
        experiment_name=EXP, trial_name=TRIAL, actor_engine=eng,
        stream=stream,
        hp=PPOHyperparameters(disable_value=True, kl_ctl=0.0),
        control=TrainerControl(total_train_steps=10),
        train_batch_size=2, hf_family="qwen2",
    )
    return worker, eng, stream


def test_trainer_recover_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    import jax

    from areal_tpu.base import constants

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w1, eng1, _ = _tiny_trainer()
    # simulate a run that did 7 steps and consumed 28 samples
    w1.step = 7
    w1.samples_consumed = 28
    eng1.version = 7
    w1.save_recover_checkpoint()
    saved = np.asarray(jax.tree.leaves(eng1.params)[0]).copy()

    # restart-the-world: a fresh worker. The engine object is reused with
    # scrambled state (fresh seed, zeroed counters) — constructing a second
    # TrainEngine only re-pays jit compile, it would not strengthen the
    # restore proof (the checkpoint round-trips through disk either way).
    eng1.init_random(1)
    eng1.version = 0
    w2, eng2, stream2 = _tiny_trainer(eng=eng1)
    assert w2.step == 0 and eng2.version == 0
    assert not np.allclose(
        saved, np.asarray(jax.tree.leaves(eng2.params)[0])
    )
    assert w2.load_recover_checkpoint()

    # (d) matching step counters
    assert w2.step == 7
    assert w2.samples_consumed == 28
    assert eng2.version == 7
    # params actually restored (not merely counters)
    np.testing.assert_allclose(
        saved, np.asarray(jax.tree.leaves(eng2.params)[0])
    )

    # stale in-flight trajectories were dropped
    assert getattr(stream2, "cleared", False)

    # model_version republished so the fleet converges on the restored run
    raw = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
    version, _, path = raw.partition(":")
    assert int(version) == 7
    assert os.path.isdir(path)
    # training_samples republished for the staleness gate
    assert int(name_resolve.get(names.training_samples(EXP, TRIAL))) == 28


# --------------------------------------------------------------------- #
# (e) trainer survivability: atomic checkpoint commit protocol
# --------------------------------------------------------------------- #


def test_commit_protocol_resolves_newest_committed(tmp_path):
    """Every crash window of commit_checkpoint is recoverable: an
    uncommitted staging dir is discarded; a committed staging dir (crash
    between manifest fsync and rename) is promoted over an older
    committed canonical dir."""
    from areal_tpu.base import recover

    path = str(tmp_path / "ckpt")
    # canonical: committed at step 3
    os.makedirs(path)
    recover.write_manifest(path, {"step": 3, "version": 3})
    # crashed newer save: committed staging (manifest landed, rename didn't)
    newer = recover.staging_path(path, "s5")
    os.makedirs(newer)
    recover.write_manifest(newer, {"step": 5, "version": 5})
    # and an uncommitted staging leftover (no manifest)
    os.makedirs(recover.staging_path(path, "s6"))

    assert recover.resolve_committed(path) == path
    m = recover.read_manifest(path)
    assert (m["step"], m["version"]) == (5, 5)  # the newer one won
    # strays cleaned
    assert not os.path.exists(newer)
    assert not os.path.exists(recover.staging_path(path, "s6"))

    # nothing committed at all -> None
    bare = str(tmp_path / "bare")
    os.makedirs(recover.staging_path(bare, "s1"))
    assert recover.resolve_committed(bare) is None


def test_ckpt_crash_mid_save_preserves_previous_committed(
    tmp_path, monkeypatch
):
    """Acceptance: a crash injected via the ``ckpt.save`` fault point
    mid-save leaves the previous committed checkpoint loadable, and the
    restarted trainer resumes from it."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    import jax

    from areal_tpu.base import constants, recover

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w1, eng1, _ = _tiny_trainer()
    w1.step = 5
    w1.samples_consumed = 20
    eng1.version = 5
    w1.save_recover_checkpoint()  # commit #1
    committed = np.asarray(jax.tree.leaves(eng1.params)[0]).copy()
    actor_dir = os.path.join(
        constants.get_recover_root(), "trainer", "actor"
    )
    assert recover.is_committed(actor_dir)

    # the run advances, then dies mid-save of the NEXT checkpoint
    eng1.init_random(3)
    eng1._step += 7
    w1.step = 12
    eng1.version = 12
    faults.inject("ckpt.save", times=1)
    with pytest.raises(faults.FaultInjected):
        w1.save_recover_checkpoint()
    faults.reset()
    # the staged-but-uncommitted dir must not shadow the committed one
    assert recover.is_committed(actor_dir)
    assert recover.read_manifest(actor_dir)["version"] == 5

    # restart-the-world: scrambled engine, fresh worker
    eng1.init_random(9)
    eng1.version = 0
    w2, eng2, _ = _tiny_trainer(eng=eng1)
    assert w2.load_recover_checkpoint()
    assert w2.step == 5 and eng2.version == 5
    np.testing.assert_array_equal(
        committed, np.asarray(jax.tree.leaves(eng2.params)[0])
    )
    # and the fleet converges on the COMMITTED version
    raw = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
    assert int(raw.partition(":")[0]) == 5


def test_uncommitted_recover_checkpoint_falls_back_to_fresh_start(
    tmp_path, monkeypatch
):
    """A recover dir that only ever got an UNCOMMITTED save (crash on the
    very first checkpoint) is skipped: load_recover_checkpoint returns
    False instead of restoring garbage."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    from areal_tpu.base import constants

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w1, eng1, _ = _tiny_trainer()
    w1.step = 2
    faults.inject("ckpt.save", times=1)
    with pytest.raises(faults.FaultInjected):
        w1.save_recover_checkpoint()
    faults.reset()
    # RecoverInfo may exist from other tests' layout — write one explicitly
    # to prove the engine checkpoint validation is what gates the recover
    from areal_tpu.base import recover as recover_mod

    recover_mod.dump(recover_mod.RecoverInfo(samples_consumed=8))
    import jax

    before = np.asarray(jax.tree.leaves(eng1.params)[0]).copy()
    w2, _, _ = _tiny_trainer(eng=eng1)
    assert not w2.load_recover_checkpoint()
    # validation runs BEFORE any restore: a failed recover must leave the
    # engine exactly as it was (no partially-restored mixed state)
    np.testing.assert_array_equal(
        before, np.asarray(jax.tree.leaves(eng1.params)[0])
    )
    assert w2.step == 0 and w2.samples_consumed == 0


# --------------------------------------------------------------------- #
# (f) guardrail plane: K consecutive anomalies -> rollback to committed
# --------------------------------------------------------------------- #


def test_consecutive_anomalies_roll_back_to_committed(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    import jax
    import time as time_mod

    from areal_tpu.base import constants

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w, eng, _ = _tiny_trainer()
    w.step = 4
    eng.version = 4
    w.save_recover_checkpoint()  # the rollback target
    committed = np.asarray(jax.tree.leaves(eng.params)[0]).copy()

    # params drift (simulating steps whose updates slipped through or an
    # optimizer-state corruption the skip-guard cannot undo)
    eng.init_random(7)
    eng.version = 6

    k = w.control.guard_rollback_steps
    assert k >= 2
    before_rb = metrics_mod.counters.get(metrics_mod.GUARD_ROLLBACKS)
    # k-1 anomalies: counted, but NO rollback yet
    w._pending_stats = [
        (i, time_mod.time(), {"guard/step_ok": 0.0}) for i in range(k - 1)
    ]
    w.flush_stats()
    assert w._consec_anomalies == k - 1
    assert metrics_mod.counters.get(metrics_mod.GUARD_ROLLBACKS) == before_rb
    # a clean step in between resets the streak
    w._pending_stats = [(k, time_mod.time(), {"guard/step_ok": 1.0})]
    w.flush_stats()
    assert w._consec_anomalies == 0
    # k consecutive anomalies: rollback fires
    w._pending_stats = [
        (k + 1 + i, time_mod.time(), {"guard/step_ok": 0.0})
        for i in range(k)
    ]
    w.flush_stats()
    w._join_publish()
    assert (
        metrics_mod.counters.get(metrics_mod.GUARD_ROLLBACKS) == before_rb + 1
    )
    assert w._consec_anomalies == 0
    np.testing.assert_array_equal(
        committed, np.asarray(jax.tree.leaves(eng.params)[0])
    )
    # the restored weights republish under a NEW (monotonic) version: the
    # manager ignores version <= its current one, so re-announcing the
    # restored number (4) while the fleet sits at 6 would be silently
    # dropped and the fleet would keep serving the suspect weights
    assert eng.version == 7
    raw = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
    assert int(raw.partition(":")[0]) == 7
    assert metrics_mod.counters.get(metrics_mod.GUARD_ANOMALOUS_STEPS) >= k
    # trajectories buffered against the suspect policy were dropped
    # (_EmptyStream.clear pretends 3 were in flight)
    assert (
        metrics_mod.counters.get(metrics_mod.FT_STALE_DROPPED_ON_RECOVER) >= 3
    )


# --------------------------------------------------------------------- #
# (g) preemption plane: signal.term -> committed ckpt + distinct exit code
# --------------------------------------------------------------------- #


def test_preemption_commits_checkpoint_and_sets_distinct_code(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    from areal_tpu.base import constants, recover
    from areal_tpu.system import worker_base

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w, eng, _ = _tiny_trainer()
    w.step = 3
    eng.version = 3
    before = metrics_mod.counters.get(metrics_mod.FT_PREEMPTIONS)

    shutdown = worker_base.GracefulShutdown(deadline_s=30.0, install=False)
    faults.inject("signal.term", action="trip", times=1)
    w.run(shutdown=shutdown)

    assert w.preempted
    assert metrics_mod.counters.get(metrics_mod.FT_PREEMPTIONS) == before + 1
    # the recover checkpoint is COMMITTED (manifest present, right tick)
    actor_dir = os.path.join(
        constants.get_recover_root(), "trainer", "actor"
    )
    m = recover.read_manifest(actor_dir)
    assert m is not None and m["version"] == 3
    # model_version republished before exit
    raw = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
    assert int(raw.partition(":")[0]) == 3
    # the exit code the launcher maps to restart-the-world is distinct
    assert worker_base.EXIT_PREEMPTED not in (0, 1)
    assert worker_base.EXIT_PREEMPTED != worker_base.EXIT_WATCHDOG


def test_graceful_shutdown_handles_real_sigterm():
    import signal

    from areal_tpu.system import worker_base

    shutdown = worker_base.GracefulShutdown(deadline_s=5.0)
    try:
        assert not shutdown.should_stop()
        os.kill(os.getpid(), signal.SIGTERM)
        assert shutdown.should_stop()
        assert shutdown.remaining() <= 5.0
    finally:
        shutdown.uninstall()


# --------------------------------------------------------------------- #
# (h) satellites: stale RecoverInfo version, publish-failure surfacing
# --------------------------------------------------------------------- #


def test_stale_recover_info_version_cannot_win(tmp_path, monkeypatch):
    """The ENGINE checkpoint's version is authoritative: a tampered/stale
    RecoverInfo.model_version must not be what gets republished."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    from areal_tpu.base import constants
    from areal_tpu.base import recover as recover_mod

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w1, eng1, _ = _tiny_trainer()
    w1.step = 6
    eng1.version = 6
    w1.save_recover_checkpoint()
    # tamper: RecoverInfo claims an older model_version (e.g. an info file
    # surviving from an earlier tick than the engine checkpoint)
    info = recover_mod.load()
    info.model_version = 2
    recover_mod.dump(info)

    eng1.version = 0
    w2, eng2, _ = _tiny_trainer(eng=eng1)
    assert w2.load_recover_checkpoint()
    assert eng2.version == 6  # engine checkpoint won
    raw = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
    assert int(raw.partition(":")[0]) == 6  # ...everywhere it republishes


def test_publish_failure_surfaces_on_join_and_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path))
    from areal_tpu.base import constants
    from areal_tpu.models import hf as hf_conv

    constants.set_experiment_trial_names(EXP, TRIAL)
    name_resolve.reset()
    w, _, _ = _tiny_trainer()

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(hf_conv, "save_hf_checkpoint", boom)
    before = metrics_mod.counters.get(metrics_mod.FT_PUBLISH_FAILURES)
    w.publish_weights()
    with pytest.raises(RuntimeError, match="publish failed"):
        w._join_publish()
    assert (
        metrics_mod.counters.get(metrics_mod.FT_PUBLISH_FAILURES)
        == before + 1
    )


# --------------------------------------------------------------------- #
# retry plane units: client backoff + fault harness semantics
# --------------------------------------------------------------------- #


async def test_client_retries_through_transient_fault():
    s = ScriptableGenServer()
    await s.start()
    # first 2 attempts of this generate fail at the injection point, the
    # 3rd succeeds — the caller never sees the fault
    rule = faults.inject("gen.http", url=s.url, op="generate", times=2)
    before = metrics_mod.counters.get("ft/client_retries")
    async with GenAPIClient(
        timeout=5.0,
        retry=RetryPolicy(max_attempts=3, backoff_base_s=0.01),
    ) as c:
        res = await c.generate(
            s.url, rid="r1", input_ids=[1, 2], sampling_params={
                "max_new_tokens": 4,
            },
        )
    assert res.output_ids == [1, 2, 3, 4]
    assert rule.fired == 2
    assert metrics_mod.counters.get("ft/client_retries") - before == 2
    await s.stop()


async def test_client_retry_exhaustion_raises():
    s = ScriptableGenServer()
    await s.start()
    faults.inject("gen.http", url=s.url, op="generate")  # forever
    async with GenAPIClient(
        timeout=5.0,
        retry=RetryPolicy(max_attempts=3, backoff_base_s=0.01),
    ) as c:
        with pytest.raises(ConnectionError):
            await c.generate(
                s.url, rid="r1", input_ids=[1], sampling_params={
                    "max_new_tokens": 1,
                },
            )
    await s.stop()


def test_faults_zero_overhead_when_unconfigured():
    assert not faults.active()
    # no rules: maybe_fail is a no-op (and must not allocate/raise)
    faults.maybe_fail("gen.http", url="http://x", op="generate")
    rule = faults.inject("gen.http", url="http://x", after=1, times=1)
    faults.maybe_fail("gen.http", url="http://other")  # filtered: no match
    assert rule.seen == 0
    faults.maybe_fail("gen.http", url="http://x")  # skipped by `after`
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("gen.http", url="http://x")
    faults.maybe_fail("gen.http", url="http://x")  # `times` exhausted
    assert (rule.seen, rule.fired) == (3, 1)
    faults.reset()
    assert not faults.active()


# --------------------------------------------------------------------- #
# breaker unit semantics
# --------------------------------------------------------------------- #


def test_breaker_state_machine():
    t = [0.0]
    fleet = FleetHealth(
        ["http://a", "http://b"], fail_threshold=2, probe_cooldown_s=5.0,
        clock=lambda: t[0],
    )
    assert fleet.healthy_urls() == ["http://a", "http://b"]
    assert not fleet.observe_failure("http://a")   # 1 of 2
    fleet.observe_success("http://a")              # success resets the count
    assert not fleet.observe_failure("http://a")
    assert fleet.observe_failure("http://a")       # 2 consecutive → evicted
    assert fleet.get("http://a").state == OPEN
    assert fleet.healthy_urls() == ["http://b"]
    # cooldown gates probing
    assert fleet.probe_candidates() == []
    t[0] = 6.0
    assert fleet.probe_candidates() == ["http://a"]
    fleet.begin_probe("http://a")
    assert fleet.get("http://a").state == HALF_OPEN
    fleet.probe_failed("http://a")
    assert fleet.get("http://a").state == OPEN
    t[0] = 20.0
    fleet.begin_probe("http://a")
    fleet.readmit("http://a", acked_version=3)
    assert fleet.get("http://a").state == CLOSED
    assert fleet.get("http://a").acked_version == 3
    assert fleet.min_acked_version() == -1  # "b" never acked anything
    fleet.ack_version("http://b", 5)
    assert fleet.min_acked_version() == 3


# --------------------------------------------------------------------- #
# satellites: pusher send-timeout, drain cancellation
# --------------------------------------------------------------------- #


def test_pusher_drops_instead_of_hanging():
    """SNDHWM hit + dead puller: push must time out and count the drop, not
    block the rollout worker forever."""
    from areal_tpu.base import network
    from areal_tpu.system.push_pull_stream import ZMQJsonPusher

    port = network.find_free_port()  # nobody ever binds: no puller at all
    pusher = ZMQJsonPusher("127.0.0.1", port, hwm=1, send_timeout_ms=100)
    before = metrics_mod.counters.get("ft/push_drops")
    import time

    t0 = time.monotonic()
    results = [pusher.push({"i": i}) for i in range(3)]
    elapsed = time.monotonic() - t0
    # zmq buffers ~hwm messages, the rest time out quickly
    assert not all(results)
    assert pusher.drop_cnt >= 1
    assert metrics_mod.counters.get("ft/push_drops") - before == pusher.drop_cnt
    assert elapsed < 5.0  # three pushes, 100ms timeout each — not forever
    pusher.close()


async def test_drain_cancels_timed_out_tasks():
    s0 = ScriptableGenServer()
    await s0.start()
    manager = GserverManager(_mcfg(), server_urls=[s0.url])
    mgr_port = network.find_free_port()
    mgr_runner = await serve_manager(manager, "127.0.0.1", mgr_port)

    class StuckAgent(Agent):
        async def collect_trajectory(self, prompt, env, obs_queue, act_queue):
            await asyncio.sleep(3600)  # never finishes

    worker = RolloutWorker(
        experiment_name=EXP, trial_name=TRIAL, worker_index=0, n_workers=1,
        n_pullers=1, agent=StuckAgent(), env=NullEnv(),
        dataset=ListDataset(2), max_concurrent_tasks=2,
        pusher=ListPusher(), manager_url=f"http://127.0.0.1:{mgr_port}",
    )
    run = asyncio.get_event_loop().create_task(worker.run_async())
    for _ in range(200):
        await asyncio.sleep(0.01)
        if len(worker._tasks) == 2:
            break
    assert len(worker._tasks) == 2
    run.cancel()
    await asyncio.gather(run, return_exceptions=True)

    before = metrics_mod.counters.get("ft/drain_abandoned")
    await worker.drain(timeout=0.1)
    # timed-out tasks were cancelled and awaited, not left running
    assert all(t.done() for t in worker._tasks.values()) or not worker._tasks
    assert metrics_mod.counters.get("ft/drain_abandoned") - before == 2
    await mgr_runner.cleanup()
    await s0.stop()
