"""Fixture tests for the arealint v2 rule families: concurrency/races
(``rules_concurrency.py``) and cross-module dataflow
(``rules_dataflow.py``).

Every rule gets at least one positive fixture (fires on the bug
pattern) and one negative (stays quiet on the idiomatic pattern) —
the acceptance contract from docs/static_analysis.md. All fixtures run
through ``scan_sources`` so BOTH layers (file + project) execute
exactly as the CLI would.
"""

import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import scan_sources  # noqa: E402

pytestmark = pytest.mark.arealint


def dedent(s):
    return textwrap.dedent(s).lstrip()


def rules_of(sources):
    return [f.rule for f in scan_sources(sources)]


def findings(sources, rule):
    return [f for f in scan_sources(sources) if f.rule == rule]


# ------------------------------------------------------------------ #
# thread-unsafe-shared-state
# ------------------------------------------------------------------ #


class TestThreadUnsafeSharedState:
    def test_fires_on_unlocked_thread_write_async_read(self):
        src = dedent(
            """
            import threading

            class Exporter:
                def start(self):
                    self._t = threading.Thread(target=self._loop)
                    self._t.start()

                def _loop(self):
                    self.latest = compute()

                async def read(self):
                    return self.latest
            """
        )
        found = findings({"w.py": src}, "thread-unsafe-shared-state")
        assert len(found) == 1
        assert "self.latest" in found[0].message
        assert "read()" in found[0].message

    def test_fires_on_module_global(self):
        src = dedent(
            """
            import threading

            latest = None

            def start():
                threading.Thread(target=_loop).start()

            def _loop():
                global latest
                latest = compute()

            async def read():
                return latest
            """
        )
        found = findings({"g.py": src}, "thread-unsafe-shared-state")
        assert len(found) == 1
        assert "latest" in found[0].message

    def test_quiet_when_async_local_shadows_global(self):
        # assignment without ``global`` makes the name local — reading it
        # is not a global read (Python scoping, not a data race)
        src = dedent(
            """
            import threading

            count = 0

            def start():
                threading.Thread(target=_loop).start()

            def _loop():
                global count
                count = 1

            async def consumer():
                count = local_compute()
                return count
            """
        )
        assert findings({"g.py": src}, "thread-unsafe-shared-state") == []

    def test_quiet_on_async_store_only(self):
        # written-from-thread / READ-from-async is the contract; an
        # async-side store must not be mis-cited as a read
        src = dedent(
            """
            import threading

            class C:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self.x = 1

                async def reset(self):
                    self.x = 0
            """
        )
        assert findings({"w.py": src}, "thread-unsafe-shared-state") == []

    def test_quiet_when_global_locked_on_both_sides(self):
        src = dedent(
            """
            import threading

            _lock = threading.Lock()
            _state = None

            def start():
                threading.Thread(target=_loop).start()

            def _loop():
                global _state
                with _lock:
                    _state = compute()

            async def read():
                with _lock:
                    return _state
            """
        )
        assert findings({"g.py": src}, "thread-unsafe-shared-state") == []

    def test_quiet_when_both_sides_locked(self):
        src = dedent(
            """
            import threading

            class Safe:
                def __init__(self):
                    self._lock = threading.Lock()

                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    with self._lock:
                        self.latest = compute()

                async def read(self):
                    with self._lock:
                        return self.latest
            """
        )
        assert findings({"w.py": src}, "thread-unsafe-shared-state") == []

    def test_quiet_when_lock_inherited_from_other_module(self):
        # the lock lives in Base's module; this module cannot classify
        # self._lock, so the unknown context manager counts as held
        # (degrade-don't-guess, never a finding on correctly-locked code)
        srcs = {
            "base.py": dedent(
                """
                import threading

                class Base:
                    def __init__(self):
                        self._lock = threading.Lock()
                """
            ),
            "w.py": dedent(
                """
                import threading
                from base import Base

                class Exporter(Base):
                    def start(self):
                        threading.Thread(target=self._loop).start()

                    def _loop(self):
                        with self._lock:
                            self.latest = compute()

                    async def read(self):
                        with self._lock:
                            return self.latest
                """
            ),
        }
        assert findings(srcs, "thread-unsafe-shared-state") == []

    def test_quiet_on_explicit_acquire_release(self):
        # acquire()/release() bookending instead of ``with`` — the body
        # conservatively counts as lock-held (no flow tracking needed to
        # stay quiet on correctly-locked code)
        src = dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._lock.acquire()
                    self.state = compute()
                    self._lock.release()

                async def read(self):
                    with self._lock:
                        return self.state
            """
        )
        assert findings({"w.py": src}, "thread-unsafe-shared-state") == []

    def test_quiet_on_internally_synchronized_attrs(self):
        # queue.Queue / threading.Event attrs are the sanctioned handoff
        src = dedent(
            """
            import queue
            import threading

            class Handoff:
                def __init__(self):
                    self.q = queue.Queue()
                    self.stop = threading.Event()

                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self.q.put(compute())
                    self.stop.set()

                async def read(self):
                    return self.q.get_nowait()
            """
        )
        assert findings({"w.py": src}, "thread-unsafe-shared-state") == []

    def test_inline_suppression_with_reason(self):
        src = dedent(
            """
            import threading

            class Flag:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self.done = True  # arealint: ok(monotonic bool flag, torn read impossible)

                async def read(self):
                    return self.done
            """
        )
        assert findings({"w.py": src}, "thread-unsafe-shared-state") == []


# ------------------------------------------------------------------ #
# asyncio-from-thread
# ------------------------------------------------------------------ #


class TestAsyncioFromThread:
    def test_fires_on_create_task_and_queue_and_call_soon(self):
        src = dedent(
            """
            import asyncio
            import threading

            class Bridge:
                def __init__(self, loop):
                    self.q = asyncio.Queue()
                    self.loop = loop

                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    asyncio.create_task(work())
                    self.q.put_nowait(1)
                    self.loop.call_soon(cb)
            """
        )
        found = findings({"b.py": src}, "asyncio-from-thread")
        assert len(found) == 3
        msgs = " | ".join(f.message for f in found)
        assert "create_task" in msgs
        assert "put_nowait" in msgs
        assert "call_soon" in msgs

    def test_call_soon_gated_on_loop_receiver(self):
        # .call_soon on an arbitrary object is not asyncio; only
        # loop-typed receivers fire
        src = dedent(
            """
            import threading

            class W:
                def __init__(self, sched, loop):
                    self.sched = sched
                    self.loop = loop

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self.sched.call_soon(tick)
                    self.loop.call_soon(tick)
            """
        )
        found = findings({"s.py": src}, "asyncio-from-thread")
        assert len(found) == 1
        assert "call_soon" in found[0].message

    def test_nested_def_asyncio_run_does_not_exempt_outer(self):
        # asyncio.run inside a nested def is a separate execution
        # context; the outer thread target's create_task is still a race
        src = dedent(
            """
            import asyncio
            import threading

            class B:
                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    def bridge(coro):
                        asyncio.run(coro)
                    asyncio.create_task(work())
            """
        )
        found = findings({"t.py": src}, "asyncio-from-thread")
        assert len(found) == 1
        assert "create_task" in found[0].message

    def test_quiet_on_threadsafe_bridges_and_loop_starters(self):
        src = dedent(
            """
            import asyncio
            import threading

            class Good:
                def __init__(self, loop):
                    self.q = asyncio.Queue()
                    self.loop = loop

                def start(self):
                    threading.Thread(target=self._bridge).start()
                    threading.Thread(target=self._own_loop).start()

                def _bridge(self):
                    asyncio.run_coroutine_threadsafe(work(), self.loop)
                    self.loop.call_soon_threadsafe(cb)

                def _own_loop(self):
                    # starts its own loop: everything below runs in it
                    asyncio.run(main())

                async def consume(self):
                    # loop context: asyncio primitives are fine here
                    await self.q.get()
                    asyncio.create_task(work())
            """
        )
        # (the discarded create_task in consume() is a DIFFERENT rule)
        assert findings({"b.py": src}, "asyncio-from-thread") == []


# ------------------------------------------------------------------ #
# lock-order
# ------------------------------------------------------------------ #


class TestLockOrder:
    def test_fires_on_lexical_abba(self):
        src = dedent(
            """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with B:
                    with A:
                        pass
            """
        )
        found = findings({"l.py": src}, "lock-order")
        assert len(found) == 2  # both sides of the cycle are reported
        assert all("reverse order" in f.message for f in found)

    def test_fires_across_calls(self):
        # one() holds A and calls helper() which takes B; two() nests
        # B-then-A directly — the cycle is only visible through the graph
        src = dedent(
            """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def helper():
                with B:
                    pass

            def one():
                with A:
                    helper()

            def two():
                with B:
                    with A:
                        pass
            """
        )
        found = findings({"l.py": src}, "lock-order")
        assert found, "cross-call ABBA must be detected"

    def test_quiet_on_consistent_order(self):
        src = dedent(
            """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    with B:
                        pass
            """
        )
        assert findings({"l.py": src}, "lock-order") == []


# ------------------------------------------------------------------ #
# await-in-lock (file rule)
# ------------------------------------------------------------------ #


class TestAwaitInLock:
    def test_fires_on_await_under_threading_lock(self):
        src = dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                async def bad(self):
                    with self._lock:
                        await fetch()
            """
        )
        found = findings({"c.py": src}, "await-in-lock")
        assert len(found) == 1
        assert "_lock" in found[0].message

    def test_quiet_on_asyncio_lock_and_await_outside(self):
        src = dedent(
            """
            import asyncio
            import threading

            class C:
                def __init__(self):
                    self._alock = asyncio.Lock()
                    self._tlock = threading.Lock()

                async def good(self):
                    async with self._alock:
                        await fetch()
                    with self._tlock:
                        x = quick()
                    await push(x)
            """
        )
        assert findings({"c.py": src}, "await-in-lock") == []


# ------------------------------------------------------------------ #
# donation-cross-call
# ------------------------------------------------------------------ #


class TestDonationCrossCall:
    def test_fires_when_helper_donates_callers_variable(self):
        src = dedent(
            """
            import jax

            def helper(params, grads):
                step = jax.jit(apply, donate_argnums=(0,))
                return step(params, grads)

            def train(params, grads):
                new = helper(params, grads)
                return params
            """
        )
        found = findings({"t.py": src}, "donation-cross-call")
        assert len(found) == 1
        assert "'params'" in found[0].message
        assert "helper()" in found[0].message

    def test_quiet_when_helper_rebinds_param_before_donating(self):
        # the helper donates its OWN rebound buffer, not the caller's
        src = dedent(
            """
            import jax

            def helper(x):
                jf = jax.jit(f, donate_argnums=(0,))
                x = x * 2
                return jf(x)

            def caller(a):
                y = helper(a)
                return a + y
            """
        )
        assert findings({"t.py": src}, "donation-cross-call") == []

    def test_quiet_when_rebound_at_call(self):
        src = dedent(
            """
            import jax

            def helper(params, grads):
                step = jax.jit(apply, donate_argnums=(0,))
                return step(params, grads)

            def train(params, grads):
                params = helper(params, grads)
                return params
            """
        )
        assert findings({"t.py": src}, "donation-cross-call") == []

    def test_fires_when_stored_alias_survives_donation(self):
        src = dedent(
            """
            import jax

            class Cache:
                def keep(self, p):
                    self.snapshot = p

            def run(cache: Cache, params, grads):
                cache.keep(params)
                step = jax.jit(apply, donate_argnums=(0,))
                return step(params, grads)
            """
        )
        found = findings({"s.py": src}, "donation-cross-call")
        assert len(found) == 1
        assert "stored" in found[0].message

    def test_quiet_when_helper_does_not_store(self):
        src = dedent(
            """
            import jax

            class Cache:
                def note(self, p):
                    return p.shape

            def run(cache: Cache, params, grads):
                cache.note(params)
                step = jax.jit(apply, donate_argnums=(0,))
                return step(params, grads)
            """
        )
        assert findings({"s.py": src}, "donation-cross-call") == []


# ------------------------------------------------------------------ #
# jit-weak-type-drift
# ------------------------------------------------------------------ #


class TestJitWeakTypeDrift:
    def test_fires_when_sites_disagree_on_literalness(self):
        src = dedent(
            """
            import jax

            @jax.jit
            def scale(x, f):
                return x * f

            def a(x):
                return scale(x, 0.5)

            def b(x, f):
                return scale(x, f)
            """
        )
        found = findings({"j.py": src}, "jit-weak-type-drift")
        assert len(found) == 1
        assert "float literal" in found[0].message
        assert found[0].severity == "warn"

    def test_quiet_when_sites_agree(self):
        src = dedent(
            """
            import jax

            @jax.jit
            def scale(x, f):
                return x * f

            def a(x, f):
                return scale(x, f)

            def b(x, g):
                return scale(x, g)
            """
        )
        assert findings({"j.py": src}, "jit-weak-type-drift") == []
