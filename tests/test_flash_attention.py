"""Pallas flash attention vs the XLA reference path (interpret mode on CPU).

Counterpart of the reference's kernel tests (``tests/cpp_extensions``): the
custom kernel must match the straightforward masked implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.ops.attention import _attention_xla
from areal_tpu.ops.pallas.flash_attention import packed_flash_attention


def _mk(rng, T, H, Hkv, D, lens):
    q = rng.normal(size=(T, H, D)).astype(np.float32)
    k = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    v = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    seg = np.zeros(T, np.int32)
    off = 0
    for i, n in enumerate(lens):
        seg[off : off + n] = i + 1
        off += n
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(seg)


@pytest.mark.parametrize("lens", [[256], [100, 156], [7, 64, 100, 85]])
def test_flash_matches_xla(rng, lens):
    T, H, Hkv, D = 256, 4, 2, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, lens)
    scale = D**-0.5
    ref = _attention_xla(q, k, v, seg, scale)
    got = packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, block_size=128
    )
    valid = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(ref)[valid], atol=2e-5, rtol=2e-5
    )


def test_flash_with_padding_and_window(rng):
    T, H, Hkv, D = 256, 2, 2, 8
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [120, 60])  # 76 pad tokens
    scale = D**-0.5
    ref = _attention_xla(q, k, v, seg, scale, sliding_window=32)
    got = packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, sliding_window=32, block_size=128
    )
    valid = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(ref)[valid], atol=2e-5, rtol=2e-5
    )


def test_flash_gradients_match(rng):
    T, H, Hkv, D = 128, 2, 1, 8
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [50, 40])
    scale = D**-0.5

    def loss_flash(q, k, v):
        o = packed_flash_attention(q, k, v, seg, softmax_scale=scale, block_size=128)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    def loss_xla(q, k, v):
        o = _attention_xla(q, k, v, seg, scale)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)
