"""Pallas flash attention vs the XLA reference path (interpret mode on CPU).

Counterpart of the reference's kernel tests (``tests/cpp_extensions``): the
custom kernel must match the straightforward masked implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.ops.attention import _attention_xla
from areal_tpu.ops.pallas import compat
from areal_tpu.ops.pallas.flash_attention import packed_flash_attention

# graceful degradation on jax API drift (docs/static_analysis.md PR 6):
# skip — not fail deep inside a kernel build — when the installed jax
# has neither CompilerParams spelling
pytestmark = pytest.mark.skipif(
    not compat.compiler_params_available(),
    reason="installed jax lacks pltpu CompilerParams/TPUCompilerParams",
)

# These kernels run in interpret mode on CPU, which costs minutes for the
# full parity sweep. Tier-1 keeps one representative per kernel feature
# (fwd parity, window, fused bwd, multiblock bwd, band narrowing,
# pipelined grads); the exhaustive sweep stays under -m slow and runs
# whenever the kernels change (`pytest tests/test_flash_attention.py`
# with no marker filter) and compiled on chip.
slow = pytest.mark.slow


def _mk(rng, T, H, Hkv, D, lens):
    q = rng.normal(size=(T, H, D)).astype(np.float32)
    k = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    v = rng.normal(size=(T, Hkv, D)).astype(np.float32)
    seg = np.zeros(T, np.int32)
    off = 0
    for i, n in enumerate(lens):
        seg[off : off + n] = i + 1
        off += n
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(seg)


@pytest.mark.parametrize(
    "lens",
    [
        [256],
        pytest.param([100, 156], marks=slow),
        pytest.param([7, 64, 100, 85], marks=slow),
    ],
)
def test_flash_matches_xla(rng, lens):
    T, H, Hkv, D = 256, 4, 2, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, lens)
    scale = D**-0.5
    ref = _attention_xla(q, k, v, seg, scale)
    got = packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, block_size=128
    )
    valid = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(ref)[valid], atol=2e-5, rtol=2e-5
    )


def test_flash_with_padding_and_window(rng):
    T, H, Hkv, D = 256, 2, 2, 8
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [120, 60])  # 76 pad tokens
    scale = D**-0.5
    ref = _attention_xla(q, k, v, seg, scale, sliding_window=32)
    got = packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, sliding_window=32, block_size=128
    )
    valid = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(ref)[valid], atol=2e-5, rtol=2e-5
    )


def test_flash_gradients_match(rng):
    T, H, Hkv, D = 128, 2, 1, 8
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [50, 40])
    scale = D**-0.5

    def loss_flash(q, k, v):
        o = packed_flash_attention(q, k, v, seg, softmax_scale=scale, block_size=128)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    def loss_xla(q, k, v):
        o = _attention_xla(q, k, v, seg, scale)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


@slow
def test_flash_pad_rows_are_zero(rng):
    """Fully-padded query rows must output exactly 0, like the XLA path
    (ADVICE round 1: finite NEG_INF made exp(s - m) == 1 on masked rows)."""
    T, H, Hkv, D = 256, 2, 2, 8
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [100])  # 156 pad tokens
    got = np.asarray(
        packed_flash_attention(q, k, v, seg, softmax_scale=D**-0.5, block_size=128)
    )
    pad = np.asarray(seg) == 0
    np.testing.assert_array_equal(got[pad], 0.0)


@pytest.mark.parametrize(
    "kwargs",
    [
        dict(),                                            # plain causal
        pytest.param(dict(sliding_window=64), marks=slow),  # windowed
        pytest.param(dict(soft_cap=20.0), marks=slow),      # soft-cap
    ],
)
def test_flash_bwd_matches_xla_multiblock(rng, kwargs):
    """Pallas backward kernels vs XLA autodiff: GQA (n_rep=3), multiple
    q/k blocks, padding, uneven segments."""
    T, H, Hkv, D = 384, 6, 2, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [100, 156, 60])  # 68 pad tokens
    scale = D**-0.5

    def loss(attn):
        def f(q, k, v):
            o = attn(q, k, v)
            w = jnp.asarray(
                np.linspace(0.5, 1.5, o.size).reshape(o.shape), jnp.float32
            )
            return jnp.sum(jnp.where((seg > 0)[:, None, None], o * w, 0.0))
        return f

    g1 = jax.grad(
        loss(lambda q, k, v: packed_flash_attention(
            q, k, v, seg, softmax_scale=scale, block_size=128, **kwargs
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        loss(lambda q, k, v: _attention_xla(q, k, v, seg, scale, **kwargs)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@slow
def test_flash_specialized_path_matches_xla(rng, monkeypatch):
    """Force the interior/boundary dual-body kernels (normally gated on
    T >= SPECIALIZE_MIN_T) at a test-sized T: fwd and bwd must match XLA,
    including blocks that are fully interior (one long segment spanning
    many blocks) and boundary blocks (segment edges, padding)."""
    from areal_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "SPECIALIZE_MIN_T", 0)
    T, H, Hkv, D = 512, 4, 2, 16
    # one long segment (interior blocks at block_size=64) + short ones + pad
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [320, 64, 100])
    scale = D**-0.5

    ref = _attention_xla(q, k, v, seg, scale)
    got = fa.packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, block_size=64
    )
    valid = (np.asarray(seg) > 0)[:, None, None]
    np.testing.assert_allclose(
        np.asarray(got) * valid, np.asarray(ref) * valid, atol=2e-5, rtol=2e-5
    )

    def loss(attn):
        def f(q, k, v):
            o = attn(q, k, v)
            return jnp.sum(jnp.where((seg > 0)[:, None, None], o * o, 0.0))
        return f

    g1 = jax.grad(
        loss(lambda q, k, v: fa.packed_flash_attention(
            q, k, v, seg, softmax_scale=scale, block_size=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        loss(lambda q, k, v: _attention_xla(q, k, v, seg, scale)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@slow
def test_flash_bwd_fallback_sweeps_match_fused(rng, monkeypatch):
    """The separate dq/dkv fallback sweeps (taken when the fused kernel's
    whole-group dq scratch exceeds FUSED_BWD_MAX_DQ_BYTES) must produce the
    same gradients as the fused path — forced here by zeroing the budget."""
    from areal_tpu.ops.pallas import flash_attention as fa

    T, H, Hkv, D = 384, 6, 2, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [100, 156, 60])
    scale = D**-0.5

    def g():
        return jax.grad(
            lambda q, k, v: jnp.sum(
                fa.packed_flash_attention(
                    q, k, v, seg, softmax_scale=scale, block_size=128
                )
                ** 2
            ),
            argnums=(0, 1, 2),
        )(q, k, v)

    fused = g()
    monkeypatch.setattr(fa, "FUSED_BWD_MAX_DQ_BYTES", 0)
    fallback = g()
    for a, b in zip(fused, fallback):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )


@pytest.mark.parametrize(
    "max_seqlen",
    [64, pytest.param(100, marks=slow), pytest.param(200, marks=slow)],
)
def test_flash_band_narrowing_matches_xla(rng, max_seqlen):
    """The static max_seqlen band hint must not change results as long as
    every segment respects the bound — fwd and bwd, multi-segment + pad."""
    T, H, Hkv, D = 512, 4, 2, 16
    lens = [100, 64, 100, 90, 37]  # all <= 100 <= max_seqlen... for 64: no
    if max_seqlen == 64:
        lens = [64, 33, 64, 50, 21]
    q, k, v, seg = _mk(rng, T, H, Hkv, D, lens)
    scale = D**-0.5
    ref = _attention_xla(q, k, v, seg, scale)
    got = packed_flash_attention(
        q, k, v, seg, softmax_scale=scale, block_size=64, max_seqlen=max_seqlen
    )
    valid = (np.asarray(seg) > 0)[:, None, None]
    np.testing.assert_allclose(
        np.asarray(got) * valid, np.asarray(ref) * valid, atol=2e-5, rtol=2e-5
    )

    g1 = jax.grad(
        lambda q, k, v: jnp.sum(
            packed_flash_attention(
                q, k, v, seg, softmax_scale=scale, block_size=64,
                max_seqlen=max_seqlen,
            )
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(_attention_xla(q, k, v, seg, scale) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


def test_band_violation_caught_under_debug_checks(rng, monkeypatch):
    """AREAL_DEBUG_CHECKS=1 turns the silent over-band truncation into an
    error: a segment longer than the static max_seqlen hint must raise
    instead of returning truncated attention (advisor round-2 finding)."""
    monkeypatch.setenv("AREAL_DEBUG_CHECKS", "1")
    T, H, Hkv, D = 256, 2, 2, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [200, 40])  # 200 > 128 bound
    with pytest.raises(Exception, match="max_seqlen"):
        out = packed_flash_attention(
            q, k, v, seg, softmax_scale=D**-0.5, block_size=64, max_seqlen=128
        )
        jax.block_until_ready(out)
    # respecting the bound stays silent
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [100, 40])
    out = packed_flash_attention(
        q, k, v, seg, softmax_scale=D**-0.5, block_size=64, max_seqlen=128
    )
    jax.block_until_ready(out)


def test_engine_rejects_overlong_sequence():
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    cfg = ModelConfig(
        n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8, hidden_dim=16,
        intermediate_dim=32, vocab_size=64, dtype="float32",
        attn_max_seqlen=16,
    )
    eng = TrainEngine(cfg, ParallelConfig(), OptimizerConfig(lr=1e-3))
    eng.init_random(0)
    eng.setup_optimizer(10)
    sample = SequenceSample.from_default(
        ids=[0], seqlens=[24],
        data={"packed_input_ids": np.zeros(24, np.int64)},
    )
    with pytest.raises(ValueError, match="attn_max_seqlen"):
        eng.train_batch(
            sample, MicroBatchSpec(n_mbs=1, max_tokens_per_mb=64),
            lambda p, c, a: (jnp.float32(0), {}),
        )


@pytest.mark.parametrize("gqa", [False, pytest.param(True, marks=slow)])
@pytest.mark.parametrize("banded", [False, pytest.param(True, marks=slow)])
def test_flash_gradients_match_pipelined(rng, monkeypatch, gqa, banded):
    """Cross-block software-pipelined fused backward (round 5): parking
    (p, ds) one grid step must be numerically IDENTICAL to the in-step
    dots, across the triangle (banded=False) and band (max_seqlen) kernels
    and with GQA rep folding."""
    monkeypatch.setenv("AREAL_FLASH_BWD_PIPELINE", "1")
    T, H, Hkv, D = 256, 4, 2 if gqa else 4, 16
    q, k, v, seg = _mk(rng, T, H, Hkv, D, [100, 120])
    scale = D**-0.5
    kwargs = dict(softmax_scale=scale, block_size=128)
    if banded:
        kwargs["max_seqlen"] = 128

    def loss_flash(q, k, v):
        o = packed_flash_attention(q, k, v, seg, **kwargs)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    def loss_xla(q, k, v):
        o = _attention_xla(q, k, v, seg, scale)
        return jnp.sum(jnp.where((seg > 0)[:, None, None], o, 0.0) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )
