"""xplane trace analyzer (VERDICT r4 #9): a real jax.profiler trace is
captured and classified into the reference's device-time buckets
(``realhf/base/monitor.py:404-610``: compute / p2p_comm / coll_comm /
memoryIO / idle / misc) via jaxlib's ProfileData reader."""

import json
import os

import pytest

from areal_tpu.base.trace_analyzer import (
    BUCKETS,
    TraceAnalyzerUnavailable,
    analyze_xspace,
    classify,
    find_xplane_files,
    profile_data_available,
    summarize_latest,
)

# jax version drift: older/newer jaxlib builds may not ship the
# ProfileData XSpace reader at all — everything that parses a trace
# skips (classification tables and the graceful-degradation paths still
# run everywhere).
needs_profile_data = pytest.mark.skipif(
    not profile_data_available(),
    reason="jax.profiler.ProfileData not available in this jax build",
)


def test_classify_tables():
    assert classify("fusion.123", "convolution") == "compute"
    assert classify("all-reduce.5") == "coll_comm"
    assert classify("fusion.2", "all-reduce fusion") == "coll_comm"
    assert classify("collective-permute.1") == "p2p_comm"
    assert classify("copy.3") == "memoryIO"
    assert classify("dynamic-update-slice.9") == "memoryIO"
    assert classify("custom-call.pallas") == "compute"


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path_factory.mktemp("trc"))
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()  # compile OUTSIDE the trace window
    with jax.profiler.trace(d):
        for _ in range(3):
            x = f(x)
        x.block_until_ready()
    return d


@needs_profile_data
def test_analyze_real_trace(trace_dir):
    files = find_xplane_files(trace_dir)
    assert files, "profiler produced no xplane file"
    summaries = analyze_xspace(files[0])
    assert summaries, "no device/op plane found"
    s = summaries[0]
    assert s.n_events > 0
    assert s.device_total_s > 0
    # the matmul dominates compute
    assert s.buckets_s["compute"] > 0
    names = [n for n, *_ in s.top_ops]
    assert any("dot" in n for n in names), names
    # buckets are exhaustive: their sum is the device total
    assert abs(sum(s.buckets_s.values()) - s.device_total_s) < 1e-9
    d = s.as_dict()
    assert set(d["buckets_pct"]) == set(BUCKETS)


@needs_profile_data
def test_summarize_latest_and_cli(trace_dir, capsys):
    s = summarize_latest(trace_dir)
    assert s and s["planes"]

    from areal_tpu.apps.trace_analyze import main

    assert main([trace_dir, "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "compute" in out and "idle" in out

    assert main([trace_dir, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed[0]["device_total_s"] > 0


def test_cli_no_trace(tmp_path, capsys):
    from areal_tpu.apps.trace_analyze import main

    assert main([str(tmp_path)]) == 1


def test_unavailable_degrades_gracefully(tmp_path, monkeypatch, capsys):
    """jax builds without ProfileData: parsing raises the typed error,
    summarize_latest degrades to None (bench sections keep running), and
    the CLI reports instead of crashing with AttributeError."""
    from areal_tpu.base import trace_analyzer as ta

    def _unavailable():
        raise TraceAnalyzerUnavailable("no ProfileData in this build")

    monkeypatch.setattr(ta, "_profile_data", _unavailable)
    d = tmp_path / "plugins" / "profile" / "run0"
    d.mkdir(parents=True)
    f = d / "host.xplane.pb"
    f.write_bytes(b"")
    assert ta.summarize_latest(str(tmp_path)) is None
    with pytest.raises(TraceAnalyzerUnavailable):
        ta.analyze_xspace(str(f))

    from areal_tpu.apps.trace_analyze import main

    assert main([str(tmp_path)]) == 1
    assert "ProfileData" in capsys.readouterr().err


@needs_profile_data
def test_tpu_plane_counts_only_op_lines():
    """Review finding r5: a real TPU device plane carries 'XLA Modules' /
    'Steps' lines spanning the SAME wall time as the op line — only the op
    line may contribute to device_total_s."""
    import jax.profiler as jp

    from areal_tpu.base.trace_analyzer import analyze_profile_data

    txt = """
planes {
  name: "/device:TPU:0"
  lines {
    id: 1 name: "XLA Ops"
    events { metadata_id: 1 offset_ps: 0 duration_ps: 1000000 }
    events { metadata_id: 2 offset_ps: 1000000 duration_ps: 500000 }
  }
  lines {
    id: 2 name: "XLA Modules"
    events { metadata_id: 3 offset_ps: 0 duration_ps: 1500000 }
  }
  lines {
    id: 3 name: "Steps"
    events { metadata_id: 4 offset_ps: 0 duration_ps: 1500000 }
  }
  event_metadata { key: 1 value { id: 1 name: "fusion.1" } }
  event_metadata { key: 2 value { id: 2 name: "all-reduce.2" } }
  event_metadata { key: 3 value { id: 3 name: "jit_train_step" } }
  event_metadata { key: 4 value { id: 4 name: "train_step" } }
}
"""
    (s,) = analyze_profile_data(jp.ProfileData.from_text_proto(txt))
    # 1.0 us fusion + 0.5 us all-reduce; module/step spans NOT re-counted
    assert abs(s.device_total_s - 1.5e-6) < 1e-12
    assert abs(s.buckets_s["compute"] - 1.0e-6) < 1e-12
    assert abs(s.buckets_s["coll_comm"] - 0.5e-6) < 1e-12
    assert s.n_events == 2
    names = [n for n, *_ in s.top_ops]
    assert "jit_train_step" not in names and "train_step" not in names


@needs_profile_data
def test_cli_compare(trace_dir, capsys):
    from areal_tpu.apps.trace_analyze import main

    assert main([trace_dir, "--compare", trace_dir, "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "B/A" in out and "device" in out
    # identical traces compare at ratio 1.000
    assert "  1.000" in out
