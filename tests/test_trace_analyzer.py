"""xplane trace analyzer (VERDICT r4 #9): a real jax.profiler trace is
captured and classified into the reference's device-time buckets
(``realhf/base/monitor.py:404-610``: compute / p2p_comm / coll_comm /
memoryIO / idle / misc) via jaxlib's ProfileData reader."""

import json
import os

import pytest

from areal_tpu.base.trace_analyzer import (
    BUCKETS,
    analyze_xspace,
    classify,
    find_xplane_files,
    summarize_latest,
)


def test_classify_tables():
    assert classify("fusion.123", "convolution") == "compute"
    assert classify("all-reduce.5") == "coll_comm"
    assert classify("fusion.2", "all-reduce fusion") == "coll_comm"
    assert classify("collective-permute.1") == "p2p_comm"
    assert classify("copy.3") == "memoryIO"
    assert classify("dynamic-update-slice.9") == "memoryIO"
    assert classify("custom-call.pallas") == "compute"


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path_factory.mktemp("trc"))
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()  # compile OUTSIDE the trace window
    with jax.profiler.trace(d):
        for _ in range(3):
            x = f(x)
        x.block_until_ready()
    return d


def test_analyze_real_trace(trace_dir):
    files = find_xplane_files(trace_dir)
    assert files, "profiler produced no xplane file"
    summaries = analyze_xspace(files[0])
    assert summaries, "no device/op plane found"
    s = summaries[0]
    assert s.n_events > 0
    assert s.device_total_s > 0
    # the matmul dominates compute
    assert s.buckets_s["compute"] > 0
    names = [n for n, *_ in s.top_ops]
    assert any("dot" in n for n in names), names
    # buckets are exhaustive: their sum is the device total
    assert abs(sum(s.buckets_s.values()) - s.device_total_s) < 1e-9
    d = s.as_dict()
    assert set(d["buckets_pct"]) == set(BUCKETS)


def test_summarize_latest_and_cli(trace_dir, capsys):
    s = summarize_latest(trace_dir)
    assert s and s["planes"]

    from areal_tpu.apps.trace_analyze import main

    assert main([trace_dir, "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "compute" in out and "idle" in out

    assert main([trace_dir, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed[0]["device_total_s"] > 0


def test_cli_no_trace(tmp_path, capsys):
    from areal_tpu.apps.trace_analyze import main

    assert main([str(tmp_path)]) == 1
