"""Dataflow-graph (MFC) layer: build/validate/level-order + executor.

Counterpart of the reference's DFG tests (``realhf/api/core/dfg.py:238``
build path + ``realhf/system/function_executor.py`` traversal): algorithms
are declared graphs, and critic on/off + EMA-ref are pure config changes.
"""

import numpy as np
import pytest

import jax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.dfg import MFCDef, ParamReallocHook, build_graph
from areal_tpu.api.model import PPOHyperparameters
from areal_tpu.experiments.graphs import ROLLOUT_BATCH_KEYS, build_ppo_graph
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.system.function_executor import FunctionExecutor
from areal_tpu.train.engine import OptimizerConfig, TrainEngine

TINY = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


def _mfc(name, model="m", itype="inference", ins=(), outs=()):
    return MFCDef(
        name=name, model_name=model, interface_type=itype,
        input_keys=tuple(ins), output_keys=tuple(outs),
    )


class TestBuildGraph:
    def test_level_order_from_key_deps(self):
        g = build_graph(
            [
                _mfc("train", itype="train_step", ins=("ids", "adv")),
                _mfc("inf_a", ins=("ids",), outs=("lp",)),
                _mfc("inf_b", ins=("ids", "lp"), outs=("adv",)),
            ],
            batch_keys=("ids",),
        )
        assert [m.name for level in g.levels for m in level] == [
            "inf_a", "inf_b", "train"
        ]
        assert g.producers == {"lp": "inf_a", "adv": "inf_b"}

    def test_missing_input_raises(self):
        with pytest.raises(ValueError, match="needs key 'adv'"):
            build_graph([_mfc("t", ins=("adv",))], batch_keys=("ids",))

    def test_duplicate_producer_raises(self):
        with pytest.raises(ValueError, match="produced by both"):
            build_graph(
                [_mfc("a", outs=("x",)), _mfc("b", outs=("x",))],
                batch_keys=(),
            )

    def test_cycle_raises(self):
        with pytest.raises(ValueError, match="cycle"):
            build_graph(
                [_mfc("a", ins=("y",), outs=("x",)), _mfc("b", ins=("x",), outs=("y",))],
                batch_keys=(),
            )

    def test_bad_interface_type_raises(self):
        with pytest.raises(ValueError, match="interface_type"):
            MFCDef(name="x", model_name="m", interface_type="trane_step")


class TestPPOGraph:
    def test_grpo_minimal(self):
        # critic-free, no ref model: 2 nodes only
        g, ifaces = build_ppo_graph(
            PPOHyperparameters(disable_value=True), use_ref=False, use_critic=False
        )
        assert g.names == ["actor_inf", "actor_train"]
        assert ifaces["actor_inf"] is ifaces["actor_train"]  # one KL state

    def test_full_ppo_levels(self):
        g, ifaces = build_ppo_graph(
            PPOHyperparameters(), use_ref=True, use_critic=True
        )
        level_names = [[m.name for m in lvl] for lvl in g.levels]
        assert level_names == [
            ["actor_inf", "critic_inf", "ref_inf"],
            ["actor_train", "critic_train"],
        ]
        # critic shares the actor's KL controller
        assert ifaces["critic_train"].kl_ctl is ifaces["actor_train"].kl_ctl

    def test_ema_ref_is_config(self):
        g, _ = build_ppo_graph(
            PPOHyperparameters(), use_ref=True, use_critic=False, ema_ref_eta=0.3
        )
        (hook,) = next(m for m in g.mfcs if m.name == "actor_train").post_hooks
        assert hook == ParamReallocHook(source="actor", target="ref", eta=0.3)
        with pytest.raises(ValueError, match="EMA reference requires"):
            build_ppo_graph(
                PPOHyperparameters(), use_ref=False, use_critic=False,
                ema_ref_eta=0.3,
            )


def _ppo_sample(rng, n=6):
    lens = [int(x) for x in rng.integers(6, 12, size=n)]
    lps = []
    for ln in lens:
        lp = np.zeros(ln, np.float32)
        lp[2:] = -1.0
        lps.append(lp)
    return SequenceSample.from_default(
        ids=list(range(n)), seqlens=lens,
        data={
            "packed_input_ids": rng.integers(0, 128, sum(lens)).astype(np.int64),
            "prompt_mask": np.concatenate(
                [np.r_[np.ones(2, bool), np.zeros(ln - 2, bool)] for ln in lens]
            ),
            "packed_logprobs": np.concatenate(lps),
            "packed_ref_logprobs": np.concatenate(lps) * 0.95,
            "rewards": rng.normal(size=n).astype(np.float32),
            "seq_no_eos_mask": np.zeros(n, bool),
        },
    )


@pytest.fixture(scope="module")
def engines():
    par = ParallelConfig(data=2, fsdp=2, model=2)
    actor = TrainEngine(TINY, par, OptimizerConfig(lr=1e-3)).init_random(0)
    actor.setup_optimizer(total_train_steps=20)
    ref = TrainEngine(TINY, par).init_random(1)
    return actor, ref


class TestExecutor:
    def test_graph_driven_ppo_step(self, engines, rng):
        actor, ref = engines
        hp = PPOHyperparameters(disable_value=True)
        g, ifaces = build_ppo_graph(hp, use_ref=True, use_critic=False)
        ex = FunctionExecutor(
            g, {"actor": actor, "ref": ref}, ifaces,
            default_mb_spec=MicroBatchSpec(),
        )
        sample = _ppo_sample(rng)
        stats = ex.run(sample)
        assert np.isfinite(stats["actor_loss"])
        # the graph's inference nodes attached their keys to the batch
        assert "prox_logp" in sample.keys
        assert "packed_ref_logprobs" in sample.keys

    def test_ema_hook_moves_ref_toward_actor(self, engines, rng):
        actor, ref = engines
        hp = PPOHyperparameters(disable_value=True)
        g, ifaces = build_ppo_graph(
            hp, use_ref=True, use_critic=False, ema_ref_eta=0.5
        )
        ex = FunctionExecutor(
            g, {"actor": actor, "ref": ref}, ifaces,
            default_mb_spec=MicroBatchSpec(),
        )
        a0 = np.asarray(jax.tree.leaves(actor.params)[0])
        r0 = np.asarray(jax.tree.leaves(ref.params)[0])
        ex.run(_ppo_sample(rng))
        a1 = np.asarray(jax.tree.leaves(actor.params)[0])
        r1 = np.asarray(jax.tree.leaves(ref.params)[0])
        np.testing.assert_allclose(r1, 0.5 * r0 + 0.5 * a1, atol=1e-5)

    def test_undeclared_output_raises(self, engines, rng):
        actor, ref = engines
        mfc = MFCDef(
            name="inf", model_name="actor", interface_type="inference",
            interface_impl="ppo_actor",
            input_keys=("packed_input_ids",),
            output_keys=("nonexistent_key",),
        )
        g = build_graph([mfc], batch_keys=ROLLOUT_BATCH_KEYS)
        ex = FunctionExecutor(g, {"actor": actor}, default_mb_spec=MicroBatchSpec())
        with pytest.raises(ValueError, match="declared outputs"):
            ex.run(_ppo_sample(rng))
