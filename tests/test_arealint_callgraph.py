"""Tier-1 tests for the arealint project indexer + call graph
(tools/arealint/project.py, callgraph.py).

The fixture package exercises exactly the resolution features
docs/static_analysis.md guarantees: relative imports, ``import as``
aliasing, re-exports through ``__init__.py``, class methods with base
classes, constructor-typed locals, and an import cycle — plus the
degradation contract: an edge the index cannot follow produces NO edge
and NO finding, never a false positive.
"""

import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.arealint import scan_sources  # noqa: E402
from tools.arealint.callgraph import (  # noqa: E402
    build_call_graph, thread_context,
)
from tools.arealint.project import Project  # noqa: E402

pytestmark = pytest.mark.arealint


def dedent(s):
    return textwrap.dedent(s).lstrip()


# The fixture package: pkg/{__init__,core,util,alias_user,cyc_a,cyc_b}.py
FIXTURE = {
    "pkg/__init__.py": dedent(
        """
        from pkg.core import Engine, run_step
        from pkg.util import helper as exported_helper
        """
    ),
    "pkg/core.py": dedent(
        """
        from . import util
        from .util import helper, helper as h2

        class Base:
            def shared(self):
                return util.leaf()

        class Engine(Base):
            def step(self, x):
                self.prep(x)
                return helper(x)

            def prep(self, x):
                return h2(x)

            def dyn(self, x):
                return x.whatever()       # unresolvable: no edge

        def run_step(e, x):
            eng = Engine()
            eng.step(x)
            e.step(x)                     # untyped param: no edge
            return external_lib.call(x)   # unresolvable: no edge
        """
    ),
    "pkg/util.py": dedent(
        """
        def helper(x):
            return leaf()

        def leaf():
            return 1
        """
    ),
    "pkg/alias_user.py": dedent(
        """
        import pkg.util as u
        from pkg import exported_helper

        def use_alias(x):
            u.helper(x)
            exported_helper(x)
        """
    ),
    "pkg/cyc_a.py": dedent(
        """
        from pkg import cyc_b

        def ping(n):
            return cyc_b.pong(n)
        """
    ),
    "pkg/cyc_b.py": dedent(
        """
        from pkg import cyc_a

        def pong(n):
            return cyc_a.ping(n - 1)
        """
    ),
}


@pytest.fixture(scope="module")
def graph():
    proj = Project.from_sources(FIXTURE)
    return build_call_graph(proj)


class TestResolution:
    def test_module_names_and_index(self, graph):
        proj = graph.project
        assert set(proj.modules) == {
            "pkg", "pkg.core", "pkg.util", "pkg.alias_user",
            "pkg.cyc_a", "pkg.cyc_b",
        }
        assert proj.function("pkg.util.helper") is not None
        assert proj.function("pkg.core.Engine.step") is not None

    def test_relative_and_from_imports(self, graph):
        # core.Engine.step -> util.helper via ``from .util import helper``
        assert "pkg.util.helper" in graph.edges["pkg.core.Engine.step"]
        # core.Engine.prep -> util.helper via the ``as h2`` alias
        assert "pkg.util.helper" in graph.edges["pkg.core.Engine.prep"]
        # Base.shared -> util.leaf via ``from . import util``
        assert "pkg.util.leaf" in graph.edges["pkg.core.Base.shared"]

    def test_self_method_edges(self, graph):
        assert "pkg.core.Engine.prep" in graph.edges["pkg.core.Engine.step"]

    def test_import_as_module_alias(self, graph):
        # ``import pkg.util as u`` then ``u.helper(x)``
        assert "pkg.util.helper" in graph.edges["pkg.alias_user.use_alias"]

    def test_reexport_through_init(self, graph):
        # ``from pkg import exported_helper`` follows the __init__ alias
        # chain back to pkg.util.helper
        assert "pkg.util.helper" in graph.edges["pkg.alias_user.use_alias"]
        assert graph.project.resolve("pkg.exported_helper") == (
            "pkg.util.helper"
        )
        assert graph.project.resolve("pkg.Engine") == "pkg.core.Engine"

    def test_constructor_typed_local(self, graph):
        # ``eng = Engine(); eng.step(x)`` resolves through the local type
        assert "pkg.core.Engine.step" in graph.edges["pkg.core.run_step"]

    def test_import_cycle_resolves_without_hanging(self, graph):
        assert "pkg.cyc_b.pong" in graph.edges["pkg.cyc_a.ping"]
        assert "pkg.cyc_a.ping" in graph.edges["pkg.cyc_b.pong"]
        # reachability across the cycle terminates
        reach = graph.reachable(["pkg.cyc_a.ping"])
        assert {"pkg.cyc_a.ping", "pkg.cyc_b.pong"} <= reach

    def test_beyond_top_relative_import_degrades(self):
        # ``from .. import util`` in the ROOT package walks past the top
        # of the tree (ImportError at runtime) — it must not bind, and
        # calls through it must not fabricate edges
        proj = Project.from_sources({
            "pkg/__init__.py": "from .. import util\n",
            "pkg/util.py": "def f():\n    return 1\n",
            "pkg/user.py": dedent(
                """
                from pkg import util

                def g():
                    return util.f()
                """
            ),
        })
        assert "util" not in proj.modules["pkg"].imports
        # the legitimate import in user.py still resolves
        g = build_call_graph(proj)
        assert "pkg.util.f" in g.edges["pkg.user.g"]

    def test_unresolvable_degrades_to_no_edge(self, graph):
        edges = graph.edges.get("pkg.core.run_step", set())
        # external_lib.call and the untyped e.step produce no edges
        assert not any("external_lib" in e for e in edges)
        unresolved = graph.unresolved.get("pkg.core.run_step", set())
        assert "external_lib.call" in unresolved
        # dynamic attribute call: no edge from dyn
        assert "pkg.core.Engine.dyn" not in graph.edges or not any(
            "whatever" in e for e in graph.edges["pkg.core.Engine.dyn"]
        )


class TestRootInference:
    def test_sibling_prefix_dirs_share_one_root(self, tmp_path):
        """/x/foo and /x/foobar must anchor at /x — a string-prefix
        common-parent would pick /x/foo and silently break every
        cross-package edge."""
        for rel, src in {
            "foo/__init__.py": "",
            "foo/a.py": "from foobar.b import f\ndef g(x):\n    return f(x)\n",
            "foobar/__init__.py": "",
            "foobar/b.py": "def f(x):\n    return x\n",
        }.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        proj = Project.from_paths([str(tmp_path)])
        assert proj.resolve("foobar.b.f") == "foobar.b.f"
        g = build_call_graph(proj)
        assert "foobar.b.f" in g.edges.get("foo.a.g", set())


class TestDegradationNoFindings:
    def test_unresolvable_hot_path_stays_quiet(self):
        """A hot root whose callee cannot be resolved produces NO
        cross-module finding — unresolved edges degrade, they do not
        guess."""
        srcs = {
            "a.py": dedent(
                """
                import jax
                from vendor_lib import mystery

                def step(x):  # arealint: hot
                    return mystery(x)
                """
            ),
            "b.py": dedent(
                """
                import jax

                def mystery(x):
                    return jax.device_get(x)
                """
            ),
        }
        # b.mystery is NOT what a.step calls (a imports vendor_lib's), so
        # no cross-module finding may appear
        found = [
            f for f in scan_sources(srcs)
            if f.rule == "host-sync-cross-module"
        ]
        assert found == []

    def test_resolvable_version_fires(self):
        srcs = {
            "a.py": dedent(
                """
                import jax
                from b import mystery

                def step(x):  # arealint: hot
                    return mystery(x)
                """
            ),
            "b.py": dedent(
                """
                import jax

                def mystery(x):
                    return jax.device_get(x)
                """
            ),
        }
        found = [
            f for f in scan_sources(srcs)
            if f.rule == "host-sync-cross-module"
        ]
        assert len(found) == 1 and found[0].path == "b.py"


class TestThreadContext:
    def test_thread_target_closure(self):
        srcs = {
            "w.py": dedent(
                """
                import threading

                class Worker:
                    def start(self):
                        self._t = threading.Thread(target=self._loop)
                        self._t.start()

                    def _loop(self):
                        tick()

                def tick():
                    pass

                async def consume():
                    pass
                """
            ),
        }
        proj = Project.from_sources(srcs)
        g = build_call_graph(proj)
        assert g.thread_entries == {"w.Worker._loop"}
        ctx = thread_context(g)
        assert "w.tick" in ctx
        assert "w.consume" not in ctx

    def test_local_def_target(self):
        srcs = {
            "l.py": dedent(
                """
                import threading

                def spawn():
                    def runner():
                        work()
                    t = threading.Thread(target=runner)
                    t.start()

                def work():
                    pass
                """
            ),
        }
        g = build_call_graph(Project.from_sources(srcs))
        assert any(".<local>.runner" in e for e in g.thread_entries)
        assert "l.work" in thread_context(g)
