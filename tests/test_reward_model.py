"""Reward-model path: paired dataset, Bradley-Terry training, RM-scored PPO.

Counterpart of the reference's paired reward modeling
(``realhf/impl/dataset/rw_paired_dataset.py`` + the RM half of its reward
interfaces). The e2e check is VERDICT's bar: train a tiny RM on synthetic
pairs where "good" answers share a token signature, then use it to score
rollouts inside the PPO graph.
"""

import json

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.dataset import DatasetUtility
from areal_tpu.api.model import PPOHyperparameters, make_interface
from areal_tpu.datasets.rw_paired import RewardPairedDataset
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel.mesh import ParallelConfig
from areal_tpu.train.engine import OptimizerConfig, TrainEngine

TINY_RM = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32", is_critic=True,
    use_attention_bias=True,  # qwen2-family surface (HF round-trip test)
)

GOOD_TOKEN, BAD_TOKEN = 7, 13


def _write_pairs(path, n=24, seed=0):
    """Synthetic preference data: positives end with GOOD_TOKEN runs,
    negatives with BAD_TOKEN runs — a signature a tiny RM can learn."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n):
            prompt = [int(x) for x in rng.integers(20, 120, 4)]
            pos = [prompt + [GOOD_TOKEN] * int(rng.integers(3, 6)) for _ in range(2)]
            neg = [prompt + [BAD_TOKEN] * int(rng.integers(3, 6)) for _ in range(2)]
            f.write(json.dumps({
                "qid": f"p{i}", "prompt_ids": prompt,
                "pos_answer_ids": pos, "neg_answer_ids": neg,
            }) + "\n")


@pytest.fixture(scope="module")
def rw_dataset(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("rw") / "pairs.jsonl")
    _write_pairs(path)
    util = DatasetUtility(seed=1, dp_rank=0, world_size=1, tokenizer=None)
    return RewardPairedDataset(util, path)


class TestDataset:
    def test_pair_layout(self, rw_dataset):
        s = rw_dataset[0]
        assert s.keys == {"packed_input_ids", "pair_id", "pair_sign"}
        n = len(s.seqlens["packed_input_ids"][0])
        assert n == 4  # 2 pairs -> [pos0, neg0, pos1, neg1]
        np.testing.assert_array_equal(s.data["pair_sign"], [1, -1, 1, -1])
        np.testing.assert_array_equal(s.data["pair_id"], [0, 0, 1, 1])

    def test_pair_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "bad.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({
                "qid": "x", "prompt_ids": [1],
                "pos_answer_ids": [[1, 2]], "neg_answer_ids": [],
            }) + "\n")
        util = DatasetUtility(seed=1, dp_rank=0, world_size=1, tokenizer=None)
        with pytest.raises(ValueError, match="one-to-one"):
            RewardPairedDataset(util, path)


@pytest.fixture(scope="module")
def trained_rm(rw_dataset):
    eng = TrainEngine(
        TINY_RM, ParallelConfig(data=2, fsdp=2, model=2),
        OptimizerConfig(lr=3e-3),
    )
    eng.init_random(0)
    eng.setup_optimizer(total_train_steps=40)
    iface = make_interface("reward")
    stats = None
    for epoch in range(6):
        for lo in range(0, len(rw_dataset), 8):
            batch = SequenceSample.gather(
                [rw_dataset[i] for i in range(lo, min(lo + 8, len(rw_dataset)))]
            )
            stats = iface.train_step(eng, batch, MicroBatchSpec())
    return eng, iface, stats


class TestRMTraining:
    def test_bt_loss_learns_preference(self, trained_rm):
        _, _, stats = trained_rm
        assert stats["rw_acc"] > 0.9          # separates pos from neg
        assert stats["score_diff"] > 0        # s_pos > s_neg on average
        assert np.isfinite(stats["rw_loss"])

    def test_scoring_ranks_held_out(self, trained_rm):
        eng, iface, _ = trained_rm
        # held-out prompt, one good and one bad answer (grouped sample)
        seqs = [[50, 60, GOOD_TOKEN] * 2, [50, 60, BAD_TOKEN] * 2]
        lens = [len(s) for s in seqs]
        sample = SequenceSample(
            keys={"packed_input_ids"},
            ids=["h"],
            seqlens={"packed_input_ids": [lens]},
            data={"packed_input_ids": np.concatenate(
                [np.asarray(s, np.int64) for s in seqs]
            )},
        )
        out = iface.inference(eng, sample, MicroBatchSpec())
        scores = out.data["rewards"]
        assert out.seqlens["rewards"] == [[1, 1]]
        assert scores[0] > scores[1]          # good beats bad


class TestRMScoredPPO:
    def test_reward_inf_node_feeds_ppo(self, trained_rm, rng):
        """The PPO graph's reward_inf node scores rollouts with the trained
        RM — RM rewards supersede the rollout's rule-based ones."""
        from areal_tpu.experiments.graphs import build_ppo_graph
        from areal_tpu.system.function_executor import FunctionExecutor

        rm_engine, _, _ = trained_rm
        actor_cfg = ModelConfig(
            n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
            intermediate_dim=64, vocab_size=128, dtype="float32",
        )
        actor = TrainEngine(
            actor_cfg, ParallelConfig(data=2, fsdp=2, model=2),
            OptimizerConfig(lr=1e-4),
        )
        actor.init_random(1)
        actor.setup_optimizer(total_train_steps=10)

        hp = PPOHyperparameters(disable_value=True, kl_ctl=0.0)
        g, ifaces = build_ppo_graph(
            hp, use_ref=False, use_critic=False, use_reward_model=True,
        )
        assert g.names[0] == "reward_inf"
        assert g.producers["rewards"] == "reward_inf"
        ex = FunctionExecutor(
            g, {"actor": actor, "reward": rm_engine}, ifaces,
            default_mb_spec=MicroBatchSpec(),
        )
        # grouped rollout sample: one good + one bad continuation
        seqs = [
            [30, 40, GOOD_TOKEN, GOOD_TOKEN, GOOD_TOKEN],
            [30, 40, BAD_TOKEN, BAD_TOKEN, BAD_TOKEN],
        ]
        lens = [len(s) for s in seqs]
        lp = np.zeros(sum(lens), np.float32)
        sample = SequenceSample(
            keys={"packed_input_ids", "prompt_mask", "packed_logprobs",
                  "seq_no_eos_mask"},
            ids=["q"],
            seqlens={
                "packed_input_ids": [lens], "prompt_mask": [lens],
                "packed_logprobs": [lens], "seq_no_eos_mask": [[1, 1]],
            },
            data={
                "packed_input_ids": np.concatenate(
                    [np.asarray(s, np.int64) for s in seqs]
                ),
                "prompt_mask": np.concatenate(
                    [np.r_[np.ones(2, bool), np.zeros(ln - 2, bool)]
                     for ln in lens]
                ),
                "packed_logprobs": lp,
                "seq_no_eos_mask": np.zeros(2, bool),
            },
        )
        stats = ex.run(sample)
        assert np.isfinite(stats["actor_loss"])
        # the RM's scores were attached and favor the good continuation
        rewards = sample.data["rewards"]
        assert rewards[0] > rewards[1]


def test_rw_experiment_e2e(tmp_path):
    """Launcher-level RM training run: loss drops, HF export lands."""
    from areal_tpu.apps import launcher
    from areal_tpu.experiments import RWExperiment, load_config

    data = str(tmp_path / "pairs.jsonl")
    _write_pairs(data, n=16)
    arch = dict(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, dtype="float32",
    )
    cfg = load_config(RWExperiment, None, [
        "experiment_name=rw-test",
        "trial_name=t0",
        f"fileroot={tmp_path}/root",
        f"dataset.path={data}",
        "dataset.name=rw_paired",
        "batch_size=8",
        "max_tokens_per_mb=512",
        "control.total_train_steps=6",
        "control.save_freq_steps=6",
        "model.parallel=d2m1",
        f"model.arch={json.dumps(arch)}",
        "model.optimizer.lr=0.003",
    ])
    assert launcher.run_rw(cfg) == 0
    import os

    metrics = os.path.join(f"{tmp_path}/root", "logs", "rw-test", "t0",
                           "metrics.jsonl")
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 6
    assert lines[-1]["reward/rw_loss"] < lines[0]["reward/rw_loss"]
    save_dir = os.path.join(f"{tmp_path}/root", "checkpoints", "rw-test",
                            "t0", "step6")
    assert os.path.exists(os.path.join(save_dir, "model.safetensors"))

def test_critic_checkpoint_roundtrips_value_head(tmp_path):
    """Critic/RM HF exports keep their trained scalar head (score.weight +
    is_critic marker); reloading from DISK preserves scores exactly — the
    RM-scored-PPO workflow depends on this round trip."""
    import jax

    from areal_tpu.models import hf as hf_conv, transformer as tfm

    params = tfm.init_params(TINY_RM, jax.random.key(3))
    host = jax.tree.map(lambda x: np.asarray(x), params)
    path = str(tmp_path / "rm")
    hf_conv.save_hf_checkpoint(host, TINY_RM, "qwen2", path)
    cfg2, loaded = hf_conv.load_hf_checkpoint(path)
    assert cfg2.is_critic
    np.testing.assert_allclose(
        loaded["head"]["weight"], host["head"]["weight"], atol=1e-7
    )
    ids = np.arange(1, 9, dtype=np.int32)
    v1 = tfm.forward_packed(
        params, TINY_RM, ids, np.ones(8, np.int32), np.arange(8, dtype=np.int32)
    )
    v2 = tfm.forward_packed(
        jax.tree.map(np.asarray, loaded), TINY_RM, ids,
        np.ones(8, np.int32), np.arange(8, dtype=np.int32)
    )
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-6)


def test_load_hf_init_critic_head_keeps_trained_head(tmp_path):
    """Review regression: _load_engine(is_critic=True) -> load_hf(
    init_critic_head=True) must NOT re-randomize a checkpoint that already
    carries a trained value head (RM-scored PPO would score with noise)."""
    import jax

    from areal_tpu.models import hf as hf_conv, transformer as tfm

    params = tfm.init_params(TINY_RM, jax.random.key(3))
    host = jax.tree.map(lambda x: np.asarray(x), params)
    path = str(tmp_path / "rm")
    hf_conv.save_hf_checkpoint(host, TINY_RM, "qwen2", path)
    eng = TrainEngine(TINY_RM, ParallelConfig())
    eng.load_hf(path, init_critic_head=True)
    np.testing.assert_allclose(
        np.asarray(eng.params["head"]["weight"]), host["head"]["weight"],
        atol=1e-7,
    )
