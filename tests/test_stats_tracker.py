"""≈ reference ``tests/data/test_stats_tracker.py``."""

import numpy as np
import pytest

from areal_tpu.base.stats_tracker import DistributedStatsTracker, ReduceType


def test_masked_avg():
    t = DistributedStatsTracker()
    mask = np.array([1, 1, 0, 0], dtype=bool)
    vals = np.array([1.0, 3.0, 100.0, 100.0], dtype=np.float32)
    t.denominator(mask=mask)
    t.stat("mask", loss=vals)
    out = t.export()
    assert out["loss"] == pytest.approx(2.0)
    assert out["mask/n"] == 2


def test_scopes_and_reduce_types():
    t = DistributedStatsTracker()
    with t.scope("actor"):
        m = np.ones(3, dtype=bool)
        t.denominator(n_tokens=m)
        t.stat("n_tokens", reduce_type=ReduceType.SUM, x=np.array([1.0, 2, 3]))
        t.stat("n_tokens", reduce_type=ReduceType.MAX, y=np.array([1.0, 5, 3]))
        t.stat("n_tokens", reduce_type=ReduceType.MIN, z=np.array([1.0, 5, -3]))
    t.scalar(lr=0.1)
    out = t.export()
    assert out["actor/x"] == 6.0
    assert out["actor/y"] == 5.0
    assert out["actor/z"] == -3.0
    assert out["lr"] == pytest.approx(0.1)


def test_accumulate_multiple_steps():
    t = DistributedStatsTracker()
    for i in range(3):
        mask = np.array([1, i % 2], dtype=bool)
        t.denominator(m=mask)
        t.stat("m", v=np.array([1.0, 10.0]))
    out = t.export()
    # masks: [1,0],[1,1],[1,0] -> selected vals [1],[1,10],[1] => mean 13/4
    assert out["v"] == pytest.approx(13 / 4)


def test_shape_mismatch_raises():
    t = DistributedStatsTracker()
    t.denominator(m=np.ones(3, dtype=bool))
    with pytest.raises(ValueError):
        t.stat("m", v=np.ones(4))
    with pytest.raises(ValueError):
        t.stat("nope", v=np.ones(3))


def test_export_resets():
    t = DistributedStatsTracker()
    t.scalar(a=1.0)
    assert "a" in t.export()
    assert t.export() == {}
