"""Fleet telemetry plane (docs/observability.md): histogram math, the
metric-kind registry, the per-worker exporter publish/collect roundtrip
through name_resolve, central aggregation across workers, and the ops CLI
rendering.

Everything runs against the in-memory name_resolve backend — the same
publish/collect code paths the multiprocess world exercises over the
file backend (tests/test_experiment_e2e.py asserts that end to end).
"""

import json
import threading
import time

import pytest

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.base import name_resolve, names
from areal_tpu.base.metrics import (
    DEFAULT_HISTOGRAM_BOUNDARIES,
    KIND_HISTOGRAM,
    KIND_PEAK,
    KIND_SUM,
    VERSION_LAG_BOUNDARIES,
    CounterRegistry,
    Histogram,
)
from areal_tpu.system import telemetry
from areal_tpu.system.worker_base import TelemetryExporter


class TestHistogram:
    def test_default_boundaries_log_spaced_ascending(self):
        b = DEFAULT_HISTOGRAM_BOUNDARIES
        assert b == sorted(b)
        assert b[0] == pytest.approx(1e-4)
        assert b[-1] == pytest.approx(1e4)
        # 4 buckets/decade over 8 decades -> 33 edges
        assert len(b) == 33
        # neighbouring edges are a constant ratio (log-spaced)
        ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
        assert all(r == pytest.approx(10 ** 0.25, rel=1e-6) for r in ratios)

    def test_observe_bucket_placement(self):
        h = Histogram(boundaries=[1.0, 10.0, 100.0])
        assert len(h.counts) == 4
        h.observe(0.5)    # <= 1.0 -> bucket 0
        h.observe(1.0)    # == edge -> bucket 0 (counts values <= edge)
        h.observe(5.0)    # bucket 1
        h.observe(100.0)  # bucket 2
        h.observe(1e6)    # overflow bucket
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5
        assert h.sum == pytest.approx(0.5 + 1.0 + 5.0 + 100.0 + 1e6)
        assert h.min == 0.5 and h.max == 1e6

    def test_percentile_empty_and_identical(self):
        h = Histogram(boundaries=[1.0, 10.0])
        assert h.percentile(50) == 0.0
        assert h.summary() == {"count": 0.0}
        for _ in range(100):
            h.observe(3.0)
        # interpolation is clamped to observed min/max: all-identical
        # observations report exactly that value at every percentile
        for q in (0, 50, 95, 99, 100):
            assert h.percentile(q) == pytest.approx(3.0)

    def test_percentile_monotone_and_sane(self):
        h = Histogram()
        for i in range(1, 1001):
            h.observe(i / 1000.0)  # uniform on (0, 1]
        p50, p95, p99 = h.percentile(50), h.percentile(95), h.percentile(99)
        assert p50 <= p95 <= p99 <= h.max
        # +-33% bucket resolution: the estimates stay near truth
        assert p50 == pytest.approx(0.5, rel=0.45)
        assert p99 == pytest.approx(0.99, rel=0.45)

    def test_percentile_overflow_bucket_clamped_to_max(self):
        h = Histogram(boundaries=[1.0])
        h.observe(50.0)
        h.observe(70.0)
        # both live in the unbounded overflow bucket: estimates must come
        # from the observed range, not infinity
        assert h.percentile(99) <= 70.0
        assert h.percentile(1) >= 1.0

    def test_merge(self):
        a = Histogram(boundaries=[1.0, 10.0])
        b = Histogram(boundaries=[1.0, 10.0])
        a.observe(0.5)
        b.observe(5.0)
        b.observe(20.0)
        a.merge(b)
        assert a.counts == [1, 1, 1]
        assert a.count == 3
        assert a.min == 0.5 and a.max == 20.0
        assert a.sum == pytest.approx(25.5)

    def test_merge_mismatched_boundaries_raises(self):
        a = Histogram(boundaries=[1.0, 10.0])
        b = Histogram(boundaries=[2.0, 10.0])
        with pytest.raises(ValueError):
            a.merge(b)

    def test_state_roundtrip(self):
        h = Histogram(boundaries=VERSION_LAG_BOUNDARIES)
        for v in (0, 0, 1, 2, 7, 200):
            h.observe(v)
        r = Histogram.from_state(json.loads(json.dumps(h.state())))
        assert r.counts == h.counts
        assert r.count == h.count and r.sum == h.sum
        assert r.min == h.min and r.max == h.max
        assert r.summary() == h.summary()

    def test_state_roundtrip_empty(self):
        r = Histogram.from_state(json.loads(json.dumps(Histogram().state())))
        assert r.count == 0
        # empty min/max serialize as None and come back as the identities
        r.observe(3.0)
        assert r.min == 3.0 and r.max == 3.0

    def test_version_lag_boundaries_separate_small_integers(self):
        """Staleness 0/1/2 are the values the bounded-staleness story is
        about — the integer-centered edges keep them in distinct buckets."""
        h = Histogram(boundaries=VERSION_LAG_BOUNDARIES)
        for v, n in ((0, 10), (1, 5), (2, 1)):
            for _ in range(n):
                h.observe(v)
        assert h.counts[0] == 10 and h.counts[1] == 5 and h.counts[2] == 1


class TestRegistryKinds:
    def test_delta_by_kind_not_suffix(self):
        reg = CounterRegistry()
        reg.add("a/total", 5)
        reg.peak("a/depth", 3)
        before = reg.snapshot()
        reg.add("a/total", 2)
        reg.peak("a/depth", 7)
        d = reg.delta(before)
        assert d["a/total"] == pytest.approx(2.0)   # sum: subtract
        assert d["a/depth"] == pytest.approx(7.0)   # peak: report as-is

    def test_catalog_declares_max_in_flight_peak(self):
        """The endswith("max_in_flight") hack is gone: the kind comes from
        the METRIC_KINDS catalog even on a registry that never saw peak()."""
        reg = CounterRegistry()
        assert reg.kind(metrics_mod.PIPE_FWD_MAX_IN_FLIGHT) == KIND_PEAK
        assert reg.kind(metrics_mod.FT_EVICTIONS) == KIND_SUM
        assert reg.kind(metrics_mod.STALENESS_VERSIONS) == KIND_HISTOGRAM
        assert reg.kind("anything/else") == KIND_SUM

    def test_register_kind_validates(self):
        reg = CounterRegistry()
        reg.register_kind("x", KIND_PEAK)
        assert reg.kind("x") == KIND_PEAK
        with pytest.raises(AssertionError):
            reg.register_kind("y", "mean")

    def test_observe_uses_catalog_boundaries(self):
        reg = CounterRegistry()
        reg.observe(metrics_mod.STALENESS_VERSIONS, 1)
        h = reg.histogram(metrics_mod.STALENESS_VERSIONS)
        assert h.boundaries == VERSION_LAG_BOUNDARIES
        reg.observe("some/duration_s", 0.1)
        assert (
            reg.histogram("some/duration_s").boundaries
            == DEFAULT_HISTOGRAM_BOUNDARIES
        )

    def test_export_state_serializable_and_complete(self):
        reg = CounterRegistry()
        reg.add("n", 2)
        reg.peak("depth", 4)
        reg.observe("lat_s", 0.25)
        st = json.loads(json.dumps(reg.export_state()))
        assert st["counters"] == {"n": 2.0, "depth": 4.0}
        assert st["kinds"] == {"n": KIND_SUM, "depth": KIND_PEAK}
        assert st["histograms"]["lat_s"]["count"] == 1

    def test_histogram_summaries_and_clear(self):
        reg = CounterRegistry()
        reg.observe("h", 1.0)
        assert reg.histogram_summaries()["h"]["count"] == 1.0
        reg.clear("h")
        assert reg.histogram("h") is None

    def test_thread_safety_smoke(self):
        reg = CounterRegistry()
        n_threads, n_each = 8, 500

        def work():
            for i in range(n_each):
                reg.add("c")
                reg.peak("p", i)
                reg.observe("h", i * 1e-3)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert reg.get("c") == n_threads * n_each
        assert reg.get("p") == n_each - 1
        h = reg.histogram("h")
        assert h.count == n_threads * n_each
        assert sum(h.counts) == h.count


def _fake_snapshot(worker, role, counters=None, kinds=None, hist_values=(),
                   gauges=None, server_states=None, step=0, pid=1):
    reg = CounterRegistry()
    for k, v in (counters or {}).items():
        if (kinds or {}).get(k) == KIND_PEAK:
            reg.peak(k, v)
        else:
            reg.add(k, v)
    for v in hist_values:
        reg.observe(metrics_mod.QUEUE_WAIT_S, v)
    snap = telemetry.build_snapshot(
        worker, role, step=step, registry=reg, gauges=gauges,
        server_states=server_states,
    )
    snap["pid"] = pid
    return snap


class TestAggregator:
    def test_merge_across_three_workers(self):
        snaps = [
            _fake_snapshot(
                "rollout_worker/0", "rollout",
                counters={metrics_mod.FT_CLIENT_RETRIES: 2,
                          metrics_mod.ROLLOUT_PUSHED: 10,
                          metrics_mod.PIPE_FWD_MAX_IN_FLIGHT: 2},
                kinds={metrics_mod.PIPE_FWD_MAX_IN_FLIGHT: KIND_PEAK},
                hist_values=[0.1, 0.2], pid=11,
            ),
            _fake_snapshot(
                "rollout_worker/1", "rollout",
                counters={metrics_mod.FT_CLIENT_RETRIES: 3,
                          metrics_mod.ROLLOUT_PUSHED: 5,
                          metrics_mod.PIPE_FWD_MAX_IN_FLIGHT: 4},
                kinds={metrics_mod.PIPE_FWD_MAX_IN_FLIGHT: KIND_PEAK},
                hist_values=[0.4], pid=12,
            ),
            _fake_snapshot(
                "gserver_manager", "manager",
                counters={metrics_mod.MANAGER_SCHEDULED: 7},
                gauges={"rollouts_running": 3.0},
                server_states={"http://a": "closed", "http://b": "open"},
                pid=13,
            ),
        ]
        agg = telemetry.aggregate(snaps)
        assert len(agg.workers) == 3
        # sum kinds add across workers; peak kinds take the fleet max
        assert agg.counters[metrics_mod.FT_CLIENT_RETRIES] == 5.0
        assert agg.counters[metrics_mod.ROLLOUT_PUSHED] == 15.0
        assert agg.counters[metrics_mod.PIPE_FWD_MAX_IN_FLIGHT] == 4.0
        # histograms merge bucket-wise: fleet percentiles come from ALL
        # observations, not an average of per-worker percentiles
        h = agg.histograms[metrics_mod.QUEUE_WAIT_S]
        assert h.count == 3
        assert h.min == pytest.approx(0.1) and h.max == pytest.approx(0.4)

        s = agg.scalars()
        assert s["workers"] == 3.0
        assert s["worker_pids"] == 3.0
        assert s[f"{metrics_mod.QUEUE_WAIT_S}/count"] == 3.0
        assert s[f"{metrics_mod.QUEUE_WAIT_S}/p99"] <= 0.4 + 1e-9
        # breaker tallies from the manager's server_states
        assert s["servers_total"] == 2.0
        assert s["servers_closed"] == 1.0 and s["servers_open"] == 1.0
        assert s["rollouts_running"] == 3.0
        # the full ft/ catalog is zero-filled: healthy-fleet zeros are
        # explicit in the record, not absent
        assert s[metrics_mod.FT_EVICTIONS] == 0.0

    def test_aggregate_deterministic_order(self):
        snaps = [
            _fake_snapshot("b", "rollout", pid=2),
            _fake_snapshot("a", "rollout", pid=1),
        ]
        agg = telemetry.aggregate(snaps)
        assert [w["worker"] for w in agg.workers] == ["a", "b"]

    def test_malformed_histogram_state_skipped(self):
        snap = _fake_snapshot("w", "rollout", hist_values=[0.1])
        snap["histograms"]["bad"] = {"counts": "nope"}
        agg = telemetry.aggregate([snap])
        assert "bad" not in agg.histograms
        assert metrics_mod.QUEUE_WAIT_S in agg.histograms

    def test_mismatched_boundaries_keeps_first(self):
        a = _fake_snapshot("a", "rollout", hist_values=[0.1])
        b = _fake_snapshot("b", "rollout", hist_values=[0.2])
        b["histograms"][metrics_mod.QUEUE_WAIT_S]["boundaries"] = [1.0]
        b["histograms"][metrics_mod.QUEUE_WAIT_S]["counts"] = [1, 0]
        agg = telemetry.aggregate([a, b])
        assert agg.histograms[metrics_mod.QUEUE_WAIT_S].count == 1

    def test_unknown_kind_defaults_to_sum(self):
        a = _fake_snapshot("a", "r", counters={"custom/key": 1})
        b = _fake_snapshot("b", "r", counters={"custom/key": 2})
        for s in (a, b):
            s["kinds"] = {}
        agg = telemetry.aggregate([a, b])
        assert agg.counters["custom/key"] == 3.0


class TestExporterRoundtrip:
    EXP, TRIAL = "telemetry-test", "roundtrip"

    def teardown_method(self):
        name_resolve.clear_subtree(
            names.telemetry_root(self.EXP, self.TRIAL)
        )

    def test_publish_collect_roundtrip(self):
        reg = CounterRegistry()
        reg.add(metrics_mod.ROLLOUT_PUSHED, 4)
        reg.observe(metrics_mod.QUEUE_WAIT_S, 0.2)
        exp = TelemetryExporter(
            self.EXP, self.TRIAL, "rollout_worker/0", "rollout",
            interval=60.0, registry=reg,
            step_fn=lambda: 17,
            gauges_fn=lambda: {"rollout_tasks_running": 2.0},
        )
        assert exp.enabled
        exp.publish_once()
        snaps = telemetry.collect_snapshots(self.EXP, self.TRIAL)
        assert len(snaps) == 1
        s = snaps[0]
        assert s["worker"] == "rollout_worker/0" and s["role"] == "rollout"
        assert s["step"] == 17
        assert s["counters"][metrics_mod.ROLLOUT_PUSHED] == 4.0
        assert s["histograms"][metrics_mod.QUEUE_WAIT_S]["count"] == 1
        assert s["gauges"]["rollout_tasks_running"] == 2.0
        # republish replaces (one live snapshot per worker, not a log)
        reg.add(metrics_mod.ROLLOUT_PUSHED, 1)
        exp.publish_once()
        snaps = telemetry.collect_snapshots(self.EXP, self.TRIAL)
        assert len(snaps) == 1
        assert snaps[0]["counters"][metrics_mod.ROLLOUT_PUSHED] == 5.0

    def test_disabled_exporter_is_noop(self, monkeypatch):
        monkeypatch.delenv("AREAL_TELEMETRY_EXPORT", raising=False)
        exp = TelemetryExporter(
            self.EXP, self.TRIAL, "w", "rollout", registry=CounterRegistry()
        )
        assert not exp.enabled
        exp.maybe_start()
        assert exp._thread is None
        exp.stop()
        assert exp.published == 0
        assert telemetry.collect_snapshots(self.EXP, self.TRIAL) == []

    def test_background_thread_publishes_and_final_flush(self):
        reg = CounterRegistry()
        exp = TelemetryExporter(
            self.EXP, self.TRIAL, "w", "rollout",
            interval=0.05, registry=reg,
        ).maybe_start()
        deadline = time.monotonic() + 5.0
        while exp.published < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert exp.published >= 2
        # a counter bumped right before stop reaches the final snapshot
        reg.add(metrics_mod.ROLLOUT_ACCEPTED, 9)
        exp.stop()
        assert exp._thread is None
        snaps = telemetry.collect_snapshots(self.EXP, self.TRIAL)
        assert snaps[0]["counters"][metrics_mod.ROLLOUT_ACCEPTED] == 9.0

    def test_failing_callback_degrades_not_crashes(self):
        def boom():
            raise RuntimeError("gauge source died")

        exp = TelemetryExporter(
            self.EXP, self.TRIAL, "w", "rollout",
            interval=60.0, registry=CounterRegistry(),
            gauges_fn=boom, step_fn=boom,
        )
        snap = exp.publish_once()
        assert snap["gauges"] == {} and snap["step"] == 0
        assert len(telemetry.collect_snapshots(self.EXP, self.TRIAL)) == 1

    def test_collect_fleet_scalars_substitutes_live_local(self):
        stale = _fake_snapshot(
            "trainer", "trainer",
            counters={metrics_mod.TRAIN_STEPS: 1}, pid=7,
        )
        telemetry.publish_snapshot(self.EXP, self.TRIAL, stale)
        other = _fake_snapshot(
            "rollout_worker/0", "rollout",
            counters={metrics_mod.ROLLOUT_PUSHED: 3}, pid=8,
        )
        telemetry.publish_snapshot(self.EXP, self.TRIAL, other)
        live = _fake_snapshot(
            "trainer", "trainer",
            counters={metrics_mod.TRAIN_STEPS: 5}, pid=7,
        )
        s = telemetry.collect_fleet_scalars(
            self.EXP, self.TRIAL, local_snapshot=live
        )
        # the caller's live registry replaces its own published snapshot
        # (not double-counted), everyone else's published state merges in
        assert s[metrics_mod.TRAIN_STEPS] == 5.0
        assert s[metrics_mod.ROLLOUT_PUSHED] == 3.0
        assert s["workers"] == 2.0

    def test_collect_fleet_scalars_none_when_empty(self):
        assert (
            telemetry.collect_fleet_scalars("telemetry-test", "nothing")
            is None
        )

    def test_malformed_published_snapshot_skipped(self):
        name_resolve.add(
            names.telemetry(self.EXP, self.TRIAL, "corrupt"),
            "{not json", replace=True,
        )
        good = _fake_snapshot("ok", "rollout", pid=3)
        telemetry.publish_snapshot(self.EXP, self.TRIAL, good)
        snaps = telemetry.collect_snapshots(self.EXP, self.TRIAL)
        assert [s["worker"] for s in snaps] == ["ok"]


class TestObsCLI:
    EXP, TRIAL = "telemetry-test", "obs"

    def teardown_method(self):
        name_resolve.clear_subtree(
            names.telemetry_root(self.EXP, self.TRIAL)
        )

    def _publish_world(self):
        telemetry.publish_snapshot(self.EXP, self.TRIAL, _fake_snapshot(
            "trainer", "trainer",
            counters={metrics_mod.TRAIN_STEPS: 12}, hist_values=[0.5],
            step=12, pid=21,
        ))
        telemetry.publish_snapshot(self.EXP, self.TRIAL, _fake_snapshot(
            "gserver_manager", "manager",
            counters={metrics_mod.MANAGER_SCHEDULED: 40},
            server_states={"http://a": "closed"}, pid=22,
        ))

    def test_render_table(self):
        from areal_tpu.apps import obs

        self._publish_world()
        agg = telemetry.aggregate(
            telemetry.collect_snapshots(self.EXP, self.TRIAL)
        )
        out = obs.render(agg)
        assert "trainer" in out and "gserver_manager" in out
        assert "steps=12" in out            # role headline counter
        assert "scheduled=40" in out
        assert "http://a" in out and "closed" in out
        assert metrics_mod.QUEUE_WAIT_S in out  # distribution table row

    def test_render_frame_json(self):
        from areal_tpu.apps import obs

        self._publish_world()
        frame = obs.render_frame(self.EXP, self.TRIAL, as_json=True)
        d = json.loads(frame)
        assert d["workers"] == 2.0
        assert d[metrics_mod.TRAIN_STEPS] == 12.0
        assert obs.render_frame(self.EXP, "no-such-trial", False) is None

    def test_main_once_and_json(self, tmp_path, capsys):
        """The CLI entrypoint end to end against a synthetic 3-worker
        aggregate: ``--once`` fleet table (trial auto-discovery too),
        ``--json``, and the no-telemetry rc-1 path."""
        from areal_tpu.apps import obs

        prev = name_resolve.default_repository()
        try:
            name_resolve.reconfigure(name_resolve.NameResolveConfig(
                type="file", root=str(tmp_path / "name_resolve")
            ))
            self._publish_world()
            telemetry.publish_snapshot(self.EXP, self.TRIAL, _fake_snapshot(
                "gen_server/0", "gen_server",
                counters={metrics_mod.GEN_SERVED: 9}, pid=23,
            ))
            rc = obs.main([
                str(tmp_path), "--experiment", self.EXP,
                "--trial", self.TRIAL, "--once",
            ])
            out = capsys.readouterr().out
            assert rc == 0
            assert "3 workers" in out
            assert "trainer" in out and "gen_server/0" in out
            assert "served=9" in out and "scheduled=40" in out
            # trial auto-discovery (no --experiment/--trial) + --json
            rc = obs.main([str(tmp_path), "--once", "--json"])
            d = json.loads(capsys.readouterr().out)
            assert rc == 0
            assert d["workers"] == 3.0
            assert d[metrics_mod.GEN_SERVED] == 9.0
            # empty fileroot: honest rc 1 with a hint on stderr
            empty = tmp_path / "empty"
            empty.mkdir()
            assert obs.main([str(empty), "--once"]) == 1
            assert "no telemetry published" in capsys.readouterr().err
        finally:
            name_resolve.set_repository(prev)
