"""Stub-binary Slurm e2e (VERDICT r4 #8): a fake control plane — real
``sbatch``/``squeue``/``sacct``/``scancel``/``srun`` executables on PATH
that run jobs as local processes — drives ``SlurmSchedulerClient`` through
submit → poll → worker-death → restart-the-world recovery, exercising the
array-job (multiprog), hostfile, and ``--wrap`` code paths for real instead
of only asserting on constructed command strings. Counterpart of the
battle-hardening in ``/root/reference/realhf/scheduler/slurm/utils.py``.
"""

import json
import os
import stat
import sys

import time

import pytest

from areal_tpu.scheduler.client import (
    JobException,
    JobState,
    SlurmSchedulerClient,
)

_SBATCH = r'''#!/usr/bin/env -S python3 -S
import os, subprocess, sys
d = os.environ["FAKE_SLURM_DIR"]
args = sys.argv[1:]
script, wrap = None, None
for a in args:
    if a.startswith("--wrap="):
        wrap = a[len("--wrap="):]
    elif not a.startswith("-"):
        script = a
seq = os.path.join(d, "seq")
jid = str(int(open(seq).read()) + 1 if os.path.exists(seq) else 1)
open(seq, "w").write(jid)
if script is None:
    script = os.path.join(d, f"wrap_{jid}.sh")
    open(script, "w").write("#!/bin/bash\n" + wrap + "\n")
log = os.path.join(d, f"{jid}.log")
# supervisor shell records the rc when the payload exits (what the real
# slurmd reports to the controller)
p = subprocess.Popen(
    ["bash", "-c", f"bash {script} >> {log} 2>&1; echo $? > {d}/{jid}.rc"],
    start_new_session=True,
    # the supervisor must NOT inherit sbatch's stdout pipe: the submitter
    # reads it to EOF, which would block `sbatch --parsable` until the JOB
    # exits (the very bug this stub had on first write)
    stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
    stderr=subprocess.DEVNULL,
)
open(os.path.join(d, f"{jid}.pid"), "w").write(str(p.pid))
print(jid)
'''

_SQUEUE = r'''#!/usr/bin/env -S python3 -S
import os, sys
d = os.environ["FAKE_SLURM_DIR"]
args = sys.argv[1:]
ids, fmt = [], "%i|%T|%N"
for i, a in enumerate(args):
    if a == "-j":
        ids = args[i + 1].split(",")
    if a == "-o":
        fmt = args[i + 1]
for jid in ids:
    if os.path.exists(os.path.join(d, f"{jid}.rc")):
        continue  # left the queue; caller falls through to sacct
    if not os.path.exists(os.path.join(d, f"{jid}.pid")):
        sys.exit(1)  # unknown id: real squeue errors
    line = fmt.replace("%i", jid).replace("%T", "RUNNING")
    line = line.replace("%N", "fakehost0")
    print(line)
'''

_SACCT = r'''#!/usr/bin/env -S python3 -S
import os, sys
d = os.environ["FAKE_SLURM_DIR"]
jid = sys.argv[sys.argv.index("-j") + 1]
rc_path = os.path.join(d, f"{jid}.rc")
if os.path.exists(os.path.join(d, f"{jid}.cancelled")):
    print("CANCELLED")
elif os.path.exists(rc_path):
    rc = open(rc_path).read().strip()
    print("COMPLETED" if rc == "0" else "FAILED")
elif os.path.exists(os.path.join(d, f"{jid}.pid")):
    print("RUNNING")
'''

_SCANCEL = r'''#!/usr/bin/env -S python3 -S
import os, signal, sys
d = os.environ["FAKE_SLURM_DIR"]
jid = sys.argv[1]
try:
    pid = int(open(os.path.join(d, f"{jid}.pid")).read())
    os.killpg(pid, signal.SIGTERM)
except (FileNotFoundError, ProcessLookupError, PermissionError):
    pass
open(os.path.join(d, f"{jid}.cancelled"), "w").write("1")
if not os.path.exists(os.path.join(d, f"{jid}.rc")):
    open(os.path.join(d, f"{jid}.rc"), "w").write("15")
'''

# srun -K -l --ntasks=N --multi-prog FILE: run every rank's command; any
# non-zero rank kills the rest and fails the step (the -K semantics the
# client's restart-the-world recovery depends on)
_SRUN = r'''#!/usr/bin/env -S python3 -S
import os, shlex, subprocess, sys
args = sys.argv[1:]
ntasks, prog = 1, None
for i, a in enumerate(args):
    if a.startswith("--ntasks="):
        ntasks = int(a.split("=", 1)[1])
    if a == "--multi-prog":
        prog = args[i + 1]
    if a.startswith("--multi-prog="):
        prog = a.split("=", 1)[1]
hosts = []
hf = os.environ.get("SLURM_HOSTFILE")
if hf and os.path.exists(hf):
    hosts = [line.strip() for line in open(hf) if line.strip()]
cmds = {}
for line in open(prog):
    line = line.strip()
    if not line:
        continue
    rank, rest = line.split(None, 1)
    cmds[int(rank)] = shlex.split(rest)
procs = {}
for rank in range(ntasks):
    env = dict(os.environ, SLURM_PROCID=str(rank))
    if hosts:
        env["SLURMD_NODENAME"] = hosts[rank]
    procs[rank] = subprocess.Popen(cmds[rank], env=env)
rc = 0
for rank, p in procs.items():
    r = p.wait()
    if r != 0 and rc == 0:
        rc = r
        for q in procs.values():  # -K: one dead step kills the job
            if q.poll() is None:
                q.terminate()
sys.exit(rc)
'''


@pytest.fixture
def fake_slurm(tmp_path, monkeypatch):
    bin_dir = tmp_path / "bin"
    state = tmp_path / "slurm_state"
    bin_dir.mkdir()
    state.mkdir()
    for name, src in (("sbatch", _SBATCH), ("squeue", _SQUEUE),
                      ("sacct", _SACCT), ("scancel", _SCANCEL),
                      ("srun", _SRUN)):
        p = bin_dir / name
        p.write_text(src)
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_SLURM_DIR", str(state))
    return state


def _client(tmp_path, **kw):
    return SlurmSchedulerClient(
        "e2e", "t0", log_dir=str(tmp_path / "logs"), **kw
    )


def test_wrap_job_lifecycle(fake_slurm, tmp_path):
    """submit (--wrap path) → RUNNING → COMPLETED, output side effect."""
    os.makedirs(tmp_path / "logs", exist_ok=True)
    cli = _client(tmp_path)
    out = tmp_path / "hello.txt"
    cli.submit(
        "hello",
        [sys.executable, "-S", "-c",
         f"import time; time.sleep(1); open({str(out)!r}, 'w').write('hi')"],
    )
    # observe RUNNING through squeue before completion
    states = set()
    for _ in range(100):
        st = cli.find("hello").state
        states.add(st)
        if st == JobState.COMPLETED:
            break
        time.sleep(0.1)
    assert JobState.COMPLETED in states
    assert JobState.RUNNING in states
    assert out.read_text() == "hi"
    infos = cli.wait(timeout=10, poll=0.1)
    assert [i.state for i in infos] == [JobState.COMPLETED]


def test_array_job_multiprog_hostfile_env(fake_slurm, tmp_path):
    """submit_array executes the self-materialized multiprog + hostfile on
    the 'batch node': every rank runs with its --worker-index, pinned host,
    and exported env."""
    os.makedirs(tmp_path / "logs", exist_ok=True)
    cli = _client(tmp_path)
    outdir = tmp_path / "ranks"
    outdir.mkdir()
    # single line: srun --multi-prog is line-oriented (the client rejects
    # newline-bearing args)
    worker = (
        "import json, os, sys; "
        "idx = [a for a in sys.argv if a.startswith('--worker-index=')]"
        "[0].split('=')[1]; "
        'rec = {"idx": idx, "procid": os.environ.get("SLURM_PROCID"), '
        '"host": os.environ.get("SLURMD_NODENAME"), '
        '"flag": os.environ.get("AREAL_E2E_FLAG")}; '
        f"open(os.path.join({str(outdir)!r}, 'r' + idx + '.json'), 'w')"
        ".write(json.dumps(rec))"
    )
    cli.submit_array(
        "workers", [sys.executable, "-S", "-c", worker], count=4,
        hosts=["hostA", "hostB"], tasks_per_host=2,
        env={"AREAL_E2E_FLAG": "on"},
    )
    infos = cli.wait(timeout=30, poll=0.1)
    assert [i.state for i in infos] == [JobState.COMPLETED]
    recs = {}
    for i in range(4):
        recs[i] = json.loads((outdir / f"r{i}.json").read_text())
    assert [recs[i]["idx"] for i in range(4)] == ["0", "1", "2", "3"]
    assert [recs[i]["procid"] for i in range(4)] == ["0", "1", "2", "3"]
    # hostfile pinning: 2 ranks per host, in order
    assert [recs[i]["host"] for i in range(4)] == \
        ["hostA", "hostA", "hostB", "hostB"]
    assert all(recs[i]["flag"] == "on" for i in range(4))


def test_worker_death_then_restart_world_recovery(fake_slurm, tmp_path):
    """rank 2 dies → srun -K fails the array → wait() raises JobException
    and stops the world → resubmission (the launcher's restart-the-world
    recovery, apps/launcher.py) completes once the fault is gone."""
    os.makedirs(tmp_path / "logs", exist_ok=True)
    cli = _client(tmp_path)
    outdir = tmp_path / "work"
    outdir.mkdir()
    marker = tmp_path / "fault_fixed"
    worker = (
        "import os, sys, time; "
        "idx = [a for a in sys.argv if a.startswith('--worker-index=')]"
        "[0].split('=')[1]; "
        f"fixed = os.path.exists({str(marker)!r}); "
        "(idx == '2' and not fixed) and sys.exit(1); "  # injected fault
        "time.sleep(0.5); "
        f"open(os.path.join({str(outdir)!r}, "
        "'done' + idx + '_' + str(int(fixed))), 'w').write('ok')"
    )

    def launch():
        cli.submit_array("fleet", [sys.executable, "-S", "-c", worker], count=4)

    launch()
    with pytest.raises(JobException) as ei:
        cli.wait(timeout=30, poll=0.1)
    assert ei.value.reason == JobState.FAILED

    # restart-the-world: fix the fault, resubmit the same worker type
    marker.write_text("1")
    launch()
    infos = cli.wait(timeout=30, poll=0.1)
    assert [i.state for i in infos] == [JobState.COMPLETED]
    for i in range(4):
        assert (outdir / f"done{i}_1").exists()


def test_scancel_on_stop(fake_slurm, tmp_path):
    """stop() cancels a running job; the state surfaces as CANCELLED."""
    os.makedirs(tmp_path / "logs", exist_ok=True)
    cli = _client(tmp_path)
    cli.submit("sleeper", [sys.executable, "-S", "-c", "import time; time.sleep(60)"])
    for _ in range(50):
        if cli.find("sleeper").state == JobState.RUNNING:
            break
        time.sleep(0.1)
    cli.stop("sleeper")
    for _ in range(50):
        st = cli.find("sleeper").state
        if st == JobState.CANCELLED:
            break
        time.sleep(0.1)
    assert st == JobState.CANCELLED
