"""Fake-ray e2e: ``RaySchedulerClient`` driven against an in-process
``ray`` stand-in (tests/fake_ray) — submit → RUNNING → COMPLETED /
FAILED, and cancel semantics including the client's process-group kill
(``scheduler/client.py`` RaySchedulerClient; counterpart of the
reference's Ray actor fleet, ``training/utils.py:119-254``)."""

import os
import sys
import time

import pytest

FAKE_RAY = os.path.join(os.path.dirname(__file__), "fake_ray")


def _purge_ray_modules():
    for m in [m for m in sys.modules if m == "ray" or m.startswith("ray.")]:
        sys.modules.pop(m)


@pytest.fixture
def ray_client(monkeypatch):
    # the fake must win the import BEFORE the client imports ray; purge any
    # previously imported copy so tests are order-independent — and purge
    # again on teardown so later tests never silently get the stand-in
    monkeypatch.syspath_prepend(FAKE_RAY)
    _purge_ray_modules()
    from areal_tpu.scheduler.client import RaySchedulerClient

    cli = RaySchedulerClient("raye2e", "t0")
    assert cli._ray.__file__.startswith(FAKE_RAY), "real ray imported?"
    yield cli
    _purge_ray_modules()


def test_ray_job_lifecycle(ray_client, tmp_path):
    from areal_tpu.scheduler.client import JobState

    out = tmp_path / "done.txt"
    ray_client.submit(
        "writer",
        [sys.executable, "-S", "-c",
         f"import time; time.sleep(0.8); open({str(out)!r}, 'w').write('ok')"],
    )
    states = set()
    for _ in range(200):
        st = ray_client.find("writer").state
        states.add(st)
        if st == JobState.COMPLETED:
            break
        time.sleep(0.05)
    assert JobState.RUNNING in states and JobState.COMPLETED in states
    assert out.read_text() == "ok"


def test_ray_failure_and_env(ray_client, tmp_path):
    from areal_tpu.scheduler.client import JobException, JobState

    out = tmp_path / "env.txt"
    ray_client.submit(
        "envw",
        [sys.executable, "-S", "-c",
         f"import os; open({str(out)!r}, 'w').write(os.environ['AREAL_X'])"],
        env={"AREAL_X": "42"},
    )
    # envw must land BEFORE the failure: wait()'s failure path stop_all()s
    # everything still running, which would race envw's file write
    for _ in range(200):
        if out.exists() and out.read_text():
            break
        time.sleep(0.05)
    ray_client.submit(
        "dier", [sys.executable, "-S", "-c", "import sys; sys.exit(3)"],
    )
    with pytest.raises(JobException) as ei:
        ray_client.wait(timeout=30, poll=0.05)
    assert ei.value.reason == JobState.FAILED
    assert out.read_text() == "42"


def test_ray_stop_kills_worker_process_group(ray_client, tmp_path):
    """stop() must cancel the task AND take the worker subprocess down
    with it (the client's finally/killpg contract — orphaned workers
    would keep holding TPU devices across a restart)."""
    from areal_tpu.scheduler.client import JobState

    pidfile = tmp_path / "pid"
    ray_client.submit(
        "sleeper",
        [sys.executable, "-S", "-c",
         "import os, time; open(%r, 'w').write(str(os.getpid())); "
         "time.sleep(120)" % str(pidfile)],
    )
    for _ in range(100):
        if pidfile.exists() and pidfile.read_text():
            break
        time.sleep(0.05)
    pid = int(pidfile.read_text())
    ray_client.stop("sleeper")
    for _ in range(100):
        if ray_client.find("sleeper").state == JobState.CANCELLED:
            break
        time.sleep(0.1)
    assert ray_client.find("sleeper").state == JobState.CANCELLED
    # the worker process itself is gone (SIGTERM via the task's finally)
    for _ in range(100):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        pytest.fail(f"worker pid {pid} still alive after stop()")
