"""Math/code verifier tests (≈ reference ``tests/reward``)."""

import pytest

from areal_tpu.rewards import code_verify, math_verify


@pytest.mark.parametrize(
    "text,expected",
    [
        (r"The answer is \boxed{42}.", "42"),
        (r"Thus \boxed{\frac{1}{2}} holds", r"\frac{1}{2}"),
        (r"nested \boxed{x^{2}+1}", "x^{2}+1"),
        ("so the answer is 3/4", "3/4"),
        ("we get 1, 2, and finally 7", "7"),
        ("no numbers here", None),
    ],
)
def test_extract_answer(text, expected):
    assert math_verify.extract_answer(text) == expected


@pytest.mark.parametrize(
    "a,b,eq",
    [
        ("42", "42", True),
        ("42.0", "42", True),
        (r"\frac{1}{2}", "0.5", True),
        ("1/2", "0.5", True),
        ("0.33", "1/3", False),
        ("x+1", "1+x", True),
        ("2x", "x*2", True),
        ("7", "8", False),
    ],
)
def test_answers_equal(a, b, eq):
    assert math_verify.answers_equal(a, b) == eq


def test_verify_math_solution():
    sol = [r"... the result is \boxed{\frac{3}{4}}"]
    assert math_verify.verify_math_solution(r"I think \boxed{0.75}", sol)
    assert not math_verify.verify_math_solution(r"I think \boxed{0.7}", sol)
    assert not math_verify.verify_math_solution("gibberish", sol)


def test_code_verify_pass_and_fail():
    gen = "Here is my solution:\n```python\nn = int(input())\nprint(n * 2)\n```"
    io = {"inputs": ["3\n", "10\n"], "outputs": ["6\n", "20\n"]}
    assert code_verify.verify_code_solution(gen, io)
    io_bad = {"inputs": ["3\n"], "outputs": ["7\n"]}
    assert not code_verify.verify_code_solution(gen, io_bad)
    assert not code_verify.verify_code_solution("no code here", io)


def test_code_verify_timeout():
    gen = "```python\nwhile True: pass\n```"
    io = {"inputs": ["1\n"], "outputs": ["1\n"]}
    assert not code_verify.verify_code_solution(gen, io, timeout=1.0)


@pytest.mark.parametrize("a,b,eq", [
    # latex fractions / nesting / mixed numbers
    (r"\frac{3}{4}", "0.75", True),
    (r"\dfrac{1}{\frac{1}{2}}", "2", True),
    (r"1\frac{1}{2}", "1.5", True),
    (r"\frac{3}{4}", "0.8", False),
    # roots and pi
    (r"\sqrt{16}", "4", True),
    (r"\sqrt[3]{27}", "3", True),
    (r"2\pi", "6.283185307", True),
    (r"\sqrt{8}", r"2\sqrt{2}", True),
    # percentages both directions
    (r"50\%", "0.5", True),
    ("0.5", "50%", True),
    ("50%", "0.4", False),
    # units / text wrappers / degrees
    (r"12\text{ cm}", "12", True),
    (r"90^\circ", "90", True),
    # thousands separators and scientific notation
    ("1,234", "1234", True),
    ("3e2", "300", True),
    # exponents
    (r"2^{10}", "1024", True),
    (r"x^2+1", r"1+x^{2}", True),
    # tuples (ordered) and sets (unordered)
    ("(1, 2)", r"(1, \frac{4}{2})", True),
    ("(1, 2)", "(2, 1)", False),
    (r"\{1, 2\}", r"\{2, 1\}", True),
    (r"\{1, 3\}", r"\{2, 1\}", False),
    # negatives / sanity
    ("-0.25", r"-\frac{1}{4}", True),
    ("", "", False),
])
def test_answers_equal_latex_matrix(a, b, eq):
    assert math_verify.answers_equal(a, b) == eq, (a, b)


@pytest.mark.parametrize("a,b,eq", [
    (r"\frac{\sqrt{3}}{2}", "0.8660254", True),   # frac with braced command
    ("1, 2", "12", False),                        # comma pair != twelve
    (r"90^{\circ}", "90", True),                  # braced degree sign
])
def test_answers_equal_review_regressions(a, b, eq):
    assert math_verify.answers_equal(a, b) == eq


@pytest.mark.parametrize("a,b,eq", [
    # latex2sympy-grammar extensions (VERDICT r3 missing #4): functions,
    # \operatorname, log bases, \binom, delimiters, sums/integrals, |x|
    (r"\sin(\pi/6)", "1/2", True),
    (r"\cos(\pi)", "-1", True),
    (r"\operatorname{lcm}(4,6)", "12", True),
    (r"\log_2 8", "3", True),
    (r"\ln(e^2)", "2", True),
    (r"\binom{5}{2}", "10", True),
    (r"\left(\frac{1}{2}\right)", "0.5", True),
    (r"\dfrac{3}{4}", "0.75", True),
    (r"\sum_{i=1}^{10} i", "55", True),
    (r"\int_{0}^{1} 2x dx", "1", True),
    (r"|{-3}|", "3", True),
    (r"\sin(\pi/6)", "1/3", False),
    (r"\log_2 8", "4", False),
    (r"\sum_{i=1}^{10} i", "54", False),
])
def test_answers_equal_latex2sympy_grammar(a, b, eq):
    assert math_verify.answers_equal(a, b) == eq, (a, b)


def test_degenerate_power_is_fast():
    """Model-controlled giant exponents must not stall the reward worker."""
    import time

    t0 = time.time()
    assert not math_verify.answers_equal(r"2^{999999999}", "5")
    assert time.time() - t0 < 2.0


# --------------------------------------------------------------------------- #
# tool-use reward (≈ reference tool_use_rw_interface)
# --------------------------------------------------------------------------- #

from areal_tpu.rewards import tool_use


TOOL_RESP = (
    'I will search first. {"function": {"name": "search", "arguments": '
    '{"query": "capital of France"}}} ... The result says Paris. '
    '{"function": {"name": "answer", "arguments": {"answer": "Paris"}}}'
)


def test_tool_use_extracts_last_answer_call():
    two = TOOL_RESP + ' {"function": {"name": "answer", "arguments": {"answer": "Lyon"}}}'
    assert tool_use.extract_answer(TOOL_RESP) == "Paris"
    assert tool_use.extract_answer(two) == "Lyon"
    assert tool_use.extract_answer('{"answer": "42"}') == "42"
    assert tool_use.extract_answer("just text") == "just text"


def test_tool_use_normalize_and_scores():
    assert tool_use.normalize_answer("The  Quick, Brown Fox!") == "quick brown fox"
    em, f1 = tool_use.em_check("the Paris", "Paris")
    assert em == 1 and f1 == 1.0
    em, f1 = tool_use.em_check("Paris France", "Paris")
    assert em == 0 and 0.0 < f1 < 1.0
    assert tool_use.f1_score("", "") == 1.0
    assert tool_use.f1_score("x", "") == 0.0


def test_tool_use_reward_combines_correctness_and_format():
    r = tool_use.tool_use_reward(TOOL_RESP, "Paris")
    assert r == pytest.approx(1.2)  # F1 1.0 + format 0.2
    assert tool_use.tool_use_reward("Paris", "Paris") == pytest.approx(1.0)
    assert tool_use.tool_use_reward("wrong", "Paris") == 0.0
    assert tool_use.tool_use_reward(TOOL_RESP, "Paris", scoring_method="em") == pytest.approx(1.2)


def test_tool_use_env_dispatch():
    import asyncio

    from areal_tpu.envs.math_code_single_step import MathCodeSingleStepEnv

    env = MathCodeSingleStepEnv(
        {"q1": {"task": "tool_use", "answer": "Paris"}}
    )
    _, scores, done, _, _ = asyncio.run(env.step(("q1", [TOOL_RESP, "nope"])))
    assert done
    # env scores are normalized into [0, 1] for binary-success consumers
    assert scores[0] == pytest.approx(1.0)
    assert scores[1] == 0.0


def test_tool_use_dataset_metadata():
    from areal_tpu.datasets.prompt import MathCodePromptDataset

    ds = MathCodePromptDataset.__new__(MathCodePromptDataset)
    ds.records = [
        {"query_id": "a", "task": "tool_use", "prompt": "p", "answer": "42"},
        {"query_id": "b", "task": "math", "prompt": "p", "solutions": ["\\boxed{1}"]},
    ]
    meta = ds.load_metadata()
    assert meta["a"] == {"task": "tool_use", "answer": "42"}
    assert meta["b"]["task"] == "math"


def test_tool_use_handles_escaped_quotes():
    resp = (
        '{"function": {"name": "answer", "arguments": '
        '{"answer": "He said \\"hi\\" loudly"}}}'
    )
    assert tool_use.extract_answer(resp) == 'He said "hi" loudly'
    em, f1 = tool_use.em_check(tool_use.extract_answer(resp), 'he said hi loudly')
    assert em == 1 and f1 == 1.0


class TestMathParityCorpus:
    """Parity corpus vs the reference verifier (math_parser.py): verdicts
    mined from its strip_string/math_equal semantics. Gate: >= 95%
    agreement on the answer-level corpus; full-text cases mirror
    process_results (no last-number fallback on the generated side);
    deliberate divergences assert OUR documented behavior."""

    @pytest.fixture(scope="class")
    def corpus(self):
        import json
        import os

        path = os.path.join(os.path.dirname(__file__), "data",
                            "math_parity.json")
        with open(path) as f:
            return json.load(f)

    def test_answer_level_agreement(self, corpus):
        from areal_tpu.rewards.math_verify import answers_equal

        wrong = []
        for given, truth, expected, family in corpus["answers"]:
            if answers_equal(given, truth) != expected:
                wrong.append((family, given, truth, expected))
        agreement = 1 - len(wrong) / len(corpus["answers"])
        assert agreement >= 0.95, (
            f"agreement {agreement:.3f}; disagreements: {wrong}"
        )

    def test_full_text_process_results_semantics(self, corpus):
        from areal_tpu.rewards.math_verify import verify_math_solution

        for generated, sols, expected, family in corpus["full_text"]:
            assert verify_math_solution(generated, sols) == expected, family

    def test_documented_divergences(self, corpus):
        from areal_tpu.rewards.math_verify import answers_equal

        for given, truth, expected, why in corpus["divergences"]:
            assert answers_equal(given, truth) == expected, why


class TestBenchmarkGoldParity:
    """VERDICT r4 #7 'Done' criterion: zero disagreements on the five
    bundled benchmark gold-answer sets — every gold answer must at minimum
    verify against itself through the full grammar (math) or the choice
    grader (gpqa), so a correct model answer can never be silently
    zero-rewarded by a parser gap."""

    def test_math_golds_self_verify(self):
        import json
        import os

        from areal_tpu.evaluation.benchmarks import BENCHMARKS
        from areal_tpu.rewards.math_verify import answers_equal

        bad = []
        for name in ("aime24", "aime25", "amc23", "math_500"):
            with open(BENCHMARKS[name].path()) as f:
                for line in f:
                    g = str(json.loads(line)["answer"])
                    if not answers_equal(g, g):
                        bad.append((name, g))
        assert not bad, bad

    def test_gpqa_golds_grade(self):
        from areal_tpu.evaluation.benchmarks import load_benchmark
        from areal_tpu.evaluation.mcq import grade_choice

        for r in load_benchmark("gpqa_diamond"):
            gold = r["solutions"][0]
            assert grade_choice(f"\\boxed{{{gold}}}", gold) == 1.0

    def test_grammar_extensions_round5(self):
        """mod / floor / ceil — where round-5 corpus disagreements
        clustered (latex2sympy mod_test/floor_test/ceil_test grammar)."""
        from areal_tpu.rewards.math_verify import answers_equal

        assert answers_equal("128 \\mod 3", "2")
        assert not answers_equal("128 \\mod 3", "1")
        assert answers_equal("-128 \\bmod 4", "0")
        assert answers_equal("\\lfloor 2.7 \\rfloor", "2")
        assert answers_equal("\\lfloor -1.5 \\rfloor", "-2")
        assert answers_equal("\\lceil 2.1 \\rceil", "3")
        assert not answers_equal("\\lceil 2.1 \\rceil", "2")

    def test_mod_precedence_matches_latex2sympy(self):
        """Review finding r5: \\mod binds at the multiplicative level
        (latex2sympy mod_test), not looser than +/-."""
        from areal_tpu.rewards.math_verify import answers_equal

        assert answers_equal("3 + 7 \\mod 4", "6")
        assert not answers_equal("3 + 7 \\mod 4", "2")
        assert answers_equal("7 \\mod 4 + 1", "4")
        assert answers_equal("6 \\pmod{4}", "2")
