"""Bundled benchmark registry + process-pool grading + aggregation schema.

Covers VERDICT r4 next-round item #6: the five headline benchmarks
(aime24/25, amc23, gpqa_diamond, math_500) ship with the package, render
the reference's prompt templates, and grade through a killable worker
pool with per-item deadlines (``/root/reference/evaluation/
eval_and_aggregate.py``, ``evaluate.py:44-60``)."""

import json
import os
import time

import pytest

from areal_tpu.evaluation import benchmarks as bm
from areal_tpu.evaluation.grading import PoolGrader
from areal_tpu.evaluation.mcq import extract_choice, grade_choice


EXPECTED_COUNTS = {
    "aime24": 30, "aime25": 30, "amc23": 40,
    "gpqa_diamond": 198, "math_500": 500,
}


def test_all_benchmarks_load_with_expected_counts():
    assert sorted(bm.benchmark_names()) == sorted(EXPECTED_COUNTS)
    for name, n in EXPECTED_COUNTS.items():
        recs = bm.load_benchmark(name)
        assert len(recs) == n, name
        for r in recs[:5]:
            assert r["prompt"].strip()
            assert r["solutions"][0] != ""
            assert r["task"] in ("math", "gpqa")
            assert r["query_id"].startswith(name)


def test_math_template_rendering():
    recs = bm.load_benchmark("aime24", max_items=1)
    p = recs[0]["prompt"]
    assert p.startswith("<｜User｜>")
    assert "\\boxed{}" in p
    assert p.endswith("<｜Assistant｜><think>\n")
    assert "{input}" not in p


def test_gpqa_template_and_gold_letters():
    recs = bm.load_benchmark("gpqa_diamond")
    assert all(r["solutions"][0] in "ABCD" for r in recs)
    assert "choice letter" in recs[0]["prompt"]
    # options are embedded in the question text
    assert "A." in recs[0]["prompt"]


def test_template_override():
    recs = bm.load_benchmark(
        "math_500", template="qwen25-math-cot", max_items=1
    )
    assert recs[0]["prompt"].startswith("<|im_start|>system")


def test_write_benchmark_jsonl_roundtrip(tmp_path):
    path = bm.write_benchmark_jsonl(
        "amc23", str(tmp_path / "amc23.jsonl"), max_items=3
    )
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert len(lines) == 3
    assert lines[0]["task"] == "math"


def test_mcq_extraction_variants():
    assert extract_choice("blah \\boxed{D}") == "D"
    assert extract_choice("\\boxed{(B)}") == "B"
    assert extract_choice("\\boxed{C. 10^-8 ev}") == "C"
    assert extract_choice("the answer is A") == "A"
    assert extract_choice("no letter here") == ""
    assert grade_choice("thus \\boxed{D}", "D") == 1.0
    assert grade_choice("thus \\boxed{A}", "D") == 0.0


def test_pool_grader_math_and_gpqa():
    with PoolGrader(n_workers=2, timeout_s=10.0) as pool:
        scores = pool.grade([
            ("math", "the answer is \\boxed{7}", ["7"]),
            ("math", "\\boxed{8}", ["7"]),
            ("gpqa", "\\boxed{D}", "D"),
            ("gpqa", "\\boxed{A}", "D"),
        ])
    assert scores[0] > 0 and scores[2] > 0
    assert scores[1] <= 0 and scores[3] == 0.0


def _hang_grader(task, answer, gold):
    if answer == "hang":
        time.sleep(60)
    return 1.0


def test_pool_grader_kills_wedged_worker():
    pool = PoolGrader(n_workers=2, timeout_s=1.0, grade_one=_hang_grader)
    try:
        t0 = time.monotonic()
        scores = pool.grade([
            ("math", "ok", ["1"]),
            ("math", "hang", ["1"]),
            ("math", "ok", ["1"]),
        ])
        assert time.monotonic() - t0 < 20
        # timeout scores as a WRONG math answer (-1.0), matching the
        # in-process convention so reward_mean stays comparable
        assert scores == [1.0, -1.0, 1.0]
        assert pool.timeout_cnt == 1
        # pool still serves after the kill/respawn
        assert pool.grade([("math", "ok", ["1"])]) == [1.0]
    finally:
        pool.close()


def test_grade_answers_dispatch_gpqa():
    from areal_tpu.apps.eval_offline import grade_answers

    meta = {"task": "gpqa", "solutions": ["B"]}
    assert grade_answers("q", ["\\boxed{B}", "\\boxed{C}"], meta) == [1.0, 0.0]


def test_aggregate_schema_matches_reference():
    from areal_tpu.apps.eval_offline import aggregate_from_records

    per_prompt = [
        {"rewards": [1.0, -1.0, 1.0, -1.0], "gen_lens": [10, 12, 9, 11],
         "answers": ["\\boxed{1}", "\\boxed{2}", "\\boxed{1}", "\\boxed{3}"],
         "greedy_reward": 1.0, "greedy_len": 10},
        {"rewards": [-1.0, -1.0, -1.0, -1.0], "gen_lens": [8, 8, 8, 8],
         "answers": ["\\boxed{4}"] * 4,
         "greedy_reward": -1.0, "greedy_len": 8},
    ]
    agg = aggregate_from_records(per_prompt, n_sampling=4, path="x.jsonl")
    # the reference's metric-table keys (eval_and_aggregate.py:163-189)
    for key in ("num_questions", "sample_length", "greedy_acc",
                "greedy_length", "sample_pass@1", "pass@1", "pass@2",
                "pass@4"):
        assert key in agg, key
    assert agg["num_questions"] == 2
    assert agg["greedy_acc"] == 0.5
    assert 0.0 < agg["pass@1"] < 1.0
    assert agg["pass@4"] == 0.5


def test_gpqa_metadata_via_prompt_dataset(tmp_path):
    """gpqa records flow through MathCodePromptDataset with task intact."""
    from areal_tpu.api.dataset import DatasetUtility, dataset_metadata, \
        make_dataset

    path = bm.write_benchmark_jsonl(
        "gpqa_diamond", str(tmp_path / "g.jsonl"), max_items=2
    )
    # prompt text needs a tokenizer; reuse prompt_ids to stay hermetic
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    for r in recs:
        r["prompt_ids"] = [1, 2, 3]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    util = DatasetUtility(seed=0, dp_rank=0, world_size=1, tokenizer=None)
    ds = make_dataset("math_code_prompt", util, path=path)
    meta = dataset_metadata(ds)
    assert all(m["task"] == "gpqa" for m in meta.values())
    assert all(m["solutions"][0] in "ABCD" for m in meta.values())


def test_eval_offline_bundled_benchmarks_e2e(tmp_path):
    """VERDICT r4 #6 'Done' criterion: ``eval_offline --benchmark`` over all
    five bundled benchmarks on a tiny random model reproduces the
    reference's metric-table schema (scores ~0 — the model is noise)."""
    import jax
    import numpy as np
    from tokenizers import Tokenizer, models as tok_models, pre_tokenizers
    import transformers

    from areal_tpu.apps import eval_offline
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.models import hf as hf_conv, transformer as tfm

    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, use_attention_bias=True,
        dtype="float32",
    )
    ckpt = str(tmp_path / "ckpt")
    hf_conv.save_hf_checkpoint(
        jax.tree.map(
            lambda x: np.asarray(x), tfm.init_params(cfg, jax.random.key(0))
        ),
        cfg, "qwen2", ckpt,
    )
    # offline word-level tokenizer over the model's 128-token vocab
    vocab = {f"t{i}": i for i in range(126)}
    vocab["[UNK]"], vocab["</s>"] = 126, 127
    tok = Tokenizer(tok_models.WordLevel(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, unk_token="[UNK]", eos_token="</s>"
    ).save_pretrained(ckpt)

    out = str(tmp_path / "eval")
    rc = eval_offline.main([
        "--model-path", ckpt, "--output-dir", out,
        "--benchmark", "all", "--max-prompts", "2",
        "--n-sampling", "2", "--max-gen-tokens", "8", "--with-greedy",
        "--batch-prompts", "2", "--grade-workers", "2",
    ])
    assert rc == 0
    agg = json.load(open(os.path.join(out, "aggregate.json")))
    assert set(agg["benchmarks"]) == set(EXPECTED_COUNTS)
    for name, b in agg["benchmarks"].items():
        for key in ("num_questions", "sample_length", "greedy_acc",
                    "greedy_length", "sample_pass@1", "pass@1", "pass@2",
                    "timeout_samples"):
            assert key in b, (name, key)
        assert b["num_questions"] == 2
        samples = os.path.join(out, name, "samples.jsonl")
        lines = [json.loads(line) for line in open(samples)]
        assert len(lines) == 2 and all(len(r["answers"]) == 2 for r in lines)

    # --from-generated re-aggregates without touching the model
    os.remove(os.path.join(out, "aggregate.json"))
    rc = eval_offline.main([
        "--model-path", ckpt, "--output-dir", out,
        "--benchmark", "all", "--max-prompts", "2", "--from-generated",
    ])
    assert rc == 0
    agg2 = json.load(open(os.path.join(out, "aggregate.json")))
    for name in EXPECTED_COUNTS:
        assert agg2["benchmarks"][name]["pass@1"] == \
            agg["benchmarks"][name]["pass@1"]


def test_from_generated_regrades_with_current_verifier(tmp_path):
    """--from-generated re-runs answers through the CURRENT graders (the
    review finding: stale stored rewards must not survive a verifier fix)
    and bypasses the aggregate-exists idempotence guard."""
    from areal_tpu.apps import eval_offline

    out = tmp_path / "eval" / "bench"
    out.mkdir(parents=True)
    data = tmp_path / "bench.jsonl"
    with open(data, "w") as f:
        f.write(json.dumps({
            "query_id": "q0", "prompt_ids": [1, 2], "task": "math",
            "solutions": ["2"],
        }) + "\n")
    # stored sweep: rewards recorded WRONG (pre-fix verifier), answers right
    with open(out / "samples.jsonl", "w") as f:
        f.write(json.dumps({
            "qid": "q0", "answers": ["\\boxed{128 \\mod 3}", "\\boxed{5}"],
            "rewards": [-1.0, -1.0], "gen_lens": [4, 1],
            "no_eos": [False, False],
        }) + "\n")
    # pre-existing aggregate must NOT short-circuit --from-generated
    with open(tmp_path / "eval" / "aggregate.json", "w") as f:
        f.write("{}")
    rc = eval_offline.main([
        "--model-path", "unused", "--output-dir", str(tmp_path / "eval"),
        "--dataset", f"bench={data}", "--from-generated",
        "--grade-workers", "0",
    ])
    assert rc == 0
    agg = json.load(open(tmp_path / "eval" / "aggregate.json"))
    b = agg["benchmarks"]["bench"]
    assert b["pass@1"] == 0.5  # 128 mod 3 == 2 now grades correct
    assert b["pass@2"] == 1.0


def _crash_grader(task, answer, gold):
    if answer == "die":
        os._exit(17)  # simulate a segfault/OOM kill
    return 1.0


def test_pool_grader_detects_dead_worker_fast():
    """Review finding r5: a CRASHED worker (not a wedge) must be detected
    by liveness, not by waiting out the deadline + spawn allowance."""
    pool = PoolGrader(n_workers=1, timeout_s=30.0, grade_one=_crash_grader)
    try:
        t0 = time.monotonic()
        scores = pool.grade([
            ("math", "ok", ["1"]),
            ("math", "die", ["1"]),
            ("math", "ok", ["1"]),
        ])
        # far below timeout_s (30) + SPAWN_ALLOWANCE (120)
        assert time.monotonic() - t0 < 25
        assert scores == [1.0, -1.0, 1.0]
        assert pool.timeout_cnt == 1
    finally:
        pool.close()
