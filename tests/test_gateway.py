"""Serving-gateway tests (docs/serving.md).

End-to-end OpenAI-compatible serving against a REAL (tiny) generation
engine: buffered + SSE completions through the gateway, chunk ordering,
early-disconnect slot release, per-tenant rate limits, KV-occupancy
admission control, weighted-fair-queue starvation freedom, the gen
server's /generate validation 400s, the streaming client, and the
autoscaler decision table on synthetic ``fleet/`` aggregates.
"""

import asyncio
import json

import aiohttp
import pytest

import jax

from areal_tpu.base import network
from areal_tpu.gateway.api import (
    ByteFallbackCodec,
    GatewayConfig,
    GatewayServer,
    serve_gateway,
)
from areal_tpu.gateway.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ScaleSignals,
    decide,
)
from areal_tpu.gateway.qos import TenantSpec, TokenBucket, WeightedFairQueue
from areal_tpu.gateway.scheduler import (
    ContinuousBatchScheduler,
    GatewayRequest,
    RateLimited,
)
from areal_tpu.gen.client import GenAPIClient
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.server import serve
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


class _Stack:
    """Engine + gen server + scheduler + gateway on real TCP ports."""

    def __init__(self, eng, gen_runner, scheduler, gw_runner, gw_url):
        self.eng = eng
        self.gen_runner = gen_runner
        self.scheduler = scheduler
        self.gw_runner = gw_runner
        self.gw_url = gw_url

    async def close(self):
        await self.scheduler.stop()
        await self.gw_runner.cleanup()
        await self.gen_runner.cleanup()


async def _stack(
    params, *, slots=4, tenants=None, max_queue=64, decode_steps=2,
    gw_config=None, metrics_poll_interval=2.0,
) -> _Stack:
    eng = GenerationEngine(CFG, params, max_slots=slots, max_seqlen=128)
    gen_port = network.find_free_port()
    gen_runner = await serve(
        eng, "127.0.0.1", gen_port, decode_steps=decode_steps
    )
    scheduler = ContinuousBatchScheduler(
        [f"http://127.0.0.1:{gen_port}"],
        tenants or {},
        max_queue=max_queue,
        metrics_poll_interval=metrics_poll_interval,
    )
    await scheduler.start()
    gw = GatewayServer(
        scheduler, ByteFallbackCodec(CFG.vocab_size),
        gw_config or GatewayConfig(max_tokens_cap=256),
    )
    gw_port = network.find_free_port()
    gw_runner = await serve_gateway(gw, "127.0.0.1", gw_port)
    return _Stack(
        eng, gen_runner, scheduler, gw_runner,
        f"http://127.0.0.1:{gw_port}",
    )


async def _sse_frames(resp):
    frames, done = [], False
    async for raw in resp.content:
        line = raw.strip()
        if not line.startswith(b"data:"):
            continue
        payload = line[len(b"data:"):].strip()
        if payload == b"[DONE]":
            done = True
            break
        frames.append(json.loads(payload))
    return frames, done


PROMPT = [3, 17, 42, 99, 5]


# --------------------------------------------------------------------- #
# OpenAI surface, end to end against the real engine
# --------------------------------------------------------------------- #


async def test_completion_e2e_buffered_and_streaming(params):
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 8, "temperature": 0},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["object"] == "text_completion"
            choice = body["choices"][0]
            assert choice["finish_reason"] in ("stop", "length")
            assert body["usage"]["completion_tokens"] == 8
            assert body["usage"]["prompt_tokens"] == len(PROMPT)
            buffered_text = choice["text"]
            assert len(buffered_text) > 0

            # same greedy prompt, streamed: the concatenated deltas must
            # equal the buffered text, finish_reason only on the last
            # frame, [DONE] terminator present
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={
                    "prompt": PROMPT, "max_tokens": 8, "temperature": 0,
                    "stream": True,
                },
            )
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            frames, done = await _sse_frames(r)
            assert done
            assert len(frames) >= 2  # decode_steps=2 < 8 tokens -> chunks
            for f in frames[:-1]:
                assert f["choices"][0]["finish_reason"] is None
            assert frames[-1]["choices"][0]["finish_reason"] in (
                "stop", "length"
            )
            streamed = "".join(f["choices"][0]["text"] for f in frames)
            assert streamed == buffered_text
    finally:
        await st.close()


async def test_chat_completion_e2e(params):
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            msgs = [
                {"role": "system", "content": "hi"},
                {"role": "user", "content": "abc"},
            ]
            r = await s.post(
                f"{st.gw_url}/v1/chat/completions",
                json={"messages": msgs, "max_tokens": 6, "temperature": 0},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["object"] == "chat.completion"
            msg = body["choices"][0]["message"]
            assert msg["role"] == "assistant"
            assert isinstance(msg["content"], str)

            r = await s.post(
                f"{st.gw_url}/v1/chat/completions",
                json={
                    "messages": msgs, "max_tokens": 6, "temperature": 0,
                    "stream": True,
                },
            )
            frames, done = await _sse_frames(r)
            assert done and frames
            assert frames[0]["object"] == "chat.completion.chunk"
            assert frames[0]["choices"][0]["delta"].get("role") == "assistant"
    finally:
        await st.close()


async def test_gateway_validation_400(params):
    st = await _stack(params)
    bad_bodies = [
        {},                                             # missing prompt
        {"prompt": ""},                                 # empty prompt
        {"prompt": PROMPT, "max_tokens": 0},            # max_tokens < 1
        {"prompt": PROMPT, "temperature": -1},          # bad temperature
        {"prompt": PROMPT, "top_p": 0},                 # bad top_p
        {"prompt": PROMPT, "n": 2},                     # unsupported n
        {"prompt": [1.5, 2.5]},                         # non-int tokens
        {"prompt": PROMPT, "stop_token_ids": 5},        # non-list stops
        {"prompt": PROMPT, "max_tokens": 256},          # beyond slot cap
    ]
    try:
        async with aiohttp.ClientSession() as s:
            for body in bad_bodies:
                r = await s.post(f"{st.gw_url}/v1/completions", json=body)
                assert r.status == 400, body
                err = (await r.json())["error"]
                assert err["type"] == "invalid_request_error"
            r = await s.post(
                f"{st.gw_url}/v1/chat/completions", json={"messages": []}
            )
            assert r.status == 400
            # tenancy: unknown key with require_api_key=False falls back
            # to anonymous and still serves
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 2},
                headers={"Authorization": "Bearer nope"},
            )
            assert r.status == 200
    finally:
        await st.close()


# --------------------------------------------------------------------- #
# QoS: rate limits, fair queueing, admission control
# --------------------------------------------------------------------- #


async def test_per_tenant_rate_limit_enforced(params):
    # tenant "small" can afford exactly one request (burst == one cost);
    # tenant "big" is unlimited and must be unaffected
    cost = len(PROMPT) + 4
    tenants = {
        "small": TenantSpec(
            "small", rate_tokens_per_s=0.001, burst_tokens=cost
        ),
        "big": TenantSpec("big"),
    }
    st = await _stack(params, tenants=tenants)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"prompt": PROMPT, "max_tokens": 4, "temperature": 0}
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "small"},
            )
            assert r.status == 200
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "small"},
            )
            assert r.status == 429
            assert "Retry-After" in r.headers
            assert (await r.json())["error"]["code"] == "rate_limit_exceeded"
            # the heavy-handed tenant's limit is not the fleet's
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "big"},
            )
            assert r.status == 200
    finally:
        await st.close()


async def test_unserveable_cost_answers_400_not_429(params):
    # cost above burst can NEVER be admitted: a 429 would retry forever
    tenants = {"tiny": TenantSpec("tiny", rate_tokens_per_s=1.0,
                                  burst_tokens=4.0)}
    st = await _stack(params, tenants=tenants)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 50},
                headers={"X-Tenant": "tiny"},
            )
            assert r.status == 400
            assert "never be admitted" in (await r.json())["error"]["message"]
    finally:
        await st.close()


async def test_unknown_x_tenant_collapses_to_default(params):
    # rotating X-Tenant must not mint fresh token buckets per name
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            for i in range(3):
                r = await s.post(
                    f"{st.gw_url}/v1/completions",
                    json={"prompt": PROMPT, "max_tokens": 2},
                    headers={"X-Tenant": f"minted-{i}"},
                )
                assert r.status == 200
        assert not any(
            t.startswith("minted-") for t in st.scheduler.tenants
        )
    finally:
        await st.close()


def test_wfq_drop_rolls_back_virtual_clock():
    # cancelled queued work must not deprioritize the tenant's future
    # traffic: after dropping its whole backlog, its next item competes
    # as if the backlog never existed
    q = WeightedFairQueue()
    for i in range(10):
        q.push("a", 100.0, 1.0, ("a", i))
    q.push("b", 150.0, 1.0, ("b", 0))
    q.drop_where(lambda it: it[0] == "a")
    q.push("a", 100.0, 1.0, ("a", "fresh"))
    # a's rolled-back stamp (100) beats b's (150); without the rollback
    # a's stamp would be 1100 and b would pop first
    assert q.pop() == ("a", "fresh")


def test_wfq_rollback_after_pop():
    # the popped-entry twin of drop_where's rollback: a popped-then-
    # cancelled request must not deprioritize the tenant's future traffic
    q = WeightedFairQueue()
    q.push("a", 100.0, 1.0, ("a", 0))
    q.push("a", 100.0, 1.0, ("a", 1))
    assert q.pop() == ("a", 0)
    q.rollback("a", 100.0, 1.0)
    # the tenant's clock holds only the SURVIVING entry's share, and that
    # entry's stamp shifted down with it
    assert q._last_vft["a"] == pytest.approx(100.0)
    assert q._queues["a"][0][0] == pytest.approx(100.0)
    assert q.pop() == ("a", 1)


def test_demand_occupancy_excludes_evictable_cache(params):
    # a cache-warm idle server must not read as "full" to the admission
    # gate: raw occupancy counts prefix-cache pages the next admission
    # would evict; the demand signal excludes them
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=512)
    prompt = list(range(1, 128)) + [5, 9, 11]  # > one page: cacheable
    eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=2,
                          greedy=True))
    eng.run_until_done(decode_steps=2)
    assert eng.n_running() == 0
    assert eng.kv_pool_occupancy() > 0.0          # cache holds pages
    assert eng.kv_pool_demand_occupancy() == 0.0  # all reclaimable


class _StubGenClient:
    """Capacity-poll-only stand-in: the dispatch path must never reach
    generate_stream in the cancel-race test."""

    def __init__(self):
        self.streams = 0

    async def metrics(self, url):
        return {
            "max_slots": 4,
            "kv_pool_demand_occupancy": 0.0,
            "slot_capacity": 4096,
        }

    async def generate_stream(self, url, rid, ids, sp):
        self.streams += 1
        yield {"token_ids": [], "logprobs": [], "finish_reason": "stop"}


async def test_cancel_while_dispatching_refunds_charge():
    """cancel() racing the dispatch pop: drop_where misses the popped
    entry and no _run_request will ever settle it — the dispatch loop
    must refund the full budget or the tenant bucket leaks one request
    cost per race (lifecycle-rule triage fix)."""
    stub = _StubGenClient()
    sched = ContinuousBatchScheduler(
        ["http://stub:1"],
        tenants={"t": TenantSpec(
            name="t", weight=1.0, rate_tokens_per_s=100.0,
            burst_tokens=10_000.0,
        )},
        client=stub,
    )
    await sched.start()
    try:
        req = GatewayRequest.build("t", [1, 2, 3], {"max_new_tokens": 61})
        bucket = sched._bucket("t")
        before = bucket.available
        # the race, made deterministic: the flag is set but the entry is
        # (about to be) popped, so cancel()'s drop_where path misses it
        req.cancelled = True
        sched.submit(req)
        assert bucket.available <= before - req.cost + 1.0
        for _ in range(200):
            await asyncio.sleep(0.01)
            if sched.queue_depth() == 0 and sched.inflight() == 0:
                break
        assert sched.queue_depth() == 0
        assert sched.inflight() == 0
        assert stub.streams == 0  # never dispatched to a backend
        assert bucket.available == pytest.approx(before, abs=2.0)
        # the fair-queue virtual clock rolled back too: the popped entry
        # never ran, so it must not count against the tenant's share
        assert sched._wfq._last_vft.get("t", 0.0) == pytest.approx(0.0)
    finally:
        await sched.stop()


def test_token_bucket_refill_and_refund():
    t = {"now": 0.0}
    b = TokenBucket(10.0, 20.0, clock=lambda: t["now"])
    assert b.try_acquire(20.0)
    assert not b.try_acquire(1.0)
    assert b.retry_after_s(1.0) == pytest.approx(0.1)
    t["now"] = 1.0  # 10 tokens refilled
    assert b.try_acquire(10.0)
    b.refund(5.0)
    assert b.try_acquire(5.0)
    # unlimited bucket never rejects
    assert TokenBucket(0.0, 0.0).try_acquire(1e12)


def test_fair_queue_starvation_free():
    q = WeightedFairQueue()
    for i in range(50):
        q.push("heavy", 100.0, 1.0, ("heavy", i))
    q.push("light", 100.0, 1.0, ("light", 0))
    # the light tenant enqueued LAST but its virtual finish time rides the
    # global clock, not the heavy backlog: it must pop within the first 2
    first_two = [q.pop() for _ in range(2)]
    assert ("light", 0) in first_two
    # weighted share: a weight-3 tenant drains ~3x faster than weight-1
    q = WeightedFairQueue()
    for i in range(30):
        q.push("w1", 10.0, 1.0, ("w1", i))
        q.push("w3", 10.0, 3.0, ("w3", i))
    head = [q.pop()[0] for _ in range(20)]
    assert head.count("w3") >= 2 * head.count("w1")


async def test_admission_holds_at_full_kv_pool(params):
    st = await _stack(params, metrics_poll_interval=9999.0)
    try:
        sched = st.scheduler
        srv = next(iter(sched._servers.values()))
        srv.kv_occupancy = 0.99  # full pool: past the admit gate
        req = GatewayRequest.build(
            "t", PROMPT, {"max_new_tokens": 4, "greedy": True}
        )
        sched.submit(req)
        await asyncio.sleep(0.2)
        # queued, NOT dispatched — the engine never sees it
        assert sched.queue_depth() == 1
        assert sched.inflight() == 0
        # pool frees up: dispatch proceeds and the request completes
        srv.kv_occupancy = 0.0
        sched._wake.set()
        got = []
        async for ev in sched.events(req):
            got.extend(ev.get("token_ids", []))
        assert len(got) == 4
        assert sched.queue_depth() == 0
    finally:
        await st.close()


async def test_queue_full_answers_429(params):
    st = await _stack(params, max_queue=1, metrics_poll_interval=9999.0)
    try:
        sched = st.scheduler
        next(iter(sched._servers.values())).kv_occupancy = 0.99  # block
        sched.submit(
            GatewayRequest.build("t", PROMPT, {"max_new_tokens": 2})
        )
        with pytest.raises(RateLimited):
            sched.submit(
                GatewayRequest.build("t", PROMPT, {"max_new_tokens": 2})
            )
    finally:
        await st.close()


# --------------------------------------------------------------------- #
# gen-server satellites: /generate validation, SSE, disconnect, client
# --------------------------------------------------------------------- #


async def test_generate_validation_400(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    url = f"http://127.0.0.1:{port}"
    bad = [
        {"input_ids": PROMPT},                                  # no rid
        {"rid": "a", "input_ids": []},                          # empty
        {"rid": "a", "input_ids": ["x"]},                       # non-int
        {"rid": "a", "input_ids": [5, 999]},                    # OOV
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"max_new_tokens": 0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"temperature": -0.5}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"top_p": 0.0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"top_k": 0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"min_new_tokens": 9, "max_new_tokens": 4}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"max_new_tokens": 4096}},           # capacity
    ]
    try:
        async with aiohttp.ClientSession() as s:
            for body in bad:
                for endpoint in ("/generate", "/generate_stream"):
                    r = await s.post(url + endpoint, json=body)
                    assert r.status == 400, (endpoint, body)
                    assert "error" in await r.json()
            # nothing leaked into the engine
            assert eng.n_running() == 0 and eng.n_pending() == 0
    finally:
        await runner.cleanup()


async def test_generate_stream_client_chunks_match_generate(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    url = f"http://127.0.0.1:{port}"
    sp = {"max_new_tokens": 10, "greedy": True}
    try:
        async with GenAPIClient() as c:
            ref = await c.generate(url, "ref", PROMPT, sp)
            toks, lps, finals = [], [], []
            async for ev in c.generate_stream(url, "stream", PROMPT, sp):
                assert len(ev["token_ids"]) == len(ev["logprobs"])
                toks.extend(ev["token_ids"])
                lps.extend(ev["logprobs"])
                if ev.get("finish_reason"):
                    finals.append(ev)
            # chunk-granular deltas concatenate to exactly the buffered
            # result, and exactly one final frame arrives
            assert toks == ref.output_ids
            assert len(finals) == 1
            assert finals[0]["finish_reason"] == ref.finish_reason
            assert finals[0]["version"] == ref.version
    finally:
        await runner.cleanup()


async def test_stream_early_disconnect_releases_slot(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    try:
        async with aiohttp.ClientSession() as s:
            resp = await s.post(
                f"http://127.0.0.1:{port}/generate_stream",
                json={
                    "rid": "dc", "input_ids": PROMPT,
                    "sampling_params": {"max_new_tokens": 120,
                                        "greedy": True},
                },
            )
            assert resp.status == 200
            async for raw in resp.content:  # first delta then hang up
                if raw.startswith(b"data:"):
                    break
            resp.close()
        # the server notices the disconnect and frees the slot + pages
        for _ in range(100):
            await asyncio.sleep(0.05)
            if eng.n_running() == 0 and eng.pool.n_free == eng.n_pages:
                break
        assert eng.n_running() == 0
        assert eng.pool.n_free == eng.n_pages
    finally:
        await runner.cleanup()


# --------------------------------------------------------------------- #
# autoscaler decision table (synthetic fleet/ aggregates)
# --------------------------------------------------------------------- #


def _signals(**kw):
    base = dict(routed=4, healthy=4, queue_depth=0.0, kv_occupancy=0.1,
                queue_wait_p95_s=0.0, breaker_open=0)
    base.update(kw)
    return ScaleSignals(**base)


def test_autoscaler_decision_table():
    cfg = AutoscalerConfig(min_servers=2, max_servers=8)
    cases = [
        # (signals, expected action, expected delta)
        (_signals(routed=1, healthy=1), "grow", 1),          # below floor
        (_signals(healthy=3, breaker_open=1), "grow", 1),    # replace open
        (_signals(queue_depth=40.0), "grow", 2),             # deep backlog
        (_signals(queue_depth=17.0), "grow", 1),             # mild backlog
        (_signals(kv_occupancy=0.9), "grow", 1),             # HBM pressure
        (_signals(queue_wait_p95_s=30.0), "grow", 1),        # latency
        (_signals(), "shrink", 1),                           # idle
        (_signals(routed=2, healthy=2), "hold", 0),          # at the floor
        (_signals(queue_depth=8.0), "hold", 0),              # loaded but ok
        (_signals(routed=8, healthy=8, queue_depth=100.0),
         "hold", 0),                                         # at the ceiling
    ]
    for sig, action, delta in cases:
        d = decide(cfg, sig)
        assert d.action == action, (sig, d)
        if action != "hold":
            assert d.delta == delta, (sig, d)
        if d.action != "hold":
            assert d.reasons


def test_autoscaler_signals_from_fleet_scalars():
    scalars = {
        "gw_queue_depth": 12.0,
        "kv_pool_occupancy": 1.8,      # gauge SUM over 2 gen servers
        "gw/queue_wait_s/p95": 3.5,
        "servers_total": 2.0,
        "servers_open": 1.0,
        "servers_half_open": 0.0,
    }
    sig = ScaleSignals.from_fleet_scalars(scalars, routed=2)
    assert sig.queue_depth == 12.0
    assert sig.kv_occupancy == pytest.approx(0.9)
    assert sig.queue_wait_p95_s == 3.5
    assert sig.breaker_open == 1
    assert sig.healthy == 1


def test_autoscaler_cooldown_and_callbacks():
    t = {"now": 0.0}
    sig = {"cur": _signals(queue_depth=100.0)}
    grown, shrunk = [], []
    asc = Autoscaler(
        AutoscalerConfig(min_servers=1, max_servers=8, cooldown_s=30.0),
        fetch_signals=lambda: sig["cur"],
        grow_cb=lambda n: grown.append(n) or n,
        shrink_cb=lambda n: shrunk.append(n) or n,
        clock=lambda: t["now"],
    )
    d = asc.step_once()
    assert d.action == "grow" and grown == [d.delta]
    # inside the cooldown window further actions are deferred
    t["now"] = 10.0
    assert asc.step_once().action == "hold"
    # after the cooldown, an idle fleet shrinks
    t["now"] = 40.0
    sig["cur"] = _signals()
    d = asc.step_once()
    assert d.action == "shrink" and shrunk == [1]
