"""Serving-gateway tests (docs/serving.md).

End-to-end OpenAI-compatible serving against a REAL (tiny) generation
engine: buffered + SSE completions through the gateway, chunk ordering,
early-disconnect slot release, per-tenant rate limits, KV-occupancy
admission control, weighted-fair-queue starvation freedom, the gen
server's /generate validation 400s, the streaming client, and the
autoscaler decision table on synthetic ``fleet/`` aggregates.
"""

import asyncio
import json
import time

import aiohttp
import pytest

import jax

from areal_tpu.base import metrics as metrics_mod
from areal_tpu.base import network
from areal_tpu.gateway.api import (
    ByteFallbackCodec,
    GatewayConfig,
    GatewayServer,
    serve_gateway,
)
from areal_tpu.gateway.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ScaleSignals,
    decide,
)
from areal_tpu.gateway.brownout import (
    BrownoutConfig,
    BrownoutController,
)
from areal_tpu.gateway.brownout import decide as brownout_decide
from areal_tpu.gateway.qos import TenantSpec, TokenBucket, WeightedFairQueue
from areal_tpu.gateway.scheduler import (
    ContinuousBatchScheduler,
    GatewayRequest,
    RateLimited,
    ServiceUnavailable,
)
from areal_tpu.gen.client import DeadlineExceeded, GenAPIClient
from areal_tpu.gen.engine import GenerationEngine, GenRequest
from areal_tpu.gen.server import serve
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig

CFG = ModelConfig(
    n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
    intermediate_dim=64, vocab_size=128, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.key(5))


class _Stack:
    """Engine + gen server + scheduler + gateway on real TCP ports."""

    def __init__(self, eng, gen_runner, scheduler, gw_runner, gw_url):
        self.eng = eng
        self.gen_runner = gen_runner
        self.scheduler = scheduler
        self.gw_runner = gw_runner
        self.gw_url = gw_url

    async def close(self):
        await self.scheduler.stop()
        await self.gw_runner.cleanup()
        await self.gen_runner.cleanup()


async def _stack(
    params, *, slots=4, tenants=None, max_queue=64, decode_steps=2,
    gw_config=None, metrics_poll_interval=2.0,
) -> _Stack:
    eng = GenerationEngine(CFG, params, max_slots=slots, max_seqlen=128)
    gen_port = network.find_free_port()
    gen_runner = await serve(
        eng, "127.0.0.1", gen_port, decode_steps=decode_steps
    )
    scheduler = ContinuousBatchScheduler(
        [f"http://127.0.0.1:{gen_port}"],
        tenants or {},
        max_queue=max_queue,
        metrics_poll_interval=metrics_poll_interval,
    )
    await scheduler.start()
    gw = GatewayServer(
        scheduler, ByteFallbackCodec(CFG.vocab_size),
        gw_config or GatewayConfig(max_tokens_cap=256),
    )
    gw_port = network.find_free_port()
    gw_runner = await serve_gateway(gw, "127.0.0.1", gw_port)
    return _Stack(
        eng, gen_runner, scheduler, gw_runner,
        f"http://127.0.0.1:{gw_port}",
    )


async def _sse_frames(resp):
    frames, done = [], False
    async for raw in resp.content:
        line = raw.strip()
        if not line.startswith(b"data:"):
            continue
        payload = line[len(b"data:"):].strip()
        if payload == b"[DONE]":
            done = True
            break
        frames.append(json.loads(payload))
    return frames, done


PROMPT = [3, 17, 42, 99, 5]


# --------------------------------------------------------------------- #
# OpenAI surface, end to end against the real engine
# --------------------------------------------------------------------- #


async def test_completion_e2e_buffered_and_streaming(params):
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 8, "temperature": 0},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["object"] == "text_completion"
            choice = body["choices"][0]
            assert choice["finish_reason"] in ("stop", "length")
            assert body["usage"]["completion_tokens"] == 8
            assert body["usage"]["prompt_tokens"] == len(PROMPT)
            buffered_text = choice["text"]
            assert len(buffered_text) > 0

            # same greedy prompt, streamed: the concatenated deltas must
            # equal the buffered text, finish_reason only on the last
            # frame, [DONE] terminator present
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={
                    "prompt": PROMPT, "max_tokens": 8, "temperature": 0,
                    "stream": True,
                },
            )
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            frames, done = await _sse_frames(r)
            assert done
            assert len(frames) >= 2  # decode_steps=2 < 8 tokens -> chunks
            for f in frames[:-1]:
                assert f["choices"][0]["finish_reason"] is None
            assert frames[-1]["choices"][0]["finish_reason"] in (
                "stop", "length"
            )
            streamed = "".join(f["choices"][0]["text"] for f in frames)
            assert streamed == buffered_text
    finally:
        await st.close()


async def test_chat_completion_e2e(params):
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            msgs = [
                {"role": "system", "content": "hi"},
                {"role": "user", "content": "abc"},
            ]
            r = await s.post(
                f"{st.gw_url}/v1/chat/completions",
                json={"messages": msgs, "max_tokens": 6, "temperature": 0},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["object"] == "chat.completion"
            msg = body["choices"][0]["message"]
            assert msg["role"] == "assistant"
            assert isinstance(msg["content"], str)

            r = await s.post(
                f"{st.gw_url}/v1/chat/completions",
                json={
                    "messages": msgs, "max_tokens": 6, "temperature": 0,
                    "stream": True,
                },
            )
            frames, done = await _sse_frames(r)
            assert done and frames
            assert frames[0]["object"] == "chat.completion.chunk"
            assert frames[0]["choices"][0]["delta"].get("role") == "assistant"
    finally:
        await st.close()


async def test_gateway_validation_400(params):
    st = await _stack(params)
    bad_bodies = [
        {},                                             # missing prompt
        {"prompt": ""},                                 # empty prompt
        {"prompt": PROMPT, "max_tokens": 0},            # max_tokens < 1
        {"prompt": PROMPT, "temperature": -1},          # bad temperature
        {"prompt": PROMPT, "top_p": 0},                 # bad top_p
        {"prompt": PROMPT, "n": 2},                     # unsupported n
        {"prompt": [1.5, 2.5]},                         # non-int tokens
        {"prompt": PROMPT, "stop_token_ids": 5},        # non-list stops
        {"prompt": PROMPT, "max_tokens": 256},          # beyond slot cap
    ]
    try:
        async with aiohttp.ClientSession() as s:
            for body in bad_bodies:
                r = await s.post(f"{st.gw_url}/v1/completions", json=body)
                assert r.status == 400, body
                err = (await r.json())["error"]
                assert err["type"] == "invalid_request_error"
            r = await s.post(
                f"{st.gw_url}/v1/chat/completions", json={"messages": []}
            )
            assert r.status == 400
            # tenancy: unknown key with require_api_key=False falls back
            # to anonymous and still serves
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 2},
                headers={"Authorization": "Bearer nope"},
            )
            assert r.status == 200
    finally:
        await st.close()


# --------------------------------------------------------------------- #
# QoS: rate limits, fair queueing, admission control
# --------------------------------------------------------------------- #


async def test_per_tenant_rate_limit_enforced(params):
    # tenant "small" can afford exactly one request (burst == one cost);
    # tenant "big" is unlimited and must be unaffected
    cost = len(PROMPT) + 4
    tenants = {
        "small": TenantSpec(
            "small", rate_tokens_per_s=0.001, burst_tokens=cost
        ),
        "big": TenantSpec("big"),
    }
    st = await _stack(params, tenants=tenants)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"prompt": PROMPT, "max_tokens": 4, "temperature": 0}
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "small"},
            )
            assert r.status == 200
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "small"},
            )
            assert r.status == 429
            assert "Retry-After" in r.headers
            assert (await r.json())["error"]["code"] == "rate_limit_exceeded"
            # the heavy-handed tenant's limit is not the fleet's
            r = await s.post(
                f"{st.gw_url}/v1/completions", json=body,
                headers={"X-Tenant": "big"},
            )
            assert r.status == 200
    finally:
        await st.close()


async def test_unserveable_cost_answers_400_not_429(params):
    # cost above burst can NEVER be admitted: a 429 would retry forever
    tenants = {"tiny": TenantSpec("tiny", rate_tokens_per_s=1.0,
                                  burst_tokens=4.0)}
    st = await _stack(params, tenants=tenants)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 50},
                headers={"X-Tenant": "tiny"},
            )
            assert r.status == 400
            assert "never be admitted" in (await r.json())["error"]["message"]
    finally:
        await st.close()


async def test_unknown_x_tenant_collapses_to_default(params):
    # rotating X-Tenant must not mint fresh token buckets per name
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            for i in range(3):
                r = await s.post(
                    f"{st.gw_url}/v1/completions",
                    json={"prompt": PROMPT, "max_tokens": 2},
                    headers={"X-Tenant": f"minted-{i}"},
                )
                assert r.status == 200
        assert not any(
            t.startswith("minted-") for t in st.scheduler.tenants
        )
    finally:
        await st.close()


def test_wfq_drop_rolls_back_virtual_clock():
    # cancelled queued work must not deprioritize the tenant's future
    # traffic: after dropping its whole backlog, its next item competes
    # as if the backlog never existed
    q = WeightedFairQueue()
    for i in range(10):
        q.push("a", 100.0, 1.0, ("a", i))
    q.push("b", 150.0, 1.0, ("b", 0))
    q.drop_where(lambda it: it[0] == "a")
    q.push("a", 100.0, 1.0, ("a", "fresh"))
    # a's rolled-back stamp (100) beats b's (150); without the rollback
    # a's stamp would be 1100 and b would pop first
    assert q.pop() == ("a", "fresh")


def test_wfq_rollback_after_pop():
    # the popped-entry twin of drop_where's rollback: a popped-then-
    # cancelled request must not deprioritize the tenant's future traffic
    q = WeightedFairQueue()
    q.push("a", 100.0, 1.0, ("a", 0))
    q.push("a", 100.0, 1.0, ("a", 1))
    assert q.pop() == ("a", 0)
    q.rollback("a", 100.0, 1.0)
    # the tenant's clock holds only the SURVIVING entry's share, and that
    # entry's stamp shifted down with it
    assert q._last_vft["a"] == pytest.approx(100.0)
    assert q._queues["a"][0][0] == pytest.approx(100.0)
    assert q.pop() == ("a", 1)


def test_demand_occupancy_excludes_evictable_cache(params):
    # a cache-warm idle server must not read as "full" to the admission
    # gate: raw occupancy counts prefix-cache pages the next admission
    # would evict; the demand signal excludes them
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=512)
    prompt = list(range(1, 128)) + [5, 9, 11]  # > one page: cacheable
    eng.submit(GenRequest(rid="a", input_ids=prompt, max_new_tokens=2,
                          greedy=True))
    eng.run_until_done(decode_steps=2)
    assert eng.n_running() == 0
    assert eng.kv_pool_occupancy() > 0.0          # cache holds pages
    assert eng.kv_pool_demand_occupancy() == 0.0  # all reclaimable


class _StubGenClient:
    """Capacity-poll-only stand-in: the dispatch path must never reach
    generate_stream in the cancel-race test."""

    def __init__(self):
        self.streams = 0

    async def metrics(self, url):
        return {
            "max_slots": 4,
            "kv_pool_demand_occupancy": 0.0,
            "slot_capacity": 4096,
        }

    async def generate_stream(self, url, rid, ids, sp, deadline_s=None):
        self.streams += 1
        yield {"token_ids": [], "logprobs": [], "finish_reason": "stop"}


async def test_cancel_while_dispatching_refunds_charge():
    """cancel() racing the dispatch pop: drop_where misses the popped
    entry and no _run_request will ever settle it — the dispatch loop
    must refund the full budget or the tenant bucket leaks one request
    cost per race (lifecycle-rule triage fix)."""
    stub = _StubGenClient()
    sched = ContinuousBatchScheduler(
        ["http://stub:1"],
        tenants={"t": TenantSpec(
            name="t", weight=1.0, rate_tokens_per_s=100.0,
            burst_tokens=10_000.0,
        )},
        client=stub,
    )
    await sched.start()
    try:
        req = GatewayRequest.build("t", [1, 2, 3], {"max_new_tokens": 61})
        bucket = sched._bucket("t")
        before = bucket.available
        # the race, made deterministic: the flag is set but the entry is
        # (about to be) popped, so cancel()'s drop_where path misses it
        req.cancelled = True
        sched.submit(req)
        assert bucket.available <= before - req.cost + 1.0
        for _ in range(200):
            await asyncio.sleep(0.01)
            if sched.queue_depth() == 0 and sched.inflight() == 0:
                break
        assert sched.queue_depth() == 0
        assert sched.inflight() == 0
        assert stub.streams == 0  # never dispatched to a backend
        assert bucket.available == pytest.approx(before, abs=2.0)
        # the fair-queue virtual clock rolled back too: the popped entry
        # never ran, so it must not count against the tenant's share
        assert sched._wfq._last_vft.get("t", 0.0) == pytest.approx(0.0)
    finally:
        await sched.stop()


def test_token_bucket_refill_and_refund():
    t = {"now": 0.0}
    b = TokenBucket(10.0, 20.0, clock=lambda: t["now"])
    assert b.try_acquire(20.0)
    assert not b.try_acquire(1.0)
    assert b.retry_after_s(1.0) == pytest.approx(0.1)
    t["now"] = 1.0  # 10 tokens refilled
    assert b.try_acquire(10.0)
    b.refund(5.0)
    assert b.try_acquire(5.0)
    # unlimited bucket never rejects
    assert TokenBucket(0.0, 0.0).try_acquire(1e12)


def test_fair_queue_starvation_free():
    q = WeightedFairQueue()
    for i in range(50):
        q.push("heavy", 100.0, 1.0, ("heavy", i))
    q.push("light", 100.0, 1.0, ("light", 0))
    # the light tenant enqueued LAST but its virtual finish time rides the
    # global clock, not the heavy backlog: it must pop within the first 2
    first_two = [q.pop() for _ in range(2)]
    assert ("light", 0) in first_two
    # weighted share: a weight-3 tenant drains ~3x faster than weight-1
    q = WeightedFairQueue()
    for i in range(30):
        q.push("w1", 10.0, 1.0, ("w1", i))
        q.push("w3", 10.0, 3.0, ("w3", i))
    head = [q.pop()[0] for _ in range(20)]
    assert head.count("w3") >= 2 * head.count("w1")


async def test_admission_holds_at_full_kv_pool(params):
    st = await _stack(params, metrics_poll_interval=9999.0)
    try:
        sched = st.scheduler
        srv = next(iter(sched._servers.values()))
        srv.kv_occupancy = 0.99  # full pool: past the admit gate
        req = GatewayRequest.build(
            "t", PROMPT, {"max_new_tokens": 4, "greedy": True}
        )
        sched.submit(req)
        await asyncio.sleep(0.2)
        # queued, NOT dispatched — the engine never sees it
        assert sched.queue_depth() == 1
        assert sched.inflight() == 0
        # pool frees up: dispatch proceeds and the request completes
        srv.kv_occupancy = 0.0
        sched._wake.set()
        got = []
        async for ev in sched.events(req):
            got.extend(ev.get("token_ids", []))
        assert len(got) == 4
        assert sched.queue_depth() == 0
    finally:
        await st.close()


async def test_queue_full_answers_429(params):
    st = await _stack(params, max_queue=1, metrics_poll_interval=9999.0)
    try:
        sched = st.scheduler
        next(iter(sched._servers.values())).kv_occupancy = 0.99  # block
        sched.submit(
            GatewayRequest.build("t", PROMPT, {"max_new_tokens": 2})
        )
        with pytest.raises(RateLimited):
            sched.submit(
                GatewayRequest.build("t", PROMPT, {"max_new_tokens": 2})
            )
    finally:
        await st.close()


# --------------------------------------------------------------------- #
# gen-server satellites: /generate validation, SSE, disconnect, client
# --------------------------------------------------------------------- #


async def test_generate_validation_400(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    url = f"http://127.0.0.1:{port}"
    bad = [
        {"input_ids": PROMPT},                                  # no rid
        {"rid": "a", "input_ids": []},                          # empty
        {"rid": "a", "input_ids": ["x"]},                       # non-int
        {"rid": "a", "input_ids": [5, 999]},                    # OOV
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"max_new_tokens": 0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"temperature": -0.5}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"top_p": 0.0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"top_k": 0}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"min_new_tokens": 9, "max_new_tokens": 4}},
        {"rid": "a", "input_ids": PROMPT,
         "sampling_params": {"max_new_tokens": 4096}},           # capacity
    ]
    try:
        async with aiohttp.ClientSession() as s:
            for body in bad:
                for endpoint in ("/generate", "/generate_stream"):
                    r = await s.post(url + endpoint, json=body)
                    assert r.status == 400, (endpoint, body)
                    assert "error" in await r.json()
            # nothing leaked into the engine
            assert eng.n_running() == 0 and eng.n_pending() == 0
    finally:
        await runner.cleanup()


async def test_generate_stream_client_chunks_match_generate(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    url = f"http://127.0.0.1:{port}"
    sp = {"max_new_tokens": 10, "greedy": True}
    try:
        async with GenAPIClient() as c:
            ref = await c.generate(url, "ref", PROMPT, sp)
            toks, lps, finals = [], [], []
            async for ev in c.generate_stream(url, "stream", PROMPT, sp):
                assert len(ev["token_ids"]) == len(ev["logprobs"])
                toks.extend(ev["token_ids"])
                lps.extend(ev["logprobs"])
                if ev.get("finish_reason"):
                    finals.append(ev)
            # chunk-granular deltas concatenate to exactly the buffered
            # result, and exactly one final frame arrives
            assert toks == ref.output_ids
            assert len(finals) == 1
            assert finals[0]["finish_reason"] == ref.finish_reason
            assert finals[0]["version"] == ref.version
    finally:
        await runner.cleanup()


async def test_stream_early_disconnect_releases_slot(params):
    eng = GenerationEngine(CFG, params, max_slots=2, max_seqlen=128)
    port = network.find_free_port()
    runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
    try:
        async with aiohttp.ClientSession() as s:
            resp = await s.post(
                f"http://127.0.0.1:{port}/generate_stream",
                json={
                    "rid": "dc", "input_ids": PROMPT,
                    "sampling_params": {"max_new_tokens": 120,
                                        "greedy": True},
                },
            )
            assert resp.status == 200
            async for raw in resp.content:  # first delta then hang up
                if raw.startswith(b"data:"):
                    break
            resp.close()
        # the server notices the disconnect and frees the slot + pages
        for _ in range(100):
            await asyncio.sleep(0.05)
            if eng.n_running() == 0 and eng.pool.n_free == eng.n_pages:
                break
        assert eng.n_running() == 0
        assert eng.pool.n_free == eng.n_pages
    finally:
        await runner.cleanup()


# --------------------------------------------------------------------- #
# autoscaler decision table (synthetic fleet/ aggregates)
# --------------------------------------------------------------------- #


def _signals(**kw):
    base = dict(routed=4, healthy=4, queue_depth=0.0, kv_occupancy=0.1,
                queue_wait_p95_s=0.0, breaker_open=0)
    base.update(kw)
    return ScaleSignals(**base)


def test_autoscaler_decision_table():
    cfg = AutoscalerConfig(min_servers=2, max_servers=8)
    cases = [
        # (signals, expected action, expected delta)
        (_signals(routed=1, healthy=1), "grow", 1),          # below floor
        (_signals(healthy=3, breaker_open=1), "grow", 1),    # replace open
        (_signals(queue_depth=40.0), "grow", 2),             # deep backlog
        (_signals(queue_depth=17.0), "grow", 1),             # mild backlog
        (_signals(kv_occupancy=0.9), "grow", 1),             # HBM pressure
        (_signals(queue_wait_p95_s=30.0), "grow", 1),        # latency
        (_signals(), "shrink", 1),                           # idle
        (_signals(routed=2, healthy=2), "hold", 0),          # at the floor
        (_signals(queue_depth=8.0), "hold", 0),              # loaded but ok
        (_signals(routed=8, healthy=8, queue_depth=100.0),
         "hold", 0),                                         # at the ceiling
    ]
    for sig, action, delta in cases:
        d = decide(cfg, sig)
        assert d.action == action, (sig, d)
        if action != "hold":
            assert d.delta == delta, (sig, d)
        if d.action != "hold":
            assert d.reasons


def test_autoscaler_signals_from_fleet_scalars():
    scalars = {
        "gw_queue_depth": 12.0,
        "kv_pool_occupancy": 1.8,      # gauge SUM over 2 gen servers
        "gw/queue_wait_s/p95": 3.5,
        "servers_total": 2.0,
        "servers_open": 1.0,
        "servers_half_open": 0.0,
    }
    sig = ScaleSignals.from_fleet_scalars(scalars, routed=2)
    assert sig.queue_depth == 12.0
    assert sig.kv_occupancy == pytest.approx(0.9)
    assert sig.queue_wait_p95_s == 3.5
    assert sig.breaker_open == 1
    assert sig.healthy == 1


def test_autoscaler_cooldown_and_callbacks():
    t = {"now": 0.0}
    sig = {"cur": _signals(queue_depth=100.0)}
    grown, shrunk = [], []
    asc = Autoscaler(
        AutoscalerConfig(min_servers=1, max_servers=8, cooldown_s=30.0),
        fetch_signals=lambda: sig["cur"],
        grow_cb=lambda n: grown.append(n) or n,
        shrink_cb=lambda n: shrunk.append(n) or n,
        clock=lambda: t["now"],
    )
    d = asc.step_once()
    assert d.action == "grow" and grown == [d.delta]
    # inside the cooldown window further actions are deferred
    t["now"] = 10.0
    assert asc.step_once().action == "hold"
    # after the cooldown, an idle fleet shrinks
    t["now"] = 40.0
    sig["cur"] = _signals()
    d = asc.step_once()
    assert d.action == "shrink" and shrunk == [1]


# --------------------------------------------------------------------- #
# survivability: deadlines, hedged dispatch, brownout, 503s
# --------------------------------------------------------------------- #


class _BlockedStubClient:
    """Reports a pinned KV pool so dispatch never proceeds — requests
    stay queued, which is where the deadline sweep must find them."""

    def __init__(self):
        self.streams = 0

    async def metrics(self, url):
        return {
            "max_slots": 4,
            "kv_pool_demand_occupancy": 1.0,
            "slot_capacity": 4096,
        }

    async def generate_stream(self, url, rid, ids, sp, deadline_s=None):
        self.streams += 1
        yield {"token_ids": [], "logprobs": [], "finish_reason": "stop"}


async def test_deadline_expire_in_queue_refunds_and_rolls_back():
    """A queued request whose deadline lapses is shed IN QUEUE: full
    token-bucket refund, fair-clock rollback, a final deadline event for
    the waiting handler — and the backend never sees it."""
    t = {"now": 0.0}
    stub = _BlockedStubClient()
    sched = ContinuousBatchScheduler(
        ["http://stub:1"],
        tenants={"t": TenantSpec(
            name="t", rate_tokens_per_s=100.0, burst_tokens=10_000.0,
        )},
        client=stub,
        clock=lambda: t["now"],
    )
    await sched.start()
    try:
        shed0 = metrics_mod.counters.get(metrics_mod.GW_DEADLINE_SHED)
        bucket = sched._bucket("t")
        before = bucket.available
        req = GatewayRequest.build(
            "t", [1, 2, 3], {"max_new_tokens": 8}, deadline_s=5.0,
        )
        sched.submit(req)
        assert req.deadline_t == pytest.approx(5.0)
        assert bucket.available < before  # charged on admit
        t["now"] = 10.0
        assert sched.sweep_deadlines() == 1
        evs = []
        async for ev in sched.events(req):
            evs.append(ev)
        assert evs[-1]["finish_reason"] == "deadline"
        assert stub.streams == 0          # never dispatched
        assert sched.queue_depth() == 0
        assert bucket.available == pytest.approx(before)
        assert sched._wfq._last_vft.get("t", 0.0) == pytest.approx(0.0)
        assert (
            metrics_mod.counters.get(metrics_mod.GW_DEADLINE_SHED) - shed0
            == 1
        )
    finally:
        await sched.stop()


class _HedgeStubClient:
    """One backend wedges pre-first-chunk, the other streams; records
    every stream open/close so the test can assert the loser was torn
    down and no slot is left bound."""

    def __init__(self, slow_url):
        self.slow_url = slow_url
        self.streams = []
        self.closed = []

    async def metrics(self, url):
        return {
            "max_slots": 4,
            "kv_pool_demand_occupancy": 0.0,
            "slot_capacity": 4096,
        }

    async def generate_stream(self, url, rid, ids, sp, deadline_s=None):
        self.streams.append((url, rid))
        try:
            if url == self.slow_url:
                await asyncio.sleep(3600)
            for _ in range(4):
                yield {"token_ids": [7], "logprobs": [0.0],
                       "finish_reason": None}
                await asyncio.sleep(0.02)
            yield {"token_ids": [], "logprobs": [], "finish_reason": "stop"}
        finally:
            self.closed.append((url, rid))


async def test_cancel_during_hedge_settles_slots_and_bucket():
    """Wedged primary -> the hedge wins; the client then cancels
    mid-stream. Both backends' slot holds must come back, the loser's
    stream must be closed, and the bucket must settle to exactly what
    was consumed — the hedge must never double-charge."""
    metrics_mod.counters.clear(metrics_mod.GW_TTFT_S)
    urls = ["http://a:1", "http://b:1"]
    stub = _HedgeStubClient(slow_url=urls[0])
    sched = ContinuousBatchScheduler(
        list(urls),
        tenants={"t": TenantSpec(
            # near-zero refill so the final balance shows REFUNDS, not
            # the bucket quietly refilling behind the assertion
            name="t", rate_tokens_per_s=0.01, burst_tokens=10_000.0,
        )},
        client=stub,
        hedge_enabled=True,
        hedge_min_delay_s=0.05,
    )
    await sched.start()
    try:
        hedges0 = metrics_mod.counters.get(metrics_mod.GW_HEDGES)
        wins0 = metrics_mod.counters.get(metrics_mod.GW_HEDGE_WINS)
        bucket = sched._bucket("t")
        before = bucket.available
        req = GatewayRequest.build("t", [1, 2, 3], {"max_new_tokens": 64})
        sched.submit(req)
        got = []
        async for ev in sched.events(req):
            got.extend(ev.get("token_ids", []))
            if len(got) >= 2:
                sched.cancel(req)
                break
        for _ in range(300):
            await asyncio.sleep(0.01)
            if sched.inflight() == 0:
                break
        assert sched.inflight() == 0
        assert metrics_mod.counters.get(metrics_mod.GW_HEDGES) - hedges0 == 1
        assert (
            metrics_mod.counters.get(metrics_mod.GW_HEDGE_WINS) - wins0 == 1
        )
        # both backends were opened; the wedged loser was closed
        assert {u for u, _ in stub.streams} == set(urls)
        assert urls[0] in {u for u, _ in stub.closed}
        # bucket settled at cost-of-what-ran, not the full budget and
        # not a double (hedged) charge
        used = 3 + req.n_generated
        assert bucket.available == pytest.approx(before - used, abs=1.0)
    finally:
        await sched.stop()


async def test_all_breakers_open_answers_503_with_retry_after(params):
    """Every backend breaker open: submit raises ServiceUnavailable and
    the HTTP surface turns it into 503 + an honest Retry-After — not a
    silent hang, not a 429 blaming the client."""
    st = await _stack(params, metrics_poll_interval=9999.0)
    try:
        for s in st.scheduler._servers.values():
            s.healthy = False
        with pytest.raises(ServiceUnavailable) as ei:
            st.scheduler.submit(
                GatewayRequest.build("t", PROMPT, {"max_new_tokens": 2})
            )
        assert ei.value.retry_after_s > 0
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 2},
            )
            assert r.status == 503
            assert int(r.headers["Retry-After"]) >= 1
            err = (await r.json())["error"]
            assert err["code"] == "service_unavailable"
    finally:
        await st.close()


def test_queue_full_retry_after_is_drain_estimate():
    """The queue-full 429 hint tracks the live queue-wait p95 (clamped
    to [1, 60]) instead of a made-up constant."""
    sched = ContinuousBatchScheduler(
        ["http://stub:1"], client=_BlockedStubClient(),
    )
    metrics_mod.counters.clear(metrics_mod.GW_QUEUE_WAIT_S)
    assert sched._queue_retry_after_s() == pytest.approx(1.0)
    for _ in range(20):
        metrics_mod.counters.observe(metrics_mod.GW_QUEUE_WAIT_S, 5.0)
    assert sched._queue_retry_after_s() == pytest.approx(5.0, rel=0.2)
    for _ in range(200):
        metrics_mod.counters.observe(metrics_mod.GW_QUEUE_WAIT_S, 120.0)
    assert sched._queue_retry_after_s() == pytest.approx(60.0)
    metrics_mod.counters.clear(metrics_mod.GW_QUEUE_WAIT_S)


async def test_generate_stream_connect_retries_honor_deadline():
    """Connect retries against a dead backend stop at the request
    deadline with the typed DeadlineExceeded — not after the full
    backoff ladder."""
    from areal_tpu.gen.client import RetryPolicy

    url = f"http://127.0.0.1:{network.find_free_port()}"  # nobody there
    # backoff big enough that the attempt budget alone would outlive the
    # deadline: only the deadline check can end the loop
    async with GenAPIClient(
        timeout=5.0,
        retry=RetryPolicy(max_attempts=100, backoff_base_s=0.5, jitter=0.0),
    ) as cl:
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            async for _ in cl.generate_stream(
                url, "r-dead", [1, 2], {"max_new_tokens": 2},
                deadline_s=0.4,
            ):
                pass
        assert time.monotonic() - t0 < 4.0


async def test_deadline_e2e_504_and_validation(params):
    """A request whose budget can't be met answers 504 (its own typed
    error, not a generic 500); a malformed deadline answers 400."""
    st = await _stack(params)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 8,
                      "timeout": 0.0001},
            )
            assert r.status == 504, await r.text()
            err = (await r.json())["error"]
            assert err["code"] == "deadline_exceeded"
            # header spelling of the same deadline
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 8},
                headers={"X-Request-Deadline": "0.0001"},
            )
            assert r.status == 504
            for bad in (-1, "soon", float("inf")):
                r = await s.post(
                    f"{st.gw_url}/v1/completions",
                    json={"prompt": PROMPT, "max_tokens": 2,
                          "timeout": bad},
                )
                assert r.status == 400, bad
            # a generous deadline changes nothing
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 4, "timeout": 300,
                      "temperature": 0},
            )
            assert r.status == 200
            assert (await r.json())["usage"]["completion_tokens"] == 4
    finally:
        await st.close()


def test_brownout_decide_table():
    cfg = BrownoutConfig()

    def sig(**kw):
        return ScaleSignals(routed=4, healthy=4, **kw)

    # healthy fleet holds at 0
    assert brownout_decide(cfg, sig(), 0) == 0
    # each signal kind can trip a rung on its own
    assert brownout_decide(cfg, sig(kv_occupancy=0.91), 0) == 1
    assert brownout_decide(cfg, sig(queue_wait_p95_s=16.0), 0) == 2
    assert brownout_decide(cfg, sig(breaker_open=3), 0) == 3
    # escalation jumps straight to the worst tripped rung
    assert brownout_decide(cfg, sig(kv_occupancy=0.995), 0) == 4
    assert brownout_decide(cfg, sig(kv_occupancy=0.995), 2) == 4
    # hysteresis: below the entry bound but above entry*h holds the rung
    assert brownout_decide(cfg, sig(kv_occupancy=0.80), 1) == 1
    assert brownout_decide(cfg, sig(kv_occupancy=0.50), 1) == 0
    # de-escalation is one rung at a time even from a silent fleet
    assert brownout_decide(cfg, sig(), 4) == 3


async def test_brownout_controller_dwell_and_levers():
    calls = {"clamp": [], "spec": [], "shed": [], "pause": []}
    t = {"now": 0.0}
    sig = {"s": ScaleSignals(routed=2, healthy=2)}

    async def spec_cb(enabled):
        calls["spec"].append(enabled)

    cfg = BrownoutConfig(min_hold_s=10.0, interval_s=1.0)
    ctrl = BrownoutController(
        cfg,
        lambda: sig["s"],
        lambda v: calls["clamp"].append(v),
        spec_cb,
        lambda floor, ra: calls["shed"].append(floor),
        lambda paused, ra: calls["pause"].append(paused),
        clock=lambda: t["now"],
    )
    sig["s"] = ScaleSignals(routed=2, healthy=2, kv_occupancy=0.96)
    assert await ctrl.step_once() == 2
    assert calls["clamp"][-1] == cfg.clamp_max_tokens
    assert calls["spec"] == [False]
    # recovery is dwell-gated...
    sig["s"] = ScaleSignals(routed=2, healthy=2)
    t["now"] = 5.0
    assert await ctrl.step_once() == 2
    # ...and one rung per pass once the hold lapses
    t["now"] = 20.0
    assert await ctrl.step_once() == 1
    assert calls["spec"] == [False, True]
    t["now"] = 40.0
    assert await ctrl.step_once() == 0
    assert calls["clamp"][-1] is None
    # escalation is NEVER dwell-gated
    sig["s"] = ScaleSignals(routed=2, healthy=2, kv_occupancy=0.995)
    t["now"] = 40.5
    assert await ctrl.step_once() == 4
    assert calls["shed"][-1] == cfg.weight_floor
    assert calls["pause"][-1] is True
    # the Retry-After hint is at least one controller interval
    assert ctrl.retry_after_s() >= cfg.interval_s


async def test_brownout_clamp_applies_to_new_requests(params):
    """Level-1 clamp: the gateway caps max_tokens fleet-wide without
    erroring the request — shorter answers, not failures."""
    gw_config = GatewayConfig(max_tokens_cap=256)
    st = await _stack(params, gw_config=gw_config)
    try:
        gw_config.brownout_max_tokens = 3
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{st.gw_url}/v1/completions",
                json={"prompt": PROMPT, "max_tokens": 64,
                      "temperature": 0},
            )
            assert r.status == 200, await r.text()
            assert (await r.json())["usage"]["completion_tokens"] == 3
    finally:
        await st.close()
