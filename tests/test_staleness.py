"""Staleness hardening: version-window accounting matrix + trainer intake.

Mirrors the reference's off-policyness control matrix
(``tests/system/test_gserver_manager.py:173-270``) and adds the trainer-side
guarantee the reference enforces on arrival: samples older than
``max_head_offpolicyness`` versions NEVER reach the optimizer.
"""

import time

import numpy as np
import pytest

from areal_tpu.api.data import SequenceSample
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.base import name_resolve, names
from areal_tpu.system.buffer import (
    SequenceBuffer,
    record_batch_consumption,
    record_consumption,
    sample_version_start,
)
from areal_tpu.system.gserver_manager import GserverManager, GserverManagerConfig


def _traj(qid, version_start, n=2, ln=6, extra_keys=True):
    lens = [ln] * n
    lp = np.zeros(n * ln, np.float32)
    data = {
        "packed_input_ids": np.arange(n * ln, dtype=np.int64),
        "prompt_mask": np.zeros(n * ln, bool),
        "packed_logprobs": lp,
        "rewards": np.ones(n, np.float32),
        "seq_no_eos_mask": np.zeros(n, bool),
    }
    seqlens = {
        "packed_input_ids": [lens],
        "prompt_mask": [lens],
        "packed_logprobs": [lens],
        "rewards": [[1] * n],
        "seq_no_eos_mask": [[1] * n],
    }
    if extra_keys:
        data["version_start"] = np.full(n, version_start, np.int32)
        seqlens["version_start"] = [[1] * n]
    return SequenceSample(
        keys=set(seqlens), ids=[qid], seqlens=seqlens, data=data
    )


class TestOffpolicynessMatrix:
    """is_staled over (offpolicyness x consumed x version): the gate allows
    starting rollouts only while
    (consumed + running) // train_batch_size <= offpolicyness + version."""

    @pytest.mark.parametrize("off", [0, 1, 4])
    @pytest.mark.parametrize("bs", [4, 16])
    def test_gate_boundary(self, off, bs):
        cfg = GserverManagerConfig(
            experiment_name="stale-mx", trial_name=f"o{off}b{bs}",
            train_batch_size=bs, max_head_offpolicyness=off,
            max_concurrent_rollouts=10_000,
        )
        m = GserverManager(cfg, server_urls=["http://x"])
        m.version = 0
        key = names.training_samples(cfg.experiment_name, cfg.trial_name)
        # exactly at the window edge: consumed = (off+1)*bs - 1 -> allowed
        name_resolve.add(key, str((off + 1) * bs - 1), replace=True)
        assert not m.is_staled()
        # one more sample crosses the boundary -> staled
        name_resolve.add(key, str((off + 1) * bs), replace=True)
        assert m.is_staled()
        # a version bump widens the window by exactly one batch
        m.version = 1
        assert not m.is_staled()
        name_resolve.add(key, str((off + 2) * bs), replace=True)
        assert m.is_staled()

    def test_running_counts_toward_window(self):
        cfg = GserverManagerConfig(
            experiment_name="stale-mx", trial_name="running",
            train_batch_size=4, max_head_offpolicyness=1,
            max_concurrent_rollouts=10_000,
        )
        m = GserverManager(cfg, server_urls=["http://x"])
        m.version = 0
        name_resolve.add(
            names.training_samples(cfg.experiment_name, cfg.trial_name),
            "0", replace=True,
        )
        m.rollout_stat.running = 7   # (0+7)//4 = 1 <= 1 -> ok
        assert not m.is_staled()
        m.rollout_stat.running = 8   # (0+8)//4 = 2 > 1 -> staled
        assert m.is_staled()


class TestSequenceBuffer:
    def test_version_priority_pop(self):
        buf = SequenceBuffer()
        buf.put(_traj("new", version_start=5), current_version=5)
        buf.put(_traj("old", version_start=1), current_version=5)
        buf.put(_traj("mid", version_start=3), current_version=5)
        out = buf.pop_batch(2, current_version=5)
        assert [s.ids[0] for s in out] == ["old", "mid"]
        assert [s.ids[0] for s in buf.pop_batch(5)] == ["new"]

    def test_overstale_dropped_at_put_and_pop(self):
        buf = SequenceBuffer(max_version_lag=2)
        buf.put(_traj("ancient", version_start=0), current_version=5)  # drop
        assert len(buf) == 0 and buf.n_dropped_stale == 1
        buf.put(_traj("ok", version_start=4), current_version=5)
        # trainer advances while the sample queues; it expires at pop
        assert buf.pop_batch(1, current_version=9) == []
        assert buf.n_dropped_stale == 2

    def test_untagged_samples_never_dropped(self):
        buf = SequenceBuffer(max_version_lag=0)
        buf.put(_traj("sync", version_start=0, extra_keys=False),
                current_version=100)
        assert len(buf) == 1
        assert sample_version_start(buf.pop_batch(1)[0]) is None

    def test_capacity_drops_oldest(self):
        buf = SequenceBuffer(capacity=2)
        buf.put(_traj("v1", version_start=1), current_version=1)
        buf.put(_traj("v2", version_start=2), current_version=2)
        buf.put(_traj("v3", version_start=3), current_version=3)
        assert len(buf) == 2 and buf.n_dropped_capacity == 1
        assert [s.ids[0] for s in buf.pop_batch(5)] == ["v2", "v3"]


LIFECYCLE_KEYS = (
    metrics_mod.STALENESS_VERSIONS,
    metrics_mod.QUEUE_WAIT_S,
    metrics_mod.E2E_LATENCY_S,
    metrics_mod.TTFC_S,
    metrics_mod.REWARD_LAG_S,
)


class TestConsumptionAttribution:
    """The trainer's batch-commit point is THE measurement point of the
    staleness/latency story (docs/observability.md): lifecycle stamps
    riding trajectory metadata become process-global histograms the
    telemetry plane exports. ``pop_batch`` itself records nothing — a
    popped batch can be re-put on the multihost starved/over-stale path,
    so recording there would double-count."""

    @pytest.fixture(autouse=True)
    def _clean_histograms(self):
        for k in LIFECYCLE_KEYS:
            metrics_mod.counters.clear(k)
        yield
        for k in LIFECYCLE_KEYS:
            metrics_mod.counters.clear(k)

    def _stamped(self, qid, version_start, submit_ago, enqueue_ago,
                 ttfc=None, reward_lag=None):
        now = time.time()
        t = _traj(qid, version_start=version_start)
        t.metadata["submit_time"] = [now - submit_ago] * 2
        t.metadata["enqueue_time"] = [now - enqueue_ago] * 2
        if ttfc is not None:
            t.metadata["first_chunk_time"] = [now - submit_ago + ttfc] * 2
        if reward_lag is not None:
            t.metadata["reward_time"] = [now - submit_ago + reward_lag] * 2
        return t

    def test_committed_batch_records_distributions(self):
        buf = SequenceBuffer()
        buf.put(self._stamped("a", version_start=3, submit_ago=10.0,
                              enqueue_ago=4.0, ttfc=0.5, reward_lag=8.0),
                current_version=5)
        buf.put(self._stamped("b", version_start=5, submit_ago=20.0,
                              enqueue_ago=2.0, ttfc=1.0, reward_lag=15.0),
                current_version=5)
        batch = buf.pop_batch(5, current_version=5)
        assert len(batch) == 2
        record_batch_consumption(batch, current_version=5)

        stale = metrics_mod.counters.histogram(metrics_mod.STALENESS_VERSIONS)
        assert stale.count == 2
        assert stale.min == 0.0 and stale.max == 2.0   # 5-5 and 5-3
        # the integer-centered edges keep 0 and 2 in separate buckets
        assert stale.counts[0] == 1 and stale.counts[2] == 1

        qw = metrics_mod.counters.histogram(metrics_mod.QUEUE_WAIT_S)
        assert qw.count == 2
        assert qw.min == pytest.approx(2.0, abs=0.5)
        assert qw.max == pytest.approx(4.0, abs=0.5)

        e2e = metrics_mod.counters.histogram(metrics_mod.E2E_LATENCY_S)
        assert e2e.count == 2
        assert e2e.max == pytest.approx(20.0, abs=0.5)
        # queue wait is a component of e2e latency
        assert qw.sum < e2e.sum

        assert metrics_mod.counters.histogram(
            metrics_mod.TTFC_S
        ).max == pytest.approx(1.0, abs=0.1)
        assert metrics_mod.counters.histogram(
            metrics_mod.REWARD_LAG_S
        ).max == pytest.approx(15.0, abs=0.5)

    def test_pop_batch_alone_records_nothing(self):
        """The multihost re-put path (trainer pops, a sibling host was
        starved, batch goes back in the buffer): popping must not touch
        the histograms, or the same trajectories count twice when the
        refilled pop finally commits."""
        buf = SequenceBuffer()
        buf.put(self._stamped("reput", version_start=4, submit_ago=10.0,
                              enqueue_ago=4.0), current_version=5)
        batch = buf.pop_batch(1, current_version=5)
        for k in LIFECYCLE_KEYS:
            assert metrics_mod.counters.histogram(k) is None
        for s in batch:  # re-put and commit on the second pop
            buf.put(s, current_version=5)
        record_batch_consumption(
            buf.pop_batch(1, current_version=5), current_version=5
        )
        assert metrics_mod.counters.histogram(
            metrics_mod.STALENESS_VERSIONS
        ).count == 1

    def test_unstamped_samples_only_record_staleness(self):
        """Sync-PPO/test trajectories carry no stamps: version staleness is
        still measured (version_start is device data), the wall-clock
        histograms simply stay empty — no fake zeros."""
        buf = SequenceBuffer()
        buf.put(_traj("plain", version_start=4), current_version=6)
        record_batch_consumption(
            buf.pop_batch(1, current_version=6), current_version=6
        )
        stale = metrics_mod.counters.histogram(metrics_mod.STALENESS_VERSIONS)
        assert stale.count == 1 and stale.max == 2.0
        for k in LIFECYCLE_KEYS[1:]:
            assert metrics_mod.counters.histogram(k) is None

    def test_untagged_unstamped_records_nothing(self):
        buf = SequenceBuffer()
        buf.put(_traj("sync", version_start=0, extra_keys=False),
                current_version=9)
        record_batch_consumption(
            buf.pop_batch(1, current_version=9), current_version=9
        )
        for k in LIFECYCLE_KEYS:
            assert metrics_mod.counters.histogram(k) is None

    def test_grouped_sample_uses_earliest_stamp(self):
        """gather() concatenates per-group metadata; attribution takes the
        EARLIEST positive stamp (worst case), and zero placeholders from
        unstamped group members are ignored."""
        now = time.time()
        t = _traj("g", version_start=1)
        t.metadata["enqueue_time"] = [now - 9.0, 0.0]
        record_consumption(t, current_version=1)
        qw = metrics_mod.counters.histogram(metrics_mod.QUEUE_WAIT_S)
        assert qw.count == 1
        assert qw.max == pytest.approx(9.0, abs=0.5)

    def test_malformed_stamps_tolerated(self):
        t = _traj("bad", version_start=1)
        t.metadata["enqueue_time"] = ["not-a-time", None]
        record_consumption(t, current_version=3)
        assert metrics_mod.counters.histogram(
            metrics_mod.QUEUE_WAIT_S
        ) is None
        # staleness still recorded: the malformed wall stamps don't block it
        assert metrics_mod.counters.histogram(
            metrics_mod.STALENESS_VERSIONS
        ).count == 1

    def test_clock_skew_clamped_nonnegative(self):
        now = time.time()
        t = _traj("skew", version_start=7)
        t.metadata["enqueue_time"] = [now + 30.0] * 2  # writer clock ahead
        t.metadata["submit_time"] = [now + 30.0] * 2
        record_consumption(t, current_version=5)  # version went backwards too
        assert metrics_mod.counters.histogram(
            metrics_mod.QUEUE_WAIT_S
        ).max == 0.0
        assert metrics_mod.counters.histogram(
            metrics_mod.STALENESS_VERSIONS
        ).max == 0.0


class TestTrainerIntake:
    """Over-stale and malformed rollouts never reach the optimizer."""

    class _Stream:
        def __init__(self, items):
            self.items = list(items)

        def get_batch(self, n, timeout=None):
            out, self.items = self.items[:n], self.items[n:]
            return out

    def _worker(self, stream, actor, window=2):
        from areal_tpu.api.model import PPOHyperparameters
        from areal_tpu.system.trainer_worker import (
            AsyncPPOTrainerWorker,
            TrainerControl,
        )

        return AsyncPPOTrainerWorker(
            "stale-int", "t0",
            actor_engine=actor,
            stream=stream,
            hp=PPOHyperparameters(disable_value=True),
            control=TrainerControl(total_train_steps=1),
            train_batch_size=4,
            max_head_offpolicyness=window,
        )

    @pytest.fixture(scope="class")
    def actor(self):
        from areal_tpu.models.config import ModelConfig
        from areal_tpu.parallel.mesh import ParallelConfig
        from areal_tpu.train.engine import OptimizerConfig, TrainEngine

        eng = TrainEngine(
            ModelConfig(
                n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8,
                hidden_dim=16, intermediate_dim=32, vocab_size=64,
                dtype="float32",
            ),
            ParallelConfig(),
            OptimizerConfig(),
        )
        eng.init_random(0)
        return eng

    def test_stale_samples_never_reach_optimizer(self, actor):
        actor.version = 10
        stream = self._Stream([
            _traj("fresh1", version_start=9),
            _traj("ancient", version_start=1),   # 10-1 > 2 -> dropped
            _traj("fresh2", version_start=10),
        ])
        w = self._worker(stream, actor, window=2)
        batch = w._collect_batch(timeout=0.5)
        assert sorted(batch.ids) == ["fresh1", "fresh2"]
        assert w._buffer.n_dropped_stale == 1

    def test_malformed_rollout_dropped_loudly(self, actor, caplog):
        actor.version = 0
        bad = _traj("bad", version_start=0)
        bad.keys.discard("packed_logprobs")
        del bad.seqlens["packed_logprobs"]
        del bad.data["packed_logprobs"]
        stream = self._Stream([_traj("good", version_start=0), bad])
        w = self._worker(stream, actor)
        import logging

        with caplog.at_level(logging.ERROR):
            batch = w._collect_batch(timeout=0.5)
        assert batch.ids == ["good"]
        assert any("missing required keys" in r.message for r in caplog.records)
        # the surviving batch still carries every graph-required key
        assert w._required_keys <= set(batch.keys)
