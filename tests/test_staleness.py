"""Staleness hardening: version-window accounting matrix + trainer intake.

Mirrors the reference's off-policyness control matrix
(``tests/system/test_gserver_manager.py:173-270``) and adds the trainer-side
guarantee the reference enforces on arrival: samples older than
``max_head_offpolicyness`` versions NEVER reach the optimizer.
"""

import numpy as np
import pytest

from areal_tpu.api.data import SequenceSample
from areal_tpu.base import name_resolve, names
from areal_tpu.system.buffer import SequenceBuffer, sample_version_start
from areal_tpu.system.gserver_manager import GserverManager, GserverManagerConfig


def _traj(qid, version_start, n=2, ln=6, extra_keys=True):
    lens = [ln] * n
    lp = np.zeros(n * ln, np.float32)
    data = {
        "packed_input_ids": np.arange(n * ln, dtype=np.int64),
        "prompt_mask": np.zeros(n * ln, bool),
        "packed_logprobs": lp,
        "rewards": np.ones(n, np.float32),
        "seq_no_eos_mask": np.zeros(n, bool),
    }
    seqlens = {
        "packed_input_ids": [lens],
        "prompt_mask": [lens],
        "packed_logprobs": [lens],
        "rewards": [[1] * n],
        "seq_no_eos_mask": [[1] * n],
    }
    if extra_keys:
        data["version_start"] = np.full(n, version_start, np.int32)
        seqlens["version_start"] = [[1] * n]
    return SequenceSample(
        keys=set(seqlens), ids=[qid], seqlens=seqlens, data=data
    )


class TestOffpolicynessMatrix:
    """is_staled over (offpolicyness x consumed x version): the gate allows
    starting rollouts only while
    (consumed + running) // train_batch_size <= offpolicyness + version."""

    @pytest.mark.parametrize("off", [0, 1, 4])
    @pytest.mark.parametrize("bs", [4, 16])
    def test_gate_boundary(self, off, bs):
        cfg = GserverManagerConfig(
            experiment_name="stale-mx", trial_name=f"o{off}b{bs}",
            train_batch_size=bs, max_head_offpolicyness=off,
            max_concurrent_rollouts=10_000,
        )
        m = GserverManager(cfg, server_urls=["http://x"])
        m.version = 0
        key = names.training_samples(cfg.experiment_name, cfg.trial_name)
        # exactly at the window edge: consumed = (off+1)*bs - 1 -> allowed
        name_resolve.add(key, str((off + 1) * bs - 1), replace=True)
        assert not m.is_staled()
        # one more sample crosses the boundary -> staled
        name_resolve.add(key, str((off + 1) * bs), replace=True)
        assert m.is_staled()
        # a version bump widens the window by exactly one batch
        m.version = 1
        assert not m.is_staled()
        name_resolve.add(key, str((off + 2) * bs), replace=True)
        assert m.is_staled()

    def test_running_counts_toward_window(self):
        cfg = GserverManagerConfig(
            experiment_name="stale-mx", trial_name="running",
            train_batch_size=4, max_head_offpolicyness=1,
            max_concurrent_rollouts=10_000,
        )
        m = GserverManager(cfg, server_urls=["http://x"])
        m.version = 0
        name_resolve.add(
            names.training_samples(cfg.experiment_name, cfg.trial_name),
            "0", replace=True,
        )
        m.rollout_stat.running = 7   # (0+7)//4 = 1 <= 1 -> ok
        assert not m.is_staled()
        m.rollout_stat.running = 8   # (0+8)//4 = 2 > 1 -> staled
        assert m.is_staled()


class TestSequenceBuffer:
    def test_version_priority_pop(self):
        buf = SequenceBuffer()
        buf.put(_traj("new", version_start=5), current_version=5)
        buf.put(_traj("old", version_start=1), current_version=5)
        buf.put(_traj("mid", version_start=3), current_version=5)
        out = buf.pop_batch(2, current_version=5)
        assert [s.ids[0] for s in out] == ["old", "mid"]
        assert [s.ids[0] for s in buf.pop_batch(5)] == ["new"]

    def test_overstale_dropped_at_put_and_pop(self):
        buf = SequenceBuffer(max_version_lag=2)
        buf.put(_traj("ancient", version_start=0), current_version=5)  # drop
        assert len(buf) == 0 and buf.n_dropped_stale == 1
        buf.put(_traj("ok", version_start=4), current_version=5)
        # trainer advances while the sample queues; it expires at pop
        assert buf.pop_batch(1, current_version=9) == []
        assert buf.n_dropped_stale == 2

    def test_untagged_samples_never_dropped(self):
        buf = SequenceBuffer(max_version_lag=0)
        buf.put(_traj("sync", version_start=0, extra_keys=False),
                current_version=100)
        assert len(buf) == 1
        assert sample_version_start(buf.pop_batch(1)[0]) is None

    def test_capacity_drops_oldest(self):
        buf = SequenceBuffer(capacity=2)
        buf.put(_traj("v1", version_start=1), current_version=1)
        buf.put(_traj("v2", version_start=2), current_version=2)
        buf.put(_traj("v3", version_start=3), current_version=3)
        assert len(buf) == 2 and buf.n_dropped_capacity == 1
        assert [s.ids[0] for s in buf.pop_batch(5)] == ["v2", "v3"]


class TestTrainerIntake:
    """Over-stale and malformed rollouts never reach the optimizer."""

    class _Stream:
        def __init__(self, items):
            self.items = list(items)

        def get_batch(self, n, timeout=None):
            out, self.items = self.items[:n], self.items[n:]
            return out

    def _worker(self, stream, actor, window=2):
        from areal_tpu.api.model import PPOHyperparameters
        from areal_tpu.system.trainer_worker import (
            AsyncPPOTrainerWorker,
            TrainerControl,
        )

        return AsyncPPOTrainerWorker(
            "stale-int", "t0",
            actor_engine=actor,
            stream=stream,
            hp=PPOHyperparameters(disable_value=True),
            control=TrainerControl(total_train_steps=1),
            train_batch_size=4,
            max_head_offpolicyness=window,
        )

    @pytest.fixture(scope="class")
    def actor(self):
        from areal_tpu.models.config import ModelConfig
        from areal_tpu.parallel.mesh import ParallelConfig
        from areal_tpu.train.engine import OptimizerConfig, TrainEngine

        eng = TrainEngine(
            ModelConfig(
                n_layers=1, n_q_heads=2, n_kv_heads=1, head_dim=8,
                hidden_dim=16, intermediate_dim=32, vocab_size=64,
                dtype="float32",
            ),
            ParallelConfig(),
            OptimizerConfig(),
        )
        eng.init_random(0)
        return eng

    def test_stale_samples_never_reach_optimizer(self, actor):
        actor.version = 10
        stream = self._Stream([
            _traj("fresh1", version_start=9),
            _traj("ancient", version_start=1),   # 10-1 > 2 -> dropped
            _traj("fresh2", version_start=10),
        ])
        w = self._worker(stream, actor, window=2)
        batch = w._collect_batch(timeout=0.5)
        assert sorted(batch.ids) == ["fresh1", "fresh2"]
        assert w._buffer.n_dropped_stale == 1

    def test_malformed_rollout_dropped_loudly(self, actor, caplog):
        actor.version = 0
        bad = _traj("bad", version_start=0)
        bad.keys.discard("packed_logprobs")
        del bad.seqlens["packed_logprobs"]
        del bad.data["packed_logprobs"]
        stream = self._Stream([_traj("good", version_start=0), bad])
        w = self._worker(stream, actor)
        import logging

        with caplog.at_level(logging.ERROR):
            batch = w._collect_batch(timeout=0.5)
        assert batch.ids == ["good"]
        assert any("missing required keys" in r.message for r in caplog.records)
        # the surviving batch still carries every graph-required key
        assert w._required_keys <= set(batch.keys)
