"""Native host packer (C++/ctypes): bit-parity with the numpy path + the
build/fallback contract.

Counterpart of the reference's ``csrc/`` CPU helpers: the host runtime's
hot loop is native, the compute path stays JAX/XLA/Pallas, and everything
degrades to numpy when no compiler is available.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from areal_tpu import native
from areal_tpu.api.data import SequenceSample
from areal_tpu.train import batching

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain; numpy fallback in use"
)


def _rand_sample(rng, n_items=16, grouped=True):
    seqs, ids = [], []
    seqlens_main = []
    for i in range(n_items):
        group = [int(x) for x in rng.integers(3, 40, size=rng.integers(1, 4))] \
            if grouped else [int(rng.integers(3, 40))]
        seqlens_main.append(group)
        ids.append(f"q{i}")
    total = sum(sum(g) for g in seqlens_main)
    n_seqs = sum(len(g) for g in seqlens_main)
    data = {
        "packed_input_ids": rng.integers(0, 1000, total).astype(np.int64),
        "packed_logprobs": rng.normal(size=total).astype(np.float32),
        "rewards": rng.normal(size=n_seqs).astype(np.float32),
        "birth_time": rng.integers(0, 99, n_items).astype(np.int64),
    }
    return SequenceSample(
        keys=set(data),
        ids=ids,
        seqlens={
            "packed_input_ids": seqlens_main,
            "packed_logprobs": seqlens_main,
            "rewards": [[1] * len(g) for g in seqlens_main],
            "birth_time": [[1] for _ in seqlens_main],
        },
        data=data,
    )


def _pack_with_fallback(sample, n_rows, **kw):
    os.environ["AREAL_DISABLE_NATIVE"] = "1"
    native._tried, native._lib = True, None
    try:
        return batching.pack_sequences(sample, n_rows, **kw)
    finally:
        del os.environ["AREAL_DISABLE_NATIVE"]
        native._tried = False


class TestParity:
    def test_plan_rows_bit_identical(self, rng):
        for _ in range(20):
            lens = [int(x) for x in rng.integers(1, 500, size=rng.integers(1, 60))]
            n_rows = int(rng.integers(1, 9))
            got = native.plan_rows_lpt(np.asarray(lens, np.int64), n_rows)
            order = sorted(range(len(lens)), key=lambda i: -lens[i])
            loads = [0] * n_rows
            want = [0] * len(lens)
            for i in order:
                r = min(range(n_rows), key=lambda j: (loads[j], j))
                want[i] = r
                loads[r] += lens[i]
            np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("n_rows", [1, 3, 8])
    def test_pack_sequences_bit_identical(self, rng, n_rows):
        sample = _rand_sample(rng)
        nat = batching.pack_sequences(sample, n_rows, pad_multiple=16)
        ref = _pack_with_fallback(sample, n_rows, pad_multiple=16)
        assert nat.capacity == ref.capacity
        assert set(nat.arrays) == set(ref.arrays)
        for k in nat.arrays:
            np.testing.assert_array_equal(nat.arrays[k], ref.arrays[k], err_msg=k)

    def test_misaligned_key_still_raises(self, rng):
        sample = _rand_sample(rng, n_items=2)
        # corrupt one key's seqlens so it can't align
        sample.seqlens["packed_logprobs"] = [
            [l + 1 for l in g] for g in sample.seqlens["packed_logprobs"]
        ]
        with pytest.raises(ValueError, match="cannot align"):
            batching.pack_sequences(sample, 2, pad_multiple=16)


def test_build_failure_falls_back(tmp_path):
    """A broken source tree degrades to numpy instead of crashing."""
    code = (
        "import areal_tpu.native as n\n"
        "n._SRC = %r\n"
        "assert not n.available()\n"
        "from areal_tpu.train import batching\n"
        "assert batching.plan_rows([5, 3, 1], 2) is not None\n"
        "print('fallback ok')\n"
    ) % str(tmp_path / "missing.cpp")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr[-800:]
    assert "fallback ok" in out.stdout


def test_native_is_fast_enough(rng):
    """Smoke: packing 8k sequences in native is not slower than numpy (it is
    typically ~10x faster; this only guards absurd regressions)."""
    import time

    sample = _rand_sample(rng, n_items=2000)
    t0 = time.perf_counter()
    batching.pack_sequences(sample, 8)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    _pack_with_fallback(sample, 8)
    t_py = time.perf_counter() - t0
    assert t_native < t_py * 1.5, (t_native, t_py)
