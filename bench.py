"""Single-chip training-throughput benchmark.

Run by the driver on real TPU hardware each round. Measures SFT train-step
token throughput on a small qwen2-profile model (packed varlen batches,
bf16 compute) and prints ONE JSON line.

``vs_baseline``: the reference publishes no absolute single-chip tokens/s
(BASELINE.md — only relative async speedups on H800 clusters), so we compare
against an analytic roofline: achieved model FLOP/s over the chip's peak
(v5e ≈ 197 TFLOP/s bf16), i.e. MFU. vs_baseline is reported as achieved-MFU /
0.4 (0.4 MFU being a strong packed-training baseline on this class of model).
"""

import json
import os
import time

import numpy as np


def main():
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import make_interface
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    # ~125M-param qwen2-profile model; fits one v5e chip with Adam fp32 states
    cfg = ModelConfig(
        n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64, hidden_dim=768,
        intermediate_dim=2048, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16",
    )
    par = ParallelConfig(data=1, fsdp=1, model=1)
    eng = TrainEngine(cfg, par, OptimizerConfig(lr=1e-4))
    eng.init_random(0)
    eng.setup_optimizer(1000)

    T = 4096          # packed tokens per micro-batch row
    N_STEPS = 8
    rng = np.random.default_rng(0)
    lens = [512] * (T // 512)

    def make_sample():
        return SequenceSample.from_default(
            ids=list(range(len(lens))),
            seqlens=lens,
            data={
                "packed_input_ids": rng.integers(
                    0, cfg.vocab_size, sum(lens)
                ).astype(np.int64),
                "prompt_mask": np.zeros(sum(lens), bool),
            },
        )

    sft = make_interface("sft")
    spec = MicroBatchSpec(n_mbs=1, max_tokens_per_mb=T)
    sft.train_step(eng, make_sample(), spec)  # compile
    jax.block_until_ready(eng.params)
    t0 = time.perf_counter()
    for _ in range(N_STEPS):
        sft.train_step(eng, make_sample(), spec)
    jax.block_until_ready(eng.params)
    dt = time.perf_counter() - t0

    tokens = N_STEPS * T
    tok_per_s = tokens / dt
    n_params = sum(x.size for x in jax.tree.leaves(eng.params))
    flop_per_token = 6 * n_params  # fwd+bwd dense transformer approximation
    achieved = tok_per_s * flop_per_token
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e bf16
    mfu = achieved / peak
    print(
        json.dumps(
            {
                "metric": "sft_train_tokens_per_sec_single_chip",
                "value": round(tok_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.4, 4),
                "detail": {
                    "n_params": int(n_params),
                    "mfu": round(mfu, 4),
                    "step_time_s": round(dt / N_STEPS, 4),
                    "device": str(jax.devices()[0].platform),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
