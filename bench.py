"""Single-chip throughput benchmark: training, generation, async-PPO.

Run by the driver on real TPU hardware each round. Prints ONE JSON line.

Training shapes (SFT train-step, packed varlen, bf16, Pallas flash):
- primary: ~125M qwen2-profile @ 4096 packed tokens (8 x 512 sequences)
- ``b1``:  ~1.08B model @ 4096 tokens (bf16 params + Adam, n_mbs=1)
- ``ctx8k`` / ``ctx32k``: long-context flash band (protocol context shape)

Generation shapes (paged engine, the serving half of the fleet —
counterpart of the reference's "Generation throughput: X tokens/s" log,
``realhf/system/gserver_manager.py:279-285``):
- ``gen``: R1-Distill-1.5B profile (the protocol's smallest model), 64
  slots @ 1k-token prompts, continuous decode — prefill + decode tokens/s
- ``gen32k``: same model, 4 slots at ~31.5k-token context (the published
  32k protocol, ``benchmark/verl_v0_3_0_post1_76084d3/README.md:39-41``)
- ``gen_spec``: vanilla vs speculative decode A/B at the 64-slot config
  on repetitive prompts — accepted-tokens/s, accept rate, vs_baseline
  (docs/performance.md "Speculative decoding")
- ``gen_kvq``: bf16 vs int8-quantized KV pool A/B at the 64-slot config
  plus a doubled-slot int8 run at equal pool HBM — tokens/s, vs_baseline,
  max decode logit delta (docs/performance.md "KV quantization")
- ``gen_sample_fused``: materialized-logits vs fused LM-head + sampling
  epilogue A/B at the 64-slot config — tokens/s, vs_baseline, max
  sampled-logprob delta (docs/performance.md "Fused sampling epilogue")
- ``ppo``: a complete in-process async-PPO round (generate a GRPO group
  per prompt -> verify -> decoupled-PPO train step -> weight swap into
  the engine) — reward-samples/sec/chip, the north-star unit

``vs_baseline``: the reference publishes no absolute single-chip numbers
(BASELINE.md — only relative async speedups on H800 clusters), so training
compares against an analytic roofline: achieved model FLOP/s over the
chip's peak (v5e ≈ 197 TFLOP/s bf16), i.e. MFU; vs_baseline = MFU / 0.4
(0.4 MFU = a strong packed-training baseline). Decode is HBM-bound, so
generation reports ``vs_roofline`` = measured / (bandwidth-limit tokens/s
from bytes-touched-per-step at 819 GB/s).

Timing protocol: dispatch N steps back-to-back with NO host pulls (each
device->host round trip costs ~70-100 ms on a tunneled chip), then fetch
one scalar to drain the queue. The generation engine syncs once per decode
chunk by design; chunks of 128 amortize that to <1 ms/token.
"""

import contextlib
import dataclasses
import json
import os
import time

import numpy as np


@contextlib.contextmanager
def _env(name, val):
    """Set one env var for an A/B arm, restoring the previous value."""
    prev = os.environ.get(name)
    os.environ[name] = val
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


def _mk_sample(cfg, lens, rng):
    from areal_tpu.api.data import SequenceSample

    return SequenceSample.from_default(
        ids=list(range(len(lens))),
        seqlens=list(lens),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, sum(lens)
            ).astype(np.int64),
            "prompt_mask": np.zeros(sum(lens), bool),
        },
    )


def _bench_shape(cfg, lens, n_steps, peak, param_dtype="float32"):
    import jax

    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.base import flops as flops_mod
    from areal_tpu.base.tracing import maybe_trace
    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    T = sum(lens)
    eng = TrainEngine(
        cfg, ParallelConfig(), OptimizerConfig(lr=1e-4), param_dtype=param_dtype
    )
    eng.init_random(0)
    eng.setup_optimizer(1000)
    rng = np.random.default_rng(0)
    sample = _mk_sample(cfg, lens, rng)
    spec = MicroBatchSpec(n_mbs=1, max_tokens_per_mb=T)

    # compile + settle donation layouts (2 warm steps), then drain
    for _ in range(2):
        stats = eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
    jax.device_get(stats["loss"])

    with maybe_trace("bench"):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            stats = eng.train_batch(
                sample, spec, sft_loss_fn, fetch_stats=False
            )
        jax.device_get(stats["loss"])  # drain
        dt = (time.perf_counter() - t0) / n_steps

    trace_breakdown = _maybe_trace_breakdown("bench")

    tok_per_s = T / dt
    fl = flops_mod.train_flops(cfg, T, seqlens=lens)
    mfu = fl / dt / peak
    # free params + Adam state NOW (the 1B shape holds ~11 GB; without an
    # explicit release the gen sections that follow OOM the chip)
    eng.params = eng.opt_state = None
    eng._jit_cache = None
    del eng
    import gc

    gc.collect()
    out = {
        "tokens_per_s": round(tok_per_s, 1),
        "step_time_s": round(dt, 4),
        "mfu": round(mfu, 4),
        "n_params": int(flops_mod.param_count(cfg)),
    }
    if trace_breakdown:
        out["trace"] = trace_breakdown
    return out


def _maybe_trace_breakdown(tag):
    """With AREAL_DUMP_TRACE set, fold the analyzer's device-time buckets
    (base/trace_analyzer.py, the reference monitor.py:404-610 categories)
    into the section result — no by-hand trace reading."""
    from areal_tpu.base.tracing import trace_dir, trace_enabled

    if not trace_enabled():
        return None
    try:
        from areal_tpu.base.trace_analyzer import summarize_latest

        s = summarize_latest(trace_dir(tag))
        if not s:
            return None
        # one compact dict per plane: bucket percentages + top-3 ops
        return [
            {
                "plane": p["plane"],
                "device_total_s": p["device_total_s"],
                "buckets_pct": p["buckets_pct"],
                "top_ops": p["top_ops"][:3],
            }
            for p in s["planes"]
        ]
    except Exception as e:  # trace analysis must never sink a bench run
        return [{"error": repr(e)[:200]}]


def _gen_model_cfg():
    """R1-Distill-Qwen-1.5B profile: the protocol's smallest benchmark
    model (28L, 12q/2kv heads @ D=128 — the Pallas paged-decode kernel's
    native head size)."""
    from areal_tpu.models.config import ModelConfig

    return ModelConfig(
        n_layers=28, n_q_heads=12, n_kv_heads=2, head_dim=128,
        hidden_dim=1536, intermediate_dim=8960, vocab_size=151936,
        use_attention_bias=True, dtype="bfloat16",
    )


def _kv_bytes_per_token(cfg) -> float:
    return 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2  # k+v, bf16


def _bench_gen(peak_bw: float, peak: float, pipelined: bool = False):
    """Prefill + decode tokens/s at realistic occupancy: 64 slots, 1k
    prompts, 512 generated tokens each. ``pipelined=True`` A/Bs the
    chunk-pipelined engine (harvest one chunk late so the per-chunk host
    sync overlaps compute); its decode window is drain-bounded so both
    modes time exactly N_CHUNKS of device work."""
    import jax

    from areal_tpu.base import flops as flops_mod
    from areal_tpu.gen.engine import GenerationEngine, GenRequest
    from areal_tpu.models import transformer as tfm

    cfg = _gen_model_cfg()
    B, PLEN, D_STEPS, N_CHUNKS = 64, 1024, 128, 4
    eng = GenerationEngine(
        cfg, tfm.init_params(cfg, jax.random.key(0), dtype="bfloat16"),
        max_slots=B, max_seqlen=2048,
        max_new_tokens_cap=64 + D_STEPS * (N_CHUNKS + 1),
        page_size=128, enable_prefix_cache=False, admit_chunk_tokens=1024,
        pipeline_chunks=pipelined,
    )
    rng = np.random.default_rng(0)

    rounds = iter(range(100))

    def submit_all(r=None):
        # cap ABOVE the executed step count: a slot finishing inside the
        # timed window triggers a per-slot harvest device pull (~100 ms
        # each on a tunneled chip) that would dominate t_decode
        r = next(rounds)
        for i in range(B):
            eng.submit(GenRequest(
                rid=f"r{r}_{i}",
                input_ids=[int(x) for x in rng.integers(1, 50000, PLEN)],
                max_new_tokens=64 + D_STEPS * (N_CHUNKS + 1),
                temperature=1.0,
            ))

    # warmup round: compiles for admit buckets, widths, decode chunk
    submit_all()
    eng.step(decode_steps=1)
    for _ in range(N_CHUNKS):
        eng.step(decode_steps=D_STEPS)
    eng.pause(); eng.resume()          # harvest leftovers, keep pool clean

    submit_all()
    t0 = time.perf_counter()
    eng.step(decode_steps=1)           # admission: all 64 prefills + 1 decode
    if pipelined:
        # the pipelined step returns at dispatch; drain so t_prefill
        # covers the actual prefill work like the unpipelined path
        jax.device_get(eng.state.lens)
    t_prefill = time.perf_counter() - t0
    eng.step(decode_steps=D_STEPS)     # throwaway: first post-admission
    if pipelined:                      # chunk carries one-time re-layout
        # steps return at dispatch here: bound the window with drains so
        # exactly N_CHUNKS of device work is inside it
        jax.device_get(eng.state.lens)
        t0 = time.perf_counter()
        for _ in range(N_CHUNKS):
            eng.step(decode_steps=D_STEPS)
        jax.device_get(eng.state.lens)
        t_decode = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        for _ in range(N_CHUNKS):
            eng.step(decode_steps=D_STEPS)
        t_decode = time.perf_counter() - t0
    eng.pause()

    prefill_tok_s = B * (PLEN - 1) / t_prefill
    decode_tok_s = B * N_CHUNKS * D_STEPS / t_decode
    # bandwidth roofline for decode: params + resident KV read per step
    pbytes = 2 * flops_mod.param_count(cfg)
    kv_read = B * (PLEN + D_STEPS * N_CHUNKS / 2) * _kv_bytes_per_token(cfg)
    roof = B / ((pbytes + kv_read) / peak_bw)
    # prefill is compute-bound (a forward pass): report MFU against the
    # chip peak. Bar: >= 0.45 at this shape (r4: 0.55+ measured after the
    # cold-prompt skip-pool extend; the rest goes to admission-bucket
    # padding, the per-wave host dispatch, and the page-table scatter —
    # all O(waves), not O(tokens)).
    prefill_mfu = (
        flops_mod.forward_flops(cfg, B * (PLEN - 1), seqlens=[PLEN - 1] * B)
        / t_prefill / peak
    )
    _free_engine(eng)
    return {
        "prefill_tokens_per_s": round(prefill_tok_s, 1),
        "prefill_mfu": round(prefill_mfu, 4),
        "decode_tokens_per_s": round(decode_tok_s, 1),
        "slots": B, "prompt_len": PLEN,
        "decode_roofline_tokens_per_s": round(roof, 1),
        "vs_roofline": round(decode_tok_s / roof, 4),
    }


def _free_engine(eng):
    """Release a generation engine's HBM (params + KV pool) so later bench
    sections start from a clean chip."""
    import gc

    eng.state = None
    eng.params = None
    eng.draft_params = None
    eng._jit_extend = eng._jit_commit = eng._jit_chunk = None
    eng._jit_spec = None
    gc.collect()


def _bench_gen_32k(peak_bw: float, peak: float):
    """Decode rate at the published protocol shape: ~31.5k-token context."""
    import jax

    from areal_tpu.base import flops as flops_mod
    from areal_tpu.gen.engine import GenerationEngine, GenRequest
    from areal_tpu.models import transformer as tfm

    cfg = _gen_model_cfg()
    B, PLEN, D_STEPS = 4, 31488, 64
    eng = GenerationEngine(
        cfg, tfm.init_params(cfg, jax.random.key(0), dtype="bfloat16"),
        max_slots=B, max_seqlen=32768, max_new_tokens_cap=1024,
        page_size=128, enable_prefix_cache=False, admit_chunk_tokens=2048,
    )
    rng = np.random.default_rng(0)

    def submit_all(r):
        for i in range(B):
            eng.submit(GenRequest(
                rid=f"{r}_{i}",
                input_ids=[int(x) for x in rng.integers(1, 50000, PLEN)],
                max_new_tokens=1024, temperature=1.0,
            ))

    # warm the admission programs (one extend per width bucket + the
    # skip-pool first-wave variant compile in ~a minute at this depth;
    # timing them as "prefill" would report compile time as throughput)
    submit_all(0)
    eng.step(decode_steps=1)
    eng.pause(); eng.resume()           # release pages, keep programs

    submit_all(1)
    t0 = time.perf_counter()
    eng.step(decode_steps=1)            # chunked prefill of 4 x 31.5k
    t_prefill = time.perf_counter() - t0
    eng.step(decode_steps=D_STEPS)      # throwaway: compile + re-layout
    t0 = time.perf_counter()
    n_chunks = 3
    for _ in range(n_chunks):
        eng.step(decode_steps=D_STEPS)
    t_decode = time.perf_counter() - t0
    eng.pause()
    decode_tok_s = B * n_chunks * D_STEPS / t_decode
    pbytes = 2 * flops_mod.param_count(cfg)
    kv_read = B * (PLEN + 128) * _kv_bytes_per_token(cfg)
    roof = B / ((pbytes + kv_read) / peak_bw)
    prefill_mfu = (
        flops_mod.forward_flops(cfg, B * (PLEN - 1), seqlens=[PLEN - 1] * B)
        / t_prefill / peak
    )
    _free_engine(eng)
    return {
        "prefill_tokens_per_s": round(B * (PLEN - 1) / t_prefill, 1),
        "prefill_mfu": round(prefill_mfu, 4),
        "decode_tokens_per_s": round(decode_tok_s, 1),
        "context_len": PLEN, "slots": B,
        "decode_roofline_tokens_per_s": round(roof, 1),
        "vs_roofline": round(decode_tok_s / roof, 4),
    }


def _draft_predictable_init(cfg, key, draft_layers: int, gamma: float):
    """Random target init whose greedy chain a shared-prefix draft can
    track: the REFINEMENT layers (``draft_layers`` onward) get their
    residual-writing projections (attention out, MLP down) scaled by
    ``gamma``, so they refine rather than overturn the early layers'
    logits. This is the random-init stand-in for the trained-model
    property draft-model spec decode exploits (a distilled draft agrees
    with its teacher on most argmaxes); a chip deployment points
    ``AREAL_SPEC_DRAFT_MODEL`` at a real distilled checkpoint instead.
    Measured on the CPU smoke shape: ~0.85 teacher-forced argmax
    agreement at gamma=0.1 vs ~0.0 for a plain-init truncation (random
    nets are chaotic in depth)."""
    import jax.numpy as jnp

    from areal_tpu.models import transformer as tfm

    params = tfm.init_params(cfg, key, dtype=cfg.dtype)

    def damp(x):
        mask = np.ones((cfg.n_layers,) + (1,) * (x.ndim - 1), np.float32)
        mask[draft_layers:] = gamma
        return (x * jnp.asarray(mask)).astype(x.dtype)

    layers = dict(params["layers"])
    attn = dict(layers["attn"])
    attn["wo"] = damp(attn["wo"])
    mlp = dict(layers["mlp"])
    for k in ("w_down", "w_proj"):
        if k in mlp:
            mlp[k] = damp(mlp[k])
    layers["attn"] = attn
    layers["mlp"] = mlp
    return {**params, "layers": layers}


def _bench_gen_spec(
    peak_bw: float,
    peak: float,
    cfg=None,
    B: int = 64,
    PLEN: int = 1024,
    D_STEPS: int = 32,
    N_CHUNKS: int = 4,
    motif_len: int = 24,
    draft_layers: int = 0,
    draft_gamma: float = 0.1,
):
    """Three-arm A/B at the standard 64-slot/1024-prompt generation
    config: vanilla vs n-gram spec decode vs DRAFT-MODEL spec decode, on
    REPETITIVE prompts — the self-drafter's sweet spot (structured
    math/code generations re-quote their context) and the corpus the
    n-gram's chip-measured 0.29 accept rate was taken on, so round-7
    chip capture can A/B the draft model against it directly.

    All arms serve the SAME target weights (``_draft_predictable_init``:
    random init with damped refinement layers so the shared-prefix draft
    — the first quarter of the stack — tracks the target; see its
    docstring for why plain random init cannot demonstrate a predictive
    draft). Greedy sampling: spec decode is token-exact, so every arm
    emits the SAME tokens and the ``vs_baseline`` ratios are pure speed.
    Reported accept rate is accepted/drafted (docs/performance.md
    "Speculative decoding"); the small ``cfg``/shape overrides exist so
    tests can smoke the stanza on CPU. Legacy keys
    (``accepted_tokens_per_s``/``accept_rate``/``vs_baseline``) keep
    naming the n-gram arm for round-over-round comparison; the draft arm
    reports under ``draft_*``."""
    import jax

    from areal_tpu.base import constants as const
    from areal_tpu.gen.drafter import TransformerDrafter
    from areal_tpu.gen.engine import GenerationEngine, GenRequest

    cfg = cfg or _gen_model_cfg()
    draft_layers = draft_layers or max(1, cfg.n_layers // 4)
    rng = np.random.default_rng(0)
    # motif stays inside the (possibly tiny test) vocab — out-of-range ids
    # would silently clamp in the embedding gather and degenerate the
    # corpus to its last token
    motif = [
        int(x)
        for x in rng.integers(1, min(50000, cfg.vocab_size - 1), motif_len)
    ]
    prompts = []
    for i in range(B):
        p = (motif * (PLEN // motif_len + 1))[:PLEN]
        p[0] = 1 + i                       # distinct slots, no prefix share
        prompts.append(p)
    params = _draft_predictable_init(
        cfg, jax.random.key(0), draft_layers, draft_gamma
    )

    def run_arm(mode: str):
        spec = mode != "vanilla"
        drafter = (
            TransformerDrafter.shared_prefix(cfg, params, draft_layers)
            if mode == "draft" else None
        )
        with _env(const.SPEC_DECODE_ENV, "1" if spec else "0"):
            eng = GenerationEngine(
                cfg, params, max_slots=B, max_seqlen=2 * PLEN,
                max_new_tokens_cap=PLEN, page_size=min(128, PLEN // 4),
                enable_prefix_cache=False,
                admit_chunk_tokens=min(1024, PLEN),
                drafter=drafter,
            )
        k = eng.spec_k
        for i, p in enumerate(prompts):
            eng.submit(GenRequest(
                rid=f"{mode[0]}{i}", input_ids=p,
                max_new_tokens=PLEN, greedy=True,
            ))
        eng.step(decode_steps=1)           # admission + first decode
        eng.step(decode_steps=D_STEPS)     # warm the chunk program
        n0 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())
        t0 = time.perf_counter()
        for _ in range(N_CHUNKS):
            eng.step(decode_steps=D_STEPS)
        n1 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())  # drain
        dt = time.perf_counter() - t0
        drafted = eng.stats["spec_draft_tokens"]
        accepted = eng.stats["spec_accepted_tokens"]
        eng.pause()
        _free_engine(eng)
        return {
            "tokens_per_s": (n1 - n0) / dt,
            "accept_rate": accepted / max(drafted, 1),
            "spec_k": k,
        }

    vanilla = run_arm("vanilla")
    ngram = run_arm("ngram")
    draft = run_arm("draft")
    base = max(vanilla["tokens_per_s"], 1e-9)
    return {
        "vanilla_tokens_per_s": round(vanilla["tokens_per_s"], 1),
        "accepted_tokens_per_s": round(ngram["tokens_per_s"], 1),
        "accept_rate": round(ngram["accept_rate"], 4),
        "spec_k": ngram["spec_k"],
        "slots": B, "prompt_len": PLEN, "prompt": "repetitive",
        "vs_baseline": round(ngram["tokens_per_s"] / base, 4),
        "draft_tokens_per_s": round(draft["tokens_per_s"], 1),
        "draft_accept_rate": round(draft["accept_rate"], 4),
        "draft_vs_baseline": round(draft["tokens_per_s"] / base, 4),
        "draft_layers": draft_layers,
        "draft_gamma": draft_gamma,
    }


def _fused_lp_delta(cfg, params, prompt) -> float:
    """Max abs sampled-logprob delta between the fused epilogue and the
    materialize-then-sample reference on one greedy decode step — the
    exactness probe the gen_sample_fused stanza reports next to its
    throughput numbers (greedy logprobs must agree to float-associativity
    noise). Pure model-layer probe, no engine state involved."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.gen.sampling import SamplingParams, sample_tokens
    from areal_tpu.models import transformer as tfm
    from areal_tpu.ops import fused_sample as fused_ops

    plen = len(prompt) - 1
    page = 8 if plen < 128 else 128
    M = -(-(plen + 1) // page)
    table = jnp.arange(M, dtype=jnp.int32)[None]
    toks = jnp.asarray(prompt[:plen], jnp.int32)[None]
    last = jnp.asarray([prompt[plen]], jnp.int32)
    cache = tfm.PagedKVCache.empty(cfg, M, page)
    cache = tfm.extend_paged(
        params, cfg, cache, toks, table,
        jnp.zeros((1,), jnp.int32), jnp.asarray([plen], jnp.int32),
    )
    args = (params, cfg, cache, last, table,
            jnp.asarray([plen], jnp.int32), jnp.ones((1,), bool))
    logits, _, _ = tfm.decode_step_paged(*args, use_pallas=False)
    hidden, _, _ = tfm.decode_step_paged(
        *args, use_pallas=False, return_hidden=True
    )
    sp = SamplingParams.filled(1, temperature=0.0)
    key = jax.random.key(0)
    _, ref_lp = sample_tokens(key, logits, sp, warp=False)
    out = fused_ops.fused_sample(
        key, hidden, tfm.head_weight(cfg, params), sp.temperature,
        sp.temperature <= 0.0, soft_cap=cfg.final_logits_soft_cap,
        use_pallas=False,
    )
    return float(np.abs(
        np.asarray(jax.device_get(out["logprobs"]))
        - np.asarray(jax.device_get(ref_lp))
    ).max())


def _bench_gen_sample_fused(
    peak_bw: float,
    peak: float,
    cfg=None,
    B: int = 64,
    PLEN: int = 1024,
    D_STEPS: int = 32,
    N_CHUNKS: int = 4,
):
    """A/B the fused LM-head + sampling epilogue (docs/performance.md
    "Fused sampling epilogue") at the standard 64-slot/1024-prompt
    generation config: the baseline arm materializes ``[B, V]`` logits
    every decode step and samples over them; the fused arm streams the
    head over vocab blocks (``AREAL_FUSED_SAMPLE=1``) so the logits
    tensor — and the per-token sort it feeds — never exist.

    Greedy sampling: the fused epilogue is token-exact there, so both
    arms decode the SAME tokens and ``vs_baseline`` is pure speed. Also
    reports the max sampled-logprob delta from a teacher-forced
    one-step probe (the exactness contract, float-associativity noise
    only). The small ``cfg``/shape overrides exist so tests can smoke
    the stanza on CPU."""
    import jax

    from areal_tpu.base import constants as const
    from areal_tpu.gen.engine import GenerationEngine, GenRequest
    from areal_tpu.models import transformer as tfm

    cfg = cfg or _gen_model_cfg()
    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, cfg.vocab_size - 1, PLEN)]
        for _ in range(B)
    ]
    params = tfm.init_params(cfg, jax.random.key(0))

    def run_arm(fused: bool):
        with _env(const.FUSED_SAMPLE_ENV, "1" if fused else "0"):
            eng = GenerationEngine(
                cfg, params, max_slots=B, max_seqlen=2 * PLEN,
                max_new_tokens_cap=PLEN, page_size=min(128, PLEN // 4),
                enable_prefix_cache=False,
                admit_chunk_tokens=min(1024, PLEN),
            )
        for i, p in enumerate(prompts):
            eng.submit(GenRequest(
                rid=f"{'f' if fused else 'b'}{i}", input_ids=p,
                max_new_tokens=PLEN, greedy=True,
            ))
        eng.step(decode_steps=1)           # admission + first decode
        eng.step(decode_steps=D_STEPS)     # warm the chunk program
        n0 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())
        t0 = time.perf_counter()
        for _ in range(N_CHUNKS):
            eng.step(decode_steps=D_STEPS)
        n1 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())  # drain
        dt = time.perf_counter() - t0
        eng.pause()
        _free_engine(eng)
        return (n1 - n0) / dt

    base = run_arm(False)
    fused = run_arm(True)
    return {
        "tokens_per_s": round(fused, 1),
        "baseline_tokens_per_s": round(base, 1),
        "vs_baseline": round(fused / max(base, 1e-9), 4),
        "slots": B, "prompt_len": PLEN,
        "max_logprob_delta": _fused_lp_delta(
            cfg, params, prompts[0][: min(PLEN, 33)]
        ),
    }


def _kvq_logit_delta(cfg, params, prompt) -> float:
    """Max abs decode-logit delta between a raw-dtype and an int8 KV pool
    holding the same prompt — the quantization-noise bound the gen_kvq
    stanza reports next to its throughput numbers. Pure model-layer probe
    (extend_paged -> decode_step_paged), no engine state involved."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import transformer as tfm

    plen = len(prompt) - 1
    page = 8 if plen < 128 else 128
    M = -(-(plen + 1) // page)
    table = jnp.arange(M, dtype=jnp.int32)[None]
    toks = jnp.asarray(prompt[:plen], jnp.int32)[None]
    last = jnp.asarray([prompt[plen]], jnp.int32)
    out = {}
    for kd in (None, "int8"):
        cache = tfm.PagedKVCache.empty(cfg, M, page, kv_dtype=kd)
        cache = tfm.extend_paged(
            params, cfg, cache, toks, table,
            jnp.zeros((1,), jnp.int32), jnp.asarray([plen], jnp.int32),
        )
        logits, _, _ = tfm.decode_step_paged(
            params, cfg, cache, last, table,
            jnp.asarray([plen], jnp.int32), jnp.ones((1,), bool),
            use_pallas=False,
        )
        out[kd] = np.asarray(jax.device_get(logits))
    return float(np.abs(out["int8"] - out[None]).max())


def _bench_gen_kvq(
    peak_bw: float,
    peak: float,
    cfg=None,
    B: int = 64,
    PLEN: int = 1024,
    D_STEPS: int = 32,
    N_CHUNKS: int = 4,
):
    """A/B the int8-quantized KV pool (docs/performance.md "KV
    quantization") at the standard 64-slot/1024-prompt generation config:

    - ``bf16``: raw serving-dtype pool, the baseline;
    - ``int8``: same slot count, pool resized to the SAME page-array HBM
      (itemsize-ratio x pages) — the pure bandwidth win: every decode step
      reads half the KV bytes;
    - ``int8_2x_slots``: twice the slots at that same pool HBM — the
      capacity win (what quantization buys a serving fleet at fixed HBM).

    Greedy sampling so every arm decodes the same workload; reports
    tokens/s per arm, ``vs_baseline`` = int8/bf16 tokens/s at equal slots,
    and the max decode logit delta from a teacher-forced probe. The small
    ``cfg``/shape overrides exist so tests can smoke the stanza on CPU."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.gen.engine import GenerationEngine, GenRequest
    from areal_tpu.models import transformer as tfm

    cfg = cfg or _gen_model_cfg()
    rng = np.random.default_rng(0)
    params = tfm.init_params(cfg, jax.random.key(0), dtype=cfg.dtype)
    page = min(128, max(8, PLEN // 4))
    ratio = jnp.dtype(cfg.dtype).itemsize  # int8 pages per serving-dtype page
    prompts = [
        [int(x) for x in rng.integers(1, min(50000, cfg.vocab_size), PLEN)]
        for _ in range(2 * B)
    ]

    def run_arm(tag, kv_dtype, slots, n_pages):
        eng = GenerationEngine(
            cfg, params, max_slots=slots, max_seqlen=2 * PLEN,
            max_new_tokens_cap=PLEN, page_size=page,
            enable_prefix_cache=False, admit_chunk_tokens=min(1024, PLEN),
            kv_dtype=kv_dtype, n_pages=n_pages,
        )
        for i in range(slots):
            eng.submit(GenRequest(
                rid=f"{tag}{i}", input_ids=prompts[i],
                max_new_tokens=PLEN, greedy=True,
            ))
        eng.step(decode_steps=1)           # admission + first decode
        eng.step(decode_steps=D_STEPS)     # warm the chunk program
        n0 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())
        t0 = time.perf_counter()
        for _ in range(N_CHUNKS):
            eng.step(decode_steps=D_STEPS)
        n1 = int(np.asarray(jax.device_get(eng.state.n_gen)).sum())  # drain
        dt = time.perf_counter() - t0
        pool_bytes = eng.kv_pool_bytes()
        base_pages = eng.n_pages
        eng.pause()
        _free_engine(eng)
        return (n1 - n0) / dt, pool_bytes, base_pages

    bf16_tok_s, bf16_bytes, base_pages = run_arm("b", None, B, None)
    int8_tok_s, int8_bytes, _ = run_arm("q", "int8", B, base_pages * ratio)
    int8_2x_tok_s, _, _ = run_arm("d", "int8", 2 * B, base_pages * ratio)
    return {
        "bf16_tokens_per_s": round(bf16_tok_s, 1),
        "int8_tokens_per_s": round(int8_tok_s, 1),
        "int8_2x_slots_tokens_per_s": round(int8_2x_tok_s, 1),
        "vs_baseline": round(int8_tok_s / max(bf16_tok_s, 1e-9), 4),
        "max_logit_delta": round(
            _kvq_logit_delta(cfg, params, prompts[0][: min(PLEN, 128)]), 5
        ),
        "slots": B, "slots_2x": 2 * B, "prompt_len": PLEN,
        "bf16_pool_bytes": int(bf16_bytes),
        "int8_pool_bytes": int(int8_bytes),
    }


def _bench_gateway():
    """Continuous batching through the serving gateway (docs/serving.md):
    N concurrent streaming clients share engine slots vs the same N
    serialized one-at-a-time. ``vs_baseline`` = concurrent/serialized
    tokens/s — continuous batching amortizes the per-chunk dispatch +
    params sweep across slots, so > 1.0 is the bar (CPU and chip alike).
    Runs a small model so the section stays cheap on CPU."""
    import asyncio

    import aiohttp
    import jax

    from areal_tpu.base import network
    from areal_tpu.gateway.api import (
        ByteFallbackCodec,
        GatewayConfig,
        GatewayServer,
        serve_gateway,
    )
    from areal_tpu.gateway.scheduler import ContinuousBatchScheduler
    from areal_tpu.gen.engine import GenerationEngine
    from areal_tpu.gen.server import serve as serve_gen
    from areal_tpu.models import transformer as tfm
    from areal_tpu.models.config import ModelConfig

    N, MAX_NEW, PLEN = 8, 64, 32
    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=16, hidden_dim=64,
        intermediate_dim=128, vocab_size=256, dtype="float32",
    )

    async def run():
        eng = GenerationEngine(
            cfg, tfm.init_params(cfg, jax.random.key(0)),
            max_slots=N, max_seqlen=256,
            # one admit bucket: staggered HTTP arrivals would otherwise
            # compile fresh [n_rows] extend/commit programs mid-window
            admit_buckets=(N,),
        )
        gen_port = network.find_free_port()
        gen_runner = await serve_gen(
            eng, "127.0.0.1", gen_port, decode_steps=8
        )
        sched = ContinuousBatchScheduler(
            [f"http://127.0.0.1:{gen_port}"], max_queue=256,
        )
        await sched.start()
        gw = GatewayServer(
            sched, ByteFallbackCodec(cfg.vocab_size),
            GatewayConfig(max_tokens_cap=1024),
        )
        gw_port = network.find_free_port()
        gw_runner = await serve_gateway(gw, "127.0.0.1", gw_port)
        url = f"http://127.0.0.1:{gw_port}/v1/completions"
        rng = np.random.default_rng(0)
        prompts = [
            [int(x) for x in rng.integers(1, cfg.vocab_size, PLEN)]
            for _ in range(N)
        ]

        async def one(session, prompt):
            async with session.post(
                url,
                json={
                    "prompt": prompt, "max_tokens": MAX_NEW,
                    "temperature": 1.0, "stream": True,
                },
            ) as resp:
                resp.raise_for_status()
                async for raw in resp.content:
                    if raw.strip() == b"data: [DONE]":
                        break

        timeout = aiohttp.ClientTimeout(total=600)
        try:
            async with aiohttp.ClientSession(timeout=timeout) as session:
                # warmup covers BOTH arms' jit paths: one full concurrent
                # round (admission + decode at occupancy) + one solo
                warm = await asyncio.gather(
                    *(one(session, p) for p in prompts),
                    return_exceptions=True,
                )
                errs = [r for r in warm if isinstance(r, BaseException)]
                if errs:
                    raise errs[0]
                await one(session, prompts[0])
                t0 = time.perf_counter()
                res = await asyncio.gather(
                    *(one(session, p) for p in prompts),
                    return_exceptions=True,
                )
                t_concurrent = time.perf_counter() - t0
                errs = [r for r in res if isinstance(r, BaseException)]
                if errs:
                    raise errs[0]
                t0 = time.perf_counter()
                for p in prompts:
                    await one(session, p)
                t_serial = time.perf_counter() - t0
        finally:
            await sched.stop()
            await gw_runner.cleanup()
            await gen_runner.cleanup()
            _free_engine(eng)
        # no stop tokens + random weights: every request runs to MAX_NEW
        tok = N * MAX_NEW
        return {
            "clients": N, "max_tokens": MAX_NEW,
            "concurrent_tokens_per_s": round(tok / t_concurrent, 1),
            "serialized_tokens_per_s": round(tok / t_serial, 1),
            "vs_baseline": round(t_serial / t_concurrent, 3),
        }

    return asyncio.run(run())


def _bench_bwd_pipe(cfg_small, cfg_32k, peak):
    """A/B the flash-bwd cross-block software pipeline (round-5 kernel
    work, default OFF until proven): re-measure the primary and ctx32k
    shapes with AREAL_FLASH_BWD_PIPELINE=1. Compare against the main
    sections' numbers (same shapes, flag off) — if these win, flip the
    default in ops/pallas/flash_attention.py::_bwd_pipeline."""
    prev = os.environ.get("AREAL_FLASH_BWD_PIPELINE")
    os.environ["AREAL_FLASH_BWD_PIPELINE"] = "1"
    try:
        return {
            "primary_pipe": _bench_shape(
                cfg_small, [512] * 8, n_steps=16, peak=peak
            ),
            "ctx32k_pipe": _bench_shape(cfg_32k, [32768], n_steps=4, peak=peak),
        }
    finally:
        if prev is None:
            os.environ.pop("AREAL_FLASH_BWD_PIPELINE", None)
        else:
            os.environ["AREAL_FLASH_BWD_PIPELINE"] = prev


def _bench_fwd_pipe(peak):
    """A/B the host↔device data-plane pipeline (round 6): serial vs
    dispatch-ahead ``forward()`` (AREAL_FWD_PIPELINE) and serial vs
    prefetched+deferred PPO step (AREAL_TRAIN_PREFETCH). ``vs_baseline`` =
    serial / pipelined wall time (>1 means the pipeline wins — if it does
    not on real hardware, flip the env defaults in base/constants.py).
    Every sub-A/B is individually guarded so the section always returns
    structured JSON."""
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import PPOHyperparameters, make_interface
    from areal_tpu.base import constants as const
    from areal_tpu.base import metrics as metrics_mod
    from areal_tpu.interfaces.ppo import logprob_output_fn
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    cfg = ModelConfig(
        n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64, hidden_dim=768,
        intermediate_dim=2048, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=12,
    )
    eng = TrainEngine(
        cfg, ParallelConfig(), OptimizerConfig(lr=1e-5), param_dtype="bfloat16"
    )
    eng.init_random(0)
    eng.setup_optimizer(100)
    rng = np.random.default_rng(0)
    # 16 x 512-token sequences at a 2048-token budget -> 4 micro-batches:
    # enough host round trips per call for the dispatch-ahead window to show
    lens = [512] * 16
    sample_fwd = _mk_sample(cfg, lens, rng)
    spec = MicroBatchSpec(n_mbs=4, max_tokens_per_mb=2048)
    out = {}

    def time_forward(knob, n_iters=4):
        with _env(const.FWD_PIPELINE_ENV, knob):
            eng.forward(sample_fwd, spec, logprob_output_fn)  # warm/compile
            t0 = time.perf_counter()
            for _ in range(n_iters):
                eng.forward(sample_fwd, spec, logprob_output_fn)
            return (time.perf_counter() - t0) / n_iters

    try:
        serial = time_forward("0")
        # the peak is a lifetime max: clear it so the value below can only
        # have come from THIS pipelined run (earlier sections also forward)
        metrics_mod.counters.clear("fwd_pipe/max_in_flight")
        piped = time_forward("2")
        out["forward"] = {
            "serial_s": round(serial, 4),
            "pipelined_s": round(piped, 4),
            "vs_baseline": round(serial / max(piped, 1e-9), 4),
            "max_in_flight": int(
                metrics_mod.counters.get("fwd_pipe/max_in_flight")
            ),
            "n_mbs": 4,
        }
    except Exception as e:
        out["forward"] = {"error": repr(e)[:200]}

    # one PPO step = prox-logprob recompute (forward MFC) + 4-minibatch
    # decoupled-PPO update — the trainer hot path run through both knobs
    PLEN, GLEN, N = 128, 384, 16

    def mk_ppo_sample():
        seqs, pmask, lps = [], [], []
        for _ in range(N):
            seqs.append(rng.integers(1, 30000, PLEN + GLEN).astype(np.int64))
            pmask.append(np.r_[np.ones(PLEN, bool), np.zeros(GLEN, bool)])
            lp = np.zeros(PLEN + GLEN, np.float32)
            lp[PLEN - 1 : PLEN - 1 + GLEN] = -1.0
            lps.append(lp)
        lp_all = np.concatenate(lps)
        return SequenceSample.from_default(
            ids=list(range(N)), seqlens=[PLEN + GLEN] * N,
            data={
                "packed_input_ids": np.concatenate(seqs),
                "prompt_mask": np.concatenate(pmask),
                "packed_logprobs": lp_all,
                "packed_ref_logprobs": lp_all.copy(),
                "rewards": rng.standard_normal(N).astype(np.float32),
                "seq_no_eos_mask": np.ones(N, bool),
            },
        )

    actor = make_interface("ppo_actor", hp=PPOHyperparameters(
        ppo_n_minibatches=4, disable_value=True, adv_norm=True,
        group_adv_norm=False, use_decoupled_loss=True,
    ))

    def one_ppo_step():
        s = mk_ppo_sample()
        s.update_(actor.inference(eng, s, spec))
        actor.train_step(eng, s, spec)

    def time_ppo(knob, n_iters=3):
        fwd_depth = "0" if knob == "0" else "2"
        with _env(const.TRAIN_PREFETCH_ENV, knob), \
                _env(const.FWD_PIPELINE_ENV, fwd_depth):
            one_ppo_step()                       # warm/compile
            jax.block_until_ready(eng.params)
            t0 = time.perf_counter()
            for _ in range(n_iters):
                one_ppo_step()
            jax.block_until_ready(eng.params)    # drain deferred dispatches
            return (time.perf_counter() - t0) / n_iters

    try:
        serial = time_ppo("0")
        piped = time_ppo("1")
        out["ppo_step"] = {
            "serial_s": round(serial, 4),
            "pipelined_s": round(piped, 4),
            "vs_baseline": round(serial / max(piped, 1e-9), 4),
            "n_minibatches": 4,
        }
    except Exception as e:
        out["ppo_step"] = {"error": repr(e)[:200]}

    eng.params = eng.opt_state = None
    eng._jit_cache = None
    del eng
    import gc

    gc.collect()
    return out


def _bench_guard(peak):
    """A/B the on-device finite-ness guard (AREAL_TRAIN_GUARD, trainer
    survivability): the isfinite(loss) & isfinite(grad_norm) check + the
    select of old-vs-new params/opt state fold into the jitted step and the
    flag rides the stats the pipelined path already fetches — so the
    per-step overhead should be ~0 (no extra host round trip). Recorded
    like the fwd_pipe section: ``vs_baseline`` = guard_off / guard_on wall
    time (≈1.0 expected; if real hardware shows a regression, flip the env
    default in base/constants.py)."""
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base import constants as const
    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    cfg = ModelConfig(
        n_layers=6, n_q_heads=8, n_kv_heads=4, head_dim=64, hidden_dim=512,
        intermediate_dim=1408, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=6,
    )
    rng = np.random.default_rng(0)
    sample = _mk_sample(cfg, [512] * 8, rng)
    spec = MicroBatchSpec(n_mbs=2, max_tokens_per_mb=2048)
    n_steps = 8

    def time_guard(knob):
        # the knob is read at jit-build time, so each arm gets a fresh
        # engine (identical seed/shapes: only the guard epilogue differs)
        with _env(const.TRAIN_GUARD_ENV, knob):
            eng = TrainEngine(
                cfg, ParallelConfig(), OptimizerConfig(lr=1e-5),
                param_dtype="bfloat16",
            )
            eng.init_random(0)
            eng.setup_optimizer(100)
            eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
            jax.block_until_ready(eng.params)           # warm/compile
            t0 = time.perf_counter()
            for _ in range(n_steps):
                eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
            jax.block_until_ready(eng.params)
            dt = (time.perf_counter() - t0) / n_steps
            eng.params = eng.opt_state = None
            return dt

    off = time_guard("0")
    on = time_guard("1")
    import gc

    gc.collect()
    return {
        "guard_off_s": round(off, 5),
        "guard_on_s": round(on, 5),
        "overhead_pct": round((on - off) / max(off, 1e-9) * 100, 2),
        "vs_baseline": round(off / max(on, 1e-9), 4),
        "n_steps": n_steps,
    }


def _bench_telemetry(peak):
    """A/B the fleet telemetry exporter (AREAL_TELEMETRY_EXPORT,
    docs/observability.md): the exporter is a background thread that
    serializes the counter/histogram registry and writes one name_resolve
    key per period — nothing rides the train-step path, so ``vs_baseline``
    = exporter_off / exporter_on wall time should be ≈ 1.0. Both arms run
    the identical step loop INCLUDING the per-batch consumption
    ``observe()`` calls (those are knob-independent: the buffer stamps
    lifecycle histograms whether or not anyone exports them); only the
    publishing thread differs. The on-arm publishes through a real
    file-backed name_resolve at an aggressive 0.25 s period — 60x the
    default rate, so a ≈1.0 here bounds the production overhead hard."""
    import tempfile

    import jax

    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.base import constants as const
    from areal_tpu.base import metrics as metrics_mod
    from areal_tpu.base import name_resolve
    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine
    from areal_tpu.system.worker_base import TelemetryExporter

    cfg = ModelConfig(
        n_layers=6, n_q_heads=8, n_kv_heads=4, head_dim=64, hidden_dim=512,
        intermediate_dim=1408, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=6,
    )
    rng = np.random.default_rng(0)
    sample = _mk_sample(cfg, [512] * 8, rng)
    spec = MicroBatchSpec(n_mbs=2, max_tokens_per_mb=2048)
    n_steps = 8

    eng = TrainEngine(
        cfg, ParallelConfig(), OptimizerConfig(lr=1e-5),
        param_dtype="bfloat16",
    )
    eng.init_random(0)
    eng.setup_optimizer(100)
    eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
    jax.block_until_ready(eng.params)                  # warm/compile

    def time_steps():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
            # a consumed batch's worth of lifecycle stamps (identical in
            # both arms — observe() is knob-independent)
            for _ in range(8):
                metrics_mod.counters.observe(
                    metrics_mod.STALENESS_VERSIONS, 1
                )
                metrics_mod.counters.observe(metrics_mod.QUEUE_WAIT_S, 0.05)
                metrics_mod.counters.observe(metrics_mod.E2E_LATENCY_S, 1.5)
        jax.block_until_ready(eng.params)
        return (time.perf_counter() - t0) / n_steps

    with _env(const.TELEMETRY_EXPORT_ENV, "0"):
        tele = TelemetryExporter("bench", "t0", "trainer", "trainer")
        tele.maybe_start()                              # no-op: knob off
        off = time_steps()
        tele.stop()

    prev_repo = name_resolve.default_repository()
    tmpdir = tempfile.mkdtemp(prefix="bench_telemetry_")
    published = 0
    try:
        name_resolve.reconfigure(
            name_resolve.NameResolveConfig(type="file", root=tmpdir)
        )
        with _env(const.TELEMETRY_EXPORT_ENV, "0.25"):
            tele = TelemetryExporter(
                "bench", "t0", "trainer", "trainer",
                step_fn=lambda: n_steps,
            ).maybe_start()
            on = time_steps()
            tele.stop()
            published = tele.published
    finally:
        name_resolve.set_repository(prev_repo)
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    eng.params = eng.opt_state = None
    import gc

    gc.collect()
    return {
        "exporter_off_s": round(off, 5),
        "exporter_on_s": round(on, 5),
        "overhead_pct": round((on - off) / max(off, 1e-9) * 100, 2),
        "vs_baseline": round(off / max(on, 1e-9), 4),
        "snapshots_published": published,
        "export_period_s": 0.25,
        "n_steps": n_steps,
    }


def _bench_tracing(peak):
    """A/B the distributed-tracing span plane (AREAL_TRACE_SPANS,
    docs/observability.md "Distributed tracing") on the REAL serving
    stack: the gateway-section request loop (N concurrent streaming
    clients through gateway -> scheduler -> gen server -> engine, every
    hop instrumented) run once with spans recording and once with the
    knob off. ``vs_baseline`` = spans_off / spans_on wall time should be
    ~= 1.0 — per-request span cost (a handful of context stamps + ring
    appends) is microseconds against a millisecond-scale request, and
    the off path is a clock read + two counter adds per span. A
    microbench of that per-span cost (disabled vs recording) rides
    along."""
    import asyncio

    import aiohttp
    import jax

    from areal_tpu.base import constants as const
    from areal_tpu.base import network, tracing
    from areal_tpu.gateway.api import (
        ByteFallbackCodec,
        GatewayConfig,
        GatewayServer,
        serve_gateway,
    )
    from areal_tpu.gateway.scheduler import ContinuousBatchScheduler
    from areal_tpu.gen.engine import GenerationEngine
    from areal_tpu.gen.server import serve as serve_gen
    from areal_tpu.models import transformer as tfm
    from areal_tpu.models.config import ModelConfig

    N, MAX_NEW, PLEN, ROUNDS = 8, 64, 32, 3
    cfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=16, hidden_dim=64,
        intermediate_dim=128, vocab_size=256, dtype="float32",
    )

    async def run():
        eng = GenerationEngine(
            cfg, tfm.init_params(cfg, jax.random.key(0)),
            max_slots=N, max_seqlen=256, admit_buckets=(N,),
        )
        gen_port = network.find_free_port()
        gen_runner = await serve_gen(
            eng, "127.0.0.1", gen_port, decode_steps=8
        )
        sched = ContinuousBatchScheduler(
            [f"http://127.0.0.1:{gen_port}"], max_queue=256,
        )
        await sched.start()
        gw = GatewayServer(
            sched, ByteFallbackCodec(cfg.vocab_size),
            GatewayConfig(max_tokens_cap=1024),
        )
        gw_port = network.find_free_port()
        gw_runner = await serve_gateway(gw, "127.0.0.1", gw_port)
        url = f"http://127.0.0.1:{gw_port}/v1/completions"
        rng = np.random.default_rng(0)
        prompts = [
            [int(x) for x in rng.integers(1, cfg.vocab_size, PLEN)]
            for _ in range(N)
        ]

        async def one(session, prompt):
            async with session.post(
                url,
                json={
                    "prompt": prompt, "max_tokens": MAX_NEW,
                    "temperature": 1.0, "stream": True,
                },
            ) as resp:
                resp.raise_for_status()
                async for raw in resp.content:
                    if raw.strip() == b"data: [DONE]":
                        break

        async def round_(session):
            t0 = time.perf_counter()
            res = await asyncio.gather(
                *(one(session, p) for p in prompts),
                return_exceptions=True,
            )
            errs = [r for r in res if isinstance(r, BaseException)]
            if errs:
                raise errs[0]
            return time.perf_counter() - t0

        timeout = aiohttp.ClientTimeout(total=600)
        try:
            async with aiohttp.ClientSession(timeout=timeout) as session:
                for _ in range(2):                      # warm both arms
                    await round_(session)
                # interleave the arms so drift (page cache, allocator,
                # CPU clocking) cancels instead of biasing one arm
                t_on = t_off = 0.0
                spans_recorded = 0
                for _ in range(ROUNDS):
                    with _env(const.TRACE_SPANS_ENV, "1"):
                        t_on += await round_(session)
                        spans_recorded += len(tracing.drain())
                    with _env(const.TRACE_SPANS_ENV, "0"):
                        t_off += await round_(session)
        finally:
            await sched.stop()
            await gw_runner.cleanup()
            await gen_runner.cleanup()
            _free_engine(eng)
        return t_on, t_off, spans_recorded

    t_on, t_off, spans_recorded = asyncio.run(run())
    n_req = N * ROUNDS

    # per-span cost microbench: the two knob settings over a bare span
    from areal_tpu.base import constants as const
    from areal_tpu.base import tracing

    def per_span(setting):
        with _env(const.TRACE_SPANS_ENV, setting):
            for _ in range(200):
                with tracing.span("bench/span"):
                    pass
            t0 = time.perf_counter()
            for _ in range(5000):
                with tracing.span("bench/span"):
                    pass
            dt = time.perf_counter() - t0
        tracing.drain()
        return dt / 5000 * 1e6

    span_off_us = per_span("0")
    span_on_us = per_span("1")
    spans_per_req = spans_recorded / max(n_req, 1)
    # the literal "tracing-off overhead": the disabled span plane's cost
    # per request as a fraction of the request itself
    off_pct = (
        span_off_us * 1e-6 * spans_per_req / max(t_off / n_req, 1e-9) * 100
    )
    return {
        "clients": N, "rounds": ROUNDS, "max_tokens": MAX_NEW,
        "spans_on_s_per_req": round(t_on / n_req, 5),
        "spans_off_s_per_req": round(t_off / n_req, 5),
        "spans_recorded_per_req": round(spans_per_req, 1),
        "span_off_us": round(span_off_us, 3),
        "span_on_us": round(span_on_us, 3),
        "off_span_overhead_pct": round(off_pct, 3),
        "vs_baseline": round(t_off / max(t_on, 1e-9), 4),
    }


def _bench_async_ppo(peak):
    """One complete async-PPO round on a single chip: generate a GRPO group
    per prompt on the paged engine, score, run the decoupled-PPO update,
    swap the new weights into the engine. Reports reward-samples/sec/chip
    (the north-star unit, BASELINE.json)."""
    from areal_tpu.models.config import ModelConfig

    cfg = ModelConfig(
        n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64, hidden_dim=768,
        intermediate_dim=2048, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=12,
    )
    return _run_ppo_round_bench(
        cfg, model="125M", n_prompts=8, group=4, plen=128, max_new=256,
        mb_tokens=16384, page_size=64,
    )


def _bench_async_ppo_1p5b(peak):
    """The same complete async-PPO round at the R1-Distill-1.5B profile —
    the protocol's smallest benchmark model and BASELINE config #2
    (Qwen2.5-1.5B PPO). At this size attention, sampling, and the 152k-vocab
    loss dominate the round the way they do in production; the 125M section
    hides them (VERDICT r4 weak #2). bf16 params + bf16 Adam state
    (~9.3 GB) + the gen engine's paged KV pool share the one chip."""
    cfg = dataclasses.replace(
        _gen_model_cfg(),
        remat_policy="dots_attn",   # 28L activations don't fit un-remat'd
        loss_chunk_size=2048,       # no [T, 152k-vocab] logits transient
    )
    return _run_ppo_round_bench(
        cfg, model="1.5B", n_prompts=8, group=4, plen=512, max_new=1024,
        mb_tokens=8192, page_size=128,
    )


def _run_ppo_round_bench(
    cfg, *, model, n_prompts, group, plen, max_new, mb_tokens, page_size
):
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import PPOHyperparameters, make_interface
    from areal_tpu.gen.engine import GenerationEngine, GenRequest
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    N_PROMPTS, GROUP, PLEN, MAX_NEW = n_prompts, group, plen, max_new
    # HBM at the 1.5B profile: params+grads+adam ~13.2 GiB bf16 leaves
    # ~2.3 GiB for the gen engine + transients on a 16 GiB v5e — cap the
    # slot count (requests queue through extra waves) so the KV pool
    # stays inside it
    max_slots = min(N_PROMPTS * GROUP, 16 if model != "125M" else 64)
    eng = TrainEngine(
        cfg, ParallelConfig(), OptimizerConfig(lr=1e-5), param_dtype="bfloat16"
    )
    eng.init_random(0)
    eng.setup_optimizer(100)
    gen = GenerationEngine(
        cfg, eng.params, max_slots=max_slots, max_seqlen=PLEN + MAX_NEW,
        max_new_tokens_cap=MAX_NEW, page_size=page_size, seed=0,
    )
    actor = make_interface("ppo_actor", hp=PPOHyperparameters(
        ppo_n_minibatches=1, disable_value=True, group_adv_norm=True,
        adv_norm=False, use_decoupled_loss=True, group_size=GROUP,
    ))
    spec = MicroBatchSpec(max_tokens_per_mb=mb_tokens)
    rng = np.random.default_rng(0)

    def one_round():
        prompts = [
            [int(x) for x in rng.integers(1, 30000, PLEN)]
            for _ in range(N_PROMPTS)
        ]
        for i, p in enumerate(prompts):
            for g in range(GROUP):   # GRPO group: prefix cache shares p
                gen.submit(GenRequest(
                    rid=f"{i}-{g}", input_ids=p, max_new_tokens=MAX_NEW,
                    temperature=1.0,
                ))
        outs = {o.rid: o for o in gen.run_until_done(decode_steps=64)}
        t_gen = time.perf_counter()
        ids_l, lens, pmask, lps, rewards = [], [], [], [], []
        keys = sorted(outs, key=lambda r: tuple(map(int, r.split("-"))))
        for rid in keys:
            o = outs[rid]
            i = int(rid.split("-")[0])
            seq = prompts[i] + o.output_ids
            lens.append(len(seq))
            ids_l.append(np.asarray(seq, np.int64))
            pmask.append(np.r_[np.ones(PLEN, bool),
                               np.zeros(len(o.output_ids), bool)])
            lp = np.zeros(len(seq), np.float32)
            lp[PLEN - 1 : PLEN - 1 + len(o.output_ids)] = o.output_logprobs
            lps.append(lp)
            # stand-in verifier: parity of the final token (host-trivial,
            # like the reference's sandboxed checker it is not on-device)
            rewards.append(float(o.output_ids[-1] % 2) if o.output_ids else 0.0)
        sample = SequenceSample.from_default(
            ids=list(range(len(keys))), seqlens=lens,
            data={
                "packed_input_ids": np.concatenate(ids_l),
                "prompt_mask": np.concatenate(pmask),
                "packed_logprobs": np.concatenate(lps),
                "packed_ref_logprobs": np.concatenate(lps),
                "rewards": np.asarray(rewards, np.float32),
                "seq_no_eos_mask": np.ones(len(keys), bool),
            },
        )
        # the real decoupled objective: recompute proximal logprobs under
        # the CURRENT policy (actor_inf MFC, ≈ ppo_interface.py:474) —
        # without prox_logp the loss silently degrades to the vanilla
        # ratio and the bench measures a cheaper round (VERDICT r3 weak #3)
        sample.update_(actor.inference(eng, sample, spec))
        actor.train_step(eng, sample, spec)
        gen.update_params(eng.params)      # weight swap into the fleet
        return len(keys), t_gen

    def cache_entries():
        return eng.n_jit_entries() + gen.n_jit_entries()

    # warm until the jit caches stop growing: round 1 compiles everything
    # once, round 2 historically compiled a SECOND train-step variant
    # (donated-state sharding drift — fixed, but the bench must not trust
    # that unmeasured); a still-growing cache means the next timed round
    # would eat a compile (VERDICT r3 weak #1)
    n, _ = one_round()
    warm_rounds, prev = 1, cache_entries()
    for _ in range(3):
        one_round()
        warm_rounds += 1
        cur = cache_entries()
        if cur == prev:
            break
        prev = cur
    # steady state: two consecutive timed rounds must agree (<10% apart)
    t0 = time.perf_counter()
    _, tg1 = one_round()
    t1 = time.perf_counter()
    n, tg2 = one_round()
    t2 = time.perf_counter()
    d1, d2 = t1 - t0, t2 - t1
    _free_engine(gen)
    del eng
    import gc

    gc.collect()
    return {
        "reward_samples_per_sec": round(2 * n / (d1 + d2), 3),
        "round_seconds": [round(d1, 2), round(d2, 2)],
        "steady": abs(d1 - d2) / max(d1, d2) < 0.10,
        "warm_rounds": warm_rounds,
        "gen_seconds": round((tg1 - t0) + (tg2 - t1), 2),
        "train_seconds": round((t1 - tg1) + (t2 - tg2), 2),
        "samples_per_round": n,
        "gen_tokens": N_PROMPTS * GROUP * MAX_NEW,
        "decoupled": True,
        "model": model,
    }


def _bench_system_ppo():
    """The ASSEMBLED async-PPO system, not the in-process loop: gen server +
    gserver manager + rollout workers + trainer as real processes over
    HTTP/ZMQ via ``apps/launcher.py`` — the overheads the in-process ``ppo``
    section hides (HTTP hops, staleness-gate polling, chunked re-scheduling)
    are exactly what the reference's async design manages
    (``realhf/system/gserver_manager.py:279-285``). Same model/workload as
    ``ppo``; steady-state rate from trainer metrics timestamps (first step
    carries every compile)."""
    import json as _json
    import shutil
    import tempfile

    from areal_tpu.apps import launcher
    from areal_tpu.experiments import AsyncPPOExperiment, load_config

    N_PROMPTS, GROUP, PLEN, MAX_NEW = 8, 4, 128, 256
    STEPS = 4
    tmp = tempfile.mkdtemp(prefix="areal_sysbench_")
    try:
        rng = np.random.default_rng(0)
        data = os.path.join(tmp, "prompts.jsonl")
        with open(data, "w") as f:
            for i in range(N_PROMPTS):
                f.write(_json.dumps({
                    "query_id": f"q{i}",
                    "prompt_ids": [int(x) for x in rng.integers(1, 30000, PLEN)],
                    "task": "math",
                    "solutions": ["\\boxed{7}"],
                }) + "\n")
        arch = dict(
            n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64,
            hidden_dim=768, intermediate_dim=2048, vocab_size=32768,
            use_attention_bias=True, dtype="bfloat16",
        )
        cfg = load_config(AsyncPPOExperiment, None, [
            "experiment_name=sysbench",
            "trial_name=t0",
            f"fileroot={tmp}/root",
            f"dataset.path={data}",
            f"train_batch_size={N_PROMPTS * GROUP}",
            "max_tokens_per_mb=16384",
            f"control.total_train_steps={STEPS}",
            "control.ckpt_freq_steps=null",
            "control.ckpt_freq_secs=null",
            f"actor.arch={_json.dumps(arch)}",
            'actor.overrides={"remat_policy": "none", "layer_scan_unroll": 12}',
            "actor.parallel=d1m1",
            "actor.optimizer.lr=0.00001",
            "actor.param_dtype=bfloat16",   # match the in-process ppo section
            "use_ref_model=false",
            "recover_mode=disabled",
            "gen.n_servers=1",
            f"gen.max_slots={N_PROMPTS * GROUP}",
            f"gen.max_seqlen={PLEN + MAX_NEW}",
            "gen.page_size=64",
            "rollout.n_workers=1",
            f"rollout.max_concurrent_tasks={N_PROMPTS * GROUP}",
            f"rollout.new_tokens_per_chunk={MAX_NEW}",
            # a REALISTIC staleness budget: with the gate wide open the
            # fleet burns its capacity generating samples whole versions
            # ahead that the buffer then drops as stale (measured: a tiny
            # smoke world served 398x what training consumed)
            "manager.max_head_offpolicyness=4",
            f'gconfig={{"n": {GROUP}, "max_new_tokens": {MAX_NEW}}}',
            'ppo={"ppo_n_minibatches": 1, "disable_value": true,'
            ' "group_adv_norm": true, "adv_norm": false,'
            f' "use_decoupled_loss": true, "group_size": {GROUP}}}',
        ])
        t0 = time.perf_counter()
        rc = launcher.run_async_ppo(cfg)
        wall = time.perf_counter() - t0
        metrics = os.path.join(tmp, "root", "logs", "sysbench", "t0",
                               "metrics.jsonl")
        if rc != 0 or not os.path.exists(metrics):
            return {"error": f"rc={rc}, metrics={os.path.exists(metrics)}"}
        with open(metrics) as f:
            lines = [_json.loads(l) for l in f]
        if len(lines) < 3:
            return {"error": f"rc={rc} steps={len(lines)}"}
        # steady state: drop step 1 (compiles); timestamps bound steps 2..N
        steady_s = lines[-1]["time"] - lines[0]["time"]
        n_samples = sum(l["ppo/n_seqs_consumed"] for l in lines[1:])
        gen_tokens = sum(l.get("ppo/n_tokens", 0) for l in lines[1:]) \
            - PLEN * n_samples  # generated tokens only
        out = {
            "reward_samples_per_sec": round(n_samples / steady_s, 3),
            "steady_seconds": round(steady_s, 2),
            "steps_timed": len(lines) - 1,
            "gen_tokens_per_sec": round(max(gen_tokens, 0) / steady_s, 1),
            "wall_seconds": round(wall, 2),
            "world": "gen_server+manager+rollout+trainer (processes)",
        }
        # the gen server dumps its phase accounting at shutdown — where the
        # serving side's wall time went (step-loop busy vs weight swaps vs
        # idle) and how many in-flight rollouts the weight syncs interrupted
        gsm = os.path.join(tmp, "root", "logs", "sysbench", "t0",
                           "gen_server_0.json")
        if os.path.exists(gsm):
            with open(gsm) as f:
                g = _json.load(f)
            out["gen_server"] = {
                k: g[k] for k in (
                    "uptime_s", "step_busy_s", "weight_update_s",
                    "n_weight_updates", "n_interrupted", "served",
                    "gen_tokens", "engine_prefill_tokens",
                ) if k in g
            }
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    import jax

    from areal_tpu.models.config import ModelConfig

    t_bench0 = time.perf_counter()  # deadline clock covers probe + primary
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e bf16
    # BENCH_SECTIONS=gen,ppo runs a subset (fast iteration); default: all
    sections = os.environ.get("BENCH_SECTIONS", "").split(",")
    sections = [s for s in sections if s]

    def want(name):
        return not sections or name in sections
    # full layer unroll + no remat: these shapes fit HBM comfortably, and
    # unrolling removes the scan's per-layer buffer shuffling (~20% step
    # time); long-context/big-model training keeps scan + remat by default
    # attn_max_seqlen statically narrows the flash kernels' block band to
    # the packed segments' actual length — at 512-token packing most grid
    # steps were out-of-band no-ops
    cfg_small = ModelConfig(
        n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64, hidden_dim=768,
        intermediate_dim=2048, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=12,
        attn_max_seqlen=512,
    )
    cfg_1b = ModelConfig(
        n_layers=20, n_q_heads=16, n_kv_heads=8, head_dim=128,
        hidden_dim=2048, intermediate_dim=5632, vocab_size=32768,
        use_attention_bias=True, dtype="bfloat16",
        remat_policy="none", layer_scan_unroll=20, attn_max_seqlen=512,
    )

    # Backend probe BEFORE any section: if the TPU tunnel is down, emit a
    # structured one-line JSON (rc=0) instead of crashing with an empty
    # capture — the driver records whatever this prints (VERDICT r4 weak #1).
    def _no_backend(msg):
        print(
            json.dumps(
                {
                    "metric": "sft_train_tokens_per_sec_single_chip",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "error": msg,
                }
            ),
            flush=True,
        )

    # Backend init can hang indefinitely when the TPU tunnel is half-up, so
    # probe in a daemon thread with a deadline.
    import threading

    probe = {}

    def _probe():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:
            probe["error"] = repr(e)[:300]

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(float(os.environ.get("BENCH_BACKEND_TIMEOUT", 600)))
    if "devices" not in probe:
        _no_backend(
            "backend unavailable: "
            + probe.get("error", "init timed out (tunnel down?)")
        )
        os._exit(0)  # daemon thread may be stuck inside PJRT init
    devices = probe["devices"]

    detail = {"device": str(devices[0].device_kind)}
    if want("primary"):
        primary = _bench_shape(cfg_small, [512] * 8, n_steps=32, peak=peak)
    else:
        primary = {"tokens_per_s": 0.0, "mfu": 0.0}
    detail["primary"] = primary

    peak_bw = float(os.environ.get("BENCH_PEAK_BW", 819e9))  # v5e HBM B/s
    cfg_8k = dataclasses.replace(cfg_small, attn_max_seqlen=None)
    # ctx32k = the 32k-context protocol shape (benchmark README): one long
    # sequence through the flash kernels; unrolled layers (the scan's carry
    # bookkeeping costs ~4% at 32k). This 125M shape FITS without remat at
    # 32k (chip-measured r4: none=0.435 vs dots_attn=0.420 MFU — the
    # dots_attn recompute of projections/MLP costs ~1 fwd of matmuls);
    # bigger models keep remat_policy="dots_attn". Chunked cross-entropy
    # (cfg.loss_chunk_size) is available for models whose [T, vocab]
    # logits don't fit — measured slightly slower here, so dense loss.
    cfg_32k = dataclasses.replace(
        cfg_small, remat_policy="none", layer_scan_unroll=12,
        attn_max_seqlen=None,
    )
    # soft deadline: if the driver caps bench wall time, a section that
    # would start too late is skipped (recorded as such) rather than
    # risking the whole run being killed before the JSON line prints
    deadline = float(os.environ.get("BENCH_DEADLINE_S", 2700))
    for name, fn, optional in (
        ("ctx8k",
         lambda: _bench_shape(cfg_8k, [8192], n_steps=8, peak=peak), False),
        ("ctx32k",
         lambda: _bench_shape(cfg_32k, [32768], n_steps=4, peak=peak), False),
        ("b1", lambda: _bench_shape(
            cfg_1b, [512] * 8, n_steps=8, peak=peak, param_dtype="bfloat16"
        ), False),
        ("gen", lambda: _bench_gen(peak_bw, peak), False),
        ("gen32k", lambda: _bench_gen_32k(peak_bw, peak), False),
        ("ppo", lambda: _bench_async_ppo(peak), False),
        ("ppo_1p5b", lambda: _bench_async_ppo_1p5b(peak), False),
        ("system_ppo", lambda: _bench_system_ppo(), False),
        # pure A/B diagnostics go LAST: if the deadline trips, the
        # pipeline flags simply stay at their measured-default settings
        ("fwd_pipe", lambda: _bench_fwd_pipe(peak), True),
        ("gen_pipe", lambda: _bench_gen(peak_bw, peak, pipelined=True), True),
        ("gen_spec", lambda: _bench_gen_spec(peak_bw, peak), True),
        ("gen_sample_fused",
         lambda: _bench_gen_sample_fused(peak_bw, peak), True),
        ("gateway", lambda: _bench_gateway(), True),
        ("gen_kvq", lambda: _bench_gen_kvq(peak_bw, peak), True),
        ("bwd_pipe",
         lambda: _bench_bwd_pipe(cfg_small, cfg_32k, peak), True),
        ("guard", lambda: _bench_guard(peak), True),
        ("telemetry", lambda: _bench_telemetry(peak), True),
        ("tracing", lambda: _bench_tracing(peak), True),
    ):
        if not want(name):
            continue
        elapsed = time.perf_counter() - t_bench0
        if optional and elapsed > deadline:
            detail[name] = {"skipped": f"deadline ({elapsed:.0f}s elapsed)"}
            continue
        try:  # keep the primary metric even if a shape OOMs
            detail[name] = fn()
        except Exception as e:
            detail[name] = {"error": repr(e)[:200]}

    print(
        json.dumps(
            {
                "metric": "sft_train_tokens_per_sec_single_chip",
                "value": primary["tokens_per_s"],
                "unit": "tokens/s",
                "vs_baseline": round(primary["mfu"] / 0.4, 4),
                # north-star units (VERDICT r2 #2). Bars: decode >= 0.4 of
                # the HBM roofline (paged engines rarely beat ~0.6 because
                # of sampling + scheduling overheads); ppo samples/sec is
                # reported with its full config for round-over-round
                # comparison (no public single-chip baseline exists).
                "gen_tokens_per_sec": detail.get("gen", {}).get(
                    "decode_tokens_per_s"
                ),
                "ppo_samples_per_sec": detail.get("ppo", {}).get(
                    "reward_samples_per_sec"
                ),
                "ppo_1p5b_samples_per_sec": detail.get("ppo_1p5b", {}).get(
                    "reward_samples_per_sec"
                ),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
