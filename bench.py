"""Single-chip training-throughput benchmark.

Run by the driver on real TPU hardware each round. Measures SFT train-step
throughput (packed varlen batches, bf16 compute, Pallas flash attention)
and prints ONE JSON line.

Shapes:
- primary: ~125M qwen2-profile @ 4096 packed tokens (8 x 512 sequences)
- ``b1``:  ~1.08B model @ 4096 tokens (bf16 params + Adam, n_mbs=1)
- ``ctx8k``: the 125M model @ 8192-token context (one long sequence) —
  exercises the flash kernels' long-context band

``vs_baseline``: the reference publishes no absolute single-chip tokens/s
(BASELINE.md — only relative async speedups on H800 clusters), so we compare
against an analytic roofline: achieved model FLOP/s over the chip's peak
(v5e ≈ 197 TFLOP/s bf16), i.e. MFU. vs_baseline is reported as achieved-MFU /
0.4 (0.4 MFU being a strong packed-training baseline on this class of model).

Timing protocol: dispatch N steps back-to-back with NO host pulls (each
device->host round trip costs ~70 ms on a tunneled chip), then fetch one
scalar to drain the queue.
"""

import dataclasses
import json
import os
import time

import numpy as np


def _mk_sample(cfg, lens, rng):
    from areal_tpu.api.data import SequenceSample

    return SequenceSample.from_default(
        ids=list(range(len(lens))),
        seqlens=list(lens),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, sum(lens)
            ).astype(np.int64),
            "prompt_mask": np.zeros(sum(lens), bool),
        },
    )


def _bench_shape(cfg, lens, n_steps, peak, param_dtype="float32"):
    import jax

    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.base import flops as flops_mod
    from areal_tpu.base.tracing import maybe_trace
    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import OptimizerConfig, TrainEngine

    T = sum(lens)
    eng = TrainEngine(
        cfg, ParallelConfig(), OptimizerConfig(lr=1e-4), param_dtype=param_dtype
    )
    eng.init_random(0)
    eng.setup_optimizer(1000)
    rng = np.random.default_rng(0)
    sample = _mk_sample(cfg, lens, rng)
    spec = MicroBatchSpec(n_mbs=1, max_tokens_per_mb=T)

    # compile + settle donation layouts (2 warm steps), then drain
    for _ in range(2):
        stats = eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
    jax.device_get(stats["loss"])

    with maybe_trace("bench"):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            stats = eng.train_batch(
                sample, spec, sft_loss_fn, fetch_stats=False
            )
        jax.device_get(stats["loss"])  # drain
        dt = (time.perf_counter() - t0) / n_steps

    tok_per_s = T / dt
    fl = flops_mod.train_flops(cfg, T, seqlens=lens)
    mfu = fl / dt / peak
    del eng
    return {
        "tokens_per_s": round(tok_per_s, 1),
        "step_time_s": round(dt, 4),
        "mfu": round(mfu, 4),
        "n_params": int(flops_mod.param_count(cfg)),
    }


def main():
    import jax

    from areal_tpu.models.config import ModelConfig

    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e bf16
    # full layer unroll + no remat: these shapes fit HBM comfortably, and
    # unrolling removes the scan's per-layer buffer shuffling (~20% step
    # time); long-context/big-model training keeps scan + remat by default
    # attn_max_seqlen statically narrows the flash kernels' block band to
    # the packed segments' actual length — at 512-token packing most grid
    # steps were out-of-band no-ops
    cfg_small = ModelConfig(
        n_layers=12, n_q_heads=12, n_kv_heads=4, head_dim=64, hidden_dim=768,
        intermediate_dim=2048, vocab_size=32768, use_attention_bias=True,
        dtype="bfloat16", remat_policy="none", layer_scan_unroll=12,
        attn_max_seqlen=512,
    )
    cfg_1b = ModelConfig(
        n_layers=20, n_q_heads=16, n_kv_heads=8, head_dim=128,
        hidden_dim=2048, intermediate_dim=5632, vocab_size=32768,
        use_attention_bias=True, dtype="bfloat16",
        remat_policy="none", layer_scan_unroll=20, attn_max_seqlen=512,
    )

    primary = _bench_shape(cfg_small, [512] * 8, n_steps=32, peak=peak)
    detail = {
        "primary": primary,
        "device": str(jax.devices()[0].device_kind),
    }
    try:
        cfg_8k = dataclasses.replace(cfg_small, attn_max_seqlen=None)
        detail["ctx8k"] = _bench_shape(cfg_8k, [8192], n_steps=8, peak=peak)
    except Exception as e:  # keep the primary metric even if a shape OOMs
        detail["ctx8k"] = {"error": repr(e)[:200]}
    try:
        # the 32k-context protocol shape (benchmark README): one long
        # sequence through the flash kernels, matmul-saving remat
        cfg_32k = dataclasses.replace(
            cfg_small, remat_policy="dots_attn", layer_scan_unroll=1,
            attn_max_seqlen=None,
        )
        detail["ctx32k"] = _bench_shape(cfg_32k, [32768], n_steps=4, peak=peak)
    except Exception as e:
        detail["ctx32k"] = {"error": repr(e)[:200]}
    try:
        detail["b1"] = _bench_shape(
            cfg_1b, [512] * 8, n_steps=8, peak=peak, param_dtype="bfloat16"
        )
    except Exception as e:
        detail["b1"] = {"error": repr(e)[:200]}

    print(
        json.dumps(
            {
                "metric": "sft_train_tokens_per_sec_single_chip",
                "value": primary["tokens_per_s"],
                "unit": "tokens/s",
                "vs_baseline": round(primary["mfu"] / 0.4, 4),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
