# Makes tools/ importable so `python -m tools.arealint` works from the
# repo root. Keep this file empty of logic: the repo's import root is
# areal_tpu/; tools/ holds dev/CI utilities only.
