#!/usr/bin/env python3
"""Static async-hygiene pass — now a thin shim over :mod:`tools.arealint`.

The four rules this script introduced (bare ``asyncio.gather``, discarded
``create_task``, ``shutil.rmtree`` outside the checkpoint commit helper,
``time.sleep`` inside ``async def``) live in the arealint framework as
first-class rules (``tools/arealint/rules_async.py``); this entry point is
kept so existing invocations and ``tests/test_async_hygiene.py`` keep
working unchanged::

    python tools/check_async_hygiene.py [paths...]     # exits 1 on findings

For the full rule set (JAX host-sync/retrace/donation hazards, env-knob
and registry hygiene) run ``python -m tools.arealint`` instead — see
docs/static_analysis.md. Suppress a deliberate violation with
``# async-hygiene: ok`` (legacy) or ``# arealint: ok(<reason>)`` on the
call's first line.
"""

import pathlib
import sys

_REPO = str(pathlib.Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.arealint import (  # noqa: E402
    Finding,
    LEGACY_ASYNC_RULES,
    scan_paths as _scan_paths,
    scan_source as _scan_source,
)

__all__ = ["Finding", "scan_source", "scan_paths", "main"]

DEFAULT_PATHS = ["areal_tpu/system", "areal_tpu/train"]


def scan_source(src, path="<string>"):
    return _scan_source(src, path, rules=LEGACY_ASYNC_RULES)


def scan_paths(paths):
    return _scan_paths(paths, rules=LEGACY_ASYNC_RULES)


def main(argv) -> int:
    paths = argv[1:] or DEFAULT_PATHS
    findings = scan_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} async-hygiene finding(s).")
        return 1
    print("async hygiene clean.")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
