#!/usr/bin/env python3
"""Static async-hygiene pass over the orchestration layer.

Flags the exact bug class behind the fleet-wedging failure this repo's
fault-tolerance subsystem fixes (docs/fault_tolerance.md):

1. **Bare ``asyncio.gather(...)``** without ``return_exceptions`` — one dead
   peer throws, the whole fan-out aborts, and every sibling result is lost
   (the old ``flush_and_update_weights`` hot-loop).
2. **Discarded ``create_task``/``ensure_future``** — a task spawned as a
   bare expression statement is never awaited *and* unreferenced: the event
   loop may garbage-collect it mid-flight and its exceptions vanish.

Suppress a deliberate violation with ``# async-hygiene: ok`` on the call's
first line.  Run from the CLI (exits 1 on findings)::

    python tools/check_async_hygiene.py [paths...]

or from tests via :func:`scan_paths` (tier-1:
``tests/test_async_hygiene.py`` keeps ``areal_tpu/system/`` clean).
"""

import ast
import pathlib
import sys
from typing import List, NamedTuple

SUPPRESS = "# async-hygiene: ok"
DEFAULT_PATHS = ["areal_tpu/system"]


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _is_gather(call: ast.Call) -> bool:
    """Match ``asyncio.gather(...)`` and bare ``gather(...)`` (from-import),
    but not e.g. ``SequenceSample.gather`` (a data join)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "gather":
        return isinstance(f.value, ast.Name) and f.value.id == "asyncio"
    return isinstance(f, ast.Name) and f.id == "gather"


def _is_spawn(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else ""
    )
    return name in ("create_task", "ensure_future")


def _suppressed(lines: List[str], node: ast.AST) -> bool:
    line = lines[node.lineno - 1] if node.lineno - 1 < len(lines) else ""
    return SUPPRESS in line


def scan_source(src: str, path: str = "<string>") -> List[Finding]:
    findings: List[Finding] = []
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_gather(node):
            if not any(k.arg == "return_exceptions" for k in node.keywords):
                if not _suppressed(lines, node):
                    findings.append(Finding(
                        path, node.lineno, "bare-gather",
                        "asyncio.gather without return_exceptions — one "
                        "failed awaitable aborts the whole fan-out",
                    ))
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and _is_spawn(node.value):
            if not _suppressed(lines, node):
                findings.append(Finding(
                    path, node.lineno, "discarded-task",
                    "create_task result discarded — task is unreferenced "
                    "(may be GC'd) and never awaited (exceptions vanish)",
                ))
    return findings


def scan_paths(paths) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        p = pathlib.Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(scan_source(f.read_text(), str(f)))
    return findings


def main(argv) -> int:
    paths = argv[1:] or DEFAULT_PATHS
    findings = scan_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} async-hygiene finding(s).")
        return 1
    print("async hygiene clean.")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
