#!/usr/bin/env python3
"""Static async-hygiene pass over the orchestration layer.

Flags the exact bug class behind the fleet-wedging failure this repo's
fault-tolerance subsystem fixes (docs/fault_tolerance.md):

1. **Bare ``asyncio.gather(...)``** without ``return_exceptions`` — one dead
   peer throws, the whole fan-out aborts, and every sibling result is lost
   (the old ``flush_and_update_weights`` hot-loop).
2. **Discarded ``create_task``/``ensure_future``** — a task spawned as a
   bare expression statement is never awaited *and* unreferenced: the event
   loop may garbage-collect it mid-flight and its exceptions vanish.
3. **``shutil.rmtree`` outside the checkpoint commit helper** — the exact
   bug behind the destroyed-restore-point failure: deleting a path that can
   hold a live checkpoint before (or instead of) an atomic commit means a
   preemption mid-save loses the only recovery state.  All deletion of
   checkpoint-capable dirs goes through ``areal_tpu/base/recover.py``
   (``prepare_staging`` / ``commit_checkpoint`` / ``discard_checkpoint``).
4. **``time.sleep`` inside ``async def``** — blocks the event loop: every
   heartbeat, probe, and in-flight rollout on that loop stalls for the
   whole sleep (use ``await asyncio.sleep``).

Suppress a deliberate violation with ``# async-hygiene: ok`` on the call's
first line.  Run from the CLI (exits 1 on findings)::

    python tools/check_async_hygiene.py [paths...]

or from tests via :func:`scan_paths` (tier-1:
``tests/test_async_hygiene.py`` keeps ``areal_tpu/system/`` and
``areal_tpu/train/`` clean).
"""

import ast
import pathlib
import sys
from typing import List, NamedTuple

SUPPRESS = "# async-hygiene: ok"
DEFAULT_PATHS = ["areal_tpu/system", "areal_tpu/train"]
# The one module where deleting checkpoint-capable dirs is legal: the
# commit protocol itself.
RMTREE_ALLOWED_SUFFIXES = ("base/recover.py",)


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _is_gather(call: ast.Call) -> bool:
    """Match ``asyncio.gather(...)`` and bare ``gather(...)`` (from-import),
    but not e.g. ``SequenceSample.gather`` (a data join)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "gather":
        return isinstance(f.value, ast.Name) and f.value.id == "asyncio"
    return isinstance(f, ast.Name) and f.id == "gather"


def _is_spawn(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else ""
    )
    return name in ("create_task", "ensure_future")


def _is_rmtree(call: ast.Call) -> bool:
    """Match ``shutil.rmtree(...)`` and bare ``rmtree(...)`` (from-import)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "rmtree":
        return isinstance(f.value, ast.Name) and f.value.id == "shutil"
    return isinstance(f, ast.Name) and f.id == "rmtree"


def _is_time_sleep(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "sleep"
        and isinstance(f.value, ast.Name)
        and f.value.id == "time"
    )


def _is_bare_sleep(call: ast.Call) -> bool:
    """``sleep(...)`` via from-import — blocking unless awaited (an awaited
    bare ``sleep`` is asyncio's, imported the same way)."""
    return isinstance(call.func, ast.Name) and call.func.id == "sleep"


def _async_sleep_findings(tree: ast.AST, lines, path: str) -> List["Finding"]:
    """``time.sleep`` (attribute or from-import form) reachable from an
    ``async def`` body — nested SYNC defs are excluded (they run where they
    are called, which may be an executor thread)."""
    found: List[Finding] = []

    def walk_async_body(node, awaited=False):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # a new (possibly sync) execution context
        if (
            isinstance(node, ast.Call)
            and (
                _is_time_sleep(node)
                or (_is_bare_sleep(node) and not awaited)
            )
            and not _suppressed(lines, node)
        ):
            found.append(Finding(
                path, node.lineno, "sleep-in-async",
                "time.sleep inside async def blocks the event loop — "
                "use await asyncio.sleep",
            ))
        for child in ast.iter_child_nodes(node):
            walk_async_body(child, awaited=isinstance(node, ast.Await))

    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            for stmt in node.body:
                walk_async_body(stmt)
    return found


def _suppressed(lines: List[str], node: ast.AST) -> bool:
    line = lines[node.lineno - 1] if node.lineno - 1 < len(lines) else ""
    return SUPPRESS in line


def scan_source(src: str, path: str = "<string>") -> List[Finding]:
    findings: List[Finding] = []
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_gather(node):
            if not any(k.arg == "return_exceptions" for k in node.keywords):
                if not _suppressed(lines, node):
                    findings.append(Finding(
                        path, node.lineno, "bare-gather",
                        "asyncio.gather without return_exceptions — one "
                        "failed awaitable aborts the whole fan-out",
                    ))
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and _is_spawn(node.value):
            if not _suppressed(lines, node):
                findings.append(Finding(
                    path, node.lineno, "discarded-task",
                    "create_task result discarded — task is unreferenced "
                    "(may be GC'd) and never awaited (exceptions vanish)",
                ))
        if isinstance(node, ast.Call) and _is_rmtree(node):
            allowed = any(
                path.replace("\\", "/").endswith(sfx)
                for sfx in RMTREE_ALLOWED_SUFFIXES
            )
            if not allowed and not _suppressed(lines, node):
                findings.append(Finding(
                    path, node.lineno, "live-checkpoint-rmtree",
                    "shutil.rmtree outside base/recover's commit helpers — "
                    "a crash mid-save can destroy the only committed "
                    "checkpoint; stage + commit via areal_tpu.base.recover",
                ))
    findings.extend(_async_sleep_findings(tree, lines, path))
    return findings


def scan_paths(paths) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        p = pathlib.Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(scan_source(f.read_text(), str(f)))
    return findings


def main(argv) -> int:
    paths = argv[1:] or DEFAULT_PATHS
    findings = scan_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} async-hygiene finding(s).")
        return 1
    print("async hygiene clean.")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
