#!/usr/bin/env python3
"""DEPRECATED — thin forwarding stub over :mod:`tools.arealint`.

This entry point is retired and will be deleted one release after
arealint v2; it survives only so scripts that still invoke it keep
working while they migrate. It runs exactly the four migrated async
rules (bare ``asyncio.gather``, discarded ``create_task``,
``shutil.rmtree`` outside the checkpoint commit helper, ``time.sleep``
inside ``async def``) — a strict subset of::

    python -m tools.arealint [paths...]

which adds the JAX host-sync/retrace/donation rules, the whole-program
call-graph rules (cross-module host-sync, thread/asyncio races,
donation dataflow), and env-knob/registry hygiene. Migrate invocations
there, and migrate any remaining legacy ``# async-hygiene: ok`` tokens
to ``# arealint: ok(<reason>)`` — the legacy token only covers the four
migrated rules and is honored for one more release
(docs/static_analysis.md "Suppressing a finding").
"""

import pathlib
import sys
import warnings

_REPO = str(pathlib.Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.arealint import (  # noqa: E402
    Finding,
    LEGACY_ASYNC_RULES,
    scan_paths as _scan_paths,
    scan_source as _scan_source,
)

__all__ = ["Finding", "scan_source", "scan_paths", "main"]

DEFAULT_PATHS = ["areal_tpu/system", "areal_tpu/train"]


def scan_source(src, path="<string>"):
    return _scan_source(src, path, rules=LEGACY_ASYNC_RULES)


def scan_paths(paths):
    return _scan_paths(paths, rules=LEGACY_ASYNC_RULES)


def main(argv) -> int:
    warnings.warn(
        "tools/check_async_hygiene.py is deprecated and will be removed "
        "one release after arealint v2 — run `python -m tools.arealint` "
        "instead (superset of these rules; see docs/static_analysis.md)",
        DeprecationWarning,
        stacklevel=2,
    )
    print(
        "warning: check_async_hygiene.py is deprecated; "
        "run `python -m tools.arealint` instead",
        file=sys.stderr,
    )
    paths = argv[1:] or DEFAULT_PATHS
    findings = scan_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} async-hygiene finding(s).")
        return 1
    print("async hygiene clean.")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
