"""Chaos soak harness for the elastic multihost world (``make chaos``).

Drives the N-process CPU fault world (the same world as
``tests/test_multihost.py``, now elastic) through a *seeded* schedule of
rank kills and hangs, with an optional tiny generation fleet serving
traffic throughout, and asserts end-state invariants:

- **loss-trajectory continuity**: the faulted N-process run's per-step
  losses (last write wins across rollbacks) match an unfaulted
  single-process run over the same global batch — surgical recovery plus
  committed-checkpoint rollback must be *semantically invisible*;
- **no version regression**: the world epoch only advances and the gen
  engine's weight version never moves backward;
- **no leaked state**: gen slots/pages all freed, exactly one liveness
  lease + heartbeat per live rank (dead ranks' keys swept on epoch bump);
- **bounded recovery**: every reformation (detection -> all ranks live at
  the new epoch) under the configured bound;
- **accounting**: ``ft/rank_restarts`` == scheduled faults,
  ``ft/world_epochs`` == reformations.

Two entry modes::

    python -m tools.chaos --seed 1 --faults 2        # scenario runner
    python -m tools.chaos --run-rank 2 --spec s.json # one rank (internal)

The runner writes a JSON report and exits 0 iff every invariant holds.
Scenario scripting rides ``base/faults.py`` (``rank.kill`` / ``rank.hang``
trip points armed per (rank, epoch, step)); the supervisor is
``apps/launcher.py::WorldSupervisor``; the rank-side protocol is
``parallel/elastic.py``.
"""

import argparse
import dataclasses
import glob
import json
import logging
import os
import random
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("tools.chaos")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# Seeded fault schedules
# --------------------------------------------------------------------- #


def make_schedule(
    seed: int,
    n_faults: int,
    num_ranks: int,
    steps: int,
    ckpt_every: int,
) -> List[Dict]:
    """Deterministic fault schedule: one event per world epoch.

    Every event is guaranteed to *fire*: epoch ``e``'s fault step is drawn
    at or after the resume point of epoch ``e`` (the committed-checkpoint
    floor of the previous fault), so the rolled-back world always reaches
    it. Same seed -> identical schedule, run to run."""
    rng = random.Random(seed)
    events: List[Dict] = []
    resume = 0
    for epoch in range(n_faults):
        lo = max(resume, 1)
        if lo >= steps:
            break  # no room for another guaranteed-firing fault
        step = rng.randrange(lo, steps)
        events.append({
            "kind": rng.choice(["kill", "hang"]),
            "rank": rng.randrange(num_ranks),
            "epoch": epoch,
            "step": step,
        })
        resume = (step // ckpt_every) * ckpt_every
    return events


# --------------------------------------------------------------------- #
# Rank body (subprocess entry: --run-rank R --spec spec.json)
# --------------------------------------------------------------------- #


def run_rank(rank: int, spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)
    n = int(spec["num_processes"])
    local_devices = int(spec["local_devices"])

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")  # arealint: ok(rank-process XLA bootstrap append, not a knob read — same pattern as tests/multihost_train_script.py)
        + f" --xla_force_host_platform_device_count={local_devices}"
    )
    # the CPU "device" IS the host: dispatch-ahead depth only oversubscribes
    # the cores N rank processes already share (same rationale as
    # tests/conftest.py)
    os.environ.setdefault("AREAL_FWD_PIPELINE", "0")
    os.environ.setdefault("AREAL_TRAIN_PREFETCH", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")

    from areal_tpu.base import faults, name_resolve
    from areal_tpu.parallel import elastic, multihost

    if n > 1:
        # gloo needs a distributed client; single-process (the baseline)
        # must NOT set it or backend creation fails on a None client
        multihost.enable_cpu_collectives()
        # serialize device dispatch: async-dispatched computations with
        # gloo collectives execute concurrently, and rank-dependent
        # execution order can wedge the transport (mismatched-preamble
        # aborts) — the exact flake class the elastic world must not
        # confuse with real faults
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(type="file", root=spec["nr_root"])
    )

    # arm this rank's scheduled faults (trip-style; epoch kwarg keeps a
    # relaunched incarnation from re-firing an older epoch's event)
    for ev in spec["schedule"]:
        if ev["rank"] == rank:
            faults.inject(
                "rank.kill" if ev["kind"] == "kill" else "rank.hang",
                action="trip", times=1,
                step=ev["step"], epoch=ev["epoch"],
            )

    elastic_on = n > 1
    mgr = None
    if elastic_on:
        mgr = elastic.WorldEpochManager(
            elastic.ElasticConfig(
                experiment_name=spec["experiment"],
                trial_name=spec["trial"],
                num_processes=n,
                process_id=rank,
                collective_timeout_s=float(spec["collective_timeout_s"]),
                lease_interval_s=float(spec["lease_interval_s"]),
                max_reforms=int(spec.get("max_reforms", 16)),
            )
        )
        mgr.join()
    assert jax.device_count() == n * local_devices, (
        jax.device_count(), n, local_devices
    )

    from areal_tpu.base import tracing
    from areal_tpu.system import worker_base
    from areal_tpu.system.worker_base import Heartbeat

    worker_name = (
        elastic.rank_worker_name(rank) if elastic_on else f"baseline/rank{rank}"
    )
    hb = None
    if elastic_on:
        hb = Heartbeat(
            spec["experiment"], spec["trial"], worker_name, interval=1.0,
        ).start()
    # the black box the scenario runner asserts exists per injected fault
    flight = worker_base.FlightRecorder(
        worker_name, root=spec.get("flight_root")
    ).install()

    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.models.config import ModelConfig
    from areal_tpu.ops import ppo as ppo_ops
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.train.engine import (
        OptimizerConfig,
        TrainEngine,
        vmapped_forward,
    )

    mcfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, dtype="float32",
    )

    def build_engine() -> TrainEngine:
        eng = TrainEngine(
            mcfg,
            parallel=ParallelConfig.from_str(spec["parallel"]),
            optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
        )
        eng.init_random(0)
        eng.setup_optimizer(total_train_steps=1000)
        return eng

    def sft_loss(params, cfg_, arrays):
        logits = vmapped_forward(params, cfg_, arrays)
        lp = jax.vmap(ppo_ops.gather_packed_shifted_log_probs)(
            logits, arrays["input_ids"], arrays["segment_ids"]
        )
        seg = arrays["segment_ids"]
        has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
        mask = has_next & ~arrays["prompt_mask"]
        return -jnp.sum(jnp.where(mask, lp, 0.0)) / jnp.maximum(
            mask.sum(), 1
        ), {}

    # identical GLOBAL batch in every configuration; this process takes a
    # strided slice of the items (same construction as the multihost test
    # world, so the single-process baseline is trajectory-comparable)
    rng = np.random.default_rng(0)
    n_items = int(spec["n_items"])
    seqlens = [int(x) for x in rng.integers(6, 14, size=n_items)]
    ids_all = rng.integers(0, 128, size=sum(seqlens)).astype(np.int64)
    pmask = np.concatenate(
        [np.r_[np.ones(2, np.bool_), np.zeros(m - 2, np.bool_)]
         for m in seqlens]
    )
    offs = np.cumsum([0] + seqlens)
    mine = list(range(rank, n_items, n))
    sample = SequenceSample.from_default(
        ids=mine,
        seqlens=[seqlens[i] for i in mine],
        data={
            "packed_input_ids": np.concatenate(
                [ids_all[offs[i]:offs[i + 1]] for i in mine]
            ),
            "prompt_mask": np.concatenate(
                [pmask[offs[i]:offs[i + 1]] for i in mine]
            ),
        },
    )

    steps = int(spec["steps"])
    ckpt_every = int(spec["ckpt_every"])
    ckpt_path = os.path.join(spec["ckpt_root"], "world")
    losses: Dict[int, float] = {}
    reforms = 0

    while True:
        # spanned so even an incarnation that trips its fault before the
        # first train step leaves span evidence in the flight dump
        with tracing.span("chaos/restore", rank=rank):
            eng = build_engine()
            try:
                eng.load_checkpoint(ckpt_path)
            except (FileNotFoundError, ValueError):
                pass  # nothing committed yet: every rank starts fresh
        try:
            for step in range(eng._step, steps):
                epoch = mgr.world.epoch if mgr is not None else 0
                if faults.maybe_trip("rank.kill", step=step, epoch=epoch):
                    logger.warning(
                        "chaos: rank.kill tripped (rank %d step %d epoch %d)",
                        rank, step, epoch,
                    )
                    flight.dump(
                        "rank.kill",
                        {"rank": rank, "step": step, "epoch": epoch},
                    )
                    os.kill(os.getpid(), signal.SIGKILL)  # hard death
                if faults.maybe_trip("rank.hang", step=step, epoch=epoch):
                    logger.warning(
                        "chaos: rank.hang tripped (rank %d step %d epoch %d)",
                        rank, step, epoch,
                    )
                    flight.dump(
                        "rank.hang",
                        {"rank": rank, "step": step, "epoch": epoch},
                    )
                    while True:  # wedged, not dead: lease keeps beating
                        time.sleep(60)
                stats = eng.train_batch(
                    sample, MicroBatchSpec(n_mbs=1), sft_loss
                )
                losses[step] = float(stats["loss"])
                if (step + 1) % ckpt_every == 0 and step + 1 < steps:
                    eng.save_checkpoint(ckpt_path)
            multihost.barrier("chaos_done")
            break
        except Exception as e:  # noqa: BLE001 — classified just below
            wf = elastic.as_world_failure(e)
            if wf is None or mgr is None:
                import traceback

                traceback.print_exc()
                elastic.hard_exit(1)
            try:
                mgr.reform(str(wf))
            except elastic.WorldFailureError:
                elastic.hard_exit(77)
            reforms += 1
            continue  # rebuild + re-restore from the committed checkpoint

    out = {
        "rank": rank,
        "final_step": steps,
        "losses": {str(k): v for k, v in sorted(losses.items())},
        "reforms": reforms,
        "final_epoch": mgr.world.epoch if mgr is not None else 0,
    }
    tmp = os.path.join(spec["out_root"], f"rank{rank}.json.tmp")
    with open(tmp, "w") as f:
        json.dump(out, f)
    os.replace(tmp, os.path.join(spec["out_root"], f"rank{rank}.json"))
    if hb is not None:
        hb.stop()
    if mgr is not None:
        mgr.stop()
        elastic.hard_exit(0)
    return 0


# --------------------------------------------------------------------- #
# Tiny generation fleet probe (serves throughout the chaos run)
# --------------------------------------------------------------------- #


class GenFleetProbe(threading.Thread):
    """A tiny in-process generation server + a client hammering it while
    the trainer world is being killed and reformed next door — proving the
    serving side keeps answering from the last published weights and leaks
    nothing. End state lands in ``self.result``."""

    def __init__(self, interval_s: float = 0.5):
        super().__init__(name="chaos-gen-fleet", daemon=True)
        self.interval_s = interval_s
        self.stop_event = threading.Event()
        self.result: Dict = {}

    def run(self):
        import asyncio

        asyncio.run(self._main())

    async def _main(self):
        import asyncio

        import jax

        from areal_tpu.base import network
        from areal_tpu.gen.client import GenAPIClient
        from areal_tpu.gen.engine import GenerationEngine
        from areal_tpu.gen.server import serve
        from areal_tpu.models import transformer as tfm
        from areal_tpu.models.config import ModelConfig

        cfg = ModelConfig(
            n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8,
            hidden_dim=32, intermediate_dim=64, vocab_size=128,
            dtype="float32",
        )
        eng = GenerationEngine(
            cfg, tfm.init_params(cfg, jax.random.key(7)),
            max_slots=2, max_seqlen=64,
        )
        v0 = eng.version
        port = network.find_free_port()
        runner = await serve(eng, "127.0.0.1", port, decode_steps=2)
        url = f"http://127.0.0.1:{port}"
        ok = failed = 0
        i = 0
        async with GenAPIClient(timeout=30.0) as client:
            while not self.stop_event.is_set():
                i += 1
                try:
                    r = await client.generate(
                        url, f"probe{i}", [1 + (i % 96), 2, 3],
                        {"max_new_tokens": 4, "greedy": True},
                    )
                    ok += 1 if r.output_ids else 0
                except Exception:
                    failed += 1
                await asyncio.sleep(self.interval_s)
        # drain: every slot/page must come home
        for _ in range(100):
            if eng.n_running() == 0 and eng.n_pending() == 0:
                break
            await asyncio.sleep(0.1)
        self.result = {
            "requests": i,
            "ok": ok,
            "failed": failed,
            "slots_running": eng.n_running(),
            "pending": eng.n_pending(),
            "pages_leaked": (
                eng.n_pages - eng.pool.n_free - eng.prefix.n_reclaimable()
            ),
            "version_regressed": eng.version < v0,
        }
        await runner.cleanup()


# --------------------------------------------------------------------- #
# Serving-plane soak (``make chaos-serve``)
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class ServeChaosConfig:
    """Knobs for the serving-plane survivability soak (``--serve``)."""

    max_new_tokens: int = 12
    storm_requests: int = 5
    storm_deadline_s: float = 0.5
    wedge_delay_s: float = 6.0
    drain_timeout_s: float = 30.0
    run_arealint: bool = True


def run_serve_scenario(cfg: ServeChaosConfig) -> Dict:
    """Serving-plane survivability soak: two tiny identical-weight gen
    servers behind the real gateway scheduler, driven through scripted
    faults (docs/serving.md "Survivability"):

    A. **backend death mid-stream** (``gw.backend_die_midstream``): the
       stream resumes on the surviving backend and the final token
       sequence is EXACTLY the unfaulted greedy reference.
    B. **backend wedge pre-first-chunk** (``gw.backend_wedge``): the
       hedge opens on the second backend, wins, and the tokens still
       match the reference.
    C. **deadline storm** (``gw.deadline_storm``): queued requests age
       out against their deadlines and are shed IN QUEUE — zero engine
       admissions, full token-bucket refund, fair-clock restored.
    D. **brownout walk**: synthetic pressure drives the ladder up level
       by level (clamp -> spec off -> shed light tenants -> admit
       nothing) and hysteresis + dwell walk it back down, restoring
       every lever.

    End state must leak nothing: no running slots, no pending requests,
    zero unaccounted KV pages, empty queue, settled buckets — and
    ``tools.arealint`` still exits 0."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import asyncio

    return asyncio.run(_serve_soak(cfg))


async def _serve_soak(cfg: ServeChaosConfig) -> Dict:
    import asyncio

    import jax

    from areal_tpu.base import faults, network
    from areal_tpu.base import metrics as metrics_mod
    from areal_tpu.gateway.autoscaler import ScaleSignals
    from areal_tpu.gateway.brownout import BrownoutConfig, wire_brownout
    from areal_tpu.gateway.qos import TenantSpec
    from areal_tpu.gateway.scheduler import (
        ContinuousBatchScheduler,
        GatewayRequest,
        RateLimited,
    )
    from areal_tpu.gen.client import GenAPIClient
    from areal_tpu.gen.engine import GenerationEngine
    from areal_tpu.gen.server import serve as serve_gen
    from areal_tpu.models import transformer as tfm
    from areal_tpu.models.config import ModelConfig

    mcfg = ModelConfig(
        n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8,
        hidden_dim=32, intermediate_dim=64, vocab_size=128,
        dtype="float32",
    )
    # IDENTICAL weights on both backends: greedy decode is then
    # deterministic across them, which is what makes "token-exact resume
    # after backend death" a checkable invariant
    params = tfm.init_params(mcfg, jax.random.key(7))
    engines = [
        GenerationEngine(mcfg, params, max_slots=2, max_seqlen=64)
        for _ in range(2)
    ]
    runners, urls = [], []
    for eng in engines:
        port = network.find_free_port()
        runners.append(
            await serve_gen(eng, "127.0.0.1", port, decode_steps=2)
        )
        urls.append(f"http://127.0.0.1:{port}")

    sched = ContinuousBatchScheduler(
        list(urls),
        tenants={
            # near-zero refill: the post-shed bucket level proves REFUNDS,
            # not refill, restored the balance
            "lim": TenantSpec(
                name="lim", rate_tokens_per_s=0.01, burst_tokens=10_000.0
            ),
            "cheap": TenantSpec(name="cheap", weight=0.5),
        },
        default_tenant=TenantSpec(name="anonymous"),
        metrics_poll_interval=0.5,
        hedge_min_delay_s=30.0,  # scenario B lowers it explicitly
        deadline_sweep_interval_s=0.1,
    )
    await sched.start()

    violations: List[str] = []
    report: Dict = {"scenarios": {}}
    prompt = [5, 6, 7]
    sp = {"max_new_tokens": cfg.max_new_tokens, "greedy": True}

    async def collect(req):
        sched.submit(req)
        toks: List[int] = []
        last = {}
        async for ev in sched.events(req):
            toks.extend(ev.get("token_ids", []))
            last = ev
        return toks, last.get("finish_reason")

    def counter(name) -> float:
        return metrics_mod.counters.get(name)

    async with GenAPIClient(timeout=60.0) as cl:
        try:
            # warm BOTH backends (absorb jit compile) so latency
            # estimates and hedge timing are not dominated by the first
            # request's compilation
            for u in urls:
                sched.set_servers([u])
                await collect(
                    GatewayRequest.build("anonymous", prompt, dict(sp))
                )
            sched.set_servers(list(urls))
            # drop compile-dominated warmup TTFTs so the live p95 (the
            # hedge-delay floor) reflects steady-state latency
            metrics_mod.counters.clear(metrics_mod.GW_TTFT_S)

            # unfaulted greedy reference
            ref_toks, ref_fin = await collect(
                GatewayRequest.build("anonymous", prompt, dict(sp))
            )
            if len(ref_toks) != cfg.max_new_tokens:
                violations.append(
                    f"reference run produced {len(ref_toks)} tokens, "
                    f"expected {cfg.max_new_tokens}"
                )

            # A: kill the backend mid-stream -> token-exact resume
            resumes0 = counter(metrics_mod.GW_STREAM_RESUMES)
            faults.inject(
                "gw.backend_die_midstream", action="fail", times=1, after=2
            )
            a_toks, a_fin = await collect(
                GatewayRequest.build("anonymous", prompt, dict(sp))
            )
            faults.reset()
            resumed = counter(metrics_mod.GW_STREAM_RESUMES) - resumes0
            report["scenarios"]["die_midstream"] = {
                "tokens_match": a_toks == ref_toks,
                "finish": a_fin,
                "stream_resumes": resumed,
            }
            if a_toks != ref_toks:
                violations.append(
                    f"resume after backend death not token-exact: "
                    f"{a_toks} != {ref_toks}"
                )
            if resumed < 1:
                violations.append("backend death triggered no stream resume")
            await sched.poll_capacity()  # re-admit the 'dead' backend

            # B: wedge the primary pre-first-chunk -> the hedge wins
            hedges0 = counter(metrics_mod.GW_HEDGES)
            wins0 = counter(metrics_mod.GW_HEDGE_WINS)
            sched.hedge_min_delay_s = 1.0
            faults.inject(
                "gw.backend_wedge", action="delay",
                delay_s=cfg.wedge_delay_s, times=1,
            )
            b_toks, b_fin = await collect(
                GatewayRequest.build("anonymous", prompt, dict(sp))
            )
            faults.reset()
            sched.hedge_min_delay_s = 30.0
            hedged = counter(metrics_mod.GW_HEDGES) - hedges0
            won = counter(metrics_mod.GW_HEDGE_WINS) - wins0
            report["scenarios"]["wedge_hedge"] = {
                "tokens_match": b_toks == ref_toks,
                "finish": b_fin,
                "hedges": hedged,
                "hedge_wins": won,
            }
            if b_toks != ref_toks:
                violations.append(
                    f"hedged stream not token-exact: {b_toks} != {ref_toks}"
                )
            if hedged < 1 or won < 1:
                violations.append(
                    f"wedge did not produce a winning hedge "
                    f"(hedges={hedged}, wins={won})"
                )

            # C: deadline storm — zero dispatch capacity, queued requests
            # age out in the fair queue and never touch a backend
            shed0 = counter(metrics_mod.GW_DEADLINE_SHED)
            admitted0 = [eng.stats["admitted"] for eng in engines]
            faults.inject("gw.deadline_storm", action="trip", times=100_000)
            storm = [
                GatewayRequest.build(
                    "lim", prompt, dict(sp),
                    deadline_s=cfg.storm_deadline_s,
                )
                for _ in range(cfg.storm_requests)
            ]
            results = await asyncio.gather(
                *(collect(r) for r in storm), return_exceptions=True
            )
            faults.reset()
            bad = [r for r in results if isinstance(r, BaseException)]
            if bad:
                violations.append(f"storm stream raised: {bad[0]!r}")
                results = [
                    r for r in results if not isinstance(r, BaseException)
                ]
            sched._wake.set()
            shed = counter(metrics_mod.GW_DEADLINE_SHED) - shed0
            admitted_delta = [
                eng.stats["admitted"] - a0
                for eng, a0 in zip(engines, admitted0)
            ]
            bucket = sched._bucket("lim")
            report["scenarios"]["deadline_storm"] = {
                "finishes": [fin for _, fin in results],
                "deadline_shed": shed,
                "backend_admissions": admitted_delta,
                "bucket_available": bucket.available,
            }
            if any(fin != "deadline" for _, fin in results):
                violations.append(
                    f"storm finishes {[f for _, f in results]} "
                    "(expected all 'deadline')"
                )
            if shed != cfg.storm_requests:
                violations.append(
                    f"gw/deadline_shed advanced {shed}, expected "
                    f"{cfg.storm_requests}"
                )
            if any(admitted_delta):
                violations.append(
                    f"deadline-shed requests reached a backend: "
                    f"admissions {admitted_delta}"
                )
            if bucket.available < bucket.burst - 1.0:
                violations.append(
                    f"token bucket not refunded after storm: "
                    f"{bucket.available} / {bucket.burst}"
                )
            # rollback must leave 'lim' with NO residual service debt:
            # its finish tag may not sit past the global virtual clock,
            # so its next push starts exactly where an innocent tenant's
            # would
            vft = sched._wfq._last_vft.get("lim", 0.0)
            if vft > sched._wfq._vtime + 1e-6:
                violations.append(
                    f"fair-queue clock not restored after storm: "
                    f"lim vft {vft} > vtime {sched._wfq._vtime}"
                )

            # D: brownout walk — up the ladder level by level on synthetic
            # pressure, back down under hysteresis + dwell
            for u in urls:
                await cl.set_spec_decode(u, True)
            trans0 = counter(metrics_mod.GW_BROWNOUT_TRANSITIONS)
            bcfg = BrownoutConfig(min_hold_s=5.0, clamp_max_tokens=8)
            fake_t = [0.0]

            class _GwCfg:
                brownout_max_tokens = None

            gw_cfg = _GwCfg()
            ctrl = wire_brownout(
                bcfg, sched, gw_cfg, cl, clock=lambda: fake_t[0]
            )
            sig = [ScaleSignals(routed=2, healthy=2)]
            ctrl.fetch_signals = lambda: sig[0]

            async def walk(kv, advance=6.0):
                fake_t[0] += advance
                sig[0] = dataclasses.replace(sig[0], kv_occupancy=kv)
                return await ctrl.step_once()

            levels = [await walk(kv) for kv in (0.92, 0.96, 0.975)]
            # level 3: a below-floor tenant is shed with an honest hint
            shed_ok = pause_ok = False
            try:
                sched.submit(
                    GatewayRequest.build("cheap", prompt, dict(sp))
                )
            except RateLimited as e:
                shed_ok = e.retry_after_s > 0
            levels.append(await walk(0.995))
            spec_off = [
                bool((await cl.metrics(u)).get("spec_decode")) for u in urls
            ]
            clamp_at_top = gw_cfg.brownout_max_tokens
            # level 4: nobody new gets in
            try:
                sched.submit(
                    GatewayRequest.build("anonymous", prompt, dict(sp))
                )
            except RateLimited as e:
                pause_ok = e.retry_after_s > 0
            # hysteresis: barely below the level-4 entry is NOT enough to
            # step down, even after the dwell
            held = await walk(0.985)
            down = [await walk(0.10) for _ in range(4)]
            spec_back = [
                bool((await cl.metrics(u)).get("spec_decode")) for u in urls
            ]
            transitions = counter(
                metrics_mod.GW_BROWNOUT_TRANSITIONS
            ) - trans0
            report["scenarios"]["brownout_walk"] = {
                "up": levels,
                "held_at": held,
                "down": down,
                "spec_disabled_at_top": [not s for s in spec_off],
                "spec_restored": spec_back,
                "clamp_at_top": clamp_at_top,
                "clamp_after": gw_cfg.brownout_max_tokens,
                "shed_429": shed_ok,
                "pause_429": pause_ok,
                "transitions": transitions,
            }
            if levels != [1, 2, 3, 4]:
                violations.append(f"brownout escalation walked {levels}")
            if held != 4:
                violations.append(
                    f"hysteresis failed: stepped to {held} on a barely-"
                    "recovered signal"
                )
            if down != [3, 2, 1, 0]:
                violations.append(f"brownout de-escalation walked {down}")
            if any(spec_off):
                violations.append("level 2 left spec decode enabled")
            if not all(spec_back):
                violations.append("recovery did not restore spec decode")
            if clamp_at_top != bcfg.clamp_max_tokens:
                violations.append("level 1 did not clamp max_tokens")
            if gw_cfg.brownout_max_tokens is not None:
                violations.append("recovery did not remove the clamp")
            if not shed_ok:
                violations.append(
                    "level 3 did not shed the below-floor tenant"
                )
            if not pause_ok:
                violations.append("level 4 admitted a new request")
            if transitions != 8:
                violations.append(
                    f"counted {transitions} brownout transitions, "
                    "expected 8 (4 up + 4 down; the held step is free)"
                )
            if sched.admit_paused or sched.shed_weight_floor:
                violations.append("brownout levers left engaged at level 0")
        finally:
            faults.reset()
            # drain: every slot, page and charge must come home
            deadline = time.monotonic() + cfg.drain_timeout_s
            while time.monotonic() < deadline:
                if all(
                    eng.n_running() == 0 and eng.n_pending() == 0
                    for eng in engines
                ) and sched.inflight() == 0 and sched.queue_depth() == 0:
                    break
                await asyncio.sleep(0.2)
            leaks = {
                "slots_running": [eng.n_running() for eng in engines],
                "pending": [eng.n_pending() for eng in engines],
                "pages_leaked": [
                    eng.n_pages - eng.pool.n_free
                    - eng.prefix.n_reclaimable()
                    for eng in engines
                ],
                "gateway_queue": sched.queue_depth(),
                "gateway_inflight": sched.inflight(),
            }
            report["leaks"] = leaks
            if any(leaks["slots_running"]) or any(leaks["pending"]):
                violations.append(f"engine slots leaked: {leaks}")
            if any(leaks["pages_leaked"]):
                violations.append(
                    f"KV pages leaked: {leaks['pages_leaked']}"
                )
            if leaks["gateway_queue"] or leaks["gateway_inflight"]:
                violations.append(
                    f"gateway queue/inflight not drained: {leaks}"
                )
            await sched.stop()
            for r in runners:
                await r.cleanup()

    if cfg.run_arealint:
        import subprocess

        rc = subprocess.call(
            [sys.executable, "-m", "tools.arealint"], cwd=_REPO_ROOT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        report["arealint_rc"] = rc
        if rc != 0:
            violations.append(f"arealint exited {rc}")

    report["violations"] = [v for v in violations if v]
    report["ok"] = not report["violations"]
    return report


# --------------------------------------------------------------------- #
# Scenario runner
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class ChaosConfig:
    seed: int = 1
    n_faults: int = 1
    num_ranks: int = 4
    local_devices: int = 2
    parallel: str = "d2f2m2"
    steps: int = 10
    ckpt_every: int = 3
    n_items: int = 12
    collective_timeout_s: float = 30.0
    lease_interval_s: float = 1.0
    report_grace_s: float = 6.0
    recovery_bound_s: float = 240.0
    loss_rtol: float = 2e-4
    timeout_s: float = 900.0
    with_gen: bool = True
    root: Optional[str] = None           # scenario dir (default: mkdtemp)
    schedule: Optional[List[Dict]] = None  # explicit (tests); else seeded


def _rank_cmd(spec_path: str):
    def cmd(rank: int) -> List[str]:
        return [
            sys.executable, "-m", "tools.chaos",
            "--run-rank", str(rank), "--spec", spec_path,
        ]
    return cmd


def run_scenario(cfg: ChaosConfig) -> Dict:
    """Run one seeded chaos scenario end to end; returns the report dict
    (``report["ok"]`` is the overall verdict, ``report["violations"]``
    names every failed invariant)."""
    import tempfile

    from areal_tpu.apps.launcher import WorldSupervisor, WorldSupervisorConfig
    from areal_tpu.base import name_resolve
    from areal_tpu.base import metrics as metrics_mod
    from areal_tpu.parallel import elastic

    root = cfg.root or tempfile.mkdtemp(prefix="areal_chaos_")
    nr_root = os.path.join(root, "name_resolve")
    out_root = os.path.join(root, "out")
    ckpt_root = os.path.join(root, "ckpt")
    log_dir = os.path.join(root, "logs")
    flight_root = os.path.join(root, "flight")
    for d in (nr_root, out_root, ckpt_root, log_dir, flight_root):
        os.makedirs(d, exist_ok=True)

    schedule = (
        cfg.schedule
        if cfg.schedule is not None
        else make_schedule(
            cfg.seed, cfg.n_faults, cfg.num_ranks, cfg.steps, cfg.ckpt_every
        )
    )
    experiment, trial = "chaos", f"seed{cfg.seed}"
    spec = {
        "experiment": experiment,
        "trial": trial,
        "nr_root": nr_root,
        "out_root": out_root,
        "ckpt_root": ckpt_root,
        "num_processes": cfg.num_ranks,
        "local_devices": cfg.local_devices,
        "parallel": cfg.parallel,
        "steps": cfg.steps,
        "ckpt_every": cfg.ckpt_every,
        "n_items": cfg.n_items,
        "collective_timeout_s": cfg.collective_timeout_s,
        "lease_interval_s": cfg.lease_interval_s,
        "schedule": schedule,
        "flight_root": flight_root,
    }
    spec_path = os.path.join(root, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f, indent=2)

    # the baseline: the SAME global batch, single process, all devices,
    # no faults — the trajectory the chaotic world must reproduce
    base_spec = dict(
        spec,
        num_processes=1,
        local_devices=cfg.local_devices * cfg.num_ranks,
        schedule=[],
        ckpt_root=os.path.join(root, "ckpt_base"),
        out_root=os.path.join(root, "out_base"),
    )
    for d in (base_spec["ckpt_root"], base_spec["out_root"]):
        os.makedirs(d, exist_ok=True)
    base_spec_path = os.path.join(root, "spec_base.json")
    with open(base_spec_path, "w") as f:
        json.dump(base_spec, f, indent=2)

    import subprocess

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    t_base = time.monotonic()
    with open(os.path.join(log_dir, "baseline.log"), "wb") as bl:
        rc_base = subprocess.call(
            _rank_cmd(base_spec_path)(0), env=env,
            stdout=bl, stderr=subprocess.STDOUT,
        )
    baseline = None
    if rc_base == 0:
        with open(os.path.join(base_spec["out_root"], "rank0.json")) as f:
            baseline = json.load(f)

    # point the runner's own name_resolve at the scenario root (restored
    # on exit so an embedding test suite keeps its repository)
    prev_repo = name_resolve.default_repository()
    name_resolve.set_repository(
        name_resolve.make_repository(
            name_resolve.NameResolveConfig(type="file", root=nr_root)
        )
    )
    probe = None
    restarts_before = metrics_mod.counters.get(metrics_mod.FT_RANK_RESTARTS)
    epochs_before = metrics_mod.counters.get(metrics_mod.FT_WORLD_EPOCHS)
    try:
        if cfg.with_gen:
            probe = GenFleetProbe()
            probe.start()
        sup = WorldSupervisor(
            WorldSupervisorConfig(
                experiment_name=experiment,
                trial_name=trial,
                num_processes=cfg.num_ranks,
                rank_cmd=_rank_cmd(spec_path),
                rank_env={
                    "PYTHONPATH": env["PYTHONPATH"],
                    "AREAL_FILEROOT": root,
                },
                collective_timeout_s=cfg.collective_timeout_s,
                report_grace_s=cfg.report_grace_s,
                max_rank_restarts=max(len(schedule) * 2, 4),
                log_dir=log_dir,
            )
        )
        t0 = time.monotonic()
        sup.start()
        rc_world = sup.run(timeout=cfg.timeout_s)
        world_wall = time.monotonic() - t0
        if probe is not None:
            probe.stop_event.set()
            probe.join(timeout=60)

        ranks = {}
        for r in range(cfg.num_ranks):
            p = os.path.join(out_root, f"rank{r}.json")
            if os.path.exists(p):
                with open(p) as f:
                    ranks[r] = json.load(f)
        leases = elastic.read_leases(experiment, trial)
        status_keys = name_resolve.find_subtree(
            f"areal_tpu/{experiment}/{trial}/worker_status"
        )
    finally:
        name_resolve.set_repository(prev_repo)

    flight_dumps: List[Dict] = []
    for p in sorted(glob.glob(os.path.join(flight_root, "*.json"))):
        try:
            with open(p) as f:
                flight_dumps.append(json.load(f))
        except (OSError, ValueError):
            flight_dumps.append({"reason": "unreadable", "path": p})

    report = {
        "root": root,
        "seed": cfg.seed,
        "schedule": schedule,
        "baseline_rc": rc_base,
        "baseline_wall_s": round(time.monotonic() - t_base, 1),
        "world_rc": rc_world,
        "world_wall_s": round(world_wall, 1),
        "rank_restarts": sup.rank_restarts,
        "world_epochs": sup.epoch,
        "recovery_times_s": [round(t, 1) for t in sup.recovery_times],
        "ranks_reported": sorted(ranks),
        "flight_dumps": [
            {
                "worker": d.get("worker"),
                "reason": d.get("reason"),
                "extra": d.get("extra"),
                "spans": len(d.get("spans") or []),
                "log_lines": len(d.get("log_tail") or []),
            }
            for d in flight_dumps
        ],
        "gen": probe.result if probe is not None else None,
        "counters": {
            "ft/rank_restarts": metrics_mod.counters.get(
                metrics_mod.FT_RANK_RESTARTS
            ) - restarts_before,
            "ft/world_epochs": metrics_mod.counters.get(
                metrics_mod.FT_WORLD_EPOCHS
            ) - epochs_before,
        },
    }
    report["violations"] = _violations(
        cfg, schedule, baseline, ranks, leases, status_keys, sup,
        rc_world, probe, flight_dumps,
    )
    report["ok"] = rc_world == 0 and not report["violations"]
    return report


def _violations(
    cfg, schedule, baseline, ranks, leases, status_keys, sup, rc_world,
    probe, flight_dumps=(),
) -> List[str]:
    v: List[str] = []
    if rc_world != 0:
        v.append(f"world did not complete cleanly (rc={rc_world})")
    if baseline is None:
        v.append("baseline run failed")
    missing = [r for r in range(cfg.num_ranks) if r not in ranks]
    if missing:
        v.append(f"ranks {missing} reported no output")
    if v:
        return v
    # loss continuity vs the unfaulted baseline: every loss any rank
    # recorded must match the baseline at that step (a relaunched rank
    # only has steps from its resume point on — the union must still
    # cover the whole run), and the FINAL step must match on every rank.
    base_losses = baseline["losses"]
    covered = set()
    for r, out in ranks.items():
        for step_s, fl in out["losses"].items():
            bl = base_losses.get(step_s)
            if bl is None:
                v.append(f"rank {r} recorded unknown step {step_s}")
                break
            covered.add(step_s)
            if abs(fl - bl) > cfg.loss_rtol * max(1.0, abs(bl)):
                v.append(
                    f"rank {r} step {step_s}: loss {fl} != baseline {bl} "
                    "(trajectory diverged across recovery)"
                )
                break
        if str(cfg.steps - 1) not in out["losses"]:
            v.append(f"rank {r} did not reach the final step")
    missing_steps = sorted(set(base_losses) - covered, key=int)
    if missing_steps:
        v.append(f"no rank recorded steps {missing_steps}")
    # accounting: every scheduled fault fired -> one rank restart + one
    # world epoch each
    if sup.rank_restarts != len(schedule):
        v.append(
            f"rank_restarts={sup.rank_restarts}, scheduled faults="
            f"{len(schedule)}"
        )
    if sup.epoch != len(schedule):
        v.append(f"world_epochs={sup.epoch}, expected {len(schedule)}")
    # bounded recovery
    slow = [t for t in sup.recovery_times if t > cfg.recovery_bound_s]
    if slow:
        v.append(f"recovery times over bound {cfg.recovery_bound_s}s: {slow}")
    # flight recorder: every injected rank fault must leave a black box
    # with span, counter-delta, and log-tail evidence
    # (docs/observability.md "Crash flight recorder")
    for ev in schedule:
        reason = f"rank.{ev['kind']}"
        match = [
            d for d in flight_dumps
            if d.get("reason") == reason
            and (d.get("extra") or {}).get("rank") == ev["rank"]
            and (d.get("extra") or {}).get("epoch") == ev["epoch"]
        ]
        if not match:
            v.append(f"no flight-recorder dump for injected fault {ev}")
            continue
        d = match[0]
        if not d.get("spans"):
            v.append(f"flight dump for {ev} has no span evidence")
        if not d.get("counters"):
            v.append(f"flight dump for {ev} has no counter deltas")
        if not d.get("log_tail"):
            v.append(f"flight dump for {ev} has no log tail")
    # lease/heartbeat hygiene: exactly one lease per rank, all at the
    # final epoch; no ghost heartbeat keys from dead incarnations
    if sorted(leases) != list(range(cfg.num_ranks)):
        v.append(f"leases for ranks {sorted(leases)} (hygiene leak?)")
    stale = [
        r for r, d in leases.items() if d.get("epoch") != sup.epoch
    ]
    if stale:
        v.append(f"leases at stale epochs for ranks {stale}")
    rank_status = [k for k in status_keys if "/trainer/rank" in k]
    if len(rank_status) != cfg.num_ranks:
        v.append(
            f"{len(rank_status)} rank heartbeat keys for "
            f"{cfg.num_ranks} ranks: {rank_status}"
        )
    # the serving side never stopped answering and leaked nothing
    if probe is not None:
        g = probe.result
        if not g:
            v.append("gen fleet probe produced no result")
        else:
            if g["failed"]:
                v.append(f"gen fleet failed {g['failed']} requests")
            if g["ok"] < 1:
                v.append("gen fleet served no successful request")
            if g["slots_running"] or g["pending"]:
                v.append(
                    f"gen slots leaked: running={g['slots_running']} "
                    f"pending={g['pending']}"
                )
            if g["pages_leaked"]:
                v.append(f"gen pages leaked: {g['pages_leaked']}")
            if g["version_regressed"]:
                v.append("gen weight version regressed")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--run-rank", type=int, default=None,
                   help="internal: run one rank body")
    p.add_argument("--spec", default=None, help="internal: rank spec json")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--faults", type=int, default=1)
    p.add_argument("--ranks", type=int, default=4)
    p.add_argument("--local-devices", type=int, default=2)
    p.add_argument("--parallel", default="d2f2m2")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--ckpt-every", type=int, default=3)
    p.add_argument("--collective-timeout", type=float, default=30.0)
    p.add_argument("--recovery-bound", type=float, default=240.0)
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--no-gen", action="store_true",
                   help="skip the serving-side probe")
    p.add_argument("--serve", action="store_true",
                   help="run the serving-plane survivability soak instead "
                        "of the training-world scenario")
    p.add_argument("--out", default=None, help="write the report JSON here")
    args = p.parse_args(argv)

    if args.run_rank is not None:
        if not args.spec:
            p.error("--run-rank requires --spec")
        return run_rank(args.run_rank, args.spec)

    if args.serve:
        report = run_serve_scenario(ServeChaosConfig())
        text = json.dumps(report, indent=2)
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
        if report["ok"]:
            print("CHAOS-SERVE OK: all invariants hold", file=sys.stderr)
            return 0
        print(
            f"CHAOS-SERVE FAILED: {len(report['violations'])} violation(s)",
            file=sys.stderr,
        )
        return 1

    cfg = ChaosConfig(
        seed=args.seed,
        n_faults=args.faults,
        num_ranks=args.ranks,
        local_devices=args.local_devices,
        parallel=args.parallel,
        steps=args.steps,
        ckpt_every=args.ckpt_every,
        collective_timeout_s=args.collective_timeout,
        recovery_bound_s=args.recovery_bound,
        timeout_s=args.timeout,
        with_gen=not args.no_gen,
    )
    report = run_scenario(cfg)
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if report["ok"]:
        print("CHAOS OK: all invariants hold", file=sys.stderr)
        return 0
    print(
        f"CHAOS FAILED: {len(report['violations'])} violation(s)",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
