"""Project indexer: parse a file set once, derive dotted module names,
and build per-module symbol tables (functions, classes, imports,
re-exports) that :mod:`tools.arealint.callgraph` resolves calls against.

Everything stays stdlib-only and purely static (docs/static_analysis.md):
imports are resolved by walking the INDEX, never by importing anything.
Resolution is deliberately conservative — a name the index cannot follow
(external library, dynamic attribute, star import) resolves to ``None``
and downstream rules treat it as "no edge", never as a finding.

What resolves (see docs/static_analysis.md "Call-graph semantics"):

- ``import a.b.c`` / ``import a.b.c as x`` — binds ``a`` (or ``x``).
- ``from a.b import c [as d]`` — module attribute OR submodule, decided
  against the index.
- ``from . import x`` / ``from ..mod import f`` — package-relative,
  resolved against the importing module's package.
- re-exports: ``__init__.py`` doing ``from .mod import f`` makes
  ``pkg.f`` an alias of ``pkg.mod.f`` (chains followed with a cycle
  guard).
- classes: methods index as ``module.Class.method``; single-name base
  classes resolvable in the index link method-resolution fallbacks.
"""

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Alias-chain / attribute-walk depth guard (import cycles, pathological
# re-export chains).
_MAX_HOPS = 32


@dataclasses.dataclass
class FunctionInfo:
    """One indexed function/method."""

    qualname: str            # "pkg.mod.func" or "pkg.mod.Class.method"
    module: str              # "pkg.mod"
    name: str                # bare name
    class_name: Optional[str]
    node: ast.AST            # FunctionDef | AsyncFunctionDef
    path: str

    @property
    def is_method(self) -> bool:
        return self.class_name is not None

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


@dataclasses.dataclass
class ClassInfo:
    qualname: str            # "pkg.mod.Class"
    module: str
    name: str
    node: ast.ClassDef
    # single-name / dotted base expressions, unresolved (resolved lazily)
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)


class ModuleInfo:
    """One parsed module: tree + symbol table."""

    def __init__(self, name: str, path: str, tree: ast.Module, src: str):
        self.name = name
        self.path = path
        self.tree = tree
        self.src = src
        # local binding -> fully-qualified dotted target. Targets may name
        # a module, a class, a function, or an attribute of any of those;
        # the project's resolver decides which.
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}   # bare name -> info
        self.classes: Dict[str, ClassInfo] = {}        # bare name -> info
        # module-level simple assignments: name -> value expression
        self.assigns: Dict[str, ast.expr] = {}

    @property
    def package(self) -> str:
        return self.name.rsplit(".", 1)[0] if "." in self.name else ""


def module_name_for(path: pathlib.Path, root: pathlib.Path) -> Optional[str]:
    """Dotted module name of ``path`` relative to ``root``
    (``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` -> ``a.b``).
    None when the path is not under the root."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return None
    parts = list(rel.parts)
    if not parts or not parts[-1].endswith(".py"):
        return None
    parts[-1] = parts[-1][: -len(".py")]
    if parts[-1] == "__init__":
        parts.pop()
    if not parts:
        return None
    return ".".join(parts)


def _index_module(mod: ModuleInfo) -> None:
    """Fill the symbol table from the module's top-level statements."""
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mod.imports[alias.asname] = alias.name
                else:
                    # ``import a.b.c`` binds the top package ``a``
                    mod.imports[alias.name.split(".")[0]] = (
                        alias.name.split(".")[0]
                    )
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # package-relative: level 1 = this package, 2 = parent, ...
                pkg_parts = mod.name.split(".")
                # a package __init__'s own name IS its package
                cut = len(pkg_parts) - (
                    node.level - 1 if _is_package_module(mod) else node.level
                )
                if cut <= 0:
                    # walks past the top of the tree: invalid Python at
                    # runtime — degrade to unresolvable, never guess
                    continue
                base = ".".join(pkg_parts[:cut] + ([base] if base else []))
            for alias in node.names:
                if alias.name == "*":
                    continue  # star imports never resolve (degrade)
                local = alias.asname or alias.name
                mod.imports[local] = (
                    f"{base}.{alias.name}" if base else alias.name
                )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = FunctionInfo(
                qualname=f"{mod.name}.{node.name}",
                module=mod.name, name=node.name, class_name=None,
                node=node, path=mod.path,
            )
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(
                qualname=f"{mod.name}.{node.name}",
                module=mod.name, name=node.name, node=node,
                bases=[d for d in map(_dotted, node.bases) if d],
            )
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[item.name] = FunctionInfo(
                        qualname=f"{mod.name}.{node.name}.{item.name}",
                        module=mod.name, name=item.name,
                        class_name=node.name, node=item, path=mod.path,
                    )
            mod.classes[node.name] = ci
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                mod.assigns[t.id] = node.value


def _is_package_module(mod: ModuleInfo) -> bool:
    return mod.path.replace("\\", "/").endswith("/__init__.py")


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def collect_aliases(tree: ast.AST) -> Dict[str, str]:
    """name -> dotted import target, collected from EVERY scope — unlike
    :class:`ModuleInfo`'s top-level import table, this sees imports done
    inside functions (the repo imports ``PartitionSpec as P`` and
    ``shard_map`` locally in several ops modules). Recognition-only: a
    scope collision just makes a match more permissive, so callers use
    it for *classifying* constructors (degrade on miss), never for
    building call-graph edges."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name
                )
    return out


class Project:
    """The indexed file set. Build with :meth:`from_paths` (real tree) or
    :meth:`from_sources` (fixture dict, used by the rule tests)."""

    def __init__(self, root: pathlib.Path):
        self.root = pathlib.Path(root)
        self.modules: Dict[str, ModuleInfo] = {}       # dotted name -> info
        self.by_path: Dict[str, ModuleInfo] = {}       # posix path -> info
        self.parse_errors: List[Tuple[str, int, str]] = []

    # ----------------------------------------------------------------- #
    # construction
    # ----------------------------------------------------------------- #

    @classmethod
    def from_paths(
        cls,
        paths: Iterable,
        root: Optional[pathlib.Path] = None,
        sources: Optional[Dict[str, str]] = None,
    ) -> "Project":
        """Index every ``*.py`` under ``paths``. ``root`` anchors dotted
        module names (defaults to the repo root heuristic: the common
        parent of the given paths). ``sources`` maps path -> already-read
        text so a caller that just scanned the files doesn't pay a second
        round of file I/O."""
        files: List[pathlib.Path] = []
        for p in paths:
            p = pathlib.Path(p)
            files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
        if root is None:
            if files:
                # common parent: handles both repo-root invocation and
                # tests that point at a single fixture directory
                root = pathlib.Path(
                    _common_parent([f.resolve() for f in files])
                )
                # a package dir is not a valid anchor — dotted names
                # would lose the package prefix and every ``from pkg
                # import x`` would fail to resolve; walk up to the
                # first non-package ancestor
                while (
                    (root / "__init__.py").is_file()
                    and root.parent != root
                ):
                    root = root.parent
            else:
                root = pathlib.Path(".")
        proj = cls(root)
        for f in files:
            src = (sources or {}).get(str(f))
            if src is None:
                try:
                    src = f.read_text()
                except OSError:
                    continue
            proj.add_source(str(f), src)
        return proj

    @classmethod
    def from_sources(
        cls, sources: Dict[str, str], root: str = "/proj"
    ) -> "Project":
        """Fixture constructor: ``{"pkg/mod.py": "src", ...}`` keyed by
        root-relative posix paths."""
        proj = cls(pathlib.Path(root))
        for rel, src in sorted(sources.items()):
            proj.add_source(str(pathlib.Path(root) / rel), src)
        return proj

    def add_source(self, path: str, src: str) -> Optional[ModuleInfo]:
        posix = path.replace("\\", "/")
        name = module_name_for(pathlib.Path(path), self.root)
        if name is None:
            # not under the root: index it as a standalone top-level module
            name = pathlib.Path(posix).stem
            if name == "__init__":
                name = pathlib.Path(posix).parent.name or "__init__"
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            self.parse_errors.append((posix, e.lineno or 0, e.msg or ""))
            return None
        mod = ModuleInfo(name, posix, tree, src)
        _index_module(mod)
        self.modules[name] = mod
        self.by_path[posix] = mod
        return mod

    # ----------------------------------------------------------------- #
    # resolution
    # ----------------------------------------------------------------- #

    def resolve(self, dotted: str) -> Optional[str]:
        """Canonical qualified name for an absolute dotted path: follows
        re-export aliases until it lands on an indexed function, class, or
        module. None when the chain leaves the index (external name) —
        callers degrade to no-edge."""
        seen: Set[str] = set()
        cur = dotted
        for _ in range(_MAX_HOPS):
            if cur in seen:
                return None  # alias cycle
            seen.add(cur)
            nxt = self._step(cur)
            if nxt is None:
                return None
            if nxt == cur:
                return cur
            cur = nxt
        return None

    def _step(self, dotted: str) -> Optional[str]:
        """One resolution hop: returns a fixed point when ``dotted`` is
        canonical, a new dotted path to continue from, or None."""
        if dotted in self.modules:
            return dotted
        if "." not in dotted:
            return None
        # find the longest module prefix, then walk attributes
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod_name = ".".join(parts[:cut])
            mod = self.modules.get(mod_name)
            if mod is None:
                continue
            attr, rest = parts[cut], parts[cut + 1:]
            if attr in mod.classes:
                ci = mod.classes[attr]
                if not rest:
                    return ci.qualname
                if len(rest) == 1 and rest[0] in ci.methods:
                    return ci.methods[rest[0]].qualname
                return None
            if attr in mod.functions:
                return mod.functions[attr].qualname if not rest else None
            if attr in mod.imports:
                # re-export: continue from the aliased target
                return ".".join([mod.imports[attr]] + rest)
            # maybe a submodule not explicitly imported
            sub = f"{mod_name}.{attr}"
            if sub in self.modules:
                return ".".join([sub] + rest)
            return None
        return None

    def resolve_in_module(
        self, mod: ModuleInfo, dotted: str
    ) -> Optional[str]:
        """Resolve a (possibly dotted) name as seen from inside ``mod``:
        local defs shadow imports, imports map to absolute targets."""
        head, _, rest = dotted.partition(".")
        target: Optional[str] = None
        if head in mod.functions:
            target = mod.functions[head].qualname
        elif head in mod.classes:
            target = mod.classes[head].qualname
        elif head in mod.imports:
            target = mod.imports[head]
        else:
            return None
        full = f"{target}.{rest}" if rest else target
        return self.resolve(full)

    def function(self, qualname: str) -> Optional[FunctionInfo]:
        """FunctionInfo for a canonical qualified name (module.func or
        module.Class.method); follows base classes for missing methods."""
        parts = qualname.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                return mod.functions.get(rest[0])
            if len(rest) == 2 and rest[0] in mod.classes:
                return self._method(mod.classes[rest[0]], rest[1])
            return None
        return None

    def class_info(self, qualname: str) -> Optional[ClassInfo]:
        parts = qualname.rsplit(".", 1)
        if len(parts) != 2:
            return None
        mod = self.modules.get(parts[0])
        return mod.classes.get(parts[1]) if mod else None

    def _method(
        self, ci: ClassInfo, name: str, _depth: int = 0
    ) -> Optional[FunctionInfo]:
        if name in ci.methods:
            return ci.methods[name]
        if _depth >= _MAX_HOPS:
            return None
        mod = self.modules.get(ci.module)
        for base in ci.bases:
            target = (
                self.resolve_in_module(mod, base) if mod else None
            )
            if target is None:
                continue
            base_ci = self.class_info(target)
            if base_ci is not None:
                found = self._method(base_ci, name, _depth + 1)
                if found is not None:
                    return found
        return None

    def all_functions(self) -> Iterable[FunctionInfo]:
        for mod in self.modules.values():
            yield from mod.functions.values()
            for ci in mod.classes.values():
                yield from ci.methods.values()


def _common_parent(paths: Sequence[pathlib.Path]) -> pathlib.Path:
    # component-wise, not string-prefix: /x/foobar must NOT count as
    # under /x/foo (a wrong root silently disables cross-module analysis)
    parent = paths[0].parent
    for p in paths[1:]:
        while parent not in p.parents:
            if parent.parent == parent:
                return parent
            parent = parent.parent
    return parent
