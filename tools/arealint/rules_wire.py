"""Wire-contract rules over the HTTP/SSE control and data plane.

Every review round since PR 10 has caught wire-drift bugs by hand — a
``/metrics_json`` key one side renamed, a status code the client never
branched on, a payload field the handler stopped reading. This family
machine-checks both sides of every HTTP/SSE seam against the endpoint
catalog ``tools/arealint/wiremodel.py`` parses (with ``ast``, never
imports) from the three route-registering server modules and the
declared client modules:

- ``unknown-endpoint`` — a client posts a literal path (or path+method
  pair) no server module registers.
- ``request-field-drift`` — a handler unconditionally subscripts a body
  field some resolved call site never sends (**error**: a guaranteed
  ``KeyError`` → 500); a client sends a field no handler for the
  endpoint reads (**warn**: dead payload, usually a rename half done).
- ``response-field-drift`` — a client reads a response-body or SSE
  frame key no producer of that endpoint emits.
- ``status-code-drift`` — a client branches on an HTTP status no
  handler of the endpoint can produce (**error**: dead error handling);
  a handler emits an explicit status none of the endpoint's callers
  handle (**warn**: the status surfaces as an unhandled exception).
- ``retry-unbounded-status`` — a status-retrying wrapper re-POSTs an
  endpoint the catalog marks non-idempotent: a timed-out ``/generate``
  may still be running server-side, so re-sending double-bills it.

Degradation contract (v2/v3/v4): dynamic paths, computed field names,
unresolvable payload dicts, and ``**splat`` response bodies all degrade
to no-finding. Under ``--changed-only`` the catalog may be partial:
rules that need the full server surface require every declared server
module in the scanned set (``servers_present``); the caller-coverage
warn additionally requires every client module (``clients_present``).

Deliberate one-sided fields (forward-compat keys, fields kept for
external dashboards) are annotated at the finding site::

    body["schema_rev"] = 2  # arealint: wire(/generate, fwd-compat key)

The annotation names the ENDPOINT (so a refactor that repoints the call
invalidates it) and requires a reason, same as ``# arealint: ok``. A
malformed or wrong-endpoint ``wire()`` does not suppress — the finding
message says so.
"""

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from tools.arealint.core import (
    ProjectContext, SEVERITY_ERROR, SEVERITY_WARN, project_rule,
)
from tools.arealint import wiremodel
from tools.arealint.wiremodel import (
    ClientCall, Endpoint, IMPLICIT_STATUSES, WireModel, wire_annotation,
)

RULE_UNKNOWN = "unknown-endpoint"
RULE_REQ_DRIFT = "request-field-drift"
RULE_RESP_DRIFT = "response-field-drift"
RULE_STATUS_DRIFT = "status-code-drift"
RULE_RETRY = "retry-unbounded-status"

_MALFORMED_NOTE = (
    " (a malformed `# arealint: wire(<endpoint>, <reason>)` does not"
    " suppress — it must name this endpoint and give a reason)"
)

FindingTuple = Tuple[str, int, str, str]


def _model(ctx: ProjectContext) -> Optional[WireModel]:
    """Build (once per scan) the wire model from the scanned subset of
    the spec's declared modules. None disables the family.

    ``project.by_path`` keys the paths exactly as the CLI passed them
    (absolute or cwd-relative), so declared repo-relative module paths
    are matched by suffix; the model keeps the canonical relative path
    and ``_report_path`` maps it back to the indexed key so the driver's
    suppression / file-context machinery finds the file."""
    spec = getattr(ctx.config, "wire", None)
    if spec is None:
        return None
    cached = getattr(ctx, "_wire_model", None)
    if cached is not None:
        return cached
    declared = set(spec.servers) | set(spec.clients)
    modules: Dict[str, tuple] = {}
    paths: Dict[str, str] = {}
    for posix, mod in ctx.project.by_path.items():
        for rel in declared:
            if posix == rel or posix.endswith("/" + rel):
                modules[rel] = (mod.tree, mod.src)
                paths[rel] = posix
    model = wiremodel.build_model(spec, modules)
    ctx._wire_model = model
    ctx._wire_paths = paths
    return model


def _report_path(ctx: ProjectContext, rel: str) -> str:
    return getattr(ctx, "_wire_paths", {}).get(rel, rel)


def _wire_suppressed(
    ctx: ProjectContext, path: str, lineno: int, endpoint: str
) -> Tuple[bool, str]:
    """(suppressed, message_suffix) for a candidate finding. A valid
    annotation naming this endpoint suppresses; a malformed one or one
    naming another endpoint fires the finding with a note."""
    mod = ctx.project.by_path.get(_report_path(ctx, path))
    if mod is None:
        return False, ""
    ann = wire_annotation(mod.src.splitlines(), lineno)
    if ann is None:
        return False, ""
    ep, _reason = ann
    if ep == endpoint:
        return True, ""
    return False, _MALFORMED_NOTE


def _endpoint_names(eps: Sequence[Endpoint]) -> str:
    return ", ".join(f"{ep.module}:{ep.handler}" for ep in eps)


@project_rule(
    RULE_UNKNOWN,
    SEVERITY_ERROR,
    "client calls a literal path/method no server module registers",
)
def check_unknown_endpoint(
    ctx: ProjectContext,
) -> Iterator[FindingTuple]:
    model = _model(ctx)
    if model is None or not model.servers_present:
        return
    for c in model.calls:
        if model.lookup(c.method, c.path):
            continue
        if model.path_known(c.path):
            methods = sorted(
                m for (m, p) in model.endpoints if p == c.path
            )
            msg = (
                f"{c.via} sends {c.method} {c.path}, but the servers "
                f"register that path only for {'/'.join(methods)} — "
                "method drift"
            )
        else:
            msg = (
                f"{c.via} calls {c.method} {c.path}, which no server "
                "module registers — the request can only 404"
            )
        ok, note = _wire_suppressed(ctx, c.module, c.lineno, c.path)
        if ok:
            continue
        yield (_report_path(ctx, c.module), c.lineno, msg + note, SEVERITY_ERROR)


@project_rule(
    RULE_REQ_DRIFT,
    SEVERITY_ERROR,
    "request body fields drift between a handler and its call sites",
)
def check_request_field_drift(
    ctx: ProjectContext,
) -> Iterator[FindingTuple]:
    model = _model(ctx)
    if model is None or not model.servers_present:
        return
    for c in model.calls:
        eps = model.lookup(c.method, c.path)
        if not eps or c.payload is None:
            continue  # unknown endpoint / unresolvable payload: degrade
        # error: a field EVERY handler of this (method, path) reads by
        # subscript is missing from this resolved payload -> KeyError
        required = set(eps[0].required)
        for ep in eps[1:]:
            required &= set(ep.required)
        for k in sorted(required):
            if k in c.payload:
                continue
            ok, note = _wire_suppressed(ctx, c.module, c.lineno, c.path)
            if ok:
                continue
            yield (
                _report_path(ctx, c.module),
                c.lineno,
                f"{c.via} posts {c.path} without field '{k}', which "
                f"the handler ({_endpoint_names(eps)}) reads "
                "unconditionally — guaranteed KeyError -> 500" + note,
                SEVERITY_ERROR,
            )
        # warn: a sent field NO handler reads (skipped entirely when any
        # handler's body escapes resolution: fields_open)
        if any(ep.fields_open for ep in eps):
            continue
        for k, ln in sorted(c.payload.items()):
            if any(
                k in ep.required or k in ep.optional for ep in eps
            ):
                continue
            ok, note = _wire_suppressed(ctx, c.module, ln, c.path)
            if ok:
                continue
            yield (
                _report_path(ctx, c.module),
                ln,
                f"{c.via} sends field '{k}' to {c.path}, but no "
                f"handler ({_endpoint_names(eps)}) reads it — dead "
                "payload, likely a half-done rename" + note,
                SEVERITY_WARN,
            )


@project_rule(
    RULE_RESP_DRIFT,
    SEVERITY_ERROR,
    "client reads a response/SSE key no producer of the endpoint emits",
)
def check_response_field_drift(
    ctx: ProjectContext,
) -> Iterator[FindingTuple]:
    model = _model(ctx)
    if model is None or not model.servers_present:
        return
    for c in model.calls:
        eps = model.lookup(c.method, c.path)
        if not eps:
            continue
        # response-body reads: provable only when every producer's key
        # set resolved closed
        if not any(ep.response.open for ep in eps):
            for k, ln in sorted(c.reads.items()):
                if any(ep.response.covers(k) for ep in eps):
                    continue
                ok, note = _wire_suppressed(ctx, c.module, ln, c.path)
                if ok:
                    continue
                yield (
                    _report_path(ctx, c.module),
                    ln,
                    f"{c.via} reads response key '{k}' from {c.path}, "
                    f"which no producer ({_endpoint_names(eps)}) emits"
                    + note,
                    SEVERITY_ERROR,
                )
        # SSE frame reads: compare against the streaming producers only
        frames = [ep.sse for ep in eps if ep.sse is not None]
        if not c.sse_reads or not frames or any(f.open for f in frames):
            continue
        for k, ln in sorted(c.sse_reads.items()):
            if any(f.covers(k) for f in frames):
                continue
            ok, note = _wire_suppressed(ctx, c.module, ln, c.path)
            if ok:
                continue
            yield (
                _report_path(ctx, c.module),
                ln,
                f"{c.via} reads SSE frame key '{k}' from {c.path}, "
                f"which no frame producer ({_endpoint_names(eps)}) "
                "writes" + note,
                SEVERITY_ERROR,
            )


@project_rule(
    RULE_STATUS_DRIFT,
    SEVERITY_ERROR,
    "HTTP status handling drifts between a handler and its callers",
)
def check_status_code_drift(
    ctx: ProjectContext,
) -> Iterator[FindingTuple]:
    model = _model(ctx)
    if model is None or not model.servers_present:
        return
    # error: a client branches on a status no handler can produce
    for c in model.calls:
        eps = model.lookup(c.method, c.path)
        if not eps:
            continue
        for s, ln in sorted(c.status_branches.items()):
            if any(ep.emits(s) for ep in eps):
                continue
            ok, note = _wire_suppressed(ctx, c.module, ln, c.path)
            if ok:
                continue
            yield (
                _report_path(ctx, c.module),
                ln,
                f"{c.via} branches on HTTP {s} from {c.method} "
                f"{c.path}, but no handler "
                f"({_endpoint_names(eps)}) can emit it — dead error "
                "handling" + note,
                SEVERITY_ERROR,
            )
    # warn: a handler emits an explicit status NO caller of the endpoint
    # handles (needs the complete caller set to be provable)
    if not model.clients_present:
        return
    for (method, path), eps in sorted(model.endpoints.items()):
        callers = model.calls_to(method, path)
        if not callers:
            continue  # external-facing endpoint: nothing to compare
        for ep in eps:
            for s, ln in sorted(ep.statuses.items()):
                if s in IMPLICIT_STATUSES:
                    continue
                if any(
                    s in c.status_branches
                    or c.generic_status_guard
                    or c.retries_status
                    for c in callers
                ):
                    continue
                ok, note = _wire_suppressed(ctx, ep.module, ln, path)
                if ok:
                    continue
                yield (
                    _report_path(ctx, ep.module),
                    ln,
                    f"{ep.handler} emits HTTP {s} for {method} {path}, "
                    "but no caller branches on it or guards with "
                    "raise_for_status — it surfaces as an unhandled "
                    "exception" + note,
                    SEVERITY_WARN,
                )


@project_rule(
    RULE_RETRY,
    SEVERITY_ERROR,
    "status-retrying wrapper re-sends a non-idempotent endpoint",
)
def check_retry_unbounded_status(
    ctx: ProjectContext,
) -> Iterator[FindingTuple]:
    # Needs only the verified spec (non_idempotent is pinned against the
    # full repo at config load), so it stays live under --changed-only.
    model = _model(ctx)
    if model is None:
        return
    for c in model.calls:
        if not c.retries_status or c.path not in model.spec.non_idempotent:
            continue
        ok, note = _wire_suppressed(ctx, c.module, c.lineno, c.path)
        if ok:
            continue
        yield (
            _report_path(ctx, c.module),
            c.lineno,
            f"{c.via} retries {c.method} {c.path} on transient HTTP "
            "statuses, but the endpoint is non-idempotent — a timed-out "
            "request may still be running server-side and a re-send "
            "double-executes it (pass retry_connection_only=True)" + note,
            SEVERITY_ERROR,
        )
